"""fabriclint dataflow — the whole-program half of the invariant checker.

PR 3's fabriclint sees one function at a time, so a digest computed in a
helper, wall-clock smuggled through two assignments into a marshaled
header, or an fsync three calls below ``commit_lock`` all slipped past
the gate.  This module closes that class: it parses every module in the
lint target set ONCE, resolves module-level imports and aliases
(``import hashlib as h``, ``from time import time``, relative imports),
builds a call graph over names it can resolve statically (module-level
functions, same-module helpers, ``self.`` methods of the enclosing
class), and computes per-function summaries to a fixpoint:

``uses_hashlib`` / ``uses_hashlib_transitive``
    touches ``hashlib`` directly / reaches it through helpers whose own
    modules are outside the CSP seam (propagation STOPS at seam modules:
    calling ``common.hashing.sha256`` is the fix, not a violation).

``returns_digest``
    returns a value produced by a hash call (hashlib, the seam's
    sha256/sha256_many, a CSP ``hash``/``hash_batch``) — directly or via
    a digest-returning callee.

``blocking`` / ``blocking_transitive``
    performs blocking I/O (fsync/flush/execute/sleep...) directly / via
    any resolvable call chain.  lint.py uses this to extend the
    under-``commit_lock`` rule across function and module boundaries.

``spawns_thread`` / ``acquires_locks``
    creates ``threading.Thread``s / lexically ``with``-acquires known
    lock roles — thread-lifecycle and lock-order context for reviewers
    and the thread-hygiene rule.

``returns_wallclock`` / ``param_to_return`` / ``param_to_sink``
    the taint summaries: the function returns a wall-clock-derived
    value; parameter *i* flows to the return value; parameter *i* flows
    into a consensus-bytes sink (protoutil call, protobuf constructor,
    ``SerializeToString``).

On top of the summaries run the interprocedural emissions:

taint
    ``time.time()`` / ``datetime.now()`` / module-level ``random.*``
    values tracked through assignments, attribute fills
    (``hdr.timestamp = ts``), f-strings, arithmetic, and resolvable
    calls, flagged where they ENTER a sink — protoutil marshaling or
    protobuf (block-header) construction — whichever module that happens
    in.  Tainted ``self`` attributes propagate across methods of the
    same class (``self._inc = int(time.time()*1000)`` in ``__init__``
    taints ``self._inc`` in every other method).

csp-seam (alias half)
    a local binding to ``hashlib`` (``h = hashlib``;
    ``digest = h.sha256``) used outside the seam — the spelling the
    intraprocedural attribute check cannot see.  The helper-call half
    (callers of hashlib-using helpers) is emitted by lint.py's checker
    using ``call_resolutions`` + the summaries here.

racecheck (v3)
    whole-program lockset inference + shared-state race detection.  A
    CLASS REGISTRY records, per class, which ``self.<attr>`` members
    are locks (``named_lock/named_rlock/named_condition`` roles, or a
    ``<Class>.<attr>`` pseudo-role for plain ``threading.Lock()``
    members) and which carry a statically known class type (annotated
    params/fields, direct constructor assignments) — the latter powers
    TYPE-INFORMED CALL RESOLUTION, so ``ledger.commit(...)`` on a
    ``ledger: KVLedger`` parameter lands in the call graph instead of
    falling off it.  A LOCKSET PASS then records, for every
    ``self._x`` (and declared module-global) read or write, the set of
    lock roles lexically held at that point, plus the lockset held at
    every resolvable call site; an interprocedural meet (set
    intersection over all incoming call paths) extends those locksets
    across function boundaries.  Fields acquire a GUARDED-BY role from
    the reviewed declaration table (``devtools/guards.py``) or, for
    undeclared mutable fields, by majority inference across their
    access sites.  Any access on a path from a THREAD ENTRY POINT
    (``lockwatch.spawn_thread``/``spawn_timer`` targets,
    ``threading.Thread``/``Timer`` ctors, ``executor.submit``, RPC/
    gossip ``.register``/``.subscribe`` handlers) whose lockset misses
    the field's guard is emitted as a racecheck flow.  ``__init__``
    bodies are excluded (the object is unpublished), a with-context
    that looks like a lock but cannot be resolved contributes an
    UNKNOWN token that suppresses rather than fabricates findings, and
    fields never written outside ``__init__`` are immune — three
    precision rules that keep the rule deployable at error severity.

hbcheck (v4)
    the happens-before layer.  The lockset walk additionally records
    POSITIONAL synchronization events per function — thread
    ``start()``/``join()``/``cancel()`` resolved to their entry qnames
    (through locals, ``self`` attrs, container joins, and chained
    ``spawn(...).start()``), ``drain_threads`` as a join of every
    entry, ``Event.set/clear/wait`` and ``Queue.put/get`` resolved to
    per-object sync tokens (class members and function-locals shared
    with closures), and ``workpool.run_chunked`` as a start+join pair
    at the call line.  A thread-entry SET (union over call paths,
    unlike the lockset meet) tells each access WHO can run it; a
    pairwise order check then proves accesses safe: same single
    domain, start-edge before every entry the counterpart runs under,
    join-edge after it completed, or a matching release→acquire token
    pair.  Proven-safe sites are exempt from racecheck emission (they
    still vote in guard inference), fully-ordered fields resolve as
    ``hb-publish`` in the guard map, and the same machinery emits
    post-``start()`` writes that race their publication point,
    cross-thread ``Event`` re-arms, and stale declared guards.  Two
    more passes ride the recorded facts: the role-level lock
    ACQUISITION-ORDER GRAPH (lexical held-sets + an interprocedural
    may-held union over production callers; ``lock_graph()`` exports
    it, lint.py fails cycles, tier-1 asserts the runtime lockwatch
    graph is a subgraph) and THREAD-LIFECYCLE reachability (every
    spawn site is classified by what happens to its handle —
    attr/local/container binding with an observed join/cancel/
    shutdown, ownership transfer by return/handoff, a stop-signal
    probe in the entry, or a bounded worker body — and anything else
    is an error).

The engine is deliberately static and approximate: only statically
resolvable names participate in the call graph, attribute calls on
foreign objects fall back to the per-name heuristics, and taint is
flow-insensitively accumulated (two body iterations per round).  The
approximations are all CONSERVATIVE for the rules built on top, and
every false positive costs exactly one reviewed pragma — the currency
this linter already trades in.
"""

from __future__ import annotations

import ast
import dataclasses
import re

# modules allowed to touch hashlib directly — the canonical definition
# (lint.py imports it from here so the two passes can never disagree)
CSP_SEAM_ALLOWED = (
    "fabric_tpu/csp/",
    "fabric_tpu/common/hashing.py",
    "fabric_tpu/common/crypto.py",
)

BLOCKING_CALLS = frozenset(
    {"fsync", "sync_files", "sleep", "flush", "execute", "executemany"}
)

# taint sinks: consensus bytes are born in these places
_SINK_MODULE_PREFIXES = ("fabric_tpu.protoutil", "fabric_tpu.protos.")
_SINK_ATTRS = frozenset({"SerializeToString", "SerializeToOstream"})

# hash producers for the returns-digest summary
_SEAM_HASH_FNS = (
    "fabric_tpu.common.hashing.sha256",
    "fabric_tpu.common.hashing.sha256_many",
    "fabric_tpu.common.crypto.sha256",
    "fabric_tpu.common.crypto.sha256_many",
)
_HASH_ATTRS = frozenset({"hash", "hash_batch", "digest", "hexdigest"})

_WALL = "wall"
_MAX_ROUNDS = 12

# -- racecheck vocabulary ----------------------------------------------------

# lock constructors recognized on `self.<attr> = ...` / module globals;
# named_* carry an explicit lockwatch role, plain threading primitives
# get a `<owner>.<attr>` pseudo-role so their guarded fields still
# participate in lockset inference
_NAMED_LOCK_FNS = frozenset({
    "fabric_tpu.devtools.lockwatch.named_lock",
    "fabric_tpu.devtools.lockwatch.named_rlock",
    "fabric_tpu.devtools.lockwatch.named_condition",
})
_PLAIN_LOCK_FNS = frozenset({
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
})

_SPAWN_THREAD_FNS = frozenset({
    "fabric_tpu.devtools.lockwatch.spawn_thread",
    "threading.Thread",
})
_SPAWN_TIMER_FNS = frozenset({
    "fabric_tpu.devtools.lockwatch.spawn_timer",
    "threading.Timer",
})
# attribute calls whose function-valued arguments run on foreign
# threads: executor submissions and RPC/gossip handler registration
_SUBMIT_ATTRS = frozenset({"submit"})
_HANDLER_REG_ATTRS = frozenset({"register", "subscribe"})

# a with-context that names a lock we cannot resolve to a role: it MAY
# be the guard, so accesses under it are never flagged and never feed
# majority inference
_UNKNOWN_LOCK = "?"

# gossip payload digests are consensus-adjacent bytes: peers compare /
# request private data by these digests, so a wall-clock-derived value
# entering one forks the gossip view exactly like a forked block header.
# Sink = the seam hash functions when called from gossip modules.
_GOSSIP_SINK_SCOPE = "fabric_tpu/gossip/"

# -- happens-before vocabulary (v4) ------------------------------------------

# synchronization-object constructors recognized on members/locals: an
# Event's set()->wait() and a Queue's put()->get() are publication
# edges (everything sequenced before the release side is visible after
# the matching acquire side)
_EVENT_CTOR_FNS = frozenset({"threading.Event"})
_QUEUE_CTOR_FNS = frozenset({
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue",
})
# executor factories: their registration is a thread-lifecycle site of
# its own (shutdown() is the stop path the rule demands)
_EXECUTOR_FNS = frozenset({
    "fabric_tpu.devtools.lockwatch.tracked_executor",
    "concurrent.futures.ThreadPoolExecutor",
})
# run_chunked(fn, ...) is a synchronous submit->result fan-out: the
# chunk callable is a thread entry (it runs on pool workers,
# concurrently with its sibling chunks), and the CALL SITE is both a
# start edge (caller's prior writes are published to the workers) and
# a join edge (workers' writes are published back before the call
# returns)
_RUN_CHUNKED_FNS = frozenset({"fabric_tpu.common.workpool.run_chunked"})
# drain_threads joins every registered worker: a join edge from ALL
# spawn_thread entries to the statements after it
_DRAIN_FNS = frozenset({"fabric_tpu.devtools.lockwatch.drain_threads"})
_CLOCKSKEW_WAIT = "fabric_tpu.devtools.clockskew.wait"

# the faultline injection API: callee qname -> seam kind.  `io` wraps a
# socket and registers TWO derived points (`<name>.read`/`<name>.write`)
_FAULTLINE_FNS = {
    "fabric_tpu.devtools.faultline.point": "point",
    "fabric_tpu.devtools.faultline.guard": "guard",
    "fabric_tpu.devtools.faultline.write": "write",
    "fabric_tpu.devtools.faultline.io": "io",
}
# the chaos seam's own implementation files: their faultline calls are
# plumbing, not production injection points (mirrors lint._CHAOS_SEAM)
_FAULTLINE_IMPL = (
    "fabric_tpu/devtools/faultline.py",
    "fabric_tpu/devtools/faultfuzz.py",
    "fabric_tpu/devtools/clockskew.py",
    "fabric_tpu/common/tracing.py",
)

# -- v6 surface scans (rpc / knob / metric conformance raw facts) ------------

# RPC method names are `svc.Method` — lowercase service, capitalized
# method (the reference's gRPC naming).  The regex is the discriminator
# that keeps unrelated `.register(...)`/`.call(...)` attribute calls
# (atexit.register, plan.call, ...) out of the map.
_RPC_METHOD_RE = re.compile(r"^[a-z][A-Za-z0-9]*\.[A-Z][A-Za-z0-9]*$")
_RPC_VERBS = ("call", "stream", "duplex")
# verb a client must use per statically inferred handler shape
_RPC_SHAPE_FOR_VERB = {"call": "unary", "stream": "stream",
                       "duplex": "duplex"}
# returned-call attr names that are bytes-producing, not
# iterator-producing: a handler `return X.SerializeToString()` is
# unary even though the callee does not resolve statically
_RPC_BYTES_ATTRS = ("encode", "SerializeToString", "digest", "dumps",
                    "to_bytes", "pack", "getvalue", "join")
# component classification for rpcmap sites: exact rels first, then
# path prefixes, else the file's package segment
_RPC_COMPONENT_FILES = {
    "fabric_tpu/node/peer_node.py": "peer",
    "fabric_tpu/node/orderer_node.py": "orderer",
    "fabric_tpu/node/devnode.py": "devnode",
    "fabric_tpu/devtools/netnode.py": "netnode",
    "fabric_tpu/devtools/netharness.py": "netharness",
    "fabric_tpu/csp/custody.py": "custody",
}
_RPC_COMPONENT_PREFIXES = (
    ("tests/", "tests"),
    ("scripts/", "scripts"),
    ("fabric_tpu/cmd/", "cli"),
    ("fabric_tpu/gateway/", "gateway"),
)

_KNOB_PREFIX = "FABRIC_TPU_"
# the one sanctioned env-read path (devtools/knob_registry.py) and the
# raw reads every other site must not use
_KNOB_IMPL = ("fabric_tpu/devtools/knob_registry.py",)
_KNOB_HELPER_FNS = (
    "fabric_tpu.devtools.knob_registry.raw",
    "fabric_tpu.devtools.knob_registry.spec",
)
_ENV_READ_FNS = ("os.environ.get", "os.getenv")

_METRIC_OPTS = {
    "fabric_tpu.common.metrics.CounterOpts": "counter",
    "fabric_tpu.common.metrics.GaugeOpts": "gauge",
    "fabric_tpu.common.metrics.HistogramOpts": "histogram",
}
_METRIC_NEW_FNS = ("new_counter", "new_gauge", "new_histogram")
# netscope's rollup/SLO code consumes series by name through string
# comparisons and `("_derived", name, ...)` ring keys; only there do
# bare snake_case literals count as metric-name consumption
_NETSCOPE_REL = "fabric_tpu/devtools/netscope.py"
_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)+$")
_HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


def _rpc_component(rel: str) -> str:
    comp = _RPC_COMPONENT_FILES.get(rel)
    if comp is not None:
        return comp
    for prefix, name in _RPC_COMPONENT_PREFIXES:
        if rel.startswith(prefix):
            return name
    parts = rel.split("/")
    return parts[1] if len(parts) > 2 else parts[-1].rsplit(".", 1)[0]


def _literal_strs(expr) -> set:
    """The string values `expr` can statically take: a literal, or an
    IfExp whose both branches are literals (cmd/peer.py picks
    `deliver.Deliver` vs `ab.Deliver` that way)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return {expr.value}
    if isinstance(expr, ast.IfExp):
        a, b = _literal_strs(expr.body), _literal_strs(expr.orelse)
        if a and b:
            return a | b
    return set()


def _str_consts(nodes) -> dict:
    """name -> possible string literal values, from single-target
    assignments in a scope's own statements (flow-insensitive)."""
    out: dict[str, set] = {}
    for n in nodes:
        if (
            isinstance(n, ast.Assign)
            and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)
        ):
            vals = _literal_strs(n.value)
            if vals:
                out.setdefault(n.targets[0].id, set()).update(vals)
    return out


def _resolve_str_arg(expr, local_consts: dict, mod_consts: dict) -> set:
    vals = _literal_strs(expr)
    if vals:
        return vals
    if isinstance(expr, ast.Name):
        return set(
            local_consts.get(expr.id) or mod_consts.get(expr.id) or ()
        )
    return set()

def _own_nodes(root):
    """AST nodes of `root` excluding nested function subtrees — a
    closure's statements run on the closure's schedule, not inline in
    the enclosing function (nested defs get their own scans)."""
    yield root
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


# the chaos/observability seams: their blocking calls (faultline.
# write's torn-path flush, clockskew/faultline injected sleeps,
# tracing's flight-recorder dump/export I/O) only execute under an
# armed plan / virtual clock / armed tracer — with nothing armed every
# seam call is a no-op, so their blocking-io summaries must not
# propagate into callers (mirror of the PR 6 decision that faultline.*
# is transparent to exception-discipline)
_CHAOS_SEAM = (
    "fabric_tpu/devtools/faultline.py",
    "fabric_tpu/devtools/clockskew.py",
    "fabric_tpu/common/tracing.py",
)


def _in_seam(rel: str) -> bool:
    return any(rel.startswith(p) for p in CSP_SEAM_ALLOWED)


def _module_dotted(rel: str) -> str:
    """Repo-relative path -> dotted module name."""
    if rel.endswith("/__init__.py"):
        rel = rel[: -len("/__init__.py")]
    elif rel.endswith(".py"):
        rel = rel[:-3]
    return rel.replace("/", ".")


def _iter_nested_defs(stmts):
    """Function definitions nested one level down inside a statement
    list (descending through control flow but not into the found defs
    themselves — recursion registers deeper levels — nor into nested
    classes, which are out of model)."""
    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield s
        elif isinstance(s, ast.ClassDef):
            continue
        else:
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if sub:
                    yield from _iter_nested_defs(sub)
            for h in getattr(s, "handlers", ()):
                yield from _iter_nested_defs(h.body)
            for c in getattr(s, "cases", ()):  # match statements
                yield from _iter_nested_defs(c.body)


def _dotted(expr) -> str | None:
    """``a.b.c`` as a string; None for anything fancier."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


@dataclasses.dataclass
class FunctionInfo:
    rel: str
    qname: str  # dotted: module[.Class].name
    name: str
    cls: str | None
    lineno: int
    params: list[str]
    node: object  # ast.FunctionDef | ast.AsyncFunctionDef
    # direct facts
    uses_hashlib: bool = False
    blocking: bool = False
    spawns_thread: bool = False
    acquires_locks: set = dataclasses.field(default_factory=set)
    calls: list = dataclasses.field(default_factory=list)  # resolved qnames
    # fixpoint facts
    uses_hashlib_transitive: bool = False
    blocking_transitive: bool = False
    returns_digest: bool = False
    returns_wallclock: bool = False
    param_to_return: set = dataclasses.field(default_factory=set)
    param_to_sink: set = dataclasses.field(default_factory=set)
    # racecheck facts: (field qname, "read"|"write", line, frozenset of
    # lock roles lexically held) and (callee qname, frozenset held)
    accesses: list = dataclasses.field(default_factory=list)
    call_locks: list = dataclasses.field(default_factory=list)
    # lock-order facts (v4): every lexical acquisition with the roles
    # already held at that point — the static acquisition-order graph
    # is assembled from these plus the interprocedural may-held set
    lock_acquires: list = dataclasses.field(default_factory=list)
    # happens-before events (v4), all positional within this function:
    # (entry qname | None, line) thread starts; (entry qname | "*",
    # line) joins; (sync token, line, heldset) event set/clear and
    # queue put on the release side, event wait and queue get on the
    # acquire side
    hb_starts: list = dataclasses.field(default_factory=list)
    hb_joins: list = dataclasses.field(default_factory=list)
    hb_rel: list = dataclasses.field(default_factory=list)
    hb_acq: list = dataclasses.field(default_factory=list)
    hb_clears: list = dataclasses.field(default_factory=list)
    # thread-lifecycle facts: this function blocks on a stop signal
    # (event wait/is_set, queue get on a known queue) / contains an
    # unbounded while loop
    stop_probe: bool = False
    has_while: bool = False
    # v5 "flowcheck": the function's control-flow graph (built during
    # the lockset pass) and the lock roles proven held somewhere by the
    # explicit acquire/release dataflow rather than a `with` scope
    cfg: object = None
    flow_lock_roles: set = dataclasses.field(default_factory=set)

    def summary(self) -> dict:
        """JSON-shaped summary (CLI ``--summaries``, tests)."""
        out = {
            "function": self.qname,
            "file": self.rel,
            "line": self.lineno,
            "returns_digest": self.returns_digest,
            "returns_wallclock": self.returns_wallclock,
            "uses_hashlib": self.uses_hashlib_transitive,
            "blocking_io": self.blocking_transitive,
            "spawns_thread": self.spawns_thread,
            "acquires_locks": sorted(self.acquires_locks),
            "param_to_sink": sorted(self.param_to_sink),
            "accesses": len(self.accesses),
        }
        # happens-before facts (v4) ride the artifact only where they
        # exist — most functions have none and the lines stay diffable
        if (self.hb_starts or self.hb_joins or self.hb_rel
                or self.hb_acq or self.stop_probe):
            out["hb"] = {
                "starts": len(self.hb_starts),
                "joins": len(self.hb_joins),
                "releases": len(self.hb_rel),
                "acquires": len(self.hb_acq),
                "stop_probe": self.stop_probe,
            }
        # CFG shape facts (v5) ride wherever a graph was built and is
        # non-trivial — straight-line helpers stay one diffable line
        if self.cfg is not None and getattr(self.cfg, "n", 0) > 1:
            out["cfg"] = self.cfg.stats()
            if self.flow_lock_roles:
                out["cfg"]["flow_locks"] = sorted(self.flow_lock_roles)
        return out


@dataclasses.dataclass
class ClassInfo:
    """Per-class registry entry for racecheck + typed call resolution."""

    rel: str
    qname: str
    name: str
    # attr -> lock role (lockwatch role string, or qname pseudo-role)
    lock_roles: dict = dataclasses.field(default_factory=dict)
    # attr -> class qname (annotated params/fields, ctor assignments)
    field_types: dict = dataclasses.field(default_factory=dict)
    # every attr assigned through `self.` anywhere in the class
    fields: set = dataclasses.field(default_factory=set)
    # attr -> "event" | "queue" (synchronization members: HB edges)
    sync_types: dict = dataclasses.field(default_factory=dict)
    # attr -> thread-entry qname (or None when the target does not
    # resolve) for members assigned from spawn_thread/spawn_timer/
    # Thread/Timer — lets `self._thread.start()`/`.join()` in OTHER
    # methods resolve to the spawned entry
    spawn_attrs: dict = dataclasses.field(default_factory=dict)
    # attrs assigned from tracked_executor/ThreadPoolExecutor
    exec_attrs: set = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class ModuleInfo:
    rel: str
    dotted: str
    tree: ast.Module
    imports: dict = dataclasses.field(default_factory=dict)  # name -> dotted
    functions: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class TaintFlow:
    """One wall-clock value entering a consensus-bytes sink."""

    rel: str
    line: int
    message: str


# -- per-function control-flow graph (v5 "flowcheck") ----------------------

_TRY_STAR = getattr(ast, "TryStar", ())
_MATCH_STMT = getattr(ast, "Match", ())


class _CFG:
    """Basic blocks + edges over ONE function's own statements.

    Built once per function during the lockset pass; every source line
    of the function maps to a program point ``(block, stmt)`` so the
    happens-before engine can ask order questions that respect branch
    structure and loop back edges instead of comparing line numbers:

    * ``event_precedes(e, a)`` — the HB event at line ``e`` (a join,
      ``Event.wait``, ``Queue.get``) is sequenced before the access at
      line ``a`` on every execution that reaches the access: same
      block in statement order, a dominating block, or a block that
      strictly precedes the access block (reaches it, never reached
      back).  Per-iteration order inside one block of a loop counts —
      a consumer that gets then reads each iteration is ordered.
    * ``access_precedes(a, e)`` — the access at ``a`` runs strictly
      before the HB event at ``e`` on EVERY execution containing both.
      A back edge defeats this: a write and a thread start in the same
      loop body are NOT ordered, because iteration 2's write races
      iteration 1's started thread.
    * ``may_follow(e, a)`` — some execution performs the event at
      ``e`` and later reaches ``a`` (the post-publication direction).

    ``with`` bodies stay inline (no branching — the lexical lockset
    scan already IS the meet-over-paths answer for them); ``try``
    bodies edge into every handler and into ``finally``; ``while
    True`` loops exit only through ``break``.  Lines the builder could
    not map (decorators, nested defs) fall back to positional order,
    so a partial graph can only make the analysis more conservative.
    """

    __slots__ = ("n", "succs", "preds", "back_edges", "_counts",
                 "_points", "_reach_memo", "_dom")

    def __init__(self):
        self.n = 0
        self.succs: list[set] = []
        self.preds: list[set] = []
        self.back_edges: set = set()
        self._counts: list[int] = []
        self._points: dict[int, tuple] = {}
        self._reach_memo: dict[int, frozenset] = {}
        self._dom: list | None = None

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, fnnode) -> "_CFG":
        cfg = cls()
        try:
            entry = cfg._new_block()
            cfg._seq(fnnode.body, entry, [])
        except RecursionError:  # pragma: no cover - pathological nesting
            cfg._points.clear()
        return cfg

    def _new_block(self) -> int:
        self.succs.append(set())
        self.preds.append(set())
        self._counts.append(0)
        self.n += 1
        return self.n - 1

    def _edge(self, a: int, b: int, back: bool = False) -> None:
        self.succs[a].add(b)
        self.preds[b].add(a)
        if back:
            self.back_edges.add((a, b))

    def _place(self, block: int, stmt, hi: int | None = None) -> None:
        """Assign ``stmt``'s lines to the next point of ``block``.

        ``hi`` caps the claimed range for compound statements so body
        lines stay claimable by the body's own blocks (first writer
        wins via setdefault)."""
        idx = self._counts[block]
        self._counts[block] += 1
        lo = stmt.lineno
        if hi is None:
            hi = getattr(stmt, "end_lineno", None) or lo
        for ln in range(lo, max(lo, hi) + 1):
            self._points.setdefault(ln, (block, idx))

    def _join(self, outs: list) -> int | None:
        outs = [b for b in dict.fromkeys(outs) if b is not None]
        if not outs:
            return None
        if len(outs) == 1:
            return outs[0]
        j = self._new_block()
        for b in outs:
            self._edge(b, j)
        return j

    def _seq(self, stmts, cur: int | None, loops: list) -> int | None:
        """Thread ``stmts`` through the graph; returns the fallthrough
        block, or None when every path ended (return/raise/break)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes own their lines
            if cur is None:
                cur = self._new_block()  # unreachable tail: orphan block
            if isinstance(stmt, ast.If):
                self._place(cur, stmt, stmt.test.end_lineno)
                t0 = self._new_block()
                self._edge(cur, t0)
                t_end = self._seq(stmt.body, t0, loops)
                if stmt.orelse:
                    e0 = self._new_block()
                    self._edge(cur, e0)
                    e_end = self._seq(stmt.orelse, e0, loops)
                    cur = self._join([t_end, e_end])
                else:
                    cur = self._join([t_end, cur])
            elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                hdr = self._new_block()
                self._edge(cur, hdr)
                cond = stmt.test if isinstance(stmt, ast.While) else stmt.iter
                self._place(hdr, stmt, cond.end_lineno)
                b0 = self._new_block()
                self._edge(hdr, b0)
                breaks: list = []
                b_end = self._seq(stmt.body, b0, loops + [(hdr, breaks)])
                if b_end is not None:
                    self._edge(b_end, hdr, back=True)
                infinite = (isinstance(stmt, ast.While)
                            and isinstance(stmt.test, ast.Constant)
                            and bool(stmt.test.value))
                outs = list(breaks)
                if not infinite:
                    o_end: int | None = hdr
                    if stmt.orelse:
                        o0 = self._new_block()
                        self._edge(hdr, o0)
                        o_end = self._seq(stmt.orelse, o0, loops)
                    outs.append(o_end)
                cur = self._join(outs)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                hi = max((it.context_expr.end_lineno or stmt.lineno)
                        for it in stmt.items)
                self._place(cur, stmt, max(stmt.lineno, hi))
                cur = self._seq(stmt.body, cur, loops)
            elif isinstance(stmt, ast.Try) or isinstance(stmt, _TRY_STAR):
                self._place(cur, stmt, stmt.lineno)
                b0 = self._new_block()
                self._edge(cur, b0)
                b_end = self._seq(stmt.body, b0, loops)
                body_hi = self.n  # blocks [b0, body_hi) can raise
                h_entries = []
                for h in stmt.handlers:
                    h0 = self._new_block()
                    self._place(h0, h, h.lineno)
                    h_entries.append(h0)
                    self._edge(cur, h0)
                    for bb in range(b0, body_hi):
                        self._edge(bb, h0)
                h_ends = [self._seq(h.body, h0, loops)
                          for h, h0 in zip(stmt.handlers, h_entries)]
                o_end = b_end
                if stmt.orelse and b_end is not None:
                    o_end = self._seq(stmt.orelse, b_end, loops)
                outs = [o_end] + h_ends
                if stmt.finalbody:
                    f0 = self._new_block()
                    for b in outs:
                        if b is not None:
                            self._edge(b, f0)
                    # exceptional entry: any body/handler block may
                    # unwind straight into the finally suite
                    for bb in range(b0, body_hi):
                        self._edge(bb, f0)
                    for h0 in h_entries:
                        self._edge(h0, f0)
                    self._edge(cur, f0)
                    cur = self._seq(stmt.finalbody, f0, loops)
                else:
                    cur = self._join(outs)
            elif isinstance(stmt, _MATCH_STMT):
                self._place(cur, stmt, stmt.subject.end_lineno)
                outs = [cur]  # conservative no-match fallthrough
                for case in stmt.cases:
                    c0 = self._new_block()
                    self._edge(cur, c0)
                    outs.append(self._seq(case.body, c0, loops))
                cur = self._join(outs)
            elif isinstance(stmt, (ast.Return, ast.Raise)):
                self._place(cur, stmt)
                cur = None
            elif isinstance(stmt, ast.Break):
                self._place(cur, stmt)
                if loops:
                    loops[-1][1].append(cur)
                cur = None
            elif isinstance(stmt, ast.Continue):
                self._place(cur, stmt)
                if loops:
                    self._edge(cur, loops[-1][0], back=True)
                cur = None
            else:
                self._place(cur, stmt)
        return cur

    # -- queries -----------------------------------------------------------

    def point(self, line: int) -> tuple | None:
        return self._points.get(line)

    def _reach(self, b: int) -> frozenset:
        """Blocks reachable from ``b`` through one or more edges."""
        memo = self._reach_memo.get(b)
        if memo is None:
            seen: set = set()
            stack = list(self.succs[b])
            while stack:
                x = stack.pop()
                if x not in seen:
                    seen.add(x)
                    stack.extend(self.succs[x] - seen)
            memo = self._reach_memo[b] = frozenset(seen)
        return memo

    def _cyclic(self, b: int) -> bool:
        return b in self._reach(b)

    def _dominators(self) -> list:
        if self._dom is None:
            every = frozenset(range(self.n))
            dom = [every] * self.n
            if self.n:
                dom[0] = frozenset([0])
            changed = True
            while changed:
                changed = False
                for b in range(1, self.n):
                    ps = self.preds[b]
                    if ps:
                        new = frozenset.intersection(
                            *[dom[p] for p in ps]) | {b}
                    else:
                        new = frozenset([b])  # orphan: its own entry
                    if new != dom[b]:
                        dom[b] = new
                        changed = True
            self._dom = dom
        return self._dom

    def event_precedes(self, event_line: int, access_line: int) -> bool:
        pe, pa = self.point(event_line), self.point(access_line)
        if pe is None or pa is None:
            return event_line < access_line  # positional fallback
        (be, se), (ba, sa) = pe, pa
        if be == ba:
            return se < sa  # per-iteration order holds in a cycle too
        if be in self._dominators()[ba]:
            return True
        return ba in self._reach(be) and be not in self._reach(ba)

    def access_precedes(self, access_line: int, event_line: int) -> bool:
        pe, pa = self.point(event_line), self.point(access_line)
        if pe is None or pa is None:
            return access_line < event_line
        (be, se), (ba, sa) = pe, pa
        if be == ba:
            return sa < se and not self._cyclic(ba)
        return be in self._reach(ba) and ba not in self._reach(be)

    def may_follow(self, event_line: int, access_line: int) -> bool:
        pe, pa = self.point(event_line), self.point(access_line)
        if pe is None or pa is None:
            return event_line < access_line
        (be, se), (ba, sa) = pe, pa
        if be == ba:
            return se < sa or self._cyclic(ba)
        return ba in self._reach(be)

    def stats(self) -> dict:
        return {
            "blocks": self.n,
            "edges": sum(len(s) for s in self.succs),
            "back_edges": len(self.back_edges),
        }


def _flow_locksets(cfg: _CFG, ops: list):
    """Forward must-hold dataflow over explicit ``.acquire()`` /
    ``.release()`` calls (``ops``: ``(line, "acq"|"rel", role)``).

    Returns ``at(line) -> frozenset(roles)`` — the roles PROVEN held at
    that program point on every path from the function entry.  IN of a
    block is the meet (intersection) over predecessor OUTs; within a
    block, ops apply in statement order, and an op's effect becomes
    visible from the NEXT statement (the acquire call itself does not
    guard its own line).  Lines outside the graph prove nothing."""
    if not ops or not cfg.n:
        empty = frozenset()
        return lambda line: empty
    block_ops: dict[int, list] = {}
    for i, (line, op, role) in enumerate(ops):
        p = cfg.point(line)
        if p is not None:
            block_ops.setdefault(p[0], []).append((p[1], i, op, role))
    for v in block_ops.values():
        v.sort()

    def transfer(b: int, held: frozenset) -> frozenset:
        for _s, _i, op, role in block_ops.get(b, ()):
            held = held | {role} if op == "acq" else held - {role}
        return held

    n = cfg.n
    in_sets: list = [None] * n
    out_sets: list = [None] * n
    for _round in range(4 * n + 8):
        changed = False
        for b in range(n):
            preds = cfg.preds[b]
            if b == 0 or not preds:
                inb: frozenset | None = frozenset()
            else:
                pouts = [out_sets[p] for p in preds
                         if out_sets[p] is not None]
                inb = frozenset.intersection(*pouts) if pouts else None
            if inb is None:
                continue
            in_sets[b] = inb
            ob = transfer(b, inb)
            if ob != out_sets[b]:
                out_sets[b] = ob
                changed = True
        if not changed:
            break

    empty = frozenset()

    def at(line: int) -> frozenset:
        p = cfg.point(line)
        if p is None or in_sets[p[0]] is None:
            return empty
        b, s = p
        held = in_sets[b]
        for si, _i, op, role in block_ops.get(b, ()):
            if si >= s:
                break
            held = held | {role} if op == "acq" else held - {role}
        return held

    return at


class Project:
    """Whole-program model over the lint target set.

    ``sanctioned_sources`` maps rel -> line numbers whose wall-clock
    source calls are covered by a reviewed ``allow[determinism]`` or
    ``allow[taint]`` pragma: a REVIEWED source does not propagate —
    otherwise one sanctioned client-side timestamp would demand a
    pragma at every downstream marshal site, and the suppression
    surface would grow instead of shrink."""

    def __init__(self, trees: dict[str, ast.Module],
                 sanctioned_sources: dict[str, set] | None = None,
                 declared_guards: dict[str, str] | None = None):
        if declared_guards is None:
            from fabric_tpu.devtools.guards import DECLARED_GUARDS

            declared_guards = DECLARED_GUARDS
        self.declared_guards = dict(declared_guards)
        self.sanctioned_sources = sanctioned_sources or {}
        # (rel, line) of sanctioned sources the engine actually hit —
        # lint.py counts their pragmas as used (the pragma's job was to
        # stop propagation, not to suppress a same-line violation)
        self.sanctioned_used: set[tuple] = set()
        self.modules: dict[str, ModuleInfo] = {}
        self.symbols: dict[str, FunctionInfo] = {}
        # (rel, lineno, col_offset) of a Call node -> resolved callee qname
        self.call_resolutions: dict[tuple, str] = {}
        # csp-seam alias violations found during the facts pass
        self.alias_violations: list[TaintFlow] = []
        self.taint_flows: list[TaintFlow] = []
        # racecheck emissions + the inferred guarded-by map behind them
        self.race_flows: list[TaintFlow] = []
        self.guard_map: dict[str, dict] = {}
        # v4: thread-lifecycle emissions, stale-guard emissions, the
        # static lock-order graph ((src role, dst role) -> sorted
        # acquisition sites), and the spawn-site registry feeding the
        # lifecycle rule
        self.lifecycle_flows: list[TaintFlow] = []
        self.stale_guard_flows: list[TaintFlow] = []
        self.lock_order_edges: dict[tuple, list] = {}
        self.spawn_sites: list[dict] = []
        # (owner qname | None, attr) pairs a join/cancel/shutdown call
        # is observed on anywhere in the program; None-owner entries
        # match by attr name (the conservative fallback when the base
        # object's class cannot be resolved)
        self._attr_joins: set = set()
        self._attr_shutdowns: set = set()
        # local sync objects (events/queues) visible to a function and
        # its closures: per-fn qname -> {name: (kind, token)}; lookup
        # walks the enclosing-scope chain, tokens are keyed by the
        # DEFINING function so sibling closures' same-named locals
        # never unify
        self._fn_local_sync: dict[str, dict] = {}
        # (field, kind, line, fn qname) -> True for accesses proven
        # safe by happens-before edges (exposed for tests/artifacts)
        self.hb_safe_sites: set = set()
        self._spawn_seen: set = set()
        # entry qnames that can run as several concurrent threads at
        # once (pool chunks, executor jobs, handlers, loop-spawned
        # workers): a shared single domain is NOT thread confinement
        self._multi_entries: set = set()
        # class registry (racecheck + typed call resolution)
        self.classes: dict[str, ClassInfo] = {}
        self.module_lock_roles: dict[str, str] = {}  # dotted name -> role
        self._attr_role_unique: dict[str, str | None] = {}
        # fn qname -> how it becomes a thread entry (for messages)
        self.thread_entries: dict[str, str] = {}
        # ClassDef qname -> names of self attributes holding wall-clock
        self._class_taint: dict[str, set] = {}
        # v5 chaos-coverage raw facts: every faultline seam call in
        # production code, seam calls whose name is not a string
        # literal, and every literal fault-plan rule anywhere
        self.faultline_seams: list[dict] = []
        self.faultline_dynamic: list[dict] = []
        self.faultline_plans: list[dict] = []
        # v6 surface-scan raw facts: the RPC register/call planes, the
        # FABRIC_TPU env-knob read sites, and the metric producer/
        # consumer planes (rules 12-14 + the --rpcmap/--knobs/
        # --metricmap artifacts consume these)
        self.rpc_registers: list[dict] = []
        self.rpc_calls: list[dict] = []
        self.knob_sites: list[dict] = []
        self.knob_dynamic: list[dict] = []
        self.metric_producers: list[dict] = []
        self.metric_derived: list[dict] = []
        self.metric_consumers: list[dict] = []
        self.metric_dynamic: list[dict] = []
        for rel, tree in sorted(trees.items()):
            self._load_module(rel, tree)
        self._collect_classes()
        self._collect_facts()
        self._fixpoint_booleans()
        self._fixpoint_taint()
        self._lockset_pass_all()
        self._interproc_lock_edges()
        self._racecheck()
        self._lifecycle()
        self._chaos_scan()
        self._rpc_scan()
        self._knob_scan()
        self._metric_scan()

    # -- module loading ----------------------------------------------------

    def _load_module(self, rel: str, tree: ast.Module) -> None:
        mod = ModuleInfo(rel=rel, dotted=_module_dotted(rel), tree=tree)
        pkg = mod.dotted.rsplit(".", 1)[0] if "." in mod.dotted else ""
        if rel.endswith("/__init__.py"):
            pkg = mod.dotted
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
                    if a.asname:
                        mod.imports[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    up = pkg.split(".") if pkg else []
                    up = up[: len(up) - (node.level - 1)]
                    base = ".".join(up + ([node.module] if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports[a.asname or a.name] = (
                        f"{base}.{a.name}" if base else a.name
                    )
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mod, stmt, cls=None)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._add_function(mod, sub, cls=stmt.name)
        self.modules[rel] = mod

    def _add_function(self, mod: ModuleInfo, node, cls: str | None,
                      parent: str | None = None) -> None:
        if parent is not None:
            qname = f"{parent}.<locals>.{node.name}"
        else:
            qname = (
                f"{mod.dotted}.{cls}.{node.name}" if cls
                else f"{mod.dotted}.{node.name}"
            )
        a = node.args
        params = [p.arg for p in a.posonlyargs + a.args]
        fn = FunctionInfo(
            rel=mod.rel, qname=qname, name=node.name, cls=cls,
            lineno=node.lineno, params=params, node=node,
        )
        mod.functions.append(fn)
        self.symbols[qname] = fn
        # locally-defined functions get their own symbols under a
        # `<qname>.<locals>.` scope: closures passed to spawn_thread /
        # Thread (the committer's commit_loop, rpc's stream pull) are
        # real thread entries racecheck must see.  They keep the
        # enclosing `cls` so closed-over `self.x` accesses resolve into
        # the class registry.
        for sub in _iter_nested_defs(node.body):
            self._add_function(mod, sub, cls=cls, parent=qname)

    # -- name resolution ---------------------------------------------------

    def _resolve_expr(self, mod: ModuleInfo, expr, cls: str | None,
                      local: dict, types: dict | None = None) -> str | None:
        """Resolve a Name/Attribute chain to a dotted target through
        local bindings, module imports, and (when `types` maps names to
        class qnames) annotated-parameter/field types.  ``self.x``
        resolves into the enclosing class; ``self.f.m`` and ``p.m``
        resolve through the class registry when ``f``/``p`` have a
        statically known class.  Returns e.g. "hashlib.sha256",
        "time.time", "fabric_tpu.ledger.kvledger.KVLedger.commit"."""
        dotted = _dotted(expr)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        if head == "self" and cls is not None:
            if not rest:
                return None
            first, _, tail = rest.partition(".")
            if tail:
                # typed self-field chain: self._ledger.commit resolves
                # through the field's declared/constructed class
                ci = self.classes.get(f"{mod.dotted}.{cls}")
                ft = ci.field_types.get(first) if ci else None
                if ft is not None:
                    return f"{ft}.{tail}"
            return f"{mod.dotted}.{cls}.{rest}"
        if types and rest and head in types:
            return f"{types[head]}.{rest}"
        target = local.get(head) or mod.imports.get(head)
        if target is None:
            # same-module symbol?
            cand = f"{mod.dotted}.{dotted}"
            if cand in self.symbols:
                return cand
            return None
        return f"{target}.{rest}" if rest else target

    # -- class registry (racecheck + typed resolution) ---------------------

    def _annotation_class(self, mod: ModuleInfo, ann) -> str | None:
        """The class qname an annotation statically names, or None.
        Handles Name/Attribute, string annotations, ``X | None`` unions
        and ``Optional[X]`` — anything fancier is out of model."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._annotation_class(mod, ann.left)
                    or self._annotation_class(mod, ann.right))
        if isinstance(ann, ast.Subscript):
            base = _dotted(ann.value)
            if base is not None and base.rsplit(".", 1)[-1] == "Optional":
                return self._annotation_class(mod, ann.slice)
            return None
        if not isinstance(ann, (ast.Name, ast.Attribute)):
            return None
        target = self._resolve_expr(mod, ann, None, {})
        if target in self.classes:
            return target
        return None

    @staticmethod
    def _role_from_ctor(target: str | None, call: ast.Call,
                        pseudo: str) -> str | None:
        """Lock role for a `<member> = <lock ctor>(...)` assignment:
        the named_* role string when constant, else the member's own
        qname as a pseudo-role (plain threading primitives included)."""
        if target in _NAMED_LOCK_FNS:
            if (
                call.args
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)
            ):
                return call.args[0].value
            return pseudo
        if target in _PLAIN_LOCK_FNS:
            return pseudo
        return None

    @staticmethod
    def _spawn_api(target: str | None) -> str | None:
        """"thread" / "timer" / "executor" when `target` is a thread-
        creating callable; None otherwise."""
        if target in _SPAWN_THREAD_FNS:
            return "thread"
        if target in _SPAWN_TIMER_FNS:
            return "timer"
        if target in _EXECUTOR_FNS:
            return "executor"
        return None

    @staticmethod
    def _spawn_kind(target: str | None, call: ast.Call) -> str:
        """The threadwatch kind of a spawn call (explicit kind= or the
        seam's default: workers from spawn_thread, services from
        spawn_timer)."""
        for k in call.keywords:
            if k.arg == "kind" and isinstance(k.value, ast.Constant):
                return str(k.value.value)
        return "service" if target in _SPAWN_TIMER_FNS else "worker"

    def _scoped_symbol(self, scope: str, name: str) -> str | None:
        """`name` resolved against `scope`'s ``<locals>`` chain: probe
        ``scope.<locals>.name``, then each enclosing function scope —
        the ONE closure-resolution rule (spawn targets, sibling-closure
        calls, and thread-entry registration all share it)."""
        while True:
            cand = f"{scope}.<locals>.{name}"
            if cand in self.symbols:
                return cand
            if ".<locals>." not in scope:
                return None
            scope = scope.rsplit(".<locals>.", 1)[0]

    def _spawn_entry(self, mod: ModuleInfo, call: ast.Call, cls,
                     local: dict, types: dict,
                     scope: str | None = None) -> str | None:
        """The thread-entry qname a spawn/Thread/Timer ctor targets (a
        known symbol, including `<locals>` closures when `scope` gives
        the enclosing function), or None when unresolvable."""
        target = self._resolve_expr(mod, call.func, cls, local, types)
        kw_name = "function" if target in _SPAWN_TIMER_FNS else "target"
        expr = None
        for k in call.keywords:
            if k.arg == kw_name:
                expr = k.value
        if expr is None:
            if target in _SPAWN_TIMER_FNS and len(call.args) >= 2:
                expr = call.args[1]
            elif (
                target in _SPAWN_THREAD_FNS
                and target != "threading.Thread"
                and call.args
            ):
                expr = call.args[0]
        if expr is None:
            return None
        if isinstance(expr, ast.Name) and scope is not None:
            scoped = self._scoped_symbol(scope, expr.id)
            if scoped is not None:
                return scoped
        q = self._resolve_expr(mod, expr, cls, local, types)
        return q if q in self.symbols else None

    def _collect_classes(self) -> None:
        # phase 1: every class must exist before any annotation can
        # resolve to it (cross-module field types)
        for mod in self.modules.values():
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    q = f"{mod.dotted}.{stmt.name}"
                    self.classes[q] = ClassInfo(
                        rel=mod.rel, qname=q, name=stmt.name
                    )
                elif (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)
                ):
                    # module-level locks guard module-level state
                    name = stmt.targets[0].id
                    target = self._resolve_expr(mod, stmt.value.func, None, {})
                    role = self._role_from_ctor(
                        target, stmt.value, f"{mod.dotted}.{name}"
                    )
                    if role is not None:
                        self.module_lock_roles[f"{mod.dotted}.{name}"] = role
        # phase 2: member scan (locks, field types, assigned attrs)
        for mod in self.modules.values():
            for stmt in mod.tree.body:
                if not isinstance(stmt, ast.ClassDef):
                    continue
                ci = self.classes[f"{mod.dotted}.{stmt.name}"]
                for fnnode in stmt.body:
                    if not isinstance(
                        fnnode, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    a = fnnode.args
                    ann_params = {
                        p.arg: p.annotation
                        for p in a.posonlyargs + a.args + a.kwonlyargs
                        if p.annotation is not None
                    }
                    for node in ast.walk(fnnode):
                        if isinstance(node, ast.Assign):
                            # every `self.X = ...` target registers,
                            # including chained assigns like
                            # `self._stop = stop = Event()`
                            attrs = [
                                t.attr for t in node.targets
                                if isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                            ]
                            if not attrs:
                                continue
                            for attr in attrs:
                                ci.fields.add(attr)
                            v = node.value
                            if isinstance(v, ast.Call):
                                target = self._resolve_expr(
                                    mod, v.func, stmt.name, {}
                                )
                                for attr in attrs:
                                    role = self._role_from_ctor(
                                        target, v, f"{ci.qname}.{attr}"
                                    )
                                    if role is not None:
                                        ci.lock_roles[attr] = role
                                    elif target in self.classes:
                                        ci.field_types.setdefault(
                                            attr, target
                                        )
                                    elif target in _EVENT_CTOR_FNS:
                                        ci.sync_types[attr] = "event"
                                    elif target in _QUEUE_CTOR_FNS:
                                        ci.sync_types[attr] = "queue"
                                    elif target in _EXECUTOR_FNS:
                                        ci.exec_attrs.add(attr)
                                    elif self._spawn_api(target) in (
                                        "thread", "timer"
                                    ):
                                        ci.spawn_attrs[attr] = (
                                            self._spawn_entry(
                                                mod, v, stmt.name, {}, {}
                                            )
                                        )
                            elif (
                                isinstance(v, ast.Name)
                                and v.id in ann_params
                            ):
                                tq = self._annotation_class(
                                    mod, ann_params[v.id]
                                )
                                if tq is not None:
                                    for attr in attrs:
                                        ci.field_types.setdefault(attr, tq)
                        elif (
                            isinstance(node, (ast.AnnAssign, ast.AugAssign))
                            and isinstance(node.target, ast.Attribute)
                            and isinstance(node.target.value, ast.Name)
                            and node.target.value.id == "self"
                        ):
                            ci.fields.add(node.target.attr)
                            if isinstance(node, ast.AnnAssign):
                                tq = self._annotation_class(
                                    mod, node.annotation
                                )
                                if tq is not None:
                                    ci.field_types[node.target.attr] = tq
        # attr name -> role when ONE role owns that spelling across the
        # whole program: lets `with self._ledger.commit_lock:` resolve
        # even where the field's type is unannotated
        unique: dict[str, str | None] = {}
        for ci in self.classes.values():
            for attr, role in ci.lock_roles.items():
                if attr in unique and unique[attr] != role:
                    unique[attr] = None
                else:
                    unique[attr] = role
        self._attr_role_unique = unique

    # -- facts pass --------------------------------------------------------

    def _collect_facts(self) -> None:
        for mod in self.modules.values():
            for fn in mod.functions:
                self._facts_for(mod, fn)

    def _facts_for(self, mod: ModuleInfo, fn: FunctionInfo) -> None:
        local: dict[str, str] = {}
        seam = _in_seam(mod.rel)
        # annotated params with statically known classes: the type env
        # behind type-informed call resolution
        a = fn.node.args
        types: dict[str, str] = {}
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            tq = self._annotation_class(mod, p.annotation)
            if tq is not None:
                types[p.arg] = tq
        fn._types = types
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                # `types` rides along so a local bound from an annotated
                # param's field (`lk = ledger.commit_lock`) resolves to
                # the field's qname — the lockset pass then maps the
                # bare `with lk:` to the field's lock role
                bound = self._resolve_expr(
                    mod, node.value, fn.cls, local, types
                )
                if bound is not None and not isinstance(node.value, ast.Call):
                    local[node.targets[0].id] = bound
                    if not seam and (
                        bound == "hashlib" or bound.startswith("hashlib.")
                    ):
                        self.alias_violations.append(TaintFlow(
                            rel=mod.rel, line=node.lineno,
                            message=f"local alias "
                                    f"{node.targets[0].id!r} binds "
                                    f"{bound} outside the CSP seam — "
                                    "aliasing does not launder a direct "
                                    "hashlib dependency (route through "
                                    "common.hashing or the CSP)",
                        ))
            elif isinstance(node, ast.Call):
                target = self._resolve_expr(
                    mod, node.func, fn.cls, local, types
                )
                if target is None and isinstance(node.func, ast.Name):
                    # closure-to-closure resolution (v4): a bare-name
                    # call probes the enclosing `<locals>` scopes, so a
                    # nested def calling its own nested defs or sibling
                    # closures stays on the call graph — thread targets
                    # defined as closures keep their callees' lockset/
                    # HB facts
                    nm = node.func.id
                    if nm not in local and nm not in fn.params:
                        target = self._scoped_symbol(fn.qname, nm)
                if target is not None:
                    if target in self.symbols:
                        fn.calls.append(target)
                        self.call_resolutions[
                            (mod.rel, node.lineno, node.col_offset)
                        ] = target
                    if target == "hashlib" or target.startswith("hashlib."):
                        fn.uses_hashlib = True
                    if target in (
                        "threading.Thread",
                        "threading.Timer",
                        "fabric_tpu.devtools.lockwatch.spawn_thread",
                        "fabric_tpu.devtools.lockwatch.spawn_timer",
                    ):
                        fn.spawns_thread = True
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr in BLOCKING_CALLS:
                        fn.blocking = True
                    if (
                        isinstance(f.value, ast.Name)
                        and local.get(f.value.id, "").startswith("hashlib")
                    ):
                        fn.uses_hashlib = True
            elif isinstance(node, ast.With):
                for item in node.items:
                    name = None
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Attribute):
                        name = ctx.attr
                    elif isinstance(ctx, ast.Name):
                        name = ctx.id
                    if name is not None and (
                        "lock" in name.lower() or name in ("_idle",)
                    ):
                        fn.acquires_locks.add(name)
        fn.uses_hashlib_transitive = fn.uses_hashlib and not seam
        fn.blocking_transitive = fn.blocking and fn.rel not in _CHAOS_SEAM
        fn.returns_digest = self._returns_digest_direct(mod, fn, local)
        fn._local_bindings = local  # reused by the taint pass
        # names stored more than once anywhere in this function: a lock
        # ALIAS among them is ambiguous — the binding map is flow-
        # insensitive (last write wins), so crediting it would attach
        # the WRONG lock's role to earlier with-blocks.  _role_of_ctx
        # degrades rebound aliases to the UNKNOWN lockset instead.
        store_counts: dict[str, int] = {}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                store_counts[node.id] = store_counts.get(node.id, 0) + 1
        fn._rebound = {k for k, c in store_counts.items() if c > 1}
        # unbounded-loop fact for the thread-lifecycle rule's bounded-
        # worker heuristic (own statements only: a closure's loop runs
        # on the closure's thread, not this one)
        def _has_while(n) -> bool:
            for child in ast.iter_child_nodes(n):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if isinstance(child, ast.While) or _has_while(child):
                    return True
            return False

        fn.has_while = _has_while(fn.node)
        # callee qnames appearing inside Return expressions, computed
        # once — the returns-digest fixpoint is a set lookup, not a
        # re-walk of the caller's AST per round
        ret_calls: set = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        q = self.call_resolutions.get(
                            (mod.rel, sub.lineno, sub.col_offset)
                        )
                        if q is not None:
                            ret_calls.add(q)
        fn._return_callees = ret_calls

    def _returns_digest_direct(self, mod: ModuleInfo, fn: FunctionInfo,
                               local: dict) -> bool:
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            for sub in ast.walk(node.value):
                if not isinstance(sub, ast.Call):
                    continue
                target = self._resolve_expr(mod, sub.func, fn.cls, local)
                if target is not None and (
                    target.startswith("hashlib.")
                    or target in _SEAM_HASH_FNS
                ):
                    return True
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _HASH_ATTRS:
                    return True
        return False

    # -- boolean fixpoints -------------------------------------------------

    def _fixpoint_booleans(self) -> None:
        changed = True
        rounds = 0
        while changed and rounds < _MAX_ROUNDS:
            changed = False
            rounds += 1
            for fn in self.symbols.values():
                for callee_q in fn.calls:
                    callee = self.symbols.get(callee_q)
                    if callee is None:
                        continue
                    if callee.blocking_transitive and not fn.blocking_transitive:
                        fn.blocking_transitive = True
                        changed = True
                    # hashlib reach propagates only through NON-seam
                    # callees: calling the seam is the sanctioned route
                    if (
                        callee.uses_hashlib_transitive
                        and not _in_seam(callee.rel)
                        and not _in_seam(fn.rel)
                        and not fn.uses_hashlib_transitive
                    ):
                        fn.uses_hashlib_transitive = True
                        changed = True
                    if (
                        callee.returns_digest
                        and not fn.returns_digest
                        and callee_q in fn._return_callees
                    ):
                        fn.returns_digest = True
                        changed = True

    # -- taint -------------------------------------------------------------

    def _fixpoint_taint(self) -> None:
        for _ in range(_MAX_ROUNDS):
            changed = False
            for mod in self.modules.values():
                for fn in mod.functions:
                    if self._taint_pass(mod, fn, emit=False):
                        changed = True
            if not changed:
                break
        seen = set()
        for mod in self.modules.values():
            for fn in mod.functions:
                self._taint_pass(mod, fn, emit=True, seen=seen)

    def _is_wall_source(self, target: str | None) -> bool:
        if target is None:
            return False
        if target == "time.time":
            return True
        if target.startswith("datetime.") and target.rsplit(".", 1)[-1] in (
            "now", "utcnow", "today"
        ):
            return True
        if target.startswith("random.") and target.rsplit(".", 1)[-1] not in (
            "Random", "SystemRandom"
        ):
            return True
        return False

    def _sink_for(self, mod: ModuleInfo, node: ast.Call, cls, local):
        """(kind, detail) when this call consumes its arguments into
        consensus bytes; None otherwise."""
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _SINK_ATTRS:
            return ("serialize", f.attr)
        target = self._resolve_expr(mod, f, cls, local)
        if target is not None and any(
            target.startswith(p) for p in _SINK_MODULE_PREFIXES
        ):
            tail = target.rsplit(".", 1)[-1]
            kind = "proto-ctor" if tail[:1].isupper() else "protoutil"
            return (kind, target)
        # gossip payload digests: peers dedupe/pull/verify by these
        # bytes, so a wall-clock-derived input forks the gossip view
        if (
            mod.rel.startswith(_GOSSIP_SINK_SCOPE)
            and target is not None
            and (target in _SEAM_HASH_FNS
                 or target.startswith("hashlib."))
        ):
            return ("gossip-digest", target)
        return None

    def _taint_pass(self, mod: ModuleInfo, fn: FunctionInfo,
                    emit: bool, seen: set | None = None) -> bool:
        env: dict[str, frozenset] = {
            p: frozenset({("param", i)}) for i, p in enumerate(fn.params)
        }
        if fn.cls is not None and fn.params and fn.params[0] == "self":
            env["self"] = frozenset()
        cls_q = f"{mod.dotted}.{fn.cls}" if fn.cls else None
        local = getattr(fn, "_local_bindings", {})
        changed = [False]

        def note_param_summary(labels, add_to: set) -> None:
            for lb in labels:
                if isinstance(lb, tuple) and lb[0] == "param":
                    if lb[1] not in add_to:
                        add_to.add(lb[1])
                        changed[0] = True

        def ev(node) -> frozenset:
            if isinstance(node, ast.Name):
                return env.get(node.id, frozenset())
            if isinstance(node, ast.Constant):
                return frozenset()
            if isinstance(node, ast.Call):
                return ev_call(node)
            if isinstance(node, ast.Attribute):
                base = ev(node.value)
                dotted = _dotted(node)
                if dotted is not None and dotted in env:
                    base = base | env[dotted]
                if (
                    cls_q is not None
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in self._class_taint.get(cls_q, ())
                ):
                    base = base | frozenset({_WALL})
                return base
            if isinstance(node, ast.JoinedStr):
                out = frozenset()
                for v in node.values:
                    out |= ev(v)
                return out
            if isinstance(node, ast.FormattedValue):
                return ev(node.value)
            out = frozenset()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    out |= ev(child)
            return out

        def arg_labels(node: ast.Call, callee: FunctionInfo | None):
            """position -> labels, including keywords mapped through the
            callee's parameter names (methods: skip the self slot)."""
            out: dict[int, frozenset] = {}
            shift = 1 if callee is not None and callee.params[:1] == ["self"] \
                else 0
            for i, a in enumerate(node.args):
                out[i + shift] = ev(a)
            for kw in node.keywords:
                labels = ev(kw.value)
                if callee is not None and kw.arg in (callee.params or ()):
                    out[callee.params.index(kw.arg)] = labels
                else:
                    out.setdefault(-1, frozenset())
                    out[-1] |= labels
            return out

        def ev_call(node: ast.Call) -> frozenset:
            callee_q = self.call_resolutions.get(
                (mod.rel, node.lineno, node.col_offset)
            )
            callee = self.symbols.get(callee_q) if callee_q else None
            target = self._resolve_expr(mod, node.func, fn.cls, local)
            if self._is_wall_source(target):
                if node.lineno in self.sanctioned_sources.get(mod.rel, ()):
                    self.sanctioned_used.add((mod.rel, node.lineno))
                else:
                    return frozenset({_WALL})
            labels_by_pos = arg_labels(node, callee)
            sink = self._sink_for(mod, node, fn.cls, local)
            flowing = frozenset()
            for labels in labels_by_pos.values():
                flowing |= labels
            if isinstance(node.func, ast.Attribute) and sink:
                flowing |= ev(node.func.value)
                # a proto object filled field-by-field: any tainted
                # `obj.field` entry counts against `obj.Serialize...()`
                base_d = _dotted(node.func.value)
                if base_d is not None:
                    for k, v in env.items():
                        if k.startswith(base_d + "."):
                            flowing |= v
            if sink is not None:
                if _WALL in flowing:
                    if emit:
                        key = ("taint", mod.rel, node.lineno)
                        if seen is not None and key not in seen:
                            seen.add(key)
                            self.taint_flows.append(TaintFlow(
                                rel=mod.rel, line=node.lineno,
                                message=(
                                    "wall-clock-derived value flows into "
                                    f"consensus bytes ({sink[0]}: "
                                    f"{sink[1]}) — peers recomputing "
                                    "these bytes will disagree; thread "
                                    "an explicit timestamp argument "
                                    "instead"
                                ),
                            ))
                note_param_summary(flowing, fn.param_to_sink)
            if callee is not None:
                # arguments reaching the callee's sink-flowing params
                for pos, labels in labels_by_pos.items():
                    if pos in callee.param_to_sink:
                        if _WALL in labels and emit:
                            key = ("taint", mod.rel, node.lineno)
                            if seen is not None and key not in seen:
                                seen.add(key)
                                self.taint_flows.append(TaintFlow(
                                    rel=mod.rel, line=node.lineno,
                                    message=(
                                        "wall-clock-derived argument "
                                        f"reaches a consensus-bytes sink "
                                        f"inside {callee.qname} (param "
                                        f"{pos}) — peers recomputing "
                                        "these bytes will disagree"
                                    ),
                                ))
                        note_param_summary(labels, fn.param_to_sink)
                out = frozenset()
                if callee.returns_wallclock:
                    out |= frozenset({_WALL})
                for pos in callee.param_to_return:
                    out |= labels_by_pos.get(pos, frozenset())
                return out
            # unresolved call: conservatively propagate every input
            out = flowing
            if isinstance(node.func, ast.Attribute):
                out |= ev(node.func.value)
            return out

        def assign_to(target, labels: frozenset) -> None:
            if isinstance(target, ast.Name):
                prev = env.get(target.id, frozenset())
                if labels - prev:
                    env[target.id] = prev | labels
            elif isinstance(target, ast.Attribute):
                dotted = _dotted(target)
                if dotted is not None:
                    prev = env.get(dotted, frozenset())
                    if labels - prev:
                        env[dotted] = prev | labels
                # filling a field of a LOCAL object taints the object —
                # `hdr.timestamp = ts; return hdr` must carry the taint
                # out.  `self` is the exception: class-level attribute
                # taint tracks the individual attribute instead, so one
                # tainted field doesn't poison every self access.
                base = target.value
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id != "self":
                    prev = env.get(base.id, frozenset())
                    if labels - prev:
                        env[base.id] = prev | labels
                if (
                    cls_q is not None
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and _WALL in labels
                ):
                    attrs = self._class_taint.setdefault(cls_q, set())
                    if target.attr not in attrs:
                        attrs.add(target.attr)
                        changed[0] = True
            elif isinstance(target, ast.Subscript):
                assign_to(target.value, labels)
            elif isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    assign_to(elt, labels)

        def walk(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    # nested defs are outside the summary model (rare
                    # on the paths these rules guard)
                    continue
                elif isinstance(stmt, ast.Assign):
                    labels = ev(stmt.value)
                    for t in stmt.targets:
                        assign_to(t, labels)
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    if stmt.value is not None:
                        assign_to(stmt.target, ev(stmt.value))
                elif isinstance(stmt, ast.Return):
                    if stmt.value is not None:
                        labels = ev(stmt.value)
                        if _WALL in labels and not fn.returns_wallclock:
                            fn.returns_wallclock = True
                            changed[0] = True
                        note_param_summary(labels, fn.param_to_return)
                elif isinstance(stmt, ast.For):
                    assign_to(stmt.target, ev(stmt.iter))
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.While, ast.If)):
                    ev(stmt.test)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        labels = ev(item.context_expr)
                        if item.optional_vars is not None:
                            assign_to(item.optional_vars, labels)
                    walk(stmt.body)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body)
                    for h in stmt.handlers:
                        walk(h.body)
                    walk(stmt.orelse)
                    walk(stmt.finalbody)
                elif isinstance(stmt, ast.Expr):
                    ev(stmt.value)
                elif isinstance(stmt, (ast.Raise, ast.Assert)):
                    for child in ast.iter_child_nodes(stmt):
                        if isinstance(child, ast.expr):
                            ev(child)

        # two body iterations: taint born late in a loop body reaches
        # uses earlier in the (next) iteration; env only grows, so the
        # second sweep is the loop-closure
        walk(fn.node.body)
        walk(fn.node.body)
        return changed[0]

    # -- racecheck: lockset-at-access + guarded-by inference ---------------

    def _role_of_ctx(self, mod: ModuleInfo, ctx, ci: ClassInfo | None,
                     types: dict, local: dict | None = None,
                     rebound=()) -> str | None:
        """Lock role of a with-context expression.  None = not a lock;
        _UNKNOWN_LOCK = lock-shaped but unresolvable (suppresses rather
        than fabricates racecheck findings)."""
        dotted = _dotted(ctx)
        if dotted is None:
            return None
        parts = dotted.split(".")
        attr = parts[-1]
        lockish = (
            "lock" in attr.lower()
            or "cond" in attr.lower()
            or attr in ("_idle",)
        )
        if len(parts) == 1:
            # a bare local bound from a field/param chain (`lock =
            # self._mu; with lock:`): resolve the BINDING's qname to its
            # owner's lock role, so these scopes stop degrading to the
            # UNKNOWN lockset (which both hides dirty accesses and
            # excludes clean ones from majority inference)
            bound = (local or {}).get(attr)
            if bound is not None:
                role = self.module_lock_roles.get(bound)
                if role is None and "." in bound:
                    owner_q, _, leaf = bound.rpartition(".")
                    owner = self.classes.get(owner_q)
                    if owner is not None:
                        role = owner.lock_roles.get(leaf)
                if role is not None:
                    # a REBOUND alias (the name is stored more than
                    # once) resolved a lock role through its LAST
                    # binding — earlier with-blocks may hold a
                    # different lock, so suppress rather than credit
                    # the wrong role
                    return _UNKNOWN_LOCK if attr in rebound else role
            role = self.module_lock_roles.get(f"{mod.dotted}.{attr}")
            if role is not None:
                return role
            return _UNKNOWN_LOCK if lockish else None
        head = parts[0]
        owner: ClassInfo | None = None
        if head == "self" and ci is not None:
            if len(parts) == 2:
                owner = ci
            elif len(parts) == 3:
                ft = ci.field_types.get(parts[1])
                owner = self.classes.get(ft) if ft else None
        elif head in types and len(parts) == 2:
            owner = self.classes.get(types[head])
        if owner is not None:
            role = owner.lock_roles.get(attr)
            if role is not None:
                return role
        if lockish:
            return self._attr_role_unique.get(attr) or _UNKNOWN_LOCK
        return None

    def _spawn_scan(self, mod: ModuleInfo, fn: FunctionInfo, ci,
                    types: dict, local: dict) -> dict:
        """Classify every spawn/Thread/Timer/executor creation in this
        function by what the caller does with the handle — bound to a
        `self` attr, a local, a container append, returned/handed off,
        or discarded — registering each as a spawn SITE for the thread-
        lifecycle rule.  Returns the local-name -> entry-qname map the
        HB walk uses to resolve `t.start()`/`t.join()`.

        All three scans cover OWN statements only (``_own_nodes``): a
        nested def's spawns/joins belong to the closure's own scan — a
        closure-local ``t`` leaking into the parent's map would let an
        unrelated parent variable of the same name fabricate HB
        edges."""
        parent: dict[int, object] = {}
        for node in ast.walk(fn.node):
            for child in ast.iter_child_nodes(node):
                parent[id(child)] = node
        returned: set[str] = set()
        attr_of_local: dict[str, tuple] = {}
        for node in _own_nodes(fn.node):
            if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Name
            ):
                returned.add(node.value.id)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Name
            ):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and ci is not None
                    ):
                        attr_of_local[node.value.id] = (ci.qname, t.attr)
        local_spawn: dict[str, str | None] = {}
        for node in _own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            target = self._resolve_expr(
                mod, node.func, fn.cls, local, types
            )
            api = self._spawn_api(target)
            if api is None:
                continue
            key = (mod.rel, node.lineno, node.col_offset)
            entry = None
            if api != "executor":
                entry = self._spawn_entry(
                    mod, node, fn.cls, local, types, scope=fn.qname
                )
                if entry is not None:
                    # a spawn site inside a loop creates N concurrent
                    # instances of one entry: never thread-confined
                    anc = parent.get(id(node))
                    while anc is not None and anc is not fn.node:
                        if isinstance(
                            anc, (ast.For, ast.AsyncFor, ast.While)
                        ):
                            self._multi_entries.add(entry)
                            break
                        anc = parent.get(id(anc))
            binding: tuple = ("discard",)
            p = parent.get(id(node))
            # unwrap `spawn(...).start()` chains — the binding is
            # decided by what happens to the chain's result
            if isinstance(p, ast.Attribute) and p.attr == "start":
                pc = parent.get(id(p))
                if isinstance(pc, ast.Call):
                    p = parent.get(id(pc))
            if isinstance(p, (ast.List, ast.Tuple)):
                p = parent.get(id(p))
            if isinstance(p, ast.Assign):
                for t in p.targets:
                    if isinstance(t, ast.Name):
                        if binding[0] == "discard":
                            binding = ("local", t.id)
                        if api != "executor":
                            local_spawn[t.id] = entry
                    elif (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and ci is not None
                    ):
                        binding = ("attr", ci.qname, t.attr)
            elif (
                isinstance(p, ast.Call)
                and isinstance(p.func, ast.Attribute)
                and p.func.attr == "append"
            ):
                base = p.func.value
                if (
                    isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                    and ci is not None
                ):
                    binding = ("attr", ci.qname, base.attr)
                elif isinstance(base, ast.Name):
                    # `threads.append(spawn(...))`: the LOCAL list owns
                    # the handle — a `for t in threads: t.join()` loop
                    # satisfies it via loop_attr
                    binding = ("local", base.id)
                else:
                    # appended into a container someone else owns:
                    # ownership transfers with the reference, like the
                    # generic call-argument case below
                    binding = ("returned",)
            elif isinstance(p, ast.Return):
                binding = ("returned",)
            elif isinstance(p, (ast.Call, ast.keyword)):
                # handed to another callable: ownership transfers with
                # the reference — the receiver owns the stop path
                binding = ("returned",)
            if binding[0] == "local" and binding[1] in returned:
                binding = ("returned",)
            elif binding[0] == "local" and binding[1] in attr_of_local:
                # `t = spawn(...); ...; self._thr = t`: the attr owns it
                binding = ("attr",) + attr_of_local[binding[1]]
            if key in self._spawn_seen:
                continue  # parent already registered this closure site
            self._spawn_seen.add(key)
            self.spawn_sites.append({
                "rel": mod.rel, "line": node.lineno, "fn": fn.qname,
                "entry": entry, "api": api,
                "kind": self._spawn_kind(target, node),
                # seam spawns register with threadwatch; raw
                # threading.Thread/Timer objects are invisible to
                # drain_threads, so the drain join edge must not
                # cover them
                "seam": target not in (
                    "threading.Thread", "threading.Timer"
                ),
                "binding": binding,
            })
        return local_spawn

    def _lockset_pass_all(self) -> None:
        for mod in self.modules.values():
            for fn in mod.functions:
                # __init__ still registers spawn targets and call
                # edges, but its accesses are pre-publication: the
                # object is not shared yet, so they neither need
                # guards nor vote in majority inference
                self._lockset_pass(
                    mod, fn, record_accesses=fn.name != "__init__"
                )

    def _lockset_pass(self, mod: ModuleInfo, fn: FunctionInfo,
                      record_accesses: bool = True) -> None:
        ci = self.classes.get(f"{mod.dotted}.{fn.cls}") if fn.cls else None
        types = getattr(fn, "_types", {})
        local = getattr(fn, "_local_bindings", {})
        local_spawn = self._spawn_scan(mod, fn, ci, types, local)
        # local events/queues are shared with closures: a closure's
        # lookup walks the ENCLOSING scopes' maps (parents are
        # processed first — mod.functions is registration order), but
        # each function REGISTERS into its own map with a token keyed
        # by its own qname, so same-named locals in sibling closures
        # stay distinct objects instead of unifying into one token
        lsync = self._fn_local_sync.setdefault(fn.qname, {})

        def _lookup_local_sync(name):
            scope = fn.qname
            while True:
                ent = self._fn_local_sync.get(scope, {}).get(name)
                if ent is not None:
                    return ent
                if ".<locals>." not in scope:
                    return None
                scope = scope.rsplit(".<locals>.", 1)[0]
        # loop var -> (owner qname, attr) when iterating a self/typed
        # container field (`for t in self._threads: t.join()`)
        loop_attr: dict[str, tuple] = {}
        held: list[str] = []
        seen_access: set = set()
        # v5 flowcheck: the function's CFG plus a forward must-hold
        # dataflow over explicit .acquire()/.release() calls — `with`
        # scoping stays lexical (its push/pop IS the meet-over-paths
        # answer), while conditional acquires, early-return releases
        # and try/finally pairs resolve per program point
        cfg = _CFG.build(fn.node)
        fn.cfg = cfg
        flow_ops: list = []
        for fnode in _own_nodes(fn.node):
            if (
                isinstance(fnode, ast.Call)
                and isinstance(fnode.func, ast.Attribute)
                and fnode.func.attr in ("acquire", "release")
            ):
                role = self._role_of_ctx(
                    mod, fnode.func.value, ci, types, local,
                    getattr(fn, "_rebound", ()),
                )
                if role is not None:
                    op = "acq" if fnode.func.attr == "acquire" else "rel"
                    flow_ops.append((fnode.lineno, op, role))
                    if role != _UNKNOWN_LOCK:
                        fn.flow_lock_roles.add(role)
        flow_ops.sort()
        flow_at = _flow_locksets(cfg, flow_ops)

        def fs_held(line: int) -> frozenset:
            extra = flow_at(line)
            return frozenset(held) | extra if extra else frozenset(held)

        def sync_token(expr):
            """(kind, token) for an event/queue-valued expression, or
            None when it is not a known synchronization object."""
            if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name
            ):
                base = expr.value.id
                owner = None
                if base == "self" and ci is not None:
                    owner = ci
                elif base in types:
                    owner = self.classes.get(types[base])
                if owner is not None:
                    k = owner.sync_types.get(expr.attr)
                    if k is not None:
                        return k, f"{owner.qname}.{expr.attr}"
            elif isinstance(expr, ast.Name):
                ent = _lookup_local_sync(expr.id)
                if ent is not None:
                    return ent
            return None

        _NOSPAWN = ("<nospawn>",)

        def spawn_subject(expr):
            """The entry qname behind a `<subject>.start()/join()` —
            None when the subject IS a spawned thread whose entry did
            not resolve, _NOSPAWN when it is not a thread at all."""
            if isinstance(expr, ast.Call):
                t_ = self._resolve_expr(mod, expr.func, fn.cls, local,
                                        types)
                if self._spawn_api(t_) in ("thread", "timer"):
                    return self._spawn_entry(
                        mod, expr, fn.cls, local, types, scope=fn.qname
                    )
                return _NOSPAWN
            if isinstance(expr, ast.Name):
                if expr.id in local_spawn:
                    return local_spawn[expr.id]
                return _NOSPAWN
            if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name
            ):
                owner = None
                if expr.value.id == "self" and ci is not None:
                    owner = ci
                elif expr.value.id in types:
                    owner = self.classes.get(types[expr.value.id])
                if owner is not None and expr.attr in owner.spawn_attrs:
                    return owner.spawn_attrs[expr.attr]
            return _NOSPAWN

        def record_stop_path(base, into: set) -> None:
            """A join/cancel/shutdown observed on `base`: remember the
            (owner, attr) — and the conservative by-name fallback — so
            the lifecycle rule accepts the binding as managed."""
            if isinstance(base, ast.Attribute) and isinstance(
                base.value, ast.Name
            ):
                owner = None
                if base.value.id == "self" and ci is not None:
                    owner = ci.qname
                elif base.value.id in types:
                    owner = types[base.value.id]
                into.add((owner, base.attr))
                into.add((None, base.attr))
            elif isinstance(base, ast.Name):
                la = loop_attr.get(base.id)
                if la is not None:
                    into.add(la)
                    into.add((None, la[1]))
                # a bare name: local (same-function) management, or the
                # `global _pool` singleton pattern
                into.add((None, base.id))

        def note_field(owner: ClassInfo | None, attr: str, kind: str,
                       line: int) -> None:
            if owner is None or attr in owner.lock_roles:
                return
            if attr not in owner.fields:
                return  # inherited/foreign attr: out of model
            q = f"{owner.qname}.{attr}"
            if q in self.symbols:
                return  # a method, not state
            key = (q, kind, line)
            if key in seen_access:
                return
            seen_access.add(key)
            fn.accesses.append((q, kind, line, fs_held(line)))

        def note_attr(node: ast.Attribute, kind: str) -> None:
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self":
                    note_field(ci, node.attr, kind, node.lineno)
                elif base.id in types:
                    note_field(
                        self.classes.get(types[base.id]), node.attr,
                        kind, node.lineno,
                    )
            elif (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and ci is not None
            ):
                ft = ci.field_types.get(base.attr)
                if ft is not None:
                    note_field(
                        self.classes.get(ft), node.attr, kind, node.lineno
                    )

        def note_global(node: ast.Name, kind: str) -> None:
            q = f"{mod.dotted}.{node.id}"
            if q not in self.declared_guards:
                return
            key = (q, kind, node.lineno)
            if key in seen_access:
                return
            seen_access.add(key)
            fn.accesses.append((q, kind, node.lineno, fs_held(node.lineno)))

        def entry(reason: str, expr) -> str | None:
            # a bare name may be a locally-defined function (the
            # committer's commit_loop): its symbol lives under this
            # function's `<locals>` scope — or an enclosing one when a
            # closure spawns a sibling closure
            q = None
            if isinstance(expr, ast.Name):
                q = self._scoped_symbol(fn.qname, expr.id)
            if q is None:
                q = self._resolve_expr(mod, expr, fn.cls, local, types)
                if q is not None and q not in self.symbols:
                    q = None
            if q is None:
                return None
            self.thread_entries.setdefault(q, reason)
            # entries that run as MANY concurrent instances of one
            # qname (pool chunks, executor jobs, RPC/gossip handlers)
            # must never count as "the same thread" in the HB order
            # check — two sibling chunks share a domain but race
            if (
                reason in ("pool chunk", "executor submission")
                or reason.endswith("() handler")
            ):
                self._multi_entries.add(q)
            return q

        def handle_call(node: ast.Call) -> None:
            q = self.call_resolutions.get(
                (mod.rel, node.lineno, node.col_offset)
            )
            if q is not None:
                fn.call_locks.append((q, fs_held(node.lineno)))
            target = self._resolve_expr(mod, node.func, fn.cls, local, types)
            if target in _SPAWN_THREAD_FNS:
                for kw in node.keywords:
                    if kw.arg == "target":
                        entry("thread target", kw.value)
                # lockwatch.spawn_thread(target, ...) takes the target
                # as its first positional (threading.Thread's is
                # `group` — keyword-only there in practice)
                if target != "threading.Thread" and node.args:
                    entry("thread target", node.args[0])
            elif target in _SPAWN_TIMER_FNS:
                for kw in node.keywords:
                    if kw.arg == "function":
                        entry("timer callback", kw.value)
                if len(node.args) >= 2:
                    entry("timer callback", node.args[1])
            elif target in _RUN_CHUNKED_FNS and node.args:
                # run_chunked is a synchronous fan-out: the chunk fn is
                # a thread entry, and the call line is both the start
                # edge (prior writes published to workers) and the join
                # edge (worker writes published back on return)
                eq = entry("pool chunk", node.args[0])
                if eq is not None:
                    fn.hb_starts.append((eq, node.lineno))
                    fn.hb_joins.append((eq, node.lineno))
            elif target in _DRAIN_FNS:
                fn.hb_joins.append(("*", node.lineno))
            elif isinstance(node.func, ast.Attribute):
                if node.func.attr in _SUBMIT_ATTRS and node.args:
                    entry("executor submission", node.args[0])
                elif node.func.attr in _HANDLER_REG_ATTRS:
                    for arg in node.args:
                        if isinstance(arg, (ast.Attribute, ast.Name)):
                            entry(f".{node.func.attr}() handler", arg)
            if target == _CLOCKSKEW_WAIT and node.args:
                st = sync_token(node.args[0])
                if st is not None and st[0] == "event":
                    fn.hb_acq.append(
                        (st[1], node.lineno, fs_held(node.lineno))
                    )
                fn.stop_probe = True
            f_ = node.func
            if not isinstance(f_, ast.Attribute):
                return
            a_ = f_.attr
            if a_ == "acquire":
                # an explicit acquire joins the static acquisition-order
                # graph exactly like a `with` scope: every role already
                # held (lexically or flow-proven) orders before it
                role = self._role_of_ctx(
                    mod, f_.value, ci, types, local,
                    getattr(fn, "_rebound", ()),
                )
                if role is not None and role != _UNKNOWN_LOCK:
                    already = (set(held) | flow_at(node.lineno)) - {role}
                    for h in sorted(already):
                        if h != _UNKNOWN_LOCK:
                            self.lock_order_edges.setdefault(
                                (h, role), []
                            ).append((mod.rel, node.lineno))
                    fn.lock_acquires.append((
                        role,
                        frozenset(
                            h for h in already if h != _UNKNOWN_LOCK
                        ),
                        node.lineno,
                    ))
            if a_ == "start":
                se = spawn_subject(f_.value)
                if se != _NOSPAWN:
                    fn.hb_starts.append((se, node.lineno))
            elif a_ in ("join", "cancel"):
                se = spawn_subject(f_.value)
                if se is not None and se != _NOSPAWN:
                    # an UNRESOLVED spawned subject (se is None)
                    # contributes no HB edge: joining one unknown
                    # thread proves nothing about any particular entry
                    fn.hb_joins.append((se, node.lineno))
                record_stop_path(f_.value, self._attr_joins)
            elif a_ == "shutdown":
                record_stop_path(f_.value, self._attr_shutdowns)
            elif a_ in (
                "set", "clear", "wait", "is_set",
                "put", "put_nowait", "get", "get_nowait",
            ):
                st = sync_token(f_.value)
                if st is not None:
                    k_, tok = st
                    entry_rec = (tok, node.lineno, fs_held(node.lineno))
                    if k_ == "event":
                        if a_ == "set":
                            fn.hb_rel.append(entry_rec)
                        elif a_ == "clear":
                            fn.hb_clears.append(entry_rec)
                        elif a_ == "wait":
                            fn.hb_acq.append(entry_rec)
                            fn.stop_probe = True
                        else:  # is_set
                            fn.stop_probe = True
                    else:  # queue
                        if a_ in ("put", "put_nowait"):
                            fn.hb_rel.append(entry_rec)
                        elif a_ in ("get", "get_nowait"):
                            fn.hb_acq.append(entry_rec)
                            fn.stop_probe = True
                elif a_ in ("wait", "is_set"):
                    # a wait/is_set on something we cannot type is
                    # still a stop-signal probe for the lifecycle rule
                    # (loose on purpose: false negatives only)
                    fn.stop_probe = True

        def scan_expr(expr) -> None:
            if expr is None:
                return
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    handle_call(node)
                elif isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load
                ):
                    if record_accesses:
                        note_attr(node, "read")
                elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load
                ):
                    if record_accesses:
                        note_global(node, "read")

        def note_target(t) -> None:
            if isinstance(t, ast.Attribute):
                if record_accesses:
                    note_attr(t, "write")
                scan_expr(t.value)
            elif isinstance(t, ast.Subscript):
                v = t.value
                if isinstance(v, ast.Attribute):
                    # mutating a container field IS writing the field
                    if record_accesses:
                        note_attr(v, "write")
                    scan_expr(v.value)
                elif isinstance(v, ast.Name):
                    if record_accesses:
                        note_global(v, "write")
                else:
                    scan_expr(v)
                scan_expr(t.slice)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    note_target(e)
            elif isinstance(t, ast.Starred):
                note_target(t.value)
            elif isinstance(t, ast.Name):
                if record_accesses:
                    note_global(t, "write")

        def walk(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    pushed = 0
                    for item in stmt.items:
                        scan_expr(item.context_expr)
                        if item.optional_vars is not None:
                            note_target(item.optional_vars)
                        role = self._role_of_ctx(
                            mod, item.context_expr, ci, types, local,
                            getattr(fn, "_rebound", ()),
                        )
                        if role is not None:
                            # the static acquisition-order graph: every
                            # role already held orders before the one
                            # being acquired (UNKNOWN contributes no
                            # edges — it has no runtime counterpart)
                            if role != _UNKNOWN_LOCK:
                                already = (
                                    set(held) | flow_at(stmt.lineno)
                                )
                                for h in sorted(already):
                                    if h != role and h != _UNKNOWN_LOCK:
                                        self.lock_order_edges.setdefault(
                                            (h, role), []
                                        ).append((mod.rel, stmt.lineno))
                                fn.lock_acquires.append((
                                    role,
                                    frozenset(
                                        h for h in already
                                        if h != _UNKNOWN_LOCK
                                        and h != role
                                    ),
                                    stmt.lineno,
                                ))
                            held.append(role)
                            pushed += 1
                    walk(stmt.body)
                    for _ in range(pushed):
                        held.pop()
                elif isinstance(stmt, ast.Assign):
                    if isinstance(stmt.value, ast.Call):
                        # local Event/Queue ctors register as sync
                        # objects shared with this function's closures
                        t_ = self._resolve_expr(
                            mod, stmt.value.func, fn.cls, local, types
                        )
                        k_ = (
                            "event" if t_ in _EVENT_CTOR_FNS
                            else "queue" if t_ in _QUEUE_CTOR_FNS
                            else None
                        )
                        if k_ is not None:
                            for t in stmt.targets:
                                if isinstance(t, ast.Name):
                                    lsync[t.id] = (
                                        k_, f"{fn.qname}::{t.id}"
                                    )
                    elif isinstance(stmt.value, ast.Name) and (
                        stmt.value.id in local_spawn
                    ):
                        # `self._thr = t` after `t = spawn_thread(...)`:
                        # the attr inherits the spawn binding
                        for t in stmt.targets:
                            if (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                                and ci is not None
                            ):
                                ci.spawn_attrs.setdefault(
                                    t.attr, local_spawn[stmt.value.id]
                                )
                    scan_expr(stmt.value)
                    for t in stmt.targets:
                        note_target(t)
                elif isinstance(stmt, ast.AugAssign):
                    scan_expr(stmt.value)
                    note_target(stmt.target)
                elif isinstance(stmt, ast.AnnAssign):
                    scan_expr(stmt.value)
                    note_target(stmt.target)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    it = stmt.iter
                    if (
                        isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Name)
                        and it.func.id in ("list", "tuple")
                        and len(it.args) == 1
                    ):
                        it = it.args[0]
                    if (
                        isinstance(stmt.target, ast.Name)
                        and isinstance(it, ast.Attribute)
                        and isinstance(it.value, ast.Name)
                    ):
                        owner = None
                        if it.value.id == "self" and ci is not None:
                            owner = ci.qname
                        elif it.value.id in types:
                            owner = types[it.value.id]
                        if owner is not None:
                            loop_attr[stmt.target.id] = (owner, it.attr)
                    elif isinstance(stmt.target, ast.Name) and isinstance(
                        it, ast.Name
                    ):
                        # `for t in threads:` over a LOCAL container —
                        # joins on the loop var satisfy a ('local',
                        # 'threads') spawn binding
                        loop_attr[stmt.target.id] = (None, it.id)
                    scan_expr(stmt.iter)
                    note_target(stmt.target)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.While, ast.If)):
                    scan_expr(stmt.test)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body)
                    for h in stmt.handlers:
                        walk(h.body)
                    walk(stmt.orelse)
                    walk(stmt.finalbody)
                else:
                    for child in ast.iter_child_nodes(stmt):
                        if isinstance(child, ast.expr):
                            scan_expr(child)

        walk(fn.node.body)

    def _interproc_lock_edges(self) -> None:
        """Extend the static acquisition-order graph across call
        boundaries: a MAY-held set (union over every incoming call
        path — the graph must be a superset of anything runtime
        lockwatch can observe, or the runtime-⊆-static contract breaks)
        flows down the call graph, and every recorded acquisition
        orders each may-held role before itself.  Relaxed-profile
        callers (tests/scripts) do not contribute: a fixture lock held
        around a production call must not become a tree-wide ordering
        edge."""
        may: dict[str, frozenset] = {q: frozenset() for q in self.symbols}
        for _ in range(_MAX_ROUNDS * 4):
            changed = False
            for q, fn in self.symbols.items():
                if fn.rel.startswith(("tests/", "scripts/")):
                    continue
                for callee, heldset in fn.call_locks:
                    if callee not in may:
                        continue
                    add = (may[q] | heldset) - {_UNKNOWN_LOCK}
                    if not add <= may[callee]:
                        may[callee] = may[callee] | add
                        changed = True
            if not changed:
                break
        for q, fn in self.symbols.items():
            amb = may.get(q)
            if not amb:
                continue
            for role, _heldb4, line in fn.lock_acquires:
                for h in amb:
                    if h != role:
                        self.lock_order_edges.setdefault(
                            (h, role), []
                        ).append((fn.rel, line))
        for k in list(self.lock_order_edges):
            self.lock_order_edges[k] = sorted(
                set(self.lock_order_edges[k])
            )

    def _racecheck(self) -> None:
        # incoming call edges annotated with the caller's held lockset
        incoming: dict[str, list] = {q: [] for q in self.symbols}
        for fn in self.symbols.values():
            for callee, heldset in fn.call_locks:
                if callee in incoming:
                    incoming[callee].append((fn.qname, heldset))
        # ambient locks: the meet (intersection) over every incoming
        # call path; roots (no resolvable callers) hold nothing.  Used
        # by guard INFERENCE so helper bodies reached only under a lock
        # count as locked sites.
        ambient: dict[str, frozenset | None] = {
            q: (frozenset() if not incoming[q] else None)
            for q in self.symbols
        }
        for _ in range(_MAX_ROUNDS * 4):
            changed = False
            for q, fn in self.symbols.items():
                amb = ambient[q]
                if amb is None:
                    continue
                for callee, heldset in fn.call_locks:
                    if callee not in ambient:
                        continue
                    cand = amb | heldset
                    cur = ambient[callee]
                    new = cand if cur is None else cur & cand
                    if new != cur:
                        ambient[callee] = new
                        changed = True
            if not changed:
                break
        # thread context: the lockset guaranteed on EVERY path from a
        # thread entry point (meet again); functions absent from tctx
        # are not thread-reachable and are never flagged
        tctx: dict[str, frozenset] = {}
        origin: dict[str, str] = {}
        for q, reason in self.thread_entries.items():
            tctx[q] = frozenset()
            origin[q] = f"{q} ({reason})"
        for _ in range(_MAX_ROUNDS * 4):
            changed = False
            for q, fn in list(self.symbols.items()):
                if q not in tctx:
                    continue
                for callee, heldset in fn.call_locks:
                    if callee not in self.symbols:
                        continue
                    cand = tctx[q] | heldset
                    cur = tctx.get(callee)
                    new = cand if cur is None else cur & cand
                    if new != cur:
                        tctx[callee] = new
                        origin.setdefault(callee, origin[q])
                        changed = True
            if not changed:
                break
        # entry SETS (union, unlike the tctx meet): which thread
        # entries can reach each function — the happens-before pass
        # reasons about WHO runs an access, not just whether someone
        # does
        entry_sets: dict[str, frozenset] = {
            q: frozenset({q}) for q in self.thread_entries
        }
        for _ in range(_MAX_ROUNDS * 4):
            changed = False
            for q, fn in list(self.symbols.items()):
                es = entry_sets.get(q)
                if es is None:
                    continue
                for callee, _h in fn.call_locks:
                    if callee not in self.symbols:
                        continue
                    cur = entry_sets.get(callee, frozenset())
                    if not es <= cur:
                        entry_sets[callee] = cur | es
                        changed = True
            if not changed:
                break
        self._entry_sets = entry_sets

        # -- happens-before machinery (v4) ---------------------------------

        def _site_tokens(fn: FunctionInfo, line: int):
            """(acquire, release) HB tokens ordered around `line` in
            `fn`: joins/waits/gets sequenced BEFORE it (on every path
            reaching it) order earlier work in, starts/sets/puts the
            access strictly precedes (on EVERY path — a loop back edge
            that could replay the event first defeats the claim, v5)
            order this work out."""
            acq = set()
            rel = set()
            cfg = fn.cfg if isinstance(fn.cfg, _CFG) else None

            def before(l):  # event at l precedes the access
                return (cfg.event_precedes(l, line) if cfg is not None
                        else l < line)

            def after(l):  # the access strictly precedes the event at l
                return (cfg.access_precedes(line, l) if cfg is not None
                        else line < l)

            for e, l in fn.hb_joins:
                if before(l):
                    acq.add(("join", e))
            for tok, l, _h in fn.hb_acq:
                if before(l):
                    acq.add(("sync", tok))
            for e, l in fn.hb_starts:
                if e is not None and after(l):
                    rel.add(("start", e))
            for tok, l, _h in fn.hb_rel:
                if after(l):
                    rel.add(("sync", tok))
            return acq, rel

        multi = self._multi_entries
        # the drain_threads wildcard join only covers entries that
        # register with threadwatch as kind="worker" at EVERY spawn
        # site — drain_threads(kinds=("worker",)) joins exactly those;
        # services keep running and raw Thread/Timer objects are
        # invisible to the registry
        entry_spawns: dict[str, list] = {}
        for s in self.spawn_sites:
            if s["entry"] is not None:
                entry_spawns.setdefault(s["entry"], []).append(s)
        drained = {
            e for e, ss in entry_spawns.items()
            if all(s["seam"] and s["kind"] == "worker" for s in ss)
        }

        def _same_thread(da, db) -> bool:
            """Both main, or the same SINGLE-instance entry — an entry
            that runs as many concurrent threads (pool chunks, executor
            jobs, handlers, loop-spawned workers) shares a domain
            across racing instances and proves nothing."""
            return da == db and len(da) <= 1 and not (da & multi)

        def _joined(e, acq) -> bool:
            return ("join", e) in acq or (
                ("join", "*") in acq and e in drained
            )

        def _ordered(a, b) -> bool:
            """True when the two (domain, acq, rel) access profiles are
            sequenced by a happens-before edge: same single thread,
            thread start (a precedes every entry b runs under),
            join/drain (every entry b runs under completed before a),
            or a matching Event set→wait / Queue put→get publication
            pair."""
            da, acqa, rela = a
            db, acqb, relb = b
            if _same_thread(da, db):
                return True
            if db and all(("start", e) in rela for e in db):
                return True
            if da and all(("start", e) in relb for e in da):
                return True
            if db and all(_joined(e, acqa) for e in db):
                return True
            if da and all(_joined(e, acqb) for e in da):
                return True
            if {t for k, t in rela if k == "sync"} & {
                t for k, t in acqb if k == "sync"
            }:
                return True
            if {t for k, t in relb if k == "sync"} & {
                t for k, t in acqa if k == "sync"
            }:
                return True
            return False

        # guarded-by map: reviewed declarations first, majority next —
        # both rebuilt UNDER happens-before: ordered accesses neither
        # need a guard nor vote in the inference
        sites: dict[str, list] = {}
        for fn in self.symbols.values():
            amb = ambient.get(fn.qname) or frozenset()
            for field, kind, line, heldset in fn.accesses:
                sites.setdefault(field, []).append(
                    (fn, kind, line, amb | heldset)
                )
        field_profs: dict[str, list] = {}
        for field, ss in sites.items():
            profs = []
            for fn, kind, line, ls in ss:
                acq, rel = _site_tokens(fn, line)
                profs.append({
                    "fn": fn, "kind": kind, "line": line, "ls": ls,
                    "dom": entry_sets.get(fn.qname, frozenset()),
                    "acq": acq, "rel": rel, "safe": False,
                })
            # pairwise pruning: an access ordered against every
            # counterpart write (and, for a write, every counterpart
            # access) cannot race.  `checked` guards the vacuous case —
            # an access with NO counterpart pair (a lone write, a read
            # with no writes) is not "proven" anything and must not
            # override a declared guard
            for i, a in enumerate(profs):
                ok = True
                checked = False
                for j, b in enumerate(profs):
                    if i == j:
                        continue
                    if a["kind"] != "write" and b["kind"] != "write":
                        continue
                    checked = True
                    if not _ordered(
                        (a["dom"], a["acq"], a["rel"]),
                        (b["dom"], b["acq"], b["rel"]),
                    ):
                        ok = False
                        break
                if ok and checked:
                    a["safe"] = True
                    self.hb_safe_sites.add(
                        (field, a["kind"], a["line"], a["fn"].qname)
                    )
            field_profs[field] = profs
        self.guard_map = {}
        for field, profs in sorted(field_profs.items()):
            n_sites = len(profs)
            n_safe = sum(1 for p in profs if p["safe"])
            has_write = any(p["kind"] == "write" for p in profs)
            declared = self.declared_guards.get(field)
            if declared is not None:
                held_n = sum(1 for p in profs if declared in p["ls"])
                g = {
                    "guard": declared, "source": "declared",
                    "sites": n_sites, "held": held_n,
                }
                if n_safe:
                    g["hb_safe"] = n_safe
                threaded = any(p["dom"] for p in profs)
                if (
                    threaded
                    and n_safe == n_sites
                    and has_write
                    and held_n < n_sites
                ):
                    # every access is HB-ordered yet the declaration
                    # still demands a lock somewhere it is not held:
                    # racecheck can never fire for this field again, so
                    # the guards.py entry is dead weight to remove.
                    # `threaded` gates the call: when NO access is
                    # thread-entry-reachable the pairwise proof is
                    # vacuous (the analyzer simply cannot see the
                    # threads, e.g. a commit path reached through
                    # unresolvable indirection) and the declaration
                    # stays as the reviewed contract
                    g["stale"] = True
                    first = min(
                        profs, key=lambda p: (p["fn"].rel, p["line"])
                    )
                    self.stale_guard_flows.append(TaintFlow(
                        rel=first["fn"].rel, line=first["line"],
                        message=(
                            f"declared guard {declared!r} on {field} "
                            "is stale: every access is ordered by "
                            "happens-before edges (spawn/join/Event/"
                            "Queue publication) — remove the guards.py "
                            "declaration"
                        ),
                    ))
                self.guard_map[field] = g
                continue
            if not has_write:
                continue  # never mutated post-init: cannot race
            if n_safe == n_sites:
                # fully publication-ordered: no guard needed — named in
                # the artifact so reviewers see why no inference ran
                self.guard_map[field] = {
                    "guard": None, "source": "hb-publish",
                    "sites": n_sites, "held": 0, "hb_safe": n_safe,
                }
                continue
            # HB-safe sites are exempt from EMISSION but still vote in
            # the inference: a lock-free-but-published reader must not
            # dissolve the majority its locked siblings establish
            counted = [
                p["ls"] for p in profs if _UNKNOWN_LOCK not in p["ls"]
            ]
            if len(counted) < 2:
                continue
            tally: dict[str, int] = {}
            for ls in counted:
                for role in ls:
                    tally[role] = tally.get(role, 0) + 1
            for role, n in sorted(
                tally.items(), key=lambda kv: (-kv[1], kv[0])
            ):
                if n >= 2 and n * 2 > len(counted):
                    g = {
                        "guard": role, "source": "inferred",
                        "sites": len(counted), "held": n,
                    }
                    if n_safe:
                        g["hb_safe"] = n_safe
                    self.guard_map[field] = g
                break  # only the top role can hold a majority
        # declared guards with no observed sites still surface in the
        # artifact so a stale declaration is visible to reviewers
        for field, role in sorted(self.declared_guards.items()):
            self.guard_map.setdefault(field, {
                "guard": role, "source": "declared", "sites": 0, "held": 0,
            })
        # emission: thread-reachable accesses whose lockset misses the
        # field's guard — unless a happens-before edge from every
        # writer already orders the access
        seen: set = set()
        for fn in self.symbols.values():
            T = tctx.get(fn.qname)
            if T is None:
                continue
            for field, kind, line, heldset in fn.accesses:
                g = self.guard_map.get(field)
                if g is None or not g["sites"] or g.get("guard") is None:
                    continue
                if (field, kind, line, fn.qname) in self.hb_safe_sites:
                    continue
                eff = T | heldset
                if g["guard"] in eff or _UNKNOWN_LOCK in eff:
                    continue
                key = (fn.rel, line)
                if key in seen:
                    continue
                seen.add(key)
                self.race_flows.append(TaintFlow(
                    rel=fn.rel, line=line,
                    message=(
                        f"{kind} of {field} misses its guard lock "
                        f"{g['guard']!r} ({g['source']}, held at "
                        f"{g['held']}/{g['sites']} sites) on a thread "
                        f"path from {origin.get(fn.qname, fn.qname)} — "
                        "hold the guard across this access, move the "
                        "field behind it, or pragma a reviewed benign "
                        "race"
                    ),
                ))
        # post-publication writes (v4): a write AFTER this function
        # started a thread that accesses the same field races with it
        # unless a lock or a later publication edge covers the pair —
        # the spawner is concurrent with its target from start() on,
        # whether or not the spawner is itself thread-reachable
        for field, profs in sorted(field_profs.items()):
            for a in profs:
                if a["kind"] != "write" or a["safe"]:
                    continue
                if _UNKNOWN_LOCK in a["ls"]:
                    continue
                a_cfg = a["fn"].cfg if isinstance(a["fn"].cfg, _CFG) else None
                starts = {
                    e for e, l in a["fn"].hb_starts
                    if e is not None and (
                        a_cfg.may_follow(l, a["line"])
                        if a_cfg is not None else l < a["line"]
                    )
                }
                if not starts:
                    continue
                for b in profs:
                    if b is a:
                        continue
                    common = b["dom"] & starts
                    if not common or _UNKNOWN_LOCK in b["ls"]:
                        continue
                    if a["ls"] & b["ls"]:
                        continue  # mutual exclusion covers the pair
                    if _ordered(
                        (a["dom"], a["acq"], a["rel"]),
                        (b["dom"], b["acq"], b["rel"]),
                    ):
                        continue
                    key = (a["fn"].rel, a["line"])
                    if key in seen:
                        break
                    seen.add(key)
                    e = sorted(common)[0]
                    self.race_flows.append(TaintFlow(
                        rel=a["fn"].rel, line=a["line"],
                        message=(
                            f"write of {field} races past its "
                            f"publication point: {e} was started "
                            "earlier in this function and "
                            f"{b['kind']}s the field at "
                            f"{b['fn'].rel}:{b['line']} — move the "
                            "write before start(), hold a common lock "
                            "on both sides, or publish it through an "
                            "Event/Queue edge"
                        ),
                    ))
                    break
        # shared-Event re-arm (v4): clear() re-arms a waiter contract;
        # doing it concurrently with another thread's set()/clear()
        # loses wakeups (the deliver-client wedge class) — flag unless
        # a common lock or an HB edge sequences the pair
        clear_map: dict[str, list] = {}
        rel_map: dict[str, list] = {}
        for fn in self.symbols.values():
            dom = entry_sets.get(fn.qname, frozenset())
            for tok, line, heldset in fn.hb_clears:
                clear_map.setdefault(tok, []).append(
                    (fn, line, heldset, dom)
                )
            for tok, line, heldset in fn.hb_rel:
                rel_map.setdefault(tok, []).append(
                    (fn, line, heldset, dom)
                )
        for tok, clears in sorted(clear_map.items()):
            counters = rel_map.get(tok, []) + clears
            for cfn, cline, cheld, cdom in clears:
                for sfn, sline, sheld, sdom in counters:
                    if (sfn.qname, sline) == (cfn.qname, cline):
                        continue
                    if _same_thread(cdom, sdom):
                        continue
                    if cheld & sheld:
                        continue
                    if _UNKNOWN_LOCK in cheld or _UNKNOWN_LOCK in sheld:
                        continue
                    ca, cr = _site_tokens(cfn, cline)
                    sa, sr = _site_tokens(sfn, sline)
                    if _ordered((cdom, ca, cr), (sdom, sa, sr)):
                        continue
                    key = (cfn.rel, cline)
                    if key in seen:
                        break
                    seen.add(key)
                    self.race_flows.append(TaintFlow(
                        rel=cfn.rel, line=cline,
                        message=(
                            f"re-arming shared Event {tok} (clear) "
                            "races with its set()/clear() at "
                            f"{sfn.rel}:{sline} on a different thread "
                            "— a waiter can miss the set entirely; "
                            "use a fresh per-generation Event instead "
                            "of re-arming, or hold one lock around "
                            "both sides"
                        ),
                    ))
                    break
        self.race_flows.sort(key=lambda f: (f.rel, f.line))

    def _lifecycle(self) -> None:
        """Thread-lifecycle reachability (v4): every spawn_thread/
        spawn_timer/Thread/Timer/executor registration needs a
        statically findable stop path — a join()/cancel()/shutdown()
        on whatever holds the handle, a stop-signal loop in the
        spawned entry (Event wait/is_set, queue get, clockskew.wait),
        or a provably bounded worker body.  A handle that is returned
        or passed onward transfers ownership with the reference."""
        # stop-probe reachability as a call-graph FIXPOINT (a DFS with
        # a memoized-False cycle guard poisons members of a cycle that
        # only reach their probe through the in-progress node)
        can_stop = {
            q for q, fn in self.symbols.items() if fn.stop_probe
        }
        changed = True
        while changed:
            changed = False
            for q, fn in self.symbols.items():
                if q not in can_stop and any(
                    c in can_stop for c in fn.calls
                ):
                    can_stop.add(q)
                    changed = True

        def probe(q: str) -> bool:
            return q in can_stop

        for site in self.spawn_sites:
            api = site["api"]
            binding = site["binding"]
            entry = site["entry"]
            stops = (
                self._attr_shutdowns if api == "executor"
                else self._attr_joins
            )
            ok = False
            if binding[0] == "returned":
                ok = True
            elif binding[0] == "attr":
                ok = (
                    (binding[1], binding[2]) in stops
                    or (None, binding[2]) in stops
                )
            elif binding[0] == "local":
                ok = (None, binding[1]) in stops
            if not ok and api != "executor" and entry is not None:
                ok = probe(entry)
                if not ok and site["kind"] == "worker":
                    # a worker whose body provably terminates (no
                    # unbounded loop) drains on its own; the session
                    # threadwatch gate covers the long tail
                    efn = self.symbols.get(entry)
                    ok = efn is not None and not efn.has_while
            if ok:
                continue
            what = (
                f"its entry {entry} never blocks on a stop signal "
                "(Event wait/is_set, queue get)"
                if entry is not None
                else "its target does not resolve statically"
            )
            self.lifecycle_flows.append(TaintFlow(
                rel=site["rel"], line=site["line"],
                message=(
                    f"{api} spawned here (kind={site['kind']}) has no "
                    "statically reachable stop/join path: nothing "
                    "join()s/cancel()s/shutdown()s its handle, and "
                    f"{what} — keep the handle and join/cancel it on "
                    "the owner's stop path, loop on a stop Event, or "
                    "pragma a reviewed exemption"
                ),
            ))
        self.lifecycle_flows.sort(key=lambda f: (f.rel, f.line))

    # -- chaos-coverage raw facts (v5) -------------------------------------

    def _chaos_scan(self) -> None:
        """Statically enumerate every faultline seam in production code
        and every literal fault-plan rule anywhere in the target set —
        the raw facts behind the chaos-coverage rule and the
        ``--faultmap-out`` artifact.

        A seam is an ``ast.Call`` resolving to ``faultline.point/guard/
        write/io`` in a strict-profile file outside the seam's own
        implementation; its name must be a string literal (``io`` takes
        the name second and derives ``<name>.read``/``<name>.write``).
        A plan rule is any dict literal with a ``"point"`` string key —
        test plans count: a pinned chaos test IS coverage."""
        seams: list[dict] = []
        dynamic: list[dict] = []
        plans: list[dict] = []
        for mod in self.modules.values():
            production = (
                mod.rel.startswith("fabric_tpu/")
                and mod.rel not in _FAULTLINE_IMPL
            )
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and production:
                    q = self._resolve_expr(mod, node.func, None, {}, {})
                    kind = _FAULTLINE_FNS.get(q)
                    if kind is None:
                        continue
                    idx = 1 if kind == "io" else 0
                    arg = node.args[idx] if len(node.args) > idx else None
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str
                    ):
                        names = (
                            [f"{arg.value}.read", f"{arg.value}.write"]
                            if kind == "io" else [arg.value]
                        )
                        for name in names:
                            seams.append({
                                "name": name, "kind": kind,
                                "module": mod.rel, "line": node.lineno,
                            })
                    else:
                        dynamic.append({
                            "kind": kind, "module": mod.rel,
                            "line": node.lineno,
                        })
                elif isinstance(node, ast.Dict):
                    point = action = None
                    for k, v in zip(node.keys, node.values):
                        if isinstance(k, ast.Constant):
                            if k.value == "point":
                                point = v
                            elif k.value == "action":
                                action = v
                    if not (
                        isinstance(point, ast.Constant)
                        and isinstance(point.value, str)
                    ):
                        continue  # no/computed point key: not a pin
                    act = (
                        action.value
                        if isinstance(action, ast.Constant)
                        and isinstance(action.value, str)
                        else "raise"
                    )
                    plans.append({
                        "point": point.value, "action": act,
                        "module": mod.rel, "line": node.lineno,
                        "wildcard": (
                            point.value == "*"
                            or point.value.endswith(".*")
                        ),
                    })
        seams.sort(key=lambda s: (s["name"], s["module"], s["line"]))
        dynamic.sort(key=lambda d: (d["module"], d["line"]))
        plans.sort(key=lambda p: (p["module"], p["line"], p["point"]))
        self.faultline_seams = seams
        self.faultline_dynamic = dynamic
        self.faultline_plans = plans

    def faultmap(self) -> dict:
        """The JSON-shaped chaos-coverage artifact (``--faultmap-out``):
        every production injection seam and every pinned plan rule, both
        in deterministic order."""
        return {
            "seams": self.faultline_seams,
            "dynamic": self.faultline_dynamic,
            "plans": self.faultline_plans,
        }

    # -- surface scans (v6): rpc / knob / metric raw facts -----------------

    def _scope_items(self):
        """(mod, fn|None, cls, params, own nodes) per lexical scope:
        each module's top level (function bodies excluded — they get
        their own entries), then every function including closures.
        The shared walk for the three surface scans."""
        for mod in sorted(self.modules.values(), key=lambda m: m.rel):
            yield mod, None, None, [], list(_own_nodes(mod.tree))
            for fn in mod.functions:
                yield mod, fn, fn.cls, fn.params, list(_own_nodes(fn.node))

    def _mod_consts(self, mod) -> dict:
        cached = getattr(self, "_mod_consts_cache", None)
        if cached is None:
            cached = self._mod_consts_cache = {}
        if mod.rel not in cached:
            cached[mod.rel] = _str_consts(list(_own_nodes(mod.tree)))
        return cached[mod.rel]

    def _handler_shape(self, qname: str | None) -> str:
        """The statically inferred wire shape of a registered handler:
        ``duplex`` (reads its stream param), ``stream`` (yields, or
        returns a call to a resolvable generator), ``unary`` (returns
        bytes/None), or ``unknown`` — which never fires a mismatch."""
        fn = self.symbols.get(qname) if qname else None
        if fn is None:
            return "unknown"
        params = [p for p in fn.params if p != "self"]
        stream_param = params[1] if len(params) > 1 else None
        own = list(_own_nodes(fn.node))
        for n in own:
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "recv"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == stream_param
            ):
                return "duplex"
        if any(isinstance(n, (ast.Yield, ast.YieldFrom)) for n in own):
            return "stream"
        mod = self.modules[fn.rel]
        for n in own:
            if not (isinstance(n, ast.Return)
                    and isinstance(n.value, ast.Call)):
                continue
            ret = n.value
            q = self._resolve_expr(mod, ret.func, fn.cls, {}, {})
            callee = self.symbols.get(q) if q else None
            if callee is not None:
                if any(
                    isinstance(x, (ast.Yield, ast.YieldFrom))
                    for x in _own_nodes(callee.node)
                ):
                    return "stream"
                continue  # resolvable non-generator helper: unary-ish
            if (
                isinstance(ret.func, ast.Attribute)
                and ret.func.attr in _RPC_BYTES_ATTRS
            ):
                continue  # bytes-producing call: not iterator evidence
            return "unknown"
        return "unary"

    def _rpc_scan(self) -> None:
        """Every `register("svc.Method", handler)` site and every
        `call/stream/duplex("svc.Method", ...)` site in the tree —
        through function-local literal bindings (including IfExp
        branches) and one-level forwarders (a method passing its own
        param into a verb call, e.g. custody's `_call`)."""
        registers: list[dict] = []
        calls: list[dict] = []
        # fn qname -> (verb, call-site arg index of the method name)
        forwarders: dict[str, tuple] = {}
        for mod, fn, cls, params, nodes in self._scope_items():
            mconsts = self._mod_consts(mod)
            local = _str_consts(nodes) if fn is not None else {}
            comp = _rpc_component(mod.rel)
            for n in nodes:
                if not (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.args
                ):
                    continue
                attr = n.func.attr
                if attr == "register":
                    methods = [
                        m for m in sorted(
                            _resolve_str_arg(n.args[0], local, mconsts)
                        )
                        if _RPC_METHOD_RE.match(m)
                    ]
                    handler_q = (
                        self._resolve_expr(
                            mod, n.args[1], cls, {}, {}
                        ) if len(n.args) > 1 else None
                    )
                    for m in methods:
                        registers.append({
                            "method": m, "component": comp,
                            "module": mod.rel, "line": n.lineno,
                            "handler": handler_q,
                            "shape": self._handler_shape(handler_q),
                        })
                elif attr in _RPC_VERBS:
                    methods = [
                        m for m in sorted(
                            _resolve_str_arg(n.args[0], local, mconsts)
                        )
                        if _RPC_METHOD_RE.match(m)
                    ]
                    for m in methods:
                        calls.append({
                            "method": m, "verb": attr,
                            "component": comp,
                            "module": mod.rel, "line": n.lineno,
                        })
                    if (
                        not methods
                        and isinstance(n.args[0], ast.Name)
                        and fn is not None
                        and n.args[0].id in params
                    ):
                        idx = params.index(n.args[0].id)
                        if cls is not None and params[:1] == ["self"]:
                            idx -= 1
                        forwarders[fn.qname] = (attr, idx)
        # second pass: literal call sites of the forwarders count as
        # RPC sites with the forwarded verb
        for mod, fn, cls, params, nodes in self._scope_items():
            mconsts = self._mod_consts(mod)
            local = _str_consts(nodes) if fn is not None else {}
            comp = _rpc_component(mod.rel)
            for n in nodes:
                if not (isinstance(n, ast.Call) and n.args):
                    continue
                q = self._resolve_expr(mod, n.func, cls, {}, {})
                fwd = forwarders.get(q) if q else None
                if fwd is None:
                    continue
                verb, idx = fwd
                if idx >= len(n.args):
                    continue
                for m in sorted(
                    _resolve_str_arg(n.args[idx], local, mconsts)
                ):
                    if _RPC_METHOD_RE.match(m):
                        calls.append({
                            "method": m, "verb": verb,
                            "component": comp,
                            "module": mod.rel, "line": n.lineno,
                        })
        registers.sort(
            key=lambda r: (r["method"], r["module"], r["line"])
        )
        calls.sort(key=lambda c: (c["method"], c["module"], c["line"]))
        self.rpc_registers = registers
        self.rpc_calls = calls

    def _knob_scan(self) -> None:
        """Every FABRIC_TPU env read: through the knob registry
        (``via: registry``), or raw (``via: environ`` — a bypass the
        knob-conformance rule fails).  Names resolve through literals,
        module/function string constants (the ``_ENV = "..."``
        convention), and one-level forwarders passing a param into
        ``knob_registry.raw`` (workpool's ``stage_width``)."""
        sites: list[dict] = []
        dynamic: list[dict] = []
        forwarders: dict[str, int] = {}
        for mod, fn, cls, params, nodes in self._scope_items():
            if mod.rel in _KNOB_IMPL:
                continue  # the registry's own environ read is the seam
            mconsts = self._mod_consts(mod)
            local = _str_consts(nodes) if fn is not None else {}
            for n in nodes:
                via = arg = None
                if isinstance(n, ast.Call) and n.args:
                    q = self._resolve_expr(mod, n.func, cls, {}, {})
                    if q in _ENV_READ_FNS:
                        via, arg = "environ", n.args[0]
                    elif q in _KNOB_HELPER_FNS:
                        via, arg = "registry", n.args[0]
                elif (
                    isinstance(n, ast.Subscript)
                    and isinstance(n.ctx, ast.Load)
                ):
                    base = self._resolve_expr(mod, n.value, cls, {}, {})
                    if base == "os.environ":
                        via, arg = "environ", n.slice
                if via is None:
                    continue
                names = _resolve_str_arg(arg, local, mconsts)
                if names:
                    for name in sorted(names):
                        if name.startswith(_KNOB_PREFIX):
                            sites.append({
                                "name": name, "via": via,
                                "module": mod.rel, "line": n.lineno,
                            })
                elif via == "registry":
                    if (
                        isinstance(arg, ast.Name)
                        and fn is not None
                        and arg.id in params
                    ):
                        idx = params.index(arg.id)
                        if cls is not None and params[:1] == ["self"]:
                            idx -= 1
                        forwarders[fn.qname] = idx
                    else:
                        dynamic.append({
                            "module": mod.rel, "line": n.lineno,
                        })
        for mod, fn, cls, params, nodes in self._scope_items():
            mconsts = self._mod_consts(mod)
            local = _str_consts(nodes) if fn is not None else {}
            for n in nodes:
                if not (isinstance(n, ast.Call) and n.args):
                    continue
                q = self._resolve_expr(mod, n.func, cls, {}, {})
                idx = forwarders.get(q) if q else None
                if idx is None or idx >= len(n.args):
                    continue
                for name in sorted(
                    _resolve_str_arg(n.args[idx], local, mconsts)
                ):
                    if name.startswith(_KNOB_PREFIX):
                        sites.append({
                            "name": name, "via": "registry",
                            "module": mod.rel, "line": n.lineno,
                        })
        sites.sort(key=lambda s: (s["name"], s["module"], s["line"]))
        dynamic.sort(key=lambda d: (d["module"], d["line"]))
        self.knob_sites = sites
        self.knob_dynamic = dynamic

    def _metric_scan(self) -> None:
        """Metric producers (Counter/Gauge/HistogramOpts constructions
        in production code, with whether each is registered through a
        provider ``new_*`` call and which class/function owns it),
        netscope's derived series, and every consumer site — literal
        names passed to ``.series(...)`` anywhere, plus rollup-code
        string comparisons and ``*_series`` parameter defaults inside
        netscope itself."""
        producers: list[dict] = []
        derived: list[dict] = []
        consumers: list[dict] = []
        dynamic: list[dict] = []
        opts_sites: list[tuple] = []  # (mod, node, kind, owner)
        wrapped: set = set()  # id() of Opts calls passed to new_*
        for mod, fn, cls, params, nodes in self._scope_items():
            production = mod.rel.startswith("fabric_tpu/")
            owner = None
            if cls is not None:
                owner = f"{mod.dotted}.{cls}"
            elif fn is not None:
                owner = fn.qname
            for n in nodes:
                if isinstance(n, ast.Call):
                    if (
                        isinstance(n.func, ast.Attribute)
                        and n.func.attr in _METRIC_NEW_FNS
                    ):
                        for a in list(n.args) + [
                            kw.value for kw in n.keywords
                        ]:
                            if isinstance(a, ast.Call):
                                wrapped.add(id(a))
                    kind = self._opts_kind(mod, n)
                    if kind is not None and production:
                        opts_sites.append((mod, n, kind, owner))
                    if (
                        isinstance(n.func, ast.Attribute)
                        and n.func.attr == "series"
                        and len(n.args) >= 2
                    ):
                        for name in sorted(_literal_strs(n.args[1])):
                            consumers.append({
                                "name": name, "context": "series",
                                "module": mod.rel, "line": n.lineno,
                            })
                elif mod.rel == _NETSCOPE_REL and isinstance(
                    n, ast.Tuple
                ):
                    if (
                        len(n.elts) >= 2
                        and isinstance(n.elts[0], ast.Constant)
                        and n.elts[0].value == "_derived"
                        and isinstance(n.elts[1], ast.Constant)
                        and isinstance(n.elts[1].value, str)
                    ):
                        derived.append({
                            "name": n.elts[1].value,
                            "module": mod.rel, "line": n.lineno,
                        })
                elif mod.rel == _NETSCOPE_REL and isinstance(
                    n, ast.Compare
                ):
                    if not all(
                        isinstance(op, (ast.Eq, ast.NotEq))
                        for op in n.ops
                    ):
                        continue
                    for side in [n.left] + list(n.comparators):
                        if (
                            isinstance(side, ast.Constant)
                            and isinstance(side.value, str)
                            and _METRIC_NAME_RE.match(side.value)
                        ):
                            consumers.append({
                                "name": side.value,
                                "context": "rollup",
                                "module": mod.rel, "line": n.lineno,
                            })
            if fn is not None and mod.rel == _NETSCOPE_REL:
                # `height_series: str = "ledger_height"`-style defaults
                a = fn.node.args
                pos = a.posonlyargs + a.args
                for p, d in zip(pos[len(pos) - len(a.defaults):],
                                a.defaults):
                    if (
                        p.arg.endswith("_series")
                        and isinstance(d, ast.Constant)
                        and isinstance(d.value, str)
                    ):
                        consumers.append({
                            "name": d.value, "context": "default",
                            "module": mod.rel, "line": fn.lineno,
                        })
        for mod, n, kind, owner in opts_sites:
            kwargs = {
                kw.arg: kw.value for kw in n.keywords
                if kw.arg is not None
            }
            parts = []
            literal = True
            for key in ("namespace", "subsystem", "name"):
                v = kwargs.get(key)
                if v is None:
                    continue
                if isinstance(v, ast.Constant) and isinstance(
                    v.value, str
                ):
                    if v.value:
                        parts.append(v.value)
                else:
                    literal = False
            if not literal or "name" not in kwargs:
                dynamic.append({
                    "kind": kind, "module": mod.rel, "line": n.lineno,
                })
                continue
            producers.append({
                "name": "_".join(parts), "kind": kind,
                "module": mod.rel, "line": n.lineno,
                "registered": id(n) in wrapped, "owner": owner,
            })
        # owner reachability: an Opts-owning class/function nothing in
        # PRODUCTION instantiates/calls is dead instrumentation — its
        # metrics can never appear on a real node's scrape (orphan
        # producers; a test-only reference does not count)
        owners = {p["owner"] for p in producers if p["owner"]}
        referenced: set = set()
        if owners:
            for mod in self.modules.values():
                if not mod.rel.startswith("fabric_tpu/"):
                    continue
                for n in ast.walk(mod.tree):
                    if isinstance(n, ast.Call):
                        q = self._resolve_expr(mod, n.func, None, {}, {})
                        if q in owners:
                            referenced.add(q)
        for p in producers:
            p["reachable"] = p["owner"] is None or p["owner"] in referenced
        producers.sort(
            key=lambda p: (p["name"], p["module"], p["line"])
        )
        derived.sort(key=lambda d: (d["name"], d["module"], d["line"]))
        consumers.sort(
            key=lambda c: (c["name"], c["module"], c["line"],
                           c["context"])
        )
        dynamic.sort(key=lambda d: (d["module"], d["line"]))
        self.metric_producers = producers
        self.metric_derived = derived
        self.metric_consumers = consumers
        self.metric_dynamic = dynamic

    def _opts_kind(self, mod, call: ast.Call) -> str | None:
        """counter/gauge/histogram when `call` constructs a metric
        Opts (by import or same-module class reference); else None."""
        q = self._resolve_expr(mod, call.func, None, {}, {})
        if q is None:
            dotted = _dotted(call.func)
            if dotted is not None:
                cand = f"{mod.dotted}.{dotted}"
                if cand in _METRIC_OPTS:
                    q = cand
        return _METRIC_OPTS.get(q) if q else None

    def rpcmap(self) -> dict:
        """The JSON-shaped RPC-conformance artifact (``--rpcmap-out``):
        every method with its register and call sites, both in
        deterministic order."""
        methods: dict[str, dict] = {}
        for r in self.rpc_registers:
            m = methods.setdefault(
                r["method"], {"registers": [], "calls": []}
            )
            m["registers"].append({
                k: r[k] for k in
                ("component", "module", "line", "handler", "shape")
            })
        for c in self.rpc_calls:
            m = methods.setdefault(
                c["method"], {"registers": [], "calls": []}
            )
            m["calls"].append({
                k: c[k] for k in ("component", "module", "line", "verb")
            })
        return {"methods": {k: methods[k] for k in sorted(methods)}}

    def knob_map(self) -> dict:
        """The read-site half of the ``--knobs-out`` artifact (lint.py
        joins it with the registry entries)."""
        return {"reads": self.knob_sites, "dynamic": self.knob_dynamic}

    def metricmap(self) -> dict:
        """The JSON-shaped metrics-conformance artifact
        (``--metricmap-out``).  ``exposed`` is every series name a
        scrape can legally produce: registered producers (histograms
        expanded to their ``_bucket``/``_sum``/``_count`` series) plus
        netscope's derived series."""
        exposed: set = set()
        for p in self.metric_producers:
            exposed.add(p["name"])
            if p["kind"] == "histogram":
                for suf in _HISTOGRAM_SUFFIXES:
                    exposed.add(p["name"] + suf)
        for d in self.metric_derived:
            exposed.add(d["name"])
        return {
            "producers": self.metric_producers,
            "derived": self.metric_derived,
            "consumers": self.metric_consumers,
            "dynamic": self.metric_dynamic,
            "exposed": sorted(exposed),
        }

    # -- public API --------------------------------------------------------

    def lock_graph(self, strict_only: bool = True) -> dict:
        """The static role-level acquisition-order graph as a JSON-
        shaped artifact: ``edges[src][dst]`` lists the [rel, line]
        acquisition sites establishing src -> dst.  With
        ``strict_only`` (the default, and what the CI artifact and the
        runtime-⊆-static cross-check consume) only production sites
        count — tests may nest fixture locks in orders production
        never uses."""
        edges: dict[str, dict] = {}
        for (src, dst), site_list in sorted(self.lock_order_edges.items()):
            kept = [
                [rel, line] for rel, line in site_list
                if not strict_only
                or not rel.startswith(("tests/", "scripts/"))
            ]
            if kept:
                edges.setdefault(src, {})[dst] = kept
        roles = sorted(
            set(edges) | {d for v in edges.values() for d in v}
        )
        return {"edges": edges, "roles": roles}

    def function(self, qname: str) -> FunctionInfo | None:
        return self.symbols.get(qname)

    def summaries(self) -> list[dict]:
        return [
            fn.summary()
            for _, fn in sorted(self.symbols.items())
        ]


__all__ = [
    "Project",
    "FunctionInfo",
    "ModuleInfo",
    "ClassInfo",
    "TaintFlow",
    "CSP_SEAM_ALLOWED",
    "BLOCKING_CALLS",
]
