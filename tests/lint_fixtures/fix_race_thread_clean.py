"""CLEAN TWIN of fix_race_thread_dirty: the worker takes the guard
lock around its write, so every access site agrees."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class OffersCache:
    def __init__(self):
        self._lock = named_lock("fixture.offers")
        self._offers = {}

    def start(self):
        t = spawn_thread(
            target=self._refresh, name="fixture-refresh", kind="worker"
        )
        t.start()
        return t

    def _refresh(self):
        with self._lock:
            self._offers["latest"] = 1

    def get(self, key):
        with self._lock:
            return self._offers.get(key)

    def size(self):
        with self._lock:
            return len(self._offers)
