"""fabriclint — domain-aware invariant checker (intra- + interprocedural).

The north star routes ALL block-validation crypto through the pluggable
CSP seam so it can batch onto TPU, and PR 2 made lock/fsync discipline
in the commit path load-bearing.  Those invariants are enforced here by
machine, not reviewer memory: tier-1 runs this linter over the whole
tree (tests/test_lint_clean.py) and fails on any unsuppressed violation.

Since v2, rules are INTERPROCEDURAL where it matters: a whole-program
pass (``devtools/dataflow.py``) resolves imports/aliases, builds a call
graph, and computes per-function summaries (returns-digest,
sinks-to-consensus-bytes, spawns-thread, acquires-lock,
performs-blocking-io), so csp-seam sees digests computed via locals and
helper functions, lock-discipline sees blocking I/O reached through any
resolvable call chain under ``commit_lock``, and the taint rule follows
wall-clock values through assignments, calls, and f-strings into
protoutil marshaling.

Rules
-----
csp-seam
    No direct ``hashlib`` use outside ``fabric_tpu/csp/`` and
    ``fabric_tpu/common/hashing.py``/``crypto.py`` — including local
    aliases (``h = hashlib``) and calls to helper functions whose
    bodies reach hashlib outside the seam (interprocedural; propagation
    stops at reviewed/suppressed uses and at the seam itself).

exception-discipline
    No ``except Exception`` (or bare ``except``) in ``peer/``,
    ``policies/``, ``ledger/`` whose handler swallows without a
    structured sentinel (re-raise, sentinel assignment, logger call, or
    named error return).  ``faultline.*`` calls are a reviewed seam and
    TRANSPARENT to this analysis: an injection point inside a handler
    neither counts as the sentinel nor fires on its own.

determinism
    In validation/commit/policy paths where peers must agree (``peer/``,
    ``policies/``, ``ledger/``, ``protoutil/``): ban ``time.time()``,
    ``datetime.now()``/``utcnow()``, module-level ``random.*`` calls,
    and ``json.dumps`` without ``sort_keys=True``.

taint
    (interprocedural, whole tree) wall-clock/random values —
    ``time.time()``, ``datetime.now()``, module-level ``random.*`` —
    tracked through assignments, attribute fills, f-strings, and
    resolvable calls, flagged where they flow INTO consensus bytes:
    protoutil marshaling, protobuf (block-header) construction,
    ``SerializeToString``.  Catches the cross-function smuggle the
    determinism rule's call-site ban cannot see.

lock-discipline
    (a) bare ``x.acquire()`` outside try/finally; (b) lexically nested
    ``with`` acquisitions inverting ``commit_lock -> _lock -> _idle``;
    (c) blocking I/O (fsync, sqlite execute, sleep) — directly, through
    a same-class helper, or through ANY statically resolvable call
    chain (interprocedural) — while lexically holding ``commit_lock``,
    outside the approved group-commit seam.

racecheck
    (v3 tentpole; whole tree) lockset inference + shared-state race
    detection.  Every ``self._x`` / declared module-global access gets
    a lockset (the lock ROLES statically held there, extended
    interprocedurally by a meet over call paths, with type-informed
    resolution of attribute calls on annotated params/fields); every
    field gets a guarded-by role from the reviewed table in
    ``devtools/guards.py`` or by strict majority across its access
    sites.  An access on a path from a THREAD ENTRY POINT
    (``lockwatch.spawn_thread``/``spawn_timer`` targets,
    ``Thread``/``Timer`` ctors, ``executor.submit``, ``.register``/
    ``.subscribe`` handlers) whose lockset misses the guard is an
    error.  ``__init__`` bodies are pre-publication and exempt; fields
    never written outside ``__init__`` cannot race; an unresolvable
    lock-shaped ``with`` context suppresses rather than fabricates.
    The runtime cross-check is ``lockwatch.guarded(obj, field,
    by=role)`` at the declared hot fields — tier-1 verifies the static
    guard map against what threads actually hold.

    Since v4 ("hbcheck") the rule is HAPPENS-BEFORE aware: the engine
    models synchronization edges — ``Thread.start()`` (pre-spawn writes
    publish to the target), ``join()``/``drain_threads`` (the target's
    writes publish to the joiner), ``Event.set()→wait()``,
    ``Queue.put()→get()``, ``workpool.run_chunked`` submit→result —
    and an access ordered against every counterpart write needs no
    lock: it neither fires nor votes in guard inference (fields whose
    every access is publication-ordered show as source ``hb-publish``
    in the guard map).  Two NEW error classes ride the same machinery:
    a write that races PAST its publication point (mutating a field
    after ``start()`` that the spawned thread also touches, with no
    common lock and no later edge), and re-arming a shared ``Event``
    (``clear()``) concurrently with another thread's ``set()``/
    ``clear()`` — the lost-wakeup class behind the PR 11 deliver-client
    wedge.  A declared guards.py entry whose every access is HB-proven
    (with at least one access actually thread-reachable) is flagged
    STALE so the reviewed table only shrinks.

lock-order
    (v4; whole tree) the static twin of lockwatch's runtime
    ``LockOrderError``: the lockset pass records every lexical
    acquisition with the roles already held, an interprocedural
    MAY-held union extends the edges across resolvable call chains,
    and any cycle in the resulting role-level acquisition-order graph
    is an error (one finding per cycle).  The graph is exported as a
    CI artifact (``scripts/lint.py --lockgraph-out``) and tier-1
    cross-checks that every edge runtime lockwatch observes during a
    live commit+snapshot session is present in it (runtime ⊆ static).

thread-lifecycle
    (v4; whole tree) every ``spawn_thread``/``spawn_timer``/``Thread``/
    ``Timer``/executor registration needs a statically reachable stop
    path: a ``join()``/``cancel()``/``shutdown()`` on whatever holds
    the handle, a stop-signal loop in the spawned entry (``Event``
    wait/is_set, queue get, ``clockskew.wait``), or a provably bounded
    worker body (no unbounded loop).  A handle returned or handed to
    another callable transfers ownership with the reference.  The
    static rule fails the leak at REVIEW time; the runtime threadwatch
    drain gate remains the interpreter-exit backstop.

thread-hygiene
    No daemonized ``threading.Thread``/``Timer`` created outside the
    threadwatch seam (``devtools/lockwatch.spawn_thread``/
    ``spawn_timer``).  A daemon thread nobody can drain is exactly the
    `tpu-flush-waiter` that the interpreter killed mid-XLA-kernel
    (MULTICHIP rc=134): registration makes every worker joinable before
    interpreter exit, and the runtime threadwatch ledger (see
    lockwatch.py) asserts they actually drained.

jax-hygiene
    No host synchronization (``block_until_ready``, ``device_get``)
    inside per-item ``for``/``while`` loops.

Profiles
--------
``fabric_tpu/`` lints under the strict profile (everything at error
severity).  ``tests/`` and ``scripts/`` lint under the RELAXED profile:
determinism, taint, and jax-hygiene are off (tests fabricate
timestamps and sync per-item by design), csp-seam is advisory
(warning severity — tests hash directly to build expectations), and
everything else stays at error.  ``tests/lint_fixtures/`` is skipped
entirely (deliberately-dirty fixtures for the engine's own tests).

Suppression
-----------
Inline pragma: a ``fabriclint: allow[<rule>] <reason>`` comment on the
offending line, the contiguous comment block above it, or the comment
block opening the flagged statement's body.  A pragma MUST carry a
reason and MUST suppress something.  Cross-file entries live in
``devtools/allowlist.py``; unused entries are violations, so the
surface only shrinks.

Baseline ratchet
----------------
``--baseline FILE`` reads a JSON ``{"rule": count}`` budget: up to
``count`` unsuppressed errors per rule are tolerated (reported, but not
fatal), so a new rule can land in warn mode and be tightened in the
same PR once the tree is clean.  ``--write-baseline FILE`` records the
current per-rule counts.  The ratchet only goes DOWN: a budget above
the observed count is itself an error, so the carve-out cannot outlive
the violations it covered.

Dataflow cache
--------------
``lint_tree`` caches finished reports (violations + per-function
summaries + the guard map) in ``.fabriclint_cache/`` keyed by a digest
of the engine sources, the allowlist, the targets, and every target
file's content hash — editing any single file (or the linter itself)
changes the key, which IS the per-file invalidation.  A cache hit
serves the identical JSON in ~0.3s instead of a ~8s whole-program
pass; ``--no-cache`` (CLI and ``scripts/lint.py``) bypasses it.

CLI
---
``python -m fabric_tpu.devtools.lint [--json] [--baseline FILE]
[--guards] [--no-cache] [targets...]`` — exits non-zero on any
over-budget unsuppressed error; ``--json`` emits one JSON object per
violation plus a summary line; ``--guards`` dumps the racecheck
guarded-by map.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize

from fabric_tpu.devtools import dataflow
from fabric_tpu.devtools.dataflow import BLOCKING_CALLS, CSP_SEAM_ALLOWED

RULES = (
    "csp-seam",
    "exception-discipline",
    "determinism",
    "taint",
    "lock-discipline",
    "racecheck",
    "lock-order",
    "thread-lifecycle",
    "thread-hygiene",
    "jax-hygiene",
    "chaos-coverage",
    "rpc-conformance",
    "knob-conformance",
    "metrics-conformance",
)

# meta rules: problems with the suppression machinery itself; never
# themselves suppressible
META_RULES = ("pragma", "allowlist")

PRAGMA_RE = re.compile(
    r"#\s*fabriclint:\s*allow\[([a-z, -]+)\]\s*(.*?)\s*$"
)

# -- scopes ------------------------------------------------------------------

EXC_SCOPE = (
    "fabric_tpu/peer/",
    "fabric_tpu/policies/",
    "fabric_tpu/ledger/",
)

DET_SCOPE = EXC_SCOPE + ("fabric_tpu/protoutil/",)

# generated code is exempt from everything; lint_fixtures are the
# engine's own deliberately-dirty test corpus
SKIP_PREFIXES = ("fabric_tpu/protos/", "tests/lint_fixtures/")

# the one module allowed to construct daemon threads directly: it IS
# the registration seam
THREADWATCH_SEAM = "fabric_tpu/devtools/lockwatch.py"

DEFAULT_TARGETS = ("fabric_tpu", "tests", "scripts")

LOCK_RANKS = {
    "commit_lock": 0,
    "_commit_lock": 0,
    "_lock": 1,
    "_idle": 2,
}

COMMIT_LOCK_NAMES = ("commit_lock", "_commit_lock")

JAX_SYNC_CALLS = frozenset({"block_until_ready", "device_get"})

# -- chaos-coverage (v5) -----------------------------------------------------

# fault actions that only make sense against particular seam kinds: a
# pinned plan wiring `torn` to a plain point can never tear anything
CHAOS_ACTION_KINDS = {
    "torn": frozenset({"write"}),
    "partial": frozenset({"io"}),
    "skip": frozenset({"guard"}),
}

# the checked-in campaign-registry export (scripts/chaos.py
# --export-registry): every production seam the chaos campaign can arm
# — via observer-plan discovery on the canned workload or a pinned plan
# somewhere in the tree at export time
FAULTMAP_REGISTRY_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "faultmap_registry.json"
)


def load_faultmap_registry(path: str | None = None) -> dict:
    """``{point name: {"kinds": [...]}}`` from the checked-in registry
    export; empty when the artifact is absent (fixture projects and
    bootstrap runs check only their own plan rules then)."""
    try:
        with open(path or FAULTMAP_REGISTRY_PATH, "r",
                  encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    pts = data.get("points") if isinstance(data, dict) else None
    return pts if isinstance(pts, dict) else {}


def _chaos_coverage(
    project: "dataflow.Project",
    pinned_registry: dict | None,
) -> list["Violation"]:
    """Cross-check the statically enumerated faultline seams against
    everything that could ever arm them: exact plan rules and prefix
    wildcards pinned anywhere in the tree (a chaos test IS coverage),
    plus the checked-in campaign-registry export.  The bare ``"*"``
    soak wildcard proves nothing by itself — it arms only what the
    workload reaches, which is exactly what the registry records."""
    pinned = pinned_registry or {}
    seam_kinds: dict[str, set] = {}
    for s in project.faultline_seams:
        seam_kinds.setdefault(s["name"], set()).add(s["kind"])
    exact: set = set()
    prefixes: list = []
    for p in project.faultline_plans:
        if p["wildcard"]:
            if p["point"] != "*":
                prefixes.append(p["point"][:-1])  # keep trailing dot
        else:
            exact.add(p["point"])
    known = set(seam_kinds) | set(pinned)
    out: list[Violation] = []
    for d in project.faultline_dynamic:
        out.append(Violation(
            rule="chaos-coverage", path=d["module"], line=d["line"],
            message=(
                f"faultline.{d['kind']}() name is not a string literal "
                "— the seam cannot be enumerated into the faultmap or "
                "targeted by any pinned plan; use a literal dotted name"
            ),
        ))
    for s in project.faultline_seams:
        nm = s["name"]
        if (
            nm in exact
            or nm in pinned
            or any(nm.startswith(pre) for pre in prefixes)
        ):
            continue
        out.append(Violation(
            rule="chaos-coverage", path=s["module"], line=s["line"],
            message=(
                f"fault seam {nm!r} ({s['kind']}) can never be armed: "
                "no pinned plan rule, prefix wildcard, or campaign-"
                "registry entry matches it — add a chaos test / plan "
                "that arms it, then refresh the registry export "
                "(scripts/chaos.py --export-registry)"
            ),
        ))
    for p in project.faultline_plans:
        strict_file = profile_for(p["module"]) is STRICT_PROFILE
        if p["wildcard"]:
            if (
                strict_file
                and p["point"] != "*"
                and not any(
                    n.startswith(p["point"][:-1]) for n in known
                )
            ):
                out.append(Violation(
                    rule="chaos-coverage", path=p["module"],
                    line=p["line"],
                    message=(
                        f"prefix wildcard {p['point']!r} matches no "
                        "known fault seam — the rule is an orphan "
                        "(the seams it covered were renamed or "
                        "removed); fix the prefix or delete the rule"
                    ),
                ))
            continue
        kinds = set(seam_kinds.get(p["point"], ()))
        kinds.update((pinned.get(p["point"]) or {}).get("kinds", ()))
        if not kinds:
            if strict_file:
                out.append(Violation(
                    rule="chaos-coverage", path=p["module"],
                    line=p["line"],
                    message=(
                        f"plan rule names dead point {p['point']!r}: "
                        "no fault seam or campaign-registry entry has "
                        "that name — the injection it pinned has "
                        "rotted; fix the name or delete the rule"
                    ),
                ))
            continue
        need = CHAOS_ACTION_KINDS.get(p["action"])
        if need is not None and not (kinds & need):
            out.append(Violation(
                rule="chaos-coverage", path=p["module"], line=p["line"],
                message=(
                    f"action {p['action']!r} cannot fire at "
                    f"{p['point']!r} (kind "
                    f"{'/'.join(sorted(kinds))}): it only applies to "
                    f"{'/'.join(sorted(need))} seams — the plan can "
                    "never trip; fix the action or the point"
                ),
            ))
    return out


# -- surface conformance (v6) ------------------------------------------------

# the response shape each client verb commits to; a register site whose
# handler provably has a different shape can never satisfy the call
RPC_VERB_SHAPES = {"call": "unary", "stream": "stream", "duplex": "duplex"}

KNOB_REGISTRY_REL = "fabric_tpu/devtools/knob_registry.py"
KNOB_TABLE_BEGIN = "<!-- knob-table:begin -->"
KNOB_TABLE_END = "<!-- knob-table:end -->"


def _rpc_conformance(project: "dataflow.Project") -> list["Violation"]:
    """Cross-check the RPC register plane against the call plane: every
    statically-resolvable call site must hit a registered method, every
    registered handler must have at least one caller (tests count — a
    harness driving a handler IS its consumer), and a call verb must be
    satisfiable by at least one register site's inferred handler shape."""
    methods: dict[str, dict] = {}
    for r in project.rpc_registers:
        m = methods.setdefault(r["method"], {"regs": [], "calls": []})
        m["regs"].append(r)
    for c in project.rpc_calls:
        m = methods.setdefault(c["method"], {"regs": [], "calls": []})
        m["calls"].append(c)
    out: list[Violation] = []
    for name in sorted(methods):
        regs, calls = methods[name]["regs"], methods[name]["calls"]
        if calls and not regs:
            for c in calls:
                out.append(Violation(
                    rule="rpc-conformance", path=c["module"],
                    line=c["line"],
                    message=(
                        f"RPC {c['verb']} site targets {name!r} but no "
                        "component registers that method — the call can "
                        "only ever raise method-not-found; fix the name "
                        "or register the handler"
                    ),
                ))
        if regs and not calls:
            for r in regs:
                out.append(Violation(
                    rule="rpc-conformance", path=r["module"],
                    line=r["line"],
                    message=(
                        f"RPC handler {name!r} ({r['component']}) has "
                        "no caller anywhere in the tree — dead service "
                        "surface; add a consumer (CLI subcommand, "
                        "harness probe, or test) or delete the handler"
                    ),
                ))
        shapes = {r["shape"] for r in regs} - {"unknown"}
        if not shapes:
            continue
        for c in calls:
            want = RPC_VERB_SHAPES[c["verb"]]
            if want not in shapes:
                out.append(Violation(
                    rule="rpc-conformance", path=c["module"],
                    line=c["line"],
                    message=(
                        f"client {c['verb']}s {name!r} (a {want} "
                        "exchange) but every register site's handler "
                        f"is {'/'.join(sorted(shapes))}-shaped — the "
                        "framing can never line up; match the client "
                        "verb to the handler shape"
                    ),
                ))
    return out


def _knob_conformance(
    project: "dataflow.Project",
    sources: dict[str, str],
    readme_text: str | None,
) -> list["Violation"]:
    """Close the env-knob loop: every ``FABRIC_TPU_*`` read resolves to
    a knob_registry entry AND routes through the registry helper; every
    entry has a read site; the README table between the ``knob-table``
    markers is byte-identical to ``render_table()``.  The dead-entry and
    README checks only run when the registry module itself is in the
    linted set (partial runs can't see the whole read plane)."""
    from fabric_tpu.devtools import knob_registry

    out: list[Violation] = []
    for d in project.knob_dynamic:
        out.append(Violation(
            rule="knob-conformance", path=d["module"], line=d["line"],
            message=(
                "knob name is not a string literal — the read cannot "
                "be checked against the registry or enumerated into "
                "the --knobs artifact; use a literal FABRIC_TPU_* name"
            ),
        ))
    for s in project.knob_sites:
        if s["name"] not in knob_registry.KNOBS:
            out.append(Violation(
                rule="knob-conformance", path=s["module"],
                line=s["line"],
                message=(
                    f"env read of unregistered knob {s['name']!r} — "
                    "every FABRIC_TPU_* knob ships with a reviewed "
                    "entry (name/type/default/subsystem/doc) in "
                    "devtools/knob_registry.py; register it"
                ),
            ))
        elif s["via"] == "environ":
            out.append(Violation(
                rule="knob-conformance", path=s["module"],
                line=s["line"],
                message=(
                    f"{s['name']} read bypasses knob_registry.raw() — "
                    "direct os.environ reads skip the registration "
                    "check that keeps the knob table honest; route "
                    "the read through the registry helper"
                ),
            ))
    if KNOB_REGISTRY_REL not in sources:
        return out
    reg_lines = sources[KNOB_REGISTRY_REL].splitlines()

    def _entry_line(name: str) -> int:
        needle = f'"{name}"'
        for i, ln in enumerate(reg_lines):
            if needle in ln:
                return i + 1
        return 0

    read_names = {s["name"] for s in project.knob_sites}
    for name in sorted(set(knob_registry.KNOBS) - read_names):
        out.append(Violation(
            rule="knob-conformance", path=KNOB_REGISTRY_REL,
            line=_entry_line(name),
            message=(
                f"registry entry {name!r} has no read site anywhere "
                "in the tree — the knob is dead (its reader was "
                "removed or renamed); delete the entry or fix the "
                "reader"
            ),
        ))
    if readme_text is not None:
        i = readme_text.find(KNOB_TABLE_BEGIN)
        j = readme_text.find(KNOB_TABLE_END)
        if i < 0 or j < i:
            out.append(Violation(
                rule="knob-conformance", path=KNOB_REGISTRY_REL,
                line=0,
                message=(
                    "README.md has no knob-table marker block "
                    f"({KNOB_TABLE_BEGIN} … {KNOB_TABLE_END}) — the "
                    "generated env-knob table is part of the "
                    "registry's contract; add the block"
                ),
            ))
        else:
            block = readme_text[i + len(KNOB_TABLE_BEGIN):j]
            if block != "\n" + knob_registry.render_table():
                out.append(Violation(
                    rule="knob-conformance", path=KNOB_REGISTRY_REL,
                    line=0,
                    message=(
                        "README.md knob table has drifted from "
                        "knob_registry.render_table() — regenerate the "
                        "block between the knob-table markers "
                        "(python -c 'from fabric_tpu.devtools import "
                        "knob_registry; print(knob_registry."
                        "render_table(), end=\"\")')"
                    ),
                ))
    return out


def _metrics_conformance(project: "dataflow.Project") -> list["Violation"]:
    """Cross-check the metric producer plane against its consumers:
    every Counter/Gauge/Histogram Opts lands in a provider ``new_*``
    call (else the series silently never exists), every series name a
    rollup/SLO/bench consumes is one a scrape can expose, and every
    producer is constructed on some production path (orphan producers
    are advisory — instrumentation wired ahead of its consumer)."""
    out: list[Violation] = []
    for d in project.metric_dynamic:
        out.append(Violation(
            rule="metrics-conformance", path=d["module"], line=d["line"],
            message=(
                "metric name is not resolvable to a string literal — "
                "the series cannot be checked against its consumers "
                "or enumerated into the --metricmap artifact; use "
                "literal namespace/subsystem/name parts"
            ),
        ))
    exposed: set = set()
    for p in project.metric_producers:
        if not p["registered"]:
            out.append(Violation(
                rule="metrics-conformance", path=p["module"],
                line=p["line"],
                message=(
                    f"{p['kind']} Opts for {p['name']!r} never reaches "
                    "a provider new_* call — the series is configured "
                    "but never constructed, so no scrape will ever "
                    "carry it; register it with a provider"
                ),
            ))
        exposed.add(p["name"])
        if p["kind"] == "histogram":
            for suf in dataflow._HISTOGRAM_SUFFIXES:
                exposed.add(p["name"] + suf)
    for d in project.metric_derived:
        exposed.add(d["name"])
    for c in project.metric_consumers:
        if c["name"] not in exposed:
            out.append(Violation(
                rule="metrics-conformance", path=c["module"],
                line=c["line"],
                message=(
                    f"consumer reads series {c['name']!r} "
                    f"({c['context']}) but no producer or derived "
                    "series carries that name — the rollup/threshold "
                    "can only ever see an absent series; fix the name "
                    "or add the producer"
                ),
            ))
    for p in project.metric_producers:
        if p["registered"] and not p["reachable"]:
            out.append(Violation(
                rule="metrics-conformance", path=p["module"],
                line=p["line"], severity="warning",
                message=(
                    f"producer {p['name']!r} is only constructed from "
                    f"{p['owner']} which no production path "
                    "instantiates — the series exists in code but no "
                    "deployed process exposes it; wire the owner into "
                    "a node/harness path (advisory)"
                ),
            ))
    return out


def build_knob_artifact(knob_map: dict) -> dict:
    """The ``--knobs-out`` artifact: the reviewed registry joined with
    the statically-enumerated read plane."""
    from fabric_tpu.devtools import knob_registry

    registry = {
        name: {
            "kind": k.kind, "default": k.default,
            "subsystem": k.subsystem, "doc": k.doc,
            "choices": list(k.choices),
        }
        for name, k in sorted(knob_registry.KNOBS.items())
    }
    return {
        "registry": registry,
        "reads": knob_map["reads"],
        "dynamic": knob_map["dynamic"],
    }


# -- profiles ----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Profile:
    name: str
    disabled: tuple = ()
    advisory: tuple = ()


STRICT_PROFILE = Profile("strict")
RELAXED_PROFILE = Profile(
    "relaxed",
    # racecheck and its v4 siblings are off with determinism/taint:
    # tests drive production objects from the pytest thread without
    # the production locks by design, fixtures seed deliberate races
    # and inversions, and test helpers manage thread lifecycles
    # dynamically (start/join inline) in shapes the static rule need
    # not model
    # the v6 surface-conformance rules are whole-program checks over
    # the PRODUCTION surface: their violations anchor at production
    # sites (tests count as consumers/callers, never as the surface),
    # so test/script files carry none of their own
    disabled=("determinism", "taint", "jax-hygiene", "racecheck",
              "lock-order", "thread-lifecycle", "rpc-conformance",
              "knob-conformance", "metrics-conformance"),
    advisory=("csp-seam",),
)


def profile_for(rel: str) -> Profile:
    if rel.startswith(("tests/", "scripts/")):
        return RELAXED_PROFILE
    return STRICT_PROFILE


@dataclasses.dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    suppression: str | None = None  # "pragma: <reason>" / "allowlist: <reason>"
    severity: str = "error"  # "error" | "warning" (advisory profiles)

    def __str__(self) -> str:
        tag = f" (suppressed: {self.suppression})" if self.suppressed else ""
        sev = " [warning]" if self.severity == "warning" else ""
        return f"{self.path}:{self.line}: [{self.rule}]{sev} {self.message}{tag}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class AllowEntry:
    """One reviewed cross-file suppression.  `match` must be a substring
    of the flagged source line, so entries survive line-number drift but
    die (as unused-entry violations) when the code they covered goes
    away."""

    rule: str
    path: str
    match: str
    reason: str


# -- per-module pre-pass: which class methods (transitively) block -----------


def _method_blocking_map(tree: ast.Module) -> dict[str, set[str]]:
    """class name -> names of its methods that perform a blocking call
    directly or through other methods of the same class (fixpoint over
    ``self.x()`` edges).  The dataflow engine subsumes this for
    resolvable calls; this lexical map stays as the zero-setup fallback
    for single-snippet lint_source runs."""
    out: dict[str, set[str]] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        direct: set[str] = set()
        calls: dict[str, set[str]] = {}
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            calls[fn.name] = set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr in BLOCKING_CALLS:
                        direct.add(fn.name)
                    if (
                        isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                    ):
                        calls[fn.name].add(f.attr)
        blocking = set(direct)
        changed = True
        while changed:
            changed = False
            for name, callees in calls.items():
                if name not in blocking and callees & blocking:
                    blocking.add(name)
                    changed = True
        out[cls.name] = blocking
    return out


# -- the checker -------------------------------------------------------------


def _in_scope(rel: str, prefixes) -> bool:
    return any(rel.startswith(p) for p in prefixes)


def _is_trivial_return_value(v) -> bool:
    """True for values whose return carries no information: None,
    constants, tuples of constants, and empty containers."""
    if v is None or isinstance(v, ast.Constant):
        return True
    if isinstance(v, ast.Tuple):
        return all(isinstance(e, ast.Constant) for e in v.elts)
    if isinstance(v, (ast.List, ast.Set)):
        return not v.elts
    if isinstance(v, ast.Dict):
        return not v.keys
    return False


def _is_faultline_stmt(stmt) -> bool:
    """Expression statements calling the faultline seam
    (``faultline.point(...)`` etc.) are TRANSPARENT to the swallow
    analysis: an injection point inside an except handler is a reviewed
    seam (like the lockwatch seam) — it neither launders the swallow
    into "handled" (it is not a structured sentinel) nor constitutes a
    violation of its own."""
    if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
        return False
    f = stmt.value.func
    return (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id == "faultline"
    )


def _swallows(handler: ast.ExceptHandler) -> bool:
    if any(isinstance(n, ast.Raise) for n in ast.walk(handler)):
        return False
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if _is_faultline_stmt(stmt):
            continue
        if isinstance(stmt, ast.Return) and _is_trivial_return_value(
            stmt.value
        ):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant
        ):
            continue  # docstring / ellipsis
        return False
    return True


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _lock_name(expr) -> str | None:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _finally_releases(node: ast.Try) -> bool:
    return any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == "release"
        for stmt in node.finalbody
        for n in ast.walk(stmt)
    )


def _acquires_before_try_finally(tree: ast.Module) -> set[int]:
    """Node ids of `x.acquire()` statements whose immediately-following
    sibling is a try whose finally releases — the canonical safe idiom
    (acquire OUTSIDE the try: a failed acquire must not reach the
    finally and release a lock it never took)."""
    ok: set[int] = set()
    for node in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(node, field, None)
            if not isinstance(stmts, list):
                continue
            for a, b in zip(stmts, stmts[1:]):
                if (
                    isinstance(a, ast.Expr)
                    and isinstance(a.value, ast.Call)
                    and isinstance(a.value.func, ast.Attribute)
                    and a.value.func.attr == "acquire"
                    and isinstance(b, ast.Try)
                    and _finally_releases(b)
                ):
                    ok.add(id(a))
    return ok


def _dotted_name(expr) -> str | None:
    """`a.b.c` as the string "a.b.c"; None for non-Name/Attribute chains."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


class _FileChecker(ast.NodeVisitor):
    def __init__(self, rel: str, tree: ast.Module,
                 project: dataflow.Project | None = None):
        self.rel = rel
        self.violations: list[Violation] = []
        self._seen: set[tuple[str, int]] = set()
        self._hashlib_aliases: set[str] = set()
        self._threading_aliases: set[str] = set()
        self._thread_ctor_aliases: set[str] = set()
        self._time_fn_aliases: set[str] = set()
        self._random_fn_aliases: set[str] = set()
        self._datetime_aliases: set[str] = {"datetime", "date"}
        self._func_stack: list[str] = []
        self._class_stack: list[str] = []
        self._with_locks: list[str] = []
        self._loop_depth = 0
        self._protected_depth = 0  # inside a try whose finally releases
        self._blocking = _method_blocking_map(tree)
        self._preacquire_ok = _acquires_before_try_finally(tree)
        self._project = project

    # -- helpers -----------------------------------------------------------

    def _flag(self, rule: str, node, message: str) -> None:
        key = (rule, node.lineno)
        if key in self._seen:
            return
        self._seen.add(key)
        self.violations.append(
            Violation(rule=rule, path=self.rel, line=node.lineno,
                      message=message)
        )

    def _resolved_callee(self, node: ast.Call):
        if self._project is None:
            return None
        q = self._project.call_resolutions.get(
            (self.rel, node.lineno, node.col_offset)
        )
        return self._project.symbols.get(q) if q else None

    # -- imports (alias tracking) ------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name == "hashlib":
                self._hashlib_aliases.add(alias.asname or "hashlib")
            if alias.name == "threading":
                self._threading_aliases.add(alias.asname or "threading")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "hashlib" and not _in_scope(
            self.rel, CSP_SEAM_ALLOWED
        ):
            self._flag(
                "csp-seam", node,
                "from-import of hashlib outside the CSP seam "
                "(route through common.hashing.sha256/sha256_many or a "
                "CSP hash/hash_batch)",
            )
        if node.module == "threading":
            for alias in node.names:
                if alias.name in ("Thread", "Timer"):
                    self._thread_ctor_aliases.add(alias.asname or alias.name)
        if node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    self._time_fn_aliases.add(alias.asname or "time")
        if node.module == "random":
            # module-level functions share the hidden global Random();
            # the class constructors are fine (callers seed their own)
            for alias in node.names:
                if alias.name not in ("Random", "SystemRandom"):
                    self._random_fn_aliases.add(alias.asname or alias.name)
        if node.module == "datetime":
            for alias in node.names:
                if alias.name in ("datetime", "date"):
                    self._datetime_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id in self._hashlib_aliases
            and not _in_scope(self.rel, CSP_SEAM_ALLOWED)
        ):
            self._flag(
                "csp-seam", node,
                f"direct hashlib.{node.attr} outside the CSP seam — "
                "invisible to hash_batch/TPU batching (route through "
                "common.hashing.sha256/sha256_many or the CSP)",
            )
        self.generic_visit(node)

    # -- exception discipline ----------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if (
            _in_scope(self.rel, EXC_SCOPE)
            and _catches_broad(node)
            and _swallows(node)
        ):
            self._flag(
                "exception-discipline", node,
                "broad except swallows without a structured sentinel, "
                "re-raise, or logged reason",
            )
        self.generic_visit(node)

    # -- assignments: thread-hygiene daemon flips ---------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if self.rel != THREADWATCH_SEAM:
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "daemon"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True
                ):
                    self._flag(
                        "thread-hygiene", node,
                        "thread daemonized by attribute flip without "
                        "threadwatch registration — create it through "
                        "devtools.lockwatch.spawn_thread/spawn_timer so "
                        "it can be drained before interpreter exit",
                    )
        self.generic_visit(node)

    # -- calls: determinism + lock blocking + threads + jax hygiene ---------

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else None
        # full dotted base so `datetime.datetime.now()` resolves — a
        # Name-only base would see None and let the qualified spelling
        # through the gate
        base = (
            _dotted_name(f.value) if isinstance(f, ast.Attribute) else None
        )
        base_tail = base.rsplit(".", 1)[-1] if base else None

        if _in_scope(self.rel, DET_SCOPE):
            if (base == "time" and attr == "time") or (
                isinstance(f, ast.Name) and f.id in self._time_fn_aliases
            ):
                self._flag(
                    "determinism", node,
                    "time.time() on a consensus path — wall-clock "
                    "differs across peers (use an explicit timestamp "
                    "argument, or time.monotonic/perf_counter for "
                    "intervals)",
                )
            elif (
                attr in ("now", "utcnow", "today")
                and base_tail in self._datetime_aliases
            ):
                self._flag(
                    "determinism", node,
                    f"datetime.{attr}() on a consensus path",
                )
            elif (base == "random" and attr not in ("Random", "SystemRandom")
                  ) or (
                isinstance(f, ast.Name) and f.id in self._random_fn_aliases
            ):
                name = attr if attr is not None else f.id
                self._flag(
                    "determinism", node,
                    f"module-level random.{name}() on a consensus path "
                    "(inject a seeded random.Random instead)",
                )
            elif base == "json" and attr == "dumps":
                sorted_kw = any(
                    kw.arg == "sort_keys"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords
                )
                if not sorted_kw:
                    self._flag(
                        "determinism", node,
                        "json.dumps without sort_keys=True on a "
                        "consensus path — dict order leaks into bytes",
                    )

        # thread-hygiene: daemonized Thread/Timer outside the seam
        is_thread_ctor = (
            base in self._threading_aliases
            and attr in ("Thread", "Timer")
        ) or (
            isinstance(f, ast.Name) and f.id in self._thread_ctor_aliases
        )
        if is_thread_ctor and self.rel != THREADWATCH_SEAM:
            daemonized = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            if daemonized:
                self._flag(
                    "thread-hygiene", node,
                    "daemonized thread created outside the threadwatch "
                    "seam — a daemon thread nobody registered cannot be "
                    "drained and dies mid-kernel at interpreter exit "
                    "(the MULTICHIP rc=134 class); create it through "
                    "devtools.lockwatch.spawn_thread/spawn_timer",
                )

        callee = self._resolved_callee(node)

        if attr is not None and any(
            n in COMMIT_LOCK_NAMES for n in self._with_locks
        ):
            cls = self._class_stack[-1] if self._class_stack else None
            if attr in BLOCKING_CALLS:
                self._flag(
                    "lock-discipline", node,
                    f"blocking call .{attr}() while holding the commit "
                    "lock, outside the approved group-commit seam",
                )
            elif (
                base == "self"
                and cls is not None
                and attr in self._blocking.get(cls, ())
            ):
                self._flag(
                    "lock-discipline", node,
                    f"self.{attr}() performs blocking I/O (transitively) "
                    "while holding the commit lock, outside the approved "
                    "group-commit seam",
                )
        if (
            callee is not None
            and callee.blocking_transitive
            and any(n in COMMIT_LOCK_NAMES for n in self._with_locks)
        ):
            self._flag(
                "lock-discipline", node,
                f"call to {callee.qname} performs blocking I/O "
                "(interprocedurally) while holding the commit lock, "
                "outside the approved group-commit seam",
            )

        if attr in JAX_SYNC_CALLS and self._loop_depth > 0:
            self._flag(
                "jax-hygiene", node,
                f".{attr}() inside a per-item loop — host sync per "
                "item serializes the device; sync once per batch",
            )

        self.generic_visit(node)

    # -- lock discipline: bare acquire + with-order -------------------------

    def visit_Try(self, node: ast.Try) -> None:
        if _finally_releases(node):
            self._protected_depth += 1
            for stmt in node.body + node.orelse:
                self.visit(stmt)
            self._protected_depth -= 1
            for h in node.handlers:
                self.visit(h)
            for stmt in node.finalbody:
                self.visit(stmt)
        else:
            self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        v = node.value
        if (
            isinstance(v, ast.Call)
            and isinstance(v.func, ast.Attribute)
            and v.func.attr == "acquire"
            and self._protected_depth == 0
            and id(node) not in self._preacquire_ok
            and (not self._func_stack or self._func_stack[-1] != "__enter__")
        ):
            self._flag(
                "lock-discipline", node,
                "bare .acquire() without try/finally release "
                "(use `with`, or release in a finally)",
            )
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        names = []
        for item in node.items:
            n = _lock_name(item.context_expr)
            if n is not None and n in LOCK_RANKS:
                for outer in self._with_locks:
                    if LOCK_RANKS[n] < LOCK_RANKS[outer]:
                        self._flag(
                            "lock-discipline", node,
                            f"lock-order inversion: {n!r} (rank "
                            f"{LOCK_RANKS[n]}) acquired while holding "
                            f"{outer!r} (rank {LOCK_RANKS[outer]}); "
                            f"canonical order is commit_lock -> _lock "
                            f"-> _idle",
                        )
                names.append(n)
                self._with_locks.append(n)
        for item in node.items:
            self.visit(item)
        for stmt in node.body:
            self.visit(stmt)
        for _ in names:
            self._with_locks.pop()

    # -- structure tracking -------------------------------------------------

    def visit_FunctionDef(self, node) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_For(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_AsyncFor = visit_For
    visit_While = visit_For


# -- suppression -------------------------------------------------------------


def _parse_pragmas(source: str, rel: str):
    """Tokenize-based pragma scan: only REAL comment tokens count, so
    pragma-shaped text inside strings/docstrings never registers.

    Returns (pragmas, comment_only, meta) where `pragmas` maps line
    number -> (rules, reason), `comment_only` is the set of lines whose
    sole content is a comment (used to associate a pragma with the
    statement its comment block annotates), and `meta` lists violations
    for malformed pragmas (unknown rule, missing reason)."""
    pragmas: dict[int, tuple[set[str], str]] = {}
    comment_only: set[int] = set()
    meta: list[Violation] = []
    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(source).readline)
        )
    except (tokenize.TokenError, IndentationError):
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        i = tok.start[0]
        if not tok.line[: tok.start[1]].strip():
            comment_only.add(i)
        m = PRAGMA_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = m.group(2).strip()
        unknown = rules - set(RULES)
        if unknown:
            meta.append(Violation(
                rule="pragma", path=rel, line=i,
                message=f"pragma names unknown rule(s): "
                        f"{', '.join(sorted(unknown))}",
            ))
        if not reason:
            meta.append(Violation(
                rule="pragma", path=rel, line=i,
                message="pragma without a reason — every suppression "
                        "must say why it was reviewed",
            ))
        pragmas[i] = (rules, reason)
    return pragmas, comment_only, meta


def _pragma_candidate_lines(line: int, comment_only: set[int],
                            lines: list[str]):
    """Lines whose pragma may suppress a violation on `line`: the line
    itself (trailing comment), the contiguous comment-only block
    immediately above it (comments wrap; the pragma may sit a couple of
    lines up), and — ONLY when the flagged line opens a block (``except
    Exception:``) — the comment block at the top of that block's body.
    The body scan requires deeper indentation than the opener so a
    pragma written for the NEXT statement at the same level never leaks
    upward onto a neighboring, unreviewed violation."""
    yield line
    ln = line - 1
    while ln >= 1 and ln in comment_only:
        yield ln
        ln -= 1
    src = lines[line - 1] if 0 < line <= len(lines) else ""
    if src.split("#", 1)[0].rstrip().endswith(":"):
        opener_indent = len(src) - len(src.lstrip())
        ln = line + 1
        while ln <= len(lines) and ln in comment_only:
            body = lines[ln - 1]
            if len(body) - len(body.lstrip()) <= opener_indent:
                break
            yield ln
            ln += 1


def _apply_suppressions(
    violations: list[Violation],
    pragmas: dict[int, tuple[set[str], str]],
    comment_only: set[int],
    lines: list[str],
    allowlist: list[AllowEntry],
    used_entries: set[int],
    used_pragmas: set[int],
) -> None:
    """Mark violations suppressed in place; accumulates used pragma
    lines into `used_pragmas`."""
    for v in violations:
        if v.suppressed:
            continue
        for ln in _pragma_candidate_lines(v.line, comment_only, lines):
            p = pragmas.get(ln)
            if p and v.rule in p[0]:
                v.suppressed = True
                v.suppression = f"pragma: {p[1]}"
                used_pragmas.add(ln)
                break
        if v.suppressed:
            continue
        src = lines[v.line - 1] if 0 < v.line <= len(lines) else ""
        for idx, e in enumerate(allowlist):
            if e.rule == v.rule and e.path == v.path and e.match in src:
                v.suppressed = True
                v.suppression = f"allowlist: {e.reason}"
                used_entries.add(idx)
                break


# -- drivers -----------------------------------------------------------------


@dataclasses.dataclass
class _FileState:
    rel: str
    source: str
    lines: list[str]
    tree: ast.Module | None
    pragmas: dict
    comment_only: set
    meta: list
    violations: list = dataclasses.field(default_factory=list)
    used_pragmas: set = dataclasses.field(default_factory=set)


def _interprocedural_csp_seam(
    project: dataflow.Project,
    states: dict[str, _FileState],
    allowlist: list[AllowEntry],
    used_entries: set[int],
) -> None:
    """Flag callers of helpers whose bodies reach hashlib outside the
    seam — but only helpers whose own direct use is UNSUPPRESSED: a
    reviewed pragma on the helper is the reviewed design decision, and
    propagating past it would demand a pragma per caller for one
    reviewed fact.  Runs to a fixpoint so a dirty helper's caller that
    itself goes unsuppressed taints ITS callers in turn."""
    # call site index: callee qname -> [(rel, line)]
    sites: dict[str, list] = {}
    for (rel, line, col), q in project.call_resolutions.items():
        sites.setdefault(q, []).append((rel, line))
    for _ in range(8):
        dirty: set[str] = set()
        for q, fn in project.symbols.items():
            st = states.get(fn.rel)
            if st is None or dataflow._in_seam(fn.rel):
                continue
            end = getattr(fn.node, "end_lineno", fn.lineno)
            for v in st.violations:
                if (
                    v.rule == "csp-seam"
                    and not v.suppressed
                    and fn.lineno <= v.line <= end
                ):
                    dirty.add(q)
                    break
        new = []
        for q in dirty:
            for rel, line in sites.get(q, ()):
                st = states.get(rel)
                if st is None or dataflow._in_seam(rel):
                    continue
                if any(
                    v.rule == "csp-seam" and v.line == line
                    for v in st.violations
                ):
                    continue
                v = Violation(
                    rule="csp-seam", path=rel, line=line,
                    message=(
                        f"digest computed via helper {q} whose body "
                        "uses hashlib outside the CSP seam "
                        "(interprocedural) — route the helper through "
                        "common.hashing or the CSP"
                    ),
                )
                prof = profile_for(rel)
                if "csp-seam" in prof.disabled:
                    continue
                if "csp-seam" in prof.advisory:
                    v.severity = "warning"
                st.violations.append(v)
                new.append((st, v))
        if not new:
            break
        for st, v in new:
            _apply_suppressions(
                [v], st.pragmas, st.comment_only, st.lines,
                allowlist, used_entries, st.used_pragmas,
            )


def _lock_order_cycles(graph: dict):
    """Cycles in the static role-level acquisition-order graph
    (``dataflow.Project.lock_graph()`` shape).  Yields ``(cycle_roles,
    anchor_site)`` per strongly connected component with more than one
    role: the cycle is a deterministic role path around the component,
    the anchor the lexically-LAST acquisition site contributing to any
    of its edges (in file order that is the cycle-closing side,
    mirroring where runtime lockwatch would raise) — one finding per
    deadlock class, not one per contributing line."""
    edges = graph.get("edges", {})
    adj = {s: sorted(d) for s, d in edges.items()}
    # Tarjan SCC, iterative (the graph is tiny but recursion depth must
    # not depend on lock count)
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(adj.get(v0, ())))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)

    for v in sorted(set(adj) | {d for ds in adj.values() for d in ds}):
        if v not in index:
            strongconnect(v)
    for comp in sccs:
        if len(comp) < 2:
            continue
        comp_set = set(comp)
        # a deterministic cycle path: BFS shortest walk from the min
        # role back to itself inside the component
        start = min(comp)
        prev: dict[str, str] = {start: start}
        queue = [start]
        path = [start]
        while queue:
            node = queue.pop(0)
            hit = False
            for d in adj.get(node, ()):
                if d not in comp_set:
                    continue
                if d == start and node != start:
                    walk = [node]
                    while walk[-1] != start:
                        walk.append(prev[walk[-1]])
                    path = list(reversed(walk))
                    hit = True
                    break
                if d not in prev:
                    prev[d] = node
                    queue.append(d)
            if hit:
                break
        # anchor at the lexically-LAST contributing acquisition — in
        # file order that is the cycle-closing side, mirroring where
        # runtime lockwatch would raise
        anchor = max(
            tuple(site)
            for i, s in enumerate(path)
            for site in edges.get(s, {}).get(
                path[(i + 1) % len(path)], ()
            )
        )
        yield path, anchor


def lint_sources(
    sources: dict[str, str],
    allowlist: list[AllowEntry] | None = None,
    used_entries: set[int] | None = None,
    pinned_registry: dict | None = None,
    readme_text: str | None = None,
) -> "LintReport":
    """Lint a set of modules as one program (keys are repo-relative
    paths; interprocedural rules see across all of them).

    ``pinned_registry`` is the campaign-registry export consulted by
    chaos-coverage; ``lint_tree`` passes the checked-in artifact, while
    direct callers (fixture tests) default to None so a fixture project
    is judged against its own plan rules only.  ``readme_text`` is the
    README contents for knob-conformance's table-drift check — None
    (the direct-caller default) skips it."""
    allowlist = allowlist if allowlist is not None else []
    used_entries = used_entries if used_entries is not None else set()
    states: dict[str, _FileState] = {}
    trees: dict[str, ast.Module] = {}
    for rel, source in sorted(sources.items()):
        pragmas, comment_only, meta = _parse_pragmas(source, rel)
        try:
            tree = ast.parse(source)
        except SyntaxError as exc:
            meta = [Violation(
                rule="pragma", path=rel, line=exc.lineno or 0,
                message=f"file does not parse: {exc.msg}",
            )]
            states[rel] = _FileState(
                rel=rel, source=source, lines=source.splitlines(),
                tree=None, pragmas={}, comment_only=set(), meta=meta,
            )
            continue
        trees[rel] = tree
        states[rel] = _FileState(
            rel=rel, source=source, lines=source.splitlines(),
            tree=tree, pragmas=pragmas, comment_only=comment_only,
            meta=meta,
        )
    # reviewed wall-clock sources: lines covered by an allow[determinism]
    # / allow[taint] pragma do not SEED taint (see dataflow.Project)
    sanctioned: dict[str, set] = {}
    for rel, st in states.items():
        relevant = {
            ln for ln, (rules, _r) in st.pragmas.items()
            if rules & {"determinism", "taint"}
        }
        if not relevant:
            continue
        covered = set()
        for v_line in range(1, len(st.lines) + 1):
            for ln in _pragma_candidate_lines(
                v_line, st.comment_only, st.lines
            ):
                if ln in relevant:
                    covered.add(v_line)
                    break
        sanctioned[rel] = covered
    project = dataflow.Project(trees, sanctioned_sources=sanctioned)

    for rel, st in states.items():
        if st.tree is None:
            continue
        checker = _FileChecker(rel, st.tree, project)
        checker.visit(st.tree)
        st.violations = checker.violations

    # merge whole-program emissions into their files
    for flow in project.alias_violations:
        st = states.get(flow.rel)
        if st is not None and not any(
            v.rule == "csp-seam" and v.line == flow.line
            for v in st.violations
        ):
            st.violations.append(Violation(
                rule="csp-seam", path=flow.rel, line=flow.line,
                message=flow.message,
            ))
    for flow in project.taint_flows:
        st = states.get(flow.rel)
        if st is not None and not any(
            v.rule == "taint" and v.line == flow.line
            for v in st.violations
        ):
            st.violations.append(Violation(
                rule="taint", path=flow.rel, line=flow.line,
                message=flow.message,
            ))
    for flow in project.race_flows + project.stale_guard_flows:
        st = states.get(flow.rel)
        if st is not None and not any(
            v.rule == "racecheck" and v.line == flow.line
            for v in st.violations
        ):
            st.violations.append(Violation(
                rule="racecheck", path=flow.rel, line=flow.line,
                message=flow.message,
            ))
    for flow in project.lifecycle_flows:
        st = states.get(flow.rel)
        if st is not None and not any(
            v.rule == "thread-lifecycle" and v.line == flow.line
            for v in st.violations
        ):
            st.violations.append(Violation(
                rule="thread-lifecycle", path=flow.rel, line=flow.line,
                message=flow.message,
            ))
    # chaos-coverage (v5): seams nothing can arm, rotted plan rules
    for v in _chaos_coverage(project, pinned_registry):
        st = states.get(v.path)
        if st is not None:
            st.violations.append(v)
    # surface conformance (v6): the RPC register/call planes, the env-
    # knob read plane vs the reviewed registry, and the metric
    # producer/consumer planes
    for v in (
        _rpc_conformance(project)
        + _knob_conformance(project, sources, readme_text)
        + _metrics_conformance(project)
    ):
        st = states.get(v.path)
        if st is not None:
            st.violations.append(v)
    # static lock-order cycles (v4): one violation per cycle, anchored
    # at the lexically-last contributing acquisition (the cycle-closing
    # side in file order)
    for cycle, site in _lock_order_cycles(project.lock_graph()):
        rel, line = site
        st = states.get(rel)
        if st is not None:
            st.violations.append(Violation(
                rule="lock-order", path=rel, line=line,
                message=(
                    "static lock-order cycle: "
                    + " -> ".join(cycle + [cycle[0]])
                    + " — a thread following one order and a thread "
                    "following the other can deadlock (the static twin "
                    "of lockwatch's runtime LockOrderError); pick one "
                    "canonical order and restructure the off-order "
                    "acquisition"
                ),
            ))

    # profiles: drop disabled rules, downgrade advisory ones
    for rel, st in states.items():
        prof = profile_for(rel)
        if prof.disabled or prof.advisory:
            kept = []
            for v in st.violations:
                if v.rule in prof.disabled:
                    continue
                if v.rule in prof.advisory:
                    v.severity = "warning"
                kept.append(v)
            st.violations = kept

    for rel, st in states.items():
        _apply_suppressions(
            st.violations, st.pragmas, st.comment_only, st.lines,
            allowlist, used_entries, st.used_pragmas,
        )

    _interprocedural_csp_seam(project, states, allowlist, used_entries)

    # pragmas whose job was sanctioning a taint source count as used
    for rel, src_line in project.sanctioned_used:
        st = states.get(rel)
        if st is None:
            continue
        for ln in _pragma_candidate_lines(
            src_line, st.comment_only, st.lines
        ):
            p = st.pragmas.get(ln)
            if p and p[0] & {"determinism", "taint"}:
                st.used_pragmas.add(ln)
                break

    violations: list[Violation] = []
    for rel in sorted(states):
        st = states[rel]
        for ln in sorted(set(st.pragmas) - st.used_pragmas):
            st.meta.append(Violation(
                rule="pragma", path=rel, line=ln,
                message="unused pragma — it suppresses nothing; remove "
                        "it (or it is masking a rule that moved)",
            ))
        st.violations.sort(key=lambda v: v.line)
        violations.extend(st.violations + st.meta)
    return LintReport(
        files=len(states), violations=violations, project=project,
    )


def lint_source(
    source: str,
    rel: str,
    allowlist: list[AllowEntry] | None = None,
    used_entries: set[int] | None = None,
) -> list[Violation]:
    """Lint one module's source as if it lived at repo-relative `rel`
    (single-module program: interprocedural rules see same-file helpers
    only)."""
    report = lint_sources(
        {rel: source}, allowlist=allowlist, used_entries=used_entries
    )
    return report.violations


def repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def iter_target_files(root: str, targets) -> list[str]:
    rels: list[str] = []
    for target in targets:
        abs_t = os.path.join(root, target)
        if os.path.isfile(abs_t):
            rels.append(target.replace(os.sep, "/"))
            continue
        # a typo'd / renamed target must not silently report "clean"
        if not os.path.isdir(abs_t):
            raise FileNotFoundError(
                f"lint target {target!r} matches no file or directory "
                f"under {root}"
            )
        before = len(rels)
        for dirpath, dirnames, filenames in os.walk(abs_t):
            dirnames[:] = [
                d for d in sorted(dirnames) if d != "__pycache__"
            ]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(
                    os.path.join(dirpath, fn), root
                ).replace(os.sep, "/")
                if not _in_scope(rel, SKIP_PREFIXES):
                    rels.append(rel)
        if len(rels) == before:
            raise FileNotFoundError(
                f"lint target {target!r} contains no lintable .py files"
            )
    return rels


@dataclasses.dataclass
class LintReport:
    files: int
    violations: list[Violation]
    project: dataflow.Project | None = None
    # populated on a dataflow-cache hit (project is None then)
    cached_summaries: list | None = None
    cached_guards: dict | None = None
    cached_lockgraph: dict | None = None
    cached_faultmap: dict | None = None
    cached_rpcmap: dict | None = None
    cached_knobmap: dict | None = None
    cached_metricmap: dict | None = None
    cache_state: str = "off"  # "off" | "miss" | "hit"

    def function_summaries(self) -> list[dict]:
        """Per-function dataflow summaries, from the live project or
        the cache — callers must not care which run produced them."""
        if self.project is not None:
            return self.project.summaries()
        return list(self.cached_summaries or [])

    def guard_map(self) -> dict:
        """The racecheck guarded-by map (declared + inferred), live or
        cached."""
        if self.project is not None:
            return dict(self.project.guard_map)
        return dict(self.cached_guards or {})

    def lock_graph(self) -> dict:
        """The static role-level acquisition-order graph (production
        sites only — what the CI artifact and the runtime-⊆-static
        cross-check consume), live or cached."""
        if self.project is not None:
            return self.project.lock_graph()
        return dict(self.cached_lockgraph or {"edges": {}, "roles": []})

    def faultmap(self) -> dict:
        """The chaos-coverage faultmap artifact (every production
        injection seam + every pinned plan rule), live or cached."""
        if self.project is not None:
            return self.project.faultmap()
        return dict(
            self.cached_faultmap
            or {"seams": [], "dynamic": [], "plans": []}
        )

    def rpcmap(self) -> dict:
        """The rpc-conformance artifact (every method with its register
        and call sites), live or cached."""
        if self.project is not None:
            return self.project.rpcmap()
        return dict(self.cached_rpcmap or {"methods": {}})

    def knobmap(self) -> dict:
        """The knob-conformance artifact (the reviewed registry joined
        with the read plane), live or cached."""
        if self.project is not None:
            return build_knob_artifact(self.project.knob_map())
        return dict(
            self.cached_knobmap
            or {"registry": {}, "reads": [], "dynamic": []}
        )

    def metricmap(self) -> dict:
        """The metrics-conformance artifact (producer/derived/consumer
        planes + the exposable series set), live or cached."""
        if self.project is not None:
            return self.project.metricmap()
        return dict(
            self.cached_metricmap
            or {"producers": [], "derived": [], "consumers": [],
                "dynamic": [], "exposed": []}
        )

    @property
    def unsuppressed(self) -> list[Violation]:
        """Unsuppressed ERROR-severity violations (the gate)."""
        return [
            v for v in self.violations
            if not v.suppressed and v.severity == "error"
        ]

    @property
    def warnings(self) -> list[Violation]:
        """Unsuppressed advisory (warning-severity) violations."""
        return [
            v for v in self.violations
            if not v.suppressed and v.severity == "warning"
        ]

    @property
    def suppressed(self) -> list[Violation]:
        return [v for v in self.violations if v.suppressed]

    def summary(self) -> dict:
        by_rule: dict[str, int] = {}
        for v in self.unsuppressed:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        warn_by_rule: dict[str, int] = {}
        for v in self.warnings:
            warn_by_rule[v.rule] = warn_by_rule.get(v.rule, 0) + 1
        return {
            "tool": "fabriclint",
            "files": self.files,
            "violations": len(self.unsuppressed),
            "warnings": len(self.warnings),
            "suppressed": len(self.suppressed),
            "by_rule": dict(sorted(by_rule.items())),
            "warn_by_rule": dict(sorted(warn_by_rule.items())),
            "clean": not self.unsuppressed,
            "cache": self.cache_state,
        }


# -- dataflow-summary cache --------------------------------------------------
#
# The whole-program pass re-parses and re-analyzes ~250 files on every
# lint_tree() call; tier-1 runs several (the self-gate, CLI subprocess
# tests, the wrapper).  Results are a pure function of (engine source,
# target file contents, allowlist, targets), so lint_tree caches the
# finished report under `.fabriclint_cache/` keyed by a digest of all
# of them — any single-file edit (or an engine/allowlist change)
# changes the key, which IS the per-file invalidation.

_CACHE_DIR_NAME = ".fabriclint_cache"
# v6 (surfcheck): the rpcmap/knobs/metricmap conformance artifacts
# joined the cached report (v5 added CFG summaries, flow-sensitive
# locksets, and the faultmap) — an earlier-schema entry must never
# serve
_CACHE_SCHEMA = 4
_CACHE_KEEP = 8
_engine_fp_memo: list = []


def _engine_fingerprint() -> str:
    """Digest of the analysis engine's own sources: a rule change must
    never serve a stale cached verdict."""
    if _engine_fp_memo:
        return _engine_fp_memo[0]
    import hashlib

    from fabric_tpu.devtools import allowlist as _al
    from fabric_tpu.devtools import guards as _guards
    from fabric_tpu.devtools import knob_registry as _kr

    # fabriclint: allow[csp-seam] cache-key fingerprint of the linter's
    # own sources — tooling metadata, not consensus bytes; routing it
    # through the CSP would make the cache key depend on the backend
    h = hashlib.sha256(str(_CACHE_SCHEMA).encode())
    # ast/parsing behavior shifts across interpreter versions: a cached
    # verdict must not outlive the interpreter that computed it
    h.update(repr(sys.version_info).encode())
    for m in (dataflow, _guards, _al, _kr):
        with open(m.__file__, "rb") as f:
            # fabriclint: allow[csp-seam] cache-key fingerprint (see above)
            h.update(hashlib.sha256(f.read()).digest())
    with open(os.path.abspath(__file__), "rb") as f:
        # fabriclint: allow[csp-seam] cache-key fingerprint (see above)
        h.update(hashlib.sha256(f.read()).digest())
    # the campaign-registry export feeds chaos-coverage verdicts: a
    # refreshed export must invalidate cached reports
    try:
        with open(FAULTMAP_REGISTRY_PATH, "rb") as f:
            # fabriclint: allow[csp-seam] cache-key fingerprint (see above)
            h.update(hashlib.sha256(f.read()).digest())
    except OSError:
        h.update(b"no-faultmap-registry")
    # knob-conformance's table-drift verdict depends on README bytes,
    # which are not in the linted source set
    try:
        with open(os.path.join(repo_root(), "README.md"), "rb") as f:
            # fabriclint: allow[csp-seam] cache-key fingerprint (see above)
            h.update(hashlib.sha256(f.read()).digest())
    except OSError:
        h.update(b"no-readme")
    _engine_fp_memo.append(h.hexdigest())
    return _engine_fp_memo[0]


def _cache_key(sources: dict[str, str], allowlist, targets) -> str:
    import hashlib

    # fabriclint: allow[csp-seam] cache key over target file contents —
    # invalidation metadata, not consensus bytes
    h = hashlib.sha256(_engine_fingerprint().encode())
    h.update(repr(sorted(targets)).encode())
    for e in allowlist:
        h.update(repr((e.rule, e.path, e.match, e.reason)).encode())
    for rel in sorted(sources):
        h.update(rel.encode())
        # fabriclint: allow[csp-seam] per-file content hash (cache key)
        h.update(hashlib.sha256(sources[rel].encode()).digest())
    return h.hexdigest()


def _cache_load(cache_dir: str, key: str) -> dict | None:
    path = os.path.join(cache_dir, f"{key[:40]}.json")
    try:
        with open(path, "r", encoding="utf-8") as f:
            entry = json.load(f)
    except (OSError, ValueError):
        return None
    if entry.get("key") != key:
        return None
    return entry


def _cache_store(cache_dir: str, key: str, entry: dict) -> None:
    try:
        os.makedirs(cache_dir, exist_ok=True)
        path = os.path.join(cache_dir, f"{key[:40]}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(entry, f, sort_keys=True)
        os.replace(tmp, path)
        # prune: newest _CACHE_KEEP entries survive
        names = [
            n for n in os.listdir(cache_dir) if n.endswith(".json")
        ]
        if len(names) > _CACHE_KEEP:
            full = sorted(
                (os.path.getmtime(os.path.join(cache_dir, n)), n)
                for n in names
            )
            for _, n in full[: len(names) - _CACHE_KEEP]:
                os.remove(os.path.join(cache_dir, n))
    except OSError:
        # a read-only checkout must not fail the lint run over a cache
        return


def lint_tree(
    root: str | None = None,
    targets=DEFAULT_TARGETS,
    allowlist: list[AllowEntry] | None = None,
    cache: bool = True,
) -> LintReport:
    root = root or repo_root()
    if allowlist is None:
        from fabric_tpu.devtools.allowlist import ALLOWLIST

        allowlist = list(ALLOWLIST)
    used_entries: set[int] = set()
    rels = iter_target_files(root, targets)
    sources: dict[str, str] = {}
    for rel in rels:
        with open(os.path.join(root, rel), "r", encoding="utf-8") as f:
            sources[rel] = f.read()
    cache_dir = os.path.join(root, _CACHE_DIR_NAME)
    key = _cache_key(sources, allowlist, targets) if cache else None
    if key is not None:
        entry = _cache_load(cache_dir, key)
        if entry is not None:
            return LintReport(
                files=entry["files"],
                violations=[Violation(**v) for v in entry["violations"]],
                project=None,
                cached_summaries=entry["summaries"],
                cached_guards=entry["guards"],
                cached_lockgraph=entry["lockgraph"],
                cached_faultmap=entry["faultmap"],
                cached_rpcmap=entry["rpcmap"],
                cached_knobmap=entry["knobs"],
                cached_metricmap=entry["metricmap"],
                cache_state="hit",
            )
    try:
        with open(os.path.join(root, "README.md"), "r",
                  encoding="utf-8") as f:
            readme_text = f.read()
    except OSError:
        readme_text = None
    report = lint_sources(
        sources, allowlist, used_entries,
        pinned_registry=load_faultmap_registry(),
        readme_text=readme_text,
    )
    # an entry is in this run's scope if its file was linted, or if it
    # falls under a directory target (so full-tree runs flag entries
    # whose file was DELETED, while partial runs — one file, one subdir —
    # don't false-positive on entries they never had a chance to use)
    linted = set(rels)
    dir_prefixes = tuple(
        t.rstrip("/") + "/" for t in targets
        if not os.path.isfile(os.path.join(root, t))
    )
    for idx, e in enumerate(allowlist):
        in_scope = e.path in linted or e.path.startswith(dir_prefixes)
        if idx not in used_entries and in_scope:
            report.violations.append(Violation(
                rule="allowlist",
                path="fabric_tpu/devtools/allowlist.py",
                line=0,
                message=f"unused allowlist entry ({e.rule} @ {e.path} "
                        f"matching {e.match!r}) — the code it covered "
                        f"is gone; remove the entry",
            ))
    if key is not None:
        _cache_store(cache_dir, key, {
            "key": key,
            "files": report.files,
            "violations": [v.to_dict() for v in report.violations],
            "summaries": report.function_summaries(),
            "guards": report.guard_map(),
            "lockgraph": report.lock_graph(),
            "faultmap": report.faultmap(),
            "rpcmap": report.rpcmap(),
            "knobs": report.knobmap(),
            "metricmap": report.metricmap(),
        })
        report.cache_state = "miss"
    return report


# -- baseline ratchet --------------------------------------------------------


def load_baseline(path: str) -> dict[str, int]:
    with open(path, "r", encoding="utf-8") as f:
        budgets = json.load(f)
    if not isinstance(budgets, dict) or not all(
        isinstance(k, str) and isinstance(v, int) and v >= 0
        for k, v in budgets.items()
    ):
        raise ValueError(
            f"baseline {path!r} must be a JSON object of "
            "non-negative per-rule counts"
        )
    return budgets


def apply_baseline(report: LintReport, budgets: dict[str, int]) -> dict:
    """Ratchet evaluation: per-rule unsuppressed-error counts vs the
    budget.  Over-budget rules fail; a budget LOOSER than reality also
    fails (the ratchet only tightens — stale carve-outs must die the
    moment the tree is cleaner than they claim)."""
    counts = report.summary()["by_rule"]
    over = {
        r: c - budgets.get(r, 0)
        for r, c in counts.items()
        if c > budgets.get(r, 0)
    }
    stale = {
        r: b for r, b in budgets.items()
        if b > counts.get(r, 0)
    }
    return {
        "budgets": budgets,
        "ratcheted": sum(min(counts.get(r, 0), b)
                         for r, b in budgets.items()),
        "over_budget": over,
        "stale_budget": stale,
        "ok": not over and not stale,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fabric_tpu.devtools.lint",
        description="fabriclint: invariant checker for fabric_tpu",
    )
    ap.add_argument(
        "targets", nargs="*", default=list(DEFAULT_TARGETS),
        help="repo-relative files/dirs to lint "
             f"(default: {' '.join(DEFAULT_TARGETS)})",
    )
    ap.add_argument("--root", default=None, help="repo root override")
    ap.add_argument(
        "--json", action="store_true",
        help="one JSON object per violation + a JSON summary line",
    )
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed violations",
    )
    ap.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="JSON {rule: count} ratchet: tolerate up to COUNT "
             "unsuppressed errors per rule (stale budgets fail)",
    )
    ap.add_argument(
        "--write-baseline", default=None, metavar="FILE",
        help="record current per-rule unsuppressed-error counts and "
             "exit 0",
    )
    ap.add_argument(
        "--summaries", action="store_true",
        help="dump the dataflow engine's per-function summaries (JSON)",
    )
    ap.add_argument(
        "--guards", action="store_true",
        help="dump the racecheck guarded-by map (declared + inferred) "
             "as JSON and exit",
    )
    ap.add_argument(
        "--lockgraph", action="store_true",
        help="dump the static role-level lock acquisition-order graph "
             "(production sites) as JSON and exit",
    )
    ap.add_argument(
        "--faultmap", action="store_true",
        help="dump the chaos-coverage faultmap (every production "
             "faultline seam + every pinned plan rule) as JSON and exit",
    )
    ap.add_argument(
        "--rpcmap", action="store_true",
        help="dump the rpc-conformance map (every RPC method with its "
             "register and call sites) as JSON and exit",
    )
    ap.add_argument(
        "--knobs", action="store_true",
        help="dump the knob-conformance map (the reviewed FABRIC_TPU_* "
             "registry joined with every read site) as JSON and exit",
    )
    ap.add_argument(
        "--metricmap", action="store_true",
        help="dump the metrics-conformance map (producer/derived/"
             "consumer planes + the exposable series set) as JSON and "
             "exit",
    )
    ap.add_argument(
        "--no-cache", action="store_true",
        help="skip the .fabriclint_cache dataflow cache (escape hatch)",
    )
    args = ap.parse_args(argv)

    try:
        report = lint_tree(
            root=args.root, targets=tuple(args.targets),
            cache=not args.no_cache,
        )
    except FileNotFoundError as exc:
        print(json.dumps({"tool": "fabriclint", "error": str(exc)})
              if args.json else f"fabriclint: error: {exc}",
              file=sys.stderr)
        return 2

    if args.summaries:
        for s in report.function_summaries():
            print(json.dumps(s))
        return 0
    if args.guards:
        print(json.dumps(report.guard_map(), indent=2, sort_keys=True))
        return 0
    if args.lockgraph:
        print(json.dumps(report.lock_graph(), indent=2, sort_keys=True))
        return 0
    if args.faultmap:
        print(json.dumps(report.faultmap(), indent=2, sort_keys=True))
        return 0
    if args.rpcmap:
        print(json.dumps(report.rpcmap(), indent=2, sort_keys=True))
        return 0
    if args.knobs:
        print(json.dumps(report.knobmap(), indent=2, sort_keys=True))
        return 0
    if args.metricmap:
        print(json.dumps(report.metricmap(), indent=2, sort_keys=True))
        return 0

    shown = list(report.unsuppressed) + list(report.warnings)
    if args.show_suppressed:
        shown += report.suppressed
    for v in shown:
        print(json.dumps(v.to_dict()) if args.json else str(v))

    summary = report.summary()
    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(summary["by_rule"], f, indent=2, sort_keys=True)
            f.write("\n")
        print(json.dumps({**summary, "baseline_written":
                          args.write_baseline}))
        return 0
    if args.baseline:
        ratchet = apply_baseline(report, load_baseline(args.baseline))
        summary["baseline"] = ratchet
        print(json.dumps(summary))
        return 0 if ratchet["ok"] else 1
    print(json.dumps(summary))
    return 0 if not report.unsuppressed else 1


if __name__ == "__main__":
    sys.exit(main())
