"""faultfuzz — invariant-oracle chaos fuzzing over the faultline registry.

PR 6/7 injected HAND-WRITTEN fault plans: we only tested the failures we
had already imagined.  This module generates them instead (the
lineage-driven-fault-injection idea of Molly, the schedule-exploration
idea of CrashMonkey): a seeded :class:`random.Random` samples plans from
the LIVE fault-point registry (discovered by running the canned workload
once under ``faultline.observe()``), each plan drives the workload, and
the end state is judged by the reusable ``devtools.invariants`` oracle —
no per-plan asserts, just "do the consistency contracts still hold".

Failing plans are SHRUNK (drop rules, halve trigger counts, while the
oracle still fails) and written as replayable JSON repro artifacts; the
whole campaign is deterministic — ``Campaign(seed=7, plans=25)`` twice
yields byte-identical verdicts and canonical trip ledgers, because every
random draw comes from ``Random(f"{seed}:{plan_index}")``, the workload
is serialized (one hitter per fault point), and trips are canonicalized
by (rule, trip) order.

The canned workload per plan (all phases run UNDER the armed plan, in a
fresh working directory):

1. **commit stream** — 6 single-block commits + a 2-block commit group,
   through every ``commit.stage``/``kvstore.txn``/``store.shard_flush``/
   ``blkstorage.*`` point; a FaultCrash closes the provider and REOPENS
   it with the plan still armed, so recovery itself is fuzzed (this is
   where a ``skip`` on ``store.shard_recover`` — the sharded statedb's
   roll-forward of a committed-but-unapplied flush — turns into
   detectable corruption);
2. **snapshot export + import** — ``SnapshotManager.generate`` through
   the ``snapshot.export.stage``/``snapshot.manifest`` points, then
   ``create_from_snapshot`` into a second provider through the
   ``snapshot.import.stage`` points (a crash leaves the half-import
   marker the provider must refuse);
3. **rpc traffic** — three sequential echo calls through
   ``rpc.accept``/``rpc.server.*``/``rpc.client.*``.

Then the plan is DISARMED and the oracle judges the on-disk end state:
reopen, chain integrity, height/savepoint agreement, the per-block
write/history model against the recovered height, a continuation
commit, completed-snapshot verification, and half-import refusal.

``scripts/chaos.py`` wraps a campaign as a CI step (single JSON summary
line, nonzero exit on any oracle failure, repro artifacts under
``.faultfuzz/``, gitignored).
"""

from __future__ import annotations

import contextlib
import copy
import json
import os
import random

from fabric_tpu.devtools import faultline, invariants

CHANNEL = "fuzz"
NS = "cc"
DEFAULT_BLOCKS = 6  # single-block commits; +2 grouped ride on top

# The canned workload runs on the storage-v2 engine: a 2-way sharded
# statedb (so the two-phase group flush and its recovery seams are
# inside the fuzzed surface) with the flush fan-out pinned SERIAL —
# parallel shard prepare/apply would race the nth-counters of ctx-less
# rules and break the byte-identical trip-ledger acceptance.  Reopens
# ignore the env (the persisted shard count wins), so only creation
# needs the pin.
STORE_SHARDS = 2
_STORE_ENV = {
    "FABRIC_TPU_STORE_SHARDS": str(STORE_SHARDS),
    "FABRIC_TPU_STORE_POOL": "0",
}


@contextlib.contextmanager
def _store_env():
    saved = {k: os.environ.get(k) for k in _STORE_ENV}
    os.environ.update(_STORE_ENV)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

_RAISE_ERRORS = ["FaultInjected", "OSError", "ECONNRESET", "TimeoutError"]


def workload_writes(blocks: int = DEFAULT_BLOCKS) -> list[list[tuple]]:
    """The per-block write model (block n writes key k<n> = v<n>),
    including the trailing 2-block commit group — what the oracle
    judges state/history against."""
    return [
        [(NS, f"k{n:02d}", b"v%04d" % n)] for n in range(blocks + 2)
    ]


def _endorsed_block(ledger, num: int, writes) -> object:
    """One endorser tx writing `writes` through the ledger's own
    simulator — same construction as the ledger test helpers, kept
    stdlib+protos only so devtools stays importable everywhere."""
    from fabric_tpu import protoutil
    from fabric_tpu.protos.common import common_pb2
    from fabric_tpu.protos.peer import (
        proposal_pb2,
        proposal_response_pb2,
        transaction_pb2,
    )

    sim = ledger.new_tx_simulator()
    for ns, k, v in writes:
        sim.set_state(ns, k, v)
    rw = sim.get_tx_simulation_results()

    action = proposal_pb2.ChaincodeAction(results=rw)
    prp = proposal_response_pb2.ProposalResponsePayload(
        proposal_hash=b"\x00" * 32, extension=action.SerializeToString()
    )
    cap = transaction_pb2.ChaincodeActionPayload(
        action=transaction_pb2.ChaincodeEndorsedAction(
            proposal_response_payload=prp.SerializeToString()
        )
    )
    tx = transaction_pb2.Transaction(actions=[
        transaction_pb2.TransactionAction(payload=cap.SerializeToString())
    ])
    # fixed authoring timestamp: canned-workload blocks must be
    # byte-identical across runs (same-seed campaign replays, the
    # profiled-vs-unprofiled parity test), and a wall-clock second
    # boundary between two runs would poison the hash chain
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, CHANNEL, tx_id=f"fuzz-tx-{num}",
        timestamp=1_700_000_000.0,
    )
    shdr = protoutil.make_signature_header(b"fuzzer", b"nonce%d" % num)
    env = common_pb2.Envelope(
        payload=protoutil.make_payload_bytes(
            chdr, shdr, tx.SerializeToString()
        )
    )
    blk = common_pb2.Block()
    blk.header.number = num
    blk.header.previous_hash = ledger.block_store.last_block_hash
    blk.data.data.append(env.SerializeToString())
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    protoutil.init_block_metadata(blk)
    protoutil.set_tx_filter(blk, bytearray(1))
    return blk


# -- the canned workload ------------------------------------------------------


def _src_root(root: str) -> str:
    return os.path.join(root, "src")


def _reopen(src_root: str):
    """Reopen the ledger after a simulated process death — with the
    plan STILL ARMED, so the recovery scan itself is inside the fuzzed
    surface.  Returns (provider, ledger) or (None, None) when recovery
    died too (the judge phase reports what is then on disk)."""
    from fabric_tpu.ledger import LedgerProvider

    provider = None
    try:
        provider = LedgerProvider(src_root)
        return provider, provider.open(CHANNEL)
    except faultline.FaultCrash:
        pass
    except Exception:
        pass
    if provider is not None:
        try:
            provider.close()
        except Exception:
            pass
    return None, None


def _drive(root: str, blocks: int = DEFAULT_BLOCKS,
           comm: bool = True) -> dict:
    """Run the canned workload under whatever plan is armed; never
    raises (every injected failure is caught and recorded — judging is
    the ORACLE's job, on the end state, after disarm)."""
    from fabric_tpu.ledger import LedgerProvider

    writes = workload_writes(blocks)
    stats: dict = {
        "committed": 0, "watermarks": [], "events": [],
        "export": None, "import": None, "rpc_ok": 0,
    }
    src = _src_root(root)
    os.makedirs(src, exist_ok=True)

    provider = None
    ledger = None
    try:
        provider, ledger = _reopen(src)
        if ledger is None:
            stats["events"].append("open:failed")
            return stats

        # phase 1a: single-block commit stream with crash-reopen
        n = 0
        attempts = 0
        recoveries = 0
        while n < blocks and ledger is not None:
            blk = _endorsed_block(ledger, n, writes[n])
            try:
                ledger.commit(blk)
            except faultline.FaultCrash:
                stats["events"].append(f"commit:{n}:crash")
                try:
                    provider.close()
                except Exception:
                    pass
                provider, ledger = _reopen(src)
                recoveries += 1
                if ledger is None or recoveries > 3:
                    break
                n = ledger.height
                attempts = 0
                continue
            except Exception as exc:
                # graceful failure: the ledger rolled back; bounded
                # retries, then give up on the stream (the oracle only
                # cares that what DID commit is consistent)
                stats["events"].append(
                    f"commit:{n}:{type(exc).__name__}"
                )
                attempts += 1
                if attempts >= 3:
                    break
                continue
            stats["committed"] += 1
            stats["watermarks"].append(ledger.durable_height)
            n = ledger.height
            attempts = 0

        # phase 1b: a 2-block commit group (the coalesced-flush path)
        if ledger is not None and ledger.height == blocks:
            try:
                group = ledger.begin_commit_group()
                for gn in (blocks, blocks + 1):
                    ledger.commit(
                        _endorsed_block(ledger, gn, writes[gn]),
                        group=group,
                    )
                ledger.commit_group_flush(group)
                stats["committed"] += 2
                stats["watermarks"].append(ledger.durable_height)
            except faultline.FaultCrash:
                stats["events"].append("group:crash")
                try:
                    provider.close()
                except Exception:
                    pass
                provider, ledger = _reopen(src)
            except Exception as exc:
                stats["events"].append(f"group:{type(exc).__name__}")

        # phase 2: snapshot export + import
        export_dir = None
        if ledger is not None and ledger.durable_height > 0:
            try:
                export_dir = ledger.snapshots.generate()
                stats["export"] = export_dir
            except faultline.FaultCrash:
                stats["events"].append("export:crash")
            except Exception as exc:
                stats["events"].append(f"export:{type(exc).__name__}")
        if export_dir is not None:
            dst = None
            try:
                dst = LedgerProvider(os.path.join(root, "dst"))
                dst.create_from_snapshot(export_dir)
                stats["import"] = "done"
            except faultline.FaultCrash:
                stats["events"].append("import:crash")
                stats["import"] = "crashed"
            except Exception as exc:
                stats["import"] = f"refused:{type(exc).__name__}"
            finally:
                if dst is not None:
                    try:
                        dst.close()
                    except Exception:
                        pass

        # phase 3: serialized rpc traffic (one hitter per point, so the
        # trip ledger stays deterministic)
        if comm:
            from fabric_tpu.comm.rpc import RPCClient, RPCServer

            srv = RPCServer()
            srv.register("echo", lambda body, stream: body)
            srv.start()
            try:
                cli = RPCClient(*srv.addr, timeout=2.0)
                for _ in range(3):
                    try:
                        if cli.call("echo", b"E" * 64) == b"E" * 64:
                            stats["rpc_ok"] += 1
                    except Exception:
                        stats["events"].append("rpc:error")
            finally:
                srv.stop()
    finally:
        if provider is not None:
            try:
                provider.close()
            except Exception:
                pass
    return stats


# -- the oracle judgment ------------------------------------------------------


def _judge(root: str, stats: dict, writes) -> list[invariants.Violation]:
    """Reopen everything with NO plan armed and check the invariants.
    A reopen failure is itself a violation: whatever the faults did,
    the stores must always recover to a servable (or loudly refused
    half-import) state."""
    from fabric_tpu.ledger import LedgerProvider
    from fabric_tpu.ledger import snapshot as snap

    out: list[invariants.Violation] = []
    src = _src_root(root)
    provider = None
    try:
        try:
            provider = LedgerProvider(src)
            ledger = provider.open(CHANNEL)
        except Exception as exc:
            out.append(invariants.Violation(
                "reopen",
                f"ledger failed to reopen after the chaos run: "
                f"{type(exc).__name__}: {exc}",
            ))
            return out
        out.extend(invariants.check_ledger(
            ledger, writes, stats.get("watermarks")
        ))
        # block-file-first liveness: the chain must continue cleanly
        # from wherever recovery landed
        try:
            ledger.commit(_endorsed_block(
                ledger, ledger.height, [("probe", "cont", b"x")]
            ))
        except Exception as exc:
            out.append(invariants.Violation(
                "continuation",
                f"post-recovery commit failed: "
                f"{type(exc).__name__}: {exc}",
            ))
        out.extend(invariants.check_completed_snapshots(
            os.path.join(src, "snapshots")
        ))
    finally:
        if provider is not None:
            try:
                provider.close()
            except Exception:
                pass

    dst_root = os.path.join(root, "dst")
    if os.path.isdir(dst_root):
        try:
            dst = LedgerProvider(dst_root)
        except Exception as exc:
            # a provider that cannot even construct over the imported
            # stores is a violation to ATTRIBUTE, not a harness crash
            out.append(invariants.Violation(
                "import",
                f"destination provider failed to reopen: "
                f"{type(exc).__name__}: {exc}",
            ))
            return out
        try:
            marker = snap.import_marker(dst.kv, CHANNEL)
            if marker == snap.IMPORT_IN_PROGRESS:
                # the contract is a LOUD refusal, not silent service
                try:
                    dst.open(CHANNEL)
                except Exception:
                    pass  # refused: invariant holds
                else:
                    out.append(invariants.Violation(
                        "import",
                        "half-finished snapshot import opened without "
                        "complaint",
                    ))
            elif marker == snap.IMPORT_DONE and stats.get("export"):
                try:
                    led2 = dst.open(CHANNEL)
                except Exception as exc:
                    out.append(invariants.Violation(
                        "import",
                        f"completed import failed to open: "
                        f"{type(exc).__name__}: {exc}",
                    ))
                else:
                    out.extend(invariants.check_import_state(
                        led2, stats["export"]
                    ))
        finally:
            try:
                dst.close()
            except Exception:
                pass
    return out


def _canonical_trips(trips: list[dict], label: str) -> list[dict]:
    """This plan's trips in canonical (rule, trip) order — stable
    across scheduling interleavings, the byte-identical ledger the
    determinism acceptance pins."""
    own = [t for t in trips if t.get("plan") == label]
    return sorted(own, key=lambda t: (t["rule"], t["trip"]))


def run_plan(plan: dict, workdir: str, blocks: int = DEFAULT_BLOCKS,
             comm: bool = True) -> dict:
    """Drive the workload under `plan` in `workdir`, then judge with
    the plan disarmed.  Returns {"trips", "violations", "stats"} —
    plus "trace" (the flight-recorder export for THIS plan's run) when
    tracelens is armed: the recorder and its id counter reset before
    the drive, so same-seed plans replay to identical span sequences
    and a failing plan's dump can ship beside its repro artifact.
    With profscope armed the same contract holds for "profile": the
    profiler's aggregate resets before the drive, so the returned
    speedscope doc covers exactly this plan's workload."""
    from fabric_tpu.common import profile, tracing

    os.makedirs(workdir, exist_ok=True)
    parsed = faultline.Plan(plan)
    if tracing.enabled():
        tracing.reset()
    if profile.enabled():
        profile.reset()
    with faultline.use_plan(parsed), _store_env():
        stats = _drive(workdir, blocks, comm=comm)
        trips = _canonical_trips(faultline.trips(), parsed.label)
    trace = tracing.export() if tracing.enabled() else None
    prof = profile.export() if profile.enabled() else None
    violations = _judge(workdir, stats, workload_writes(blocks))
    out = {
        "trips": trips,
        "violations": [v.as_dict() for v in violations],
        "stats": stats,
    }
    if trace is not None:
        out["trace"] = trace
    if prof is not None:
        out["profile"] = prof
    return out


# -- plan generation ----------------------------------------------------------


def _action_pool(name: str, kinds) -> list[str]:
    """The fault-action pool matched to a point's kind (no crash on rpc
    points — a dead handler thread is noise, not signal; torn only at
    write points, partial only at io points, skip only at guard
    points).  Shared by generate_plan and mutate_plan so a mutant's
    swapped action is always one the generator itself could draw."""
    if "io" in kinds:
        return ["raise", "delay", "partial"]
    if "write" in kinds:
        return ["torn", "raise", "crash", "delay"]
    if "guard" in kinds:
        return ["skip", "raise", "delay"]
    if name.startswith("rpc."):
        return ["raise", "delay"]
    # no "skew" here: the campaign workload runs on the system clock,
    # where a skew rule is a recorded no-op — generating one would
    # waste a fuzz slot (skew plans are exercised under
    # clockskew.use_virtual in tests/test_clockskew.py)
    return ["raise", "crash", "delay"]


_TRIGGER_KEYS = ("nth", "every", "prob", "count")
_ACTION_PARAM_KEYS = ("error", "delay_s", "cut")


def _set_action(f: dict, action: str, rng: random.Random) -> None:
    """Install `action` (and its freshly sampled parameters) on a fault
    rule, dropping any previous action's parameters."""
    for k in _ACTION_PARAM_KEYS:
        f.pop(k, None)
    f["action"] = action
    if action == "raise":
        f["error"] = rng.choice(_RAISE_ERRORS)
    elif action == "delay":
        f["delay_s"] = rng.choice([0.0, 0.001, 0.003])
    elif action == "torn":
        f["cut"] = round(rng.uniform(0.1, 0.9), 2)


def _set_trigger(f: dict, rng: random.Random) -> None:
    """Sample a fresh trigger (nth/every/prob/always with bounded
    counts) onto a fault rule, dropping the previous trigger keys."""
    for k in _TRIGGER_KEYS:
        f.pop(k, None)
    trig = rng.choice(["nth", "every", "prob", "always"])
    if trig == "nth":
        f["nth"] = rng.randint(1, 6)
    elif trig == "every":
        f["every"] = rng.randint(2, 4)
        f["count"] = rng.randint(1, 4)
    elif trig == "prob":
        f["prob"] = round(rng.uniform(0.05, 0.4), 3)
        f["count"] = rng.randint(1, 4)
    else:
        f["count"] = rng.randint(1, 3)


def generate_plan(rng: random.Random, registry: dict, label: str,
                  tripped=frozenset()) -> dict:
    """Sample one plan from the discovered fault-point registry: 1-3
    rules, action pool matched to the point's kind (no crash on rpc
    points — a dead handler thread is noise, not signal; torn only at
    write/io points; skip only at guard points), trigger mix of
    nth/every/prob/always with bounded counts, and 50% ctx targeting
    from the registry's sampled ctx values.

    ``tripped`` is the set of point names already tripped earlier in
    the campaign: selection is coverage-weighted toward the cold
    remainder (all-cold → unchanged v4 behavior).  The weighting costs
    exactly one ``rng.choice`` draw either way, so two same-seed
    campaigns — whose trip ledgers are themselves deterministic — stay
    byte-identical."""
    points = sorted(registry)
    if not points:
        raise ValueError("empty fault-point registry: run discovery first")
    faults = []
    for _ in range(rng.randint(1, 3)):
        cold = [p for p in points if p not in tripped]
        name = rng.choice(cold or points)
        ent = registry[name]
        f: dict = {"point": name}
        _set_action(
            f, rng.choice(_action_pool(name, ent.get("kinds", []))), rng
        )
        _set_trigger(f, rng)
        ctx = ent.get("ctx") or {}
        if ctx and rng.random() < 0.5:
            k = rng.choice(sorted(ctx))
            if ctx[k]:
                f["ctx"] = {k: rng.choice(ctx[k])}
        faults.append(f)
    return {
        "seed": rng.randint(0, 2 ** 31 - 1),
        "label": label,
        # the campaign snapshots the registry ONCE at discovery; its
        # generated plans never read it again, so they skip the per-hit
        # registration cost like soak plans do
        "register": False,
        "faults": faults,
    }


def mutate_plan(rng: random.Random, plan: dict, registry: dict,
                label: str) -> dict:
    """One seeded single-edit mutant of a failing plan: tweak one
    rule's trigger, swap one rule's action within its point's pool, or
    drop one rule.  Everything else — the plan seed included — carries
    over verbatim, so a mutant isolates exactly one variable against
    its parent: does the failure need THIS trigger cadence, THIS
    action, THIS rule?  Mutants ride the same run/judge/shrink/repro
    path as generated plans, and the same (campaign seed, plan index,
    mutant index) always derives the same mutant."""
    mut = copy.deepcopy(plan)
    mut["label"] = label
    faults = mut["faults"]
    edits = ["trigger", "action"] + (["drop"] if len(faults) > 1 else [])
    edit = rng.choice(edits)
    i = rng.randrange(len(faults))
    if edit == "drop":
        del faults[i]
        return mut
    f = faults[i]
    if edit == "action":
        kinds = (registry.get(f["point"]) or {}).get("kinds", [])
        pool = [
            a for a in _action_pool(f["point"], kinds)
            if a != f["action"]
        ]
        if pool:
            _set_action(f, rng.choice(pool), rng)
            return mut
        # single-action pool: fall through to a trigger tweak so the
        # edit never silently degenerates into a no-op
    _set_trigger(f, rng)
    return mut


# -- shrinking ----------------------------------------------------------------


def shrink_plan(plan: dict, still_fails, max_runs: int = 16):
    """Minimize a failing plan: repeatedly try dropping whole rules,
    then halving count/nth/every, keeping any candidate the oracle
    still fails.  `still_fails(candidate_plan) -> bool` re-runs the
    workload.  Returns (shrunk_plan, runs_used)."""
    current = copy.deepcopy(plan)
    runs = 0
    progress = True
    while progress and runs < max_runs:
        progress = False
        faults = current["faults"]
        if len(faults) > 1:
            for i in range(len(faults)):
                cand = {**current, "faults": faults[:i] + faults[i + 1:]}
                runs += 1
                if still_fails(cand):
                    current = cand
                    progress = True
                    break
                if runs >= max_runs:
                    return current, runs
            if progress:
                continue
        for i, f in enumerate(faults):
            for key in ("count", "nth", "every"):
                v = f.get(key)
                if isinstance(v, int) and v > 1:
                    nf = {**f, key: v // 2}
                    cand = {
                        **current,
                        "faults": [*faults[:i], nf, *faults[i + 1:]],
                    }
                    runs += 1
                    if still_fails(cand):
                        current = cand
                        progress = True
                        break
                    if runs >= max_runs:
                        return current, runs
            if progress:
                break
    return current, runs


# -- repro artifacts ----------------------------------------------------------

REPRO_FORMAT = "faultfuzz-repro-v1"


def write_trace_doc(path: str, doc: dict) -> str:
    """Write a flight-recorder export (Chrome trace JSON) beside its
    repro artifact — one serialization, owned by the tracing module."""
    from fabric_tpu.common import tracing

    return tracing.dump_doc(path, doc)


def write_profile_doc(path: str, doc: dict) -> str:
    """Write a profscope export (speedscope JSON) beside its repro
    artifact — one serialization, owned by the profile module."""
    from fabric_tpu.common import profile

    return profile.dump_to(path, doc)


def write_repro(path: str, plan: dict, original: dict, violations: list,
                trips: list, seed: int, index: int,
                blocks: int = DEFAULT_BLOCKS) -> str:
    """A self-contained, replayable failure artifact: arm `plan` over
    the canned workload (``replay``) and the oracle fails again."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    doc = {
        "format": REPRO_FORMAT,
        "campaign_seed": seed,
        "plan_index": index,
        "workload": {"blocks": blocks},
        "plan": plan,
        "original_plan": original,
        "violations": violations,
        "trips": trips,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def replay(repro_path: str, workdir: str) -> dict:
    """Re-arm a repro artifact's (shrunk) plan over a fresh workload
    directory; returns the run_plan result — `violations` non-empty
    means the failure reproduced."""
    with open(repro_path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("format") != REPRO_FORMAT:
        raise ValueError(f"not a faultfuzz repro artifact: {repro_path}")
    blocks = int(doc.get("workload", {}).get("blocks", DEFAULT_BLOCKS))
    return run_plan(doc["plan"], workdir, blocks=blocks)


# -- campaigns ----------------------------------------------------------------


class Campaign:
    """An N-plan chaos campaign: discovery pass, generate/run/judge per
    plan, shrink + repro artifact per failure, deterministic summary.

    The summary contains no wall-clock material, so two campaigns with
    the same (seed, plans, blocks) compare equal — the determinism
    acceptance test pins exactly that."""

    def __init__(self, seed: int = 7, plans: int = 25,
                 workdir: str | None = None, out_dir: str = ".faultfuzz",
                 blocks: int = DEFAULT_BLOCKS, shrink: bool = True,
                 comm: bool = True, trace_dir: str | None = None,
                 profile_dir: str | None = None, mutants: int = 0):
        self.seed = int(seed)
        self.plans = int(plans)
        self.workdir = workdir
        self.out_dir = out_dir
        self.blocks = blocks
        self.shrink = shrink
        self.comm = comm
        # single-edit mutants derived from each FAILING plan (0 = off,
        # the v5-compatible default): does the failure survive a
        # trigger tweak, an action swap, a dropped rule?
        self.mutants = int(mutants)
        # where failing plans' flight-recorder dumps land (next to the
        # repro JSON by default); only written while tracelens is armed
        self.trace_dir = trace_dir
        # where failing plans' profscope speedscope docs land (next to
        # the repro JSON by default); only written while profiling is
        # armed — same contract as trace_dir
        self.profile_dir = profile_dir

    def discover(self, root: str) -> dict:
        """Run the workload once under the observer plan to enumerate
        the live fault-point registry this campaign samples from."""
        faultline.reset_registry()
        with faultline.observe(), _store_env():
            _drive(os.path.join(root, "discover"), self.blocks,
                   comm=self.comm)
        return faultline.registry()

    def run(self) -> dict:
        import shutil
        import tempfile

        own_root = self.workdir is None
        root = self.workdir or tempfile.mkdtemp(prefix="faultfuzz-")
        try:
            return self._run(root)
        finally:
            if own_root:
                # a campaign leaves ~plans full ledger trees behind (a
                # nightly CI job would fill the runner's tmpfs); repro
                # artifacts live in out_dir and survive this
                shutil.rmtree(root, ignore_errors=True)

    def _run(self, root: str) -> dict:
        registry = self.discover(root)
        results = []
        ledger: list[dict] = []
        repro_paths: list[str] = []
        trace_paths: list[str] = []
        profile_paths: list[str] = []
        tripped: set = set()
        for i in range(self.plans):
            rng = random.Random(f"{self.seed}:{i}")
            label = f"fuzz:{self.seed}:{i}"
            plan = generate_plan(rng, registry, label, tripped=tripped)
            res = run_plan(
                plan, os.path.join(root, f"plan{i:03d}"),
                blocks=self.blocks, comm=self.comm,
            )
            entry: dict = {
                "index": i,
                "plan": plan,
                "verdict": "fail" if res["violations"] else "pass",
                "violations": res["violations"],
                "trips": res["trips"],
            }
            if res["violations"]:
                shrunk = plan
                if self.shrink:
                    shrink_root = os.path.join(root, f"shrink{i:03d}")
                    counter = [0]

                    def still_fails(cand):
                        counter[0] += 1
                        sub = os.path.join(
                            shrink_root, f"s{counter[0]:03d}"
                        )
                        return bool(run_plan(
                            cand, sub, blocks=self.blocks,
                            comm=self.comm,
                        )["violations"])

                    shrunk, entry["shrink_runs"] = shrink_plan(
                        plan, still_fails
                    )
                path = write_repro(
                    os.path.join(
                        self.out_dir,
                        f"repro_seed{self.seed}_plan{i:03d}.json",
                    ),
                    shrunk, plan, res["violations"], res["trips"],
                    self.seed, i, self.blocks,
                )
                entry["shrunk"] = shrunk
                entry["repro"] = path
                repro_paths.append(path)
                if res.get("trace") is not None:
                    # the ORIGINAL failing run's flight recorder, next
                    # to the repro artifact: what the pipeline was doing
                    # in the spans before the oracle violation
                    entry["trace"] = write_trace_doc(
                        os.path.join(
                            self.trace_dir or self.out_dir,
                            f"repro_seed{self.seed}_plan{i:03d}"
                            ".trace.json",
                        ),
                        res["trace"],
                    )
                    trace_paths.append(entry["trace"])
                if res.get("profile") is not None:
                    # the ORIGINAL failing run's CPU/lock profile, next
                    # to the repro artifact: where the pipeline spent
                    # its time in the run the oracle failed
                    entry["profile"] = write_profile_doc(
                        os.path.join(
                            self.profile_dir or self.out_dir,
                            f"repro_seed{self.seed}_plan{i:03d}"
                            ".profile.json",
                        ),
                        res["profile"],
                    )
                    profile_paths.append(entry["profile"])
            if res["violations"] and self.mutants:
                # single-edit mutants of the failing plan, each fully
                # seed-derived from (campaign seed, plan index, mutant
                # index) and riding the same judge/shrink/repro path
                mutant_entries = []
                for j in range(self.mutants):
                    mrng = random.Random(f"{self.seed}:{i}:m{j}")
                    mplan = mutate_plan(
                        mrng, plan, registry, f"{label}:m{j}"
                    )
                    mres = run_plan(
                        mplan, os.path.join(root, f"plan{i:03d}_m{j}"),
                        blocks=self.blocks, comm=self.comm,
                    )
                    mentry: dict = {
                        "index": j,
                        "plan": mplan,
                        "verdict":
                            "fail" if mres["violations"] else "pass",
                        "violations": mres["violations"],
                        "trips": mres["trips"],
                    }
                    if mres["violations"]:
                        mshrunk = mplan
                        if self.shrink:
                            mroot = os.path.join(
                                root, f"shrink{i:03d}_m{j}"
                            )
                            mcounter = [0]

                            def m_still_fails(cand, _mr=mroot,
                                              _mc=mcounter):
                                _mc[0] += 1
                                sub = os.path.join(
                                    _mr, f"s{_mc[0]:03d}"
                                )
                                return bool(run_plan(
                                    cand, sub, blocks=self.blocks,
                                    comm=self.comm,
                                )["violations"])

                            mshrunk, mentry["shrink_runs"] = \
                                shrink_plan(mplan, m_still_fails)
                        mpath = write_repro(
                            os.path.join(
                                self.out_dir,
                                f"repro_seed{self.seed}"
                                f"_plan{i:03d}_m{j}.json",
                            ),
                            mshrunk, mplan, mres["violations"],
                            mres["trips"], self.seed, i, self.blocks,
                        )
                        mentry["shrunk"] = mshrunk
                        mentry["repro"] = mpath
                        repro_paths.append(mpath)
                    mutant_entries.append(mentry)
                    ledger.extend(mres["trips"])
                    tripped.update(
                        t["point"] for t in mres["trips"]
                    )
                entry["mutants"] = mutant_entries
            results.append(entry)
            ledger.extend(res["trips"])
            # feed the coverage weighting: the NEXT plan prefers points
            # this campaign has not yet tripped
            tripped.update(t["point"] for t in res["trips"])
        failures = sum(1 for e in results if e["verdict"] == "fail")
        mutant_failures = sum(
            1 for e in results for m in e.get("mutants", ())
            if m["verdict"] == "fail"
        )
        return {
            "experiment": "faultfuzz",
            "seed": self.seed,
            "plans": self.plans,
            "blocks": self.blocks,
            "registry_points": len(registry),
            "verdicts": [e["verdict"] for e in results],
            "failures": failures,
            "mutants_per_failure": self.mutants,
            "mutant_failures": mutant_failures,
            "trips_total": len(ledger),
            "trip_ledger": ledger,
            "repro": repro_paths,
            "trace": trace_paths,
            "profile": profile_paths,
            "results": results,
        }


# -- chaos-coverage registry export -------------------------------------------


def export_registry(blocks: int = DEFAULT_BLOCKS, comm: bool = True) -> dict:
    """Build the pinned chaos-coverage registry that fabriclint's
    chaos-coverage rule cross-checks the static faultmap against:
    observer-plan discovery on the canned campaign workload, unioned
    with every seam some pinned plan rule in the tree (exact name or
    prefix wildcard — the bare ``"*"`` soak rule deliberately proves
    nothing) can arm.

    Only statically enumerated seams are eligible, so the registry is
    a subset of the faultmap by construction — the export can record
    coverage, never invent it.  Refresh with
    ``scripts/chaos.py --export-registry`` after adding a seam plus
    the chaos test that arms it."""
    import shutil
    import tempfile

    from . import lint as lintmod

    root = tempfile.mkdtemp(prefix="faultmap-")
    try:
        runtime = Campaign(
            seed=0, plans=0, blocks=blocks, comm=comm
        ).discover(root)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    fm = lintmod.lint_tree(cache=False).faultmap()
    seam_kinds: dict = {}
    for s in fm["seams"]:
        seam_kinds.setdefault(s["name"], set()).add(s["kind"])
    exact = set()
    prefixes = []
    for rule in fm["plans"]:
        if rule["wildcard"]:
            if rule["point"] != "*":
                prefixes.append(rule["point"][:-1])  # "x.*" -> "x."
        else:
            exact.add(rule["point"])
    points = {}
    for name, kinds in sorted(seam_kinds.items()):
        armable = (
            name in runtime
            or name in exact
            or any(name.startswith(p) for p in prefixes)
        )
        if armable:
            points[name] = {"kinds": sorted(kinds)}
    return {"points": points}


__all__ = [
    "CHANNEL",
    "DEFAULT_BLOCKS",
    "workload_writes",
    "run_plan",
    "generate_plan",
    "mutate_plan",
    "shrink_plan",
    "write_repro",
    "write_trace_doc",
    "write_profile_doc",
    "replay",
    "Campaign",
    "export_registry",
]
