"""Ledger stack tests: KV stores, block store + crash recovery, MVCC
conflict semantics, simulator rwset round trip, ledger reopen recovery
(reference test model: core/ledger/kvledger tests + blkstorage tests)."""

import os
import struct

import pytest

from fabric_tpu.ledger import (
    BlockStore,
    Height,
    KVLedger,
    LedgerProvider,
    MemKVStore,
    MVCCValidator,
    NamedDB,
    SqliteKVStore,
    TxSimulator,
    VersionedDB,
    VersionedValue,
)
from fabric_tpu.ledger.txmgmt import MVCC_READ_CONFLICT, PHANTOM_READ_CONFLICT, VALID
from fabric_tpu.protos.common import common_pb2
from fabric_tpu import protoutil


@pytest.mark.parametrize("mk", [MemKVStore, None])
def test_kvstore_contract(tmp_path, mk):
    store = mk() if mk else SqliteKVStore(str(tmp_path / "kv.sqlite"))
    store.put(b"a", b"1")
    store.write_batch({b"b": b"2", b"c": b"3"}, [])
    assert store.get(b"b") == b"2"
    assert [k for k, _ in store.iterate(b"a", b"c")] == [b"a", b"b"]
    store.delete(b"b")
    assert store.get(b"b") is None
    assert [k for k, _ in store.iterate()] == [b"a", b"c"]
    # prefixed views are disjoint
    db1, db2 = NamedDB(store, "one"), NamedDB(store, "two")
    db1.put(b"k", b"v1")
    db2.put(b"k", b"v2")
    assert db1.get(b"k") == b"v1" and db2.get(b"k") == b"v2"
    assert [k for k, _ in db1.iterate()] == [b"k"]


def _mkblock(num, prev_hash, payloads, channel="ch"):
    envs = []
    for i, p in enumerate(payloads):
        chdr = protoutil.make_channel_header(
            common_pb2.ENDORSER_TRANSACTION, channel, tx_id=f"tx-{num}-{i}"
        )
        shdr = protoutil.make_signature_header(b"creator", b"nonce%d" % i)
        envs.append(
            common_pb2.Envelope(
                payload=protoutil.make_payload_bytes(chdr, shdr, p)
            )
        )
    hdr = common_pb2.BlockHeader(number=num - 1) if num else None
    blk = common_pb2.Block()
    blk.header.number = num
    blk.header.previous_hash = prev_hash
    for env in envs:
        blk.data.data.append(env.SerializeToString())
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    protoutil.init_block_metadata(blk)
    protoutil.set_tx_filter(blk, bytearray(len(envs)))
    return blk


def test_blockstore_roundtrip_and_recovery(tmp_path):
    d = str(tmp_path / "chains")
    idx = SqliteKVStore(str(tmp_path / "idx.sqlite"))
    bs = BlockStore(d, idx)
    b0 = _mkblock(0, b"", [b"g"])
    bs.add_block(b0)
    b1 = _mkblock(1, protoutil.block_header_hash(b0.header), [b"x", b"y"])
    bs.add_block(b1)
    assert bs.height == 2
    assert bs.get_block_by_number(1).header.number == 1
    assert bs.get_block_by_hash(protoutil.block_header_hash(b1.header)).header.number == 1
    assert bs.get_tx_loc("tx-1-1") == (1, 1)
    assert bs.get_tx_by_id("tx-1-0") is not None

    # simulate a torn write: append garbage partial record
    files = sorted(os.listdir(d))
    with open(os.path.join(d, files[-1]), "ab") as f:
        f.write(struct.pack(">I", 9999) + b"partial")
    bs2 = BlockStore(d, idx)
    assert bs2.height == 2
    assert bs2.get_block_by_number(1).header.number == 1
    # can append after recovery
    b2 = _mkblock(2, protoutil.block_header_hash(b1.header), [b"z"])
    bs2.add_block(b2)
    assert bs2.get_block_by_number(2) is not None

    # recovery with a stale index (checkpoint behind the file)
    idx2 = SqliteKVStore(str(tmp_path / "idx2.sqlite"))
    bs3 = BlockStore(d, idx2)
    assert bs3.height == 3
    assert bs3.get_tx_loc("tx-2-0") == (2, 0)


def test_statedb_versions():
    db = VersionedDB(MemKVStore())
    h1 = Height(1, 0)
    db.apply_updates({"cc": {"a": VersionedValue(b"va", h1), "b": VersionedValue(b"vb", h1)}}, h1)
    assert db.get_state("cc", "a").value == b"va"
    assert db.get_version("cc", "b") == h1
    assert db.savepoint() == h1
    keys = [k for k, _ in db.get_state_range("cc", "a", "")]
    assert keys == ["a", "b"]
    db.apply_updates({"cc": {"a": None}}, Height(2, 0))
    assert db.get_state("cc", "a") is None


def test_statedb_meta_ns_stays_empty_after_plain_commits(tmp_path):
    """The metadata-namespace fast path must survive commits: a store
    this code has committed to always carries the (possibly empty)
    meta-ns key, so re-loading after apply_updates never mistakes it
    for a legacy DB with unknown history (which would permanently
    disable the per-tx key-level-endorsement skip right after
    genesis)."""
    from fabric_tpu.ledger.kvstore import SqliteKVStore

    db = VersionedDB(SqliteKVStore(str(tmp_path / "state.db")))
    h1 = Height(1, 0)
    db.apply_updates({"cc": {"a": VersionedValue(b"v", h1)}}, h1)
    assert db.may_have_metadata("cc") is False  # not True-conservative
    # a reopened store over the same files stays exact too
    db2 = VersionedDB(SqliteKVStore(str(tmp_path / "state.db")))
    assert db2.may_have_metadata("cc") is False
    # writing metadata flags exactly that namespace, durably
    h2 = Height(2, 0)
    db.apply_updates(
        {"cc2": {"k": VersionedValue(b"v", h2, metadata=b"m")}}, h2
    )
    assert db.may_have_metadata("cc2") is True
    assert db.may_have_metadata("cc") is False
    db3 = VersionedDB(SqliteKVStore(str(tmp_path / "state.db")))
    assert db3.may_have_metadata("cc2") is True
    assert db3.may_have_metadata("cc") is False
    # out-of-band merge: db3 has cached its set; db writes metadata to a
    # NEW namespace through the same store; db3's next (plain) commit
    # must not un-flag it (the persisted key merges with the store, not
    # with db3's stale cache)
    h3 = Height(3, 0)
    db.apply_updates(
        {"cc3": {"k": VersionedValue(b"v", h3, metadata=b"m")}}, h3
    )
    h4 = Height(4, 0)
    db3.apply_updates({"cc": {"b": VersionedValue(b"v", h4)}}, h4)
    db4 = VersionedDB(SqliteKVStore(str(tmp_path / "state.db")))
    assert db4.may_have_metadata("cc3") is True


def _sim_rwset(db, reads=(), writes=(), ranges=()):
    sim = TxSimulator(db)
    for ns, k in reads:
        sim.get_state(ns, k)
    for ns, s, e in ranges:
        sim.get_state_range(ns, s, e)
    for ns, k, v in writes:
        sim.set_state(ns, k, v)
    return sim.get_tx_simulation_results()


def test_mvcc_validation_semantics():
    db = VersionedDB(MemKVStore())
    mvcc = MVCCValidator(db)
    h = Height(1, 0)
    db.apply_updates({"cc": {"k": VersionedValue(b"v1", h)}}, h)

    # tx0 reads k@h and writes k -> valid
    # tx1 reads k@h again -> MVCC conflict with tx0's write in same block
    # tx2 reads fresh key (absent) -> valid
    rw0 = _sim_rwset(db, reads=[("cc", "k")], writes=[("cc", "k", b"v2")])
    rw1 = _sim_rwset(db, reads=[("cc", "k")], writes=[("cc", "x", b"y")])
    rw2 = _sim_rwset(db, reads=[("cc", "absent")], writes=[("cc", "n", b"1")])
    flags = [VALID, VALID, VALID]
    batch = mvcc.validate_and_prepare(2, [rw0, rw1, rw2], flags)
    assert flags == [VALID, MVCC_READ_CONFLICT, VALID]
    assert batch["cc"]["k"].value == b"v2"
    assert batch["cc"]["k"].version == Height(2, 0)
    assert "x" not in batch["cc"]  # invalid tx contributes no writes
    db.apply_updates(batch, Height(2, 2))

    # stale read from before block 2 now conflicts against committed state
    flags = [VALID]
    mvcc.validate_and_prepare(3, [rw1], flags)
    assert flags == [MVCC_READ_CONFLICT]


def test_mvcc_phantom_detection():
    db = VersionedDB(MemKVStore())
    mvcc = MVCCValidator(db)
    h = Height(1, 0)
    db.apply_updates(
        {"cc": {"a1": VersionedValue(b"1", h), "a3": VersionedValue(b"3", h)}}, h
    )
    # tx0 range-scans [a1, a9); tx1 also scanned but tx0 inserts a2 first
    rw0 = _sim_rwset(db, ranges=[("cc", "a1", "a9")], writes=[("cc", "a2", b"2")])
    rw1 = _sim_rwset(db, ranges=[("cc", "a1", "a9")], writes=[("cc", "b", b"x")])
    flags = [VALID, VALID]
    mvcc.validate_and_prepare(2, [rw0, rw1], flags)
    assert flags == [VALID, PHANTOM_READ_CONFLICT]


def _endorsed_block(num, prev, rwsets, channel="ch"):
    """Build a block of endorser txs whose ChaincodeAction.results are the
    given rwset bytes."""
    from fabric_tpu.protos.peer import (
        proposal_pb2,
        proposal_response_pb2,
        transaction_pb2,
    )

    envs = []
    for i, rw in enumerate(rwsets):
        action = proposal_pb2.ChaincodeAction(results=rw)
        prp = proposal_response_pb2.ProposalResponsePayload(
            proposal_hash=b"\x00" * 32, extension=action.SerializeToString()
        )
        cap = transaction_pb2.ChaincodeActionPayload(
            action=transaction_pb2.ChaincodeEndorsedAction(
                proposal_response_payload=prp.SerializeToString()
            )
        )
        tx = transaction_pb2.Transaction(
            actions=[transaction_pb2.TransactionAction(payload=cap.SerializeToString())]
        )
        chdr = protoutil.make_channel_header(
            common_pb2.ENDORSER_TRANSACTION, channel, tx_id=f"tx-{num}-{i}"
        )
        shdr = protoutil.make_signature_header(b"creator", b"nonce")
        envs.append(
            common_pb2.Envelope(
                payload=protoutil.make_payload_bytes(chdr, shdr, tx.SerializeToString())
            )
        )
    blk = common_pb2.Block()
    blk.header.number = num
    blk.header.previous_hash = prev
    for env in envs:
        blk.data.data.append(env.SerializeToString())
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    protoutil.init_block_metadata(blk)
    protoutil.set_tx_filter(blk, bytearray(len(envs)))
    return blk


def test_kvledger_commit_query_history_and_recovery(tmp_path):
    prov = LedgerProvider(str(tmp_path))
    ledger = prov.open("ch")
    db = VersionedDB(MemKVStore())  # scratch db for building rwsets
    rw_g = _sim_rwset(db, writes=[("cc", "k", b"v0")])
    b0 = _endorsed_block(0, b"", [rw_g])
    ledger.commit(b0)
    assert ledger.get_state("cc", "k") == b"v0"

    sim = ledger.new_tx_simulator()
    assert sim.get_state("cc", "k") == b"v0"
    sim.set_state("cc", "k", b"v1")
    sim.set_state("cc", "k2", b"w")
    rw1 = sim.get_tx_simulation_results()
    b1 = _endorsed_block(1, ledger._blocks.last_block_hash, [rw1])
    ledger.commit(b1)
    assert ledger.get_state("cc", "k") == b"v1"
    assert ledger.get_tx_validation_code("tx-1-0") == VALID
    assert ledger.tx_id_exists("tx-0-0")
    assert not ledger.tx_id_exists("nope")
    assert ledger.get_history_for_key("cc", "k") == [(0, 0), (1, 0)]

    prov.close()
    # reopen: block store + state recover from disk
    prov2 = LedgerProvider(str(tmp_path))
    led2 = prov2.open("ch")
    assert led2.height == 2
    assert led2.get_state("cc", "k") == b"v1"
    assert led2.get_history_for_key("cc", "k2") == [(1, 0)]
    prov2.close()
