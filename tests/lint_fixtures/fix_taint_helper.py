"""Helper half of the cross-function taint fixture: its parameter flows
into a protoutil marshal, so the engine must summarize param 0 as
sink-flowing — the helper itself is NOT a violation."""

from fabric_tpu import protoutil


def marshal_at(ts):
    return protoutil.make_channel_header(3, "tx", "ch", timestamp=ts)
