"""peer channel create/update/signconfigtx + node pause CLI flows."""

from __future__ import annotations

import os

import pytest

from orgfix import make_org

from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.msp import msp_config_from_ca
from fabric_tpu.node.orderer_node import OrdererNode
from fabric_tpu.protos.common import common_pb2, configtx_pb2


@pytest.fixture
def world(tmp_path):
    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))}
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("clich", ctx.channel_group(app, ordg))
    node = OrdererNode(str(tmp_path / "orderer"), org.csp, signer=None)
    node.start()
    yield org, genesis, node, tmp_path
    node.stop()


def test_channel_create_via_participation(world):
    from fabric_tpu.cmd.peer import main

    org, genesis, node, tmp_path = world
    gpath = str(tmp_path / "clich.block")
    with open(gpath, "wb") as f:
        f.write(genesis.SerializeToString())
    rc = main([
        "channel", "create", "-f", gpath,
        "--orderer", "%s:%d" % node.addr,
    ])
    assert rc == 0


def test_signconfigtx_appends_signature(world, tmp_path):
    from fabric_tpu.cmd.peer import main

    org, genesis, node, base = world
    # write the org's MSP dir for load_signer
    mspdir = tmp_path / "msp"
    pair = org.issue("admin1", ous=["admin"])
    os.makedirs(mspdir / "signcerts")
    os.makedirs(mspdir / "keystore")
    (mspdir / "signcerts" / "cert.pem").write_bytes(pair.cert_pem)
    (mspdir / "keystore" / "key.pem").write_bytes(pair.key_pem)

    cue = configtx_pb2.ConfigUpdateEnvelope(config_update=b"update-bytes")
    payload = common_pb2.Payload(data=cue.SerializeToString())
    env = common_pb2.Envelope(payload=payload.SerializeToString())
    fpath = str(tmp_path / "update.pb")
    with open(fpath, "wb") as f:
        f.write(env.SerializeToString())

    rc = main([
        "channel", "signconfigtx", "-f", fpath,
        "--mspid", "Org1MSP", "--msp-dir", str(mspdir),
    ])
    assert rc == 0
    env2 = common_pb2.Envelope.FromString(open(fpath, "rb").read())
    p2 = common_pb2.Payload.FromString(env2.payload)
    cue2 = configtx_pb2.ConfigUpdateEnvelope.FromString(p2.data)
    assert len(cue2.signatures) == 1
    assert cue2.signatures[0].signature
    assert env2.signature  # envelope re-signed by the signer
    # signing twice appends a second signature
    rc = main([
        "channel", "signconfigtx", "-f", fpath,
        "--mspid", "Org1MSP", "--msp-dir", str(mspdir),
    ])
    assert rc == 0
    env3 = common_pb2.Envelope.FromString(open(fpath, "rb").read())
    cue3 = configtx_pb2.ConfigUpdateEnvelope.FromString(
        common_pb2.Payload.FromString(env3.payload).data
    )
    assert len(cue3.signatures) == 2
