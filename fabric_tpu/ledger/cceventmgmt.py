"""Chaincode lifecycle event management (reference
core/ledger/cceventmgmt): listeners — state-db index builders, the
lifecycle cache — are notified when a chaincode definition is committed
to a channel or a package matching a committed definition is installed.
"""

from __future__ import annotations

import dataclasses
import threading

from fabric_tpu.common.flogging import must_get_logger


@dataclasses.dataclass(frozen=True)
class ChaincodeDefinitionEvent:
    channel_id: str
    name: str
    version: str
    sequence: int


class ChaincodeEventMgr:
    """Singleton-style registry (reference cceventmgmt.GetMgr): the
    committer calls `handle_definition_committed` after a block carrying
    a _lifecycle commit lands; install flows call `handle_installed`."""

    def __init__(self):
        self._listeners: dict[str, list] = {}
        self._global: list = []
        self._lock = threading.Lock()

    def register(self, channel_id: str | None, listener) -> None:
        """listener(event) -> None; channel_id None = all channels."""
        with self._lock:
            if channel_id is None:
                self._global.append(listener)
            else:
                self._listeners.setdefault(channel_id, []).append(listener)

    def _fire(self, event: ChaincodeDefinitionEvent) -> None:
        with self._lock:
            targets = list(self._global) + list(
                self._listeners.get(event.channel_id, [])
            )
        for fn in targets:
            try:
                fn(event)
            except Exception as exc:
                # listener errors never poison the commit path — but they
                # are logged, not swallowed
                must_get_logger("ledger.cceventmgmt").warning(
                    "chaincode-event listener %r failed: %s", fn, exc
                )

    def handle_definition_committed(
        self, channel_id: str, name: str, version: str, sequence: int
    ) -> None:
        self._fire(
            ChaincodeDefinitionEvent(channel_id, name, version, sequence)
        )

    def handle_installed(self, channel_id: str, name: str,
                         version: str) -> None:
        self._fire(ChaincodeDefinitionEvent(channel_id, name, version, 0))


__all__ = ["ChaincodeEventMgr", "ChaincodeDefinitionEvent"]
