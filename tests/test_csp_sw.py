"""Unit tests for the sw CSP provider.

Mirrors the reference's bccsp/sw tests (bccsp/sw/ecdsa_test.go,
impl_test.go): sign/verify roundtrip, low-S enforcement, DER edge cases,
keystore by SKI.
"""

import hashlib

import pytest

from fabric_tpu.csp import api
from fabric_tpu.csp.sw import SWCSP


@pytest.fixture()
def csp():
    return SWCSP()


def test_sign_verify_roundtrip(csp):
    key = csp.key_gen()
    digest = csp.hash(b"hello fabric-tpu")
    sig = csp.sign(key, digest)
    assert csp.verify(key, sig, digest)
    assert csp.verify(key.public_key(), sig, digest)


def test_verify_rejects_wrong_digest(csp):
    key = csp.key_gen()
    sig = csp.sign(key, csp.hash(b"msg"))
    assert not csp.verify(key, sig, csp.hash(b"other"))


def test_sign_always_low_s(csp):
    key = csp.key_gen()
    for i in range(20):
        sig = csp.sign(key, csp.hash(b"m%d" % i))
        _, s = api.unmarshal_ecdsa_signature(sig)
        assert api.is_low_s(s)


def test_verify_rejects_high_s(csp):
    # Reference behavior: a mathematically valid but high-S signature fails
    # (bccsp/sw/ecdsa.go:41-52).
    key = csp.key_gen()
    digest = csp.hash(b"msg")
    sig = csp.sign(key, digest)
    r, s = api.unmarshal_ecdsa_signature(sig)
    high = api.marshal_ecdsa_signature(r, api.P256_N - s)
    assert not api.is_low_s(api.P256_N - s)
    assert not csp.verify(key, high, digest)


def test_verify_rejects_garbage_der(csp):
    key = csp.key_gen()
    digest = csp.hash(b"msg")
    assert not csp.verify(key, b"", digest)
    assert not csp.verify(key, b"\x30\x02\x01\x00", digest)
    assert not csp.verify(key, b"\xff" * 70, digest)


def test_ski_stable_and_key_lookup(csp):
    key = csp.key_gen()
    ski = key.ski()
    assert len(ski) == 32
    assert csp.get_key(ski).ski() == ski
    pub = key.public_key()
    assert pub.ski() == ski
    # re-import public key raw point -> same SKI
    imported = csp.key_import(pub.raw())
    assert imported.ski() == ski


def test_key_import_der_and_point(csp):
    key = csp.key_gen()
    pub = key.public_key()
    by_der = csp.key_import(pub.der())
    assert by_der.ski() == pub.ski()


def test_hash_batch_matches_hashlib(csp):
    msgs = [b"a", b"b" * 100, b"", b"c" * 1000]
    assert csp.hash_batch(msgs) == [hashlib.sha256(m).digest() for m in msgs]


def test_verify_batch_mask_semantics(csp):
    # The batch API must return a per-item mask, not all-or-nothing
    # (SURVEY.md section 7 hard part #4).
    keys = [csp.key_gen() for _ in range(4)]
    digests = [csp.hash(b"m%d" % i) for i in range(4)]
    sigs = [csp.sign(k, d) for k, d in zip(keys, digests)]
    items = [
        api.VerifyBatchItem(k.public_key(), d, s)
        for k, d, s in zip(keys, digests, sigs)
    ]
    # corrupt item 2: signature over different digest
    items[2] = api.VerifyBatchItem(
        keys[2].public_key(), csp.hash(b"tampered"), sigs[2]
    )
    assert csp.verify_batch(items) == [True, True, False, True]


def test_der_marshal_roundtrip():
    r, s = 12345678901234567890, 98765432109876543210
    der = api.marshal_ecdsa_signature(r, s)
    assert api.unmarshal_ecdsa_signature(der) == (r, s)


def test_to_low_s():
    assert api.to_low_s(api.P256_HALF_N) == api.P256_HALF_N
    assert api.to_low_s(api.P256_HALF_N + 1) == api.P256_N - api.P256_HALF_N - 1


class TestKeystores:
    def test_file_keystore_persists_across_instances(self, tmp_path):
        from fabric_tpu.csp import FileKeyStore, SWCSP

        ks_dir = str(tmp_path / "keystore")
        sw1 = SWCSP(keystore=FileKeyStore(ks_dir))
        key = sw1.key_gen()
        ski = key.ski()
        # a fresh provider over the same directory finds the key
        sw2 = SWCSP(keystore=FileKeyStore(ks_dir))
        import hashlib
        d = hashlib.sha256(b"persisted").digest()
        sig = sw2.sign(sw2.get_key(ski), d)
        assert sw1.verify(key, sig, d)

    def test_file_keystore_permissions_and_mismatch(self, tmp_path):
        import os

        from fabric_tpu.csp import FileKeyStore, SWCSP

        ks_dir = str(tmp_path / "ks")
        ks = FileKeyStore(ks_dir)
        sw = SWCSP(keystore=ks)
        key = sw.key_gen()
        sk = os.path.join(ks_dir, key.ski().hex() + "_sk.pem")
        assert os.path.exists(sk)
        assert oct(os.stat(sk).st_mode & 0o777) == "0o600"
        assert oct(os.stat(ks_dir).st_mode & 0o777) == "0o700"
        # a file renamed under the wrong SKI is rejected
        other = SWCSP().key_gen()
        bogus = os.path.join(ks_dir, other.ski().hex() + "_sk.pem")
        os.rename(sk, bogus)
        ks2 = FileKeyStore(ks_dir)
        try:
            ks2.get_key(other.ski())
            raise AssertionError("SKI mismatch must be rejected")
        except KeyError:
            pass

    def test_read_only_keystore_refuses_store(self, tmp_path):
        from fabric_tpu.csp import FileKeyStore, SWCSP

        ks = FileKeyStore(str(tmp_path / "ro"), read_only=True)
        sw = SWCSP(keystore=ks)
        try:
            sw.key_gen()
            raise AssertionError("read-only keystore must refuse stores")
        except PermissionError:
            pass

    def test_dummy_keystore(self):
        from fabric_tpu.csp import DummyKeyStore, SWCSP

        sw = SWCSP(keystore=DummyKeyStore())
        key = sw.key_gen()  # store is a no-op
        try:
            sw.get_key(key.ski())
            raise AssertionError("dummy keystore must hold nothing")
        except KeyError:
            pass

    def test_csp_from_config_selects_keystore_and_provider(self, tmp_path):
        from fabric_tpu.common.config import Config
        from fabric_tpu.csp import FileKeyStore, SWCSP, csp_from_config

        ks_dir = str(tmp_path / "cfgks")
        cfg = Config(
            {
                "bccsp": {
                    "default": "SW",
                    "sw": {"fileKeyStore": {"keyStorePath": ks_dir}},
                }
            }
        )
        csp = csp_from_config(cfg)
        assert isinstance(csp, SWCSP)
        key = csp.key_gen()
        # restart: a second config-built CSP reuses the persisted key
        csp2 = csp_from_config(cfg)
        assert csp2.get_key(key.ski()).ski() == key.ski()
        # empty path -> in-memory
        csp3 = csp_from_config(Config({"bccsp": {"default": "SW"}}))
        assert isinstance(csp3._ks, type(SWCSP()._ks))
