"""Structured-error regressions (ISSUE 3 satellites).

PR 2 established the direction with ERR_UNKNOWN_SKI: a failure on the
validation path must carry WHY, not vanish into a bare False/None.
These tests pin the two spots this PR converted from silent `except
Exception: pass` swallows — policies/manager.py's RejectPolicy and
peer/validation_plugins.py's _FailPending / PolicyProvider parsers — so
a refactor cannot quietly reintroduce the swallow (fabriclint's
exception-discipline rule guards the shape; these guard the semantics).
"""

import logging

import pytest

from fabric_tpu.peer.validation_plugins import PolicyProvider, _FailPending
from fabric_tpu.policies.manager import (
    RejectPolicy,
    manager_from_config_group,
)
from fabric_tpu.protos.common import configtx_pb2, policies_pb2

# invalid protobuf: wire type 7 is reserved, FromString always raises
GARBAGE = b"\xff\xff\xff\xff"


def _group_with_policy(name: str, ptype: int, value: bytes):
    group = configtx_pb2.ConfigGroup()
    group.policies[name].policy.type = ptype
    group.policies[name].policy.value = value
    return group


class _NeverCSP:
    """A CSP whose verify_batch must not be reached: reject paths
    carry zero batch items."""

    def verify_batch(self, items):
        assert not list(items), "reject policy produced verify work"
        return []


def test_unparsable_signature_policy_becomes_structured_reject():
    group = _group_with_policy(
        "Admins", policies_pb2.Policy.SIGNATURE, GARBAGE
    )
    mgr = manager_from_config_group("Channel", group, deserializer=None)
    pol = mgr.get_policy("Admins")
    assert isinstance(pol, RejectPolicy)
    assert "unparsable SIGNATURE policy" in pol.reason
    # fails closed, with no verify items handed to the CSP
    assert pol.evaluate_signed_data([], _NeverCSP()) is False
    pending = pol.prepare([])
    assert pending.items == []
    assert pending.finish([]) is False


def test_unsupported_policy_type_reason():
    group = _group_with_policy("Odd", 99, b"")
    mgr = manager_from_config_group("Channel", group, deserializer=None)
    pol = mgr.get_policy("Odd")
    assert isinstance(pol, RejectPolicy)
    assert "unsupported policy type 99" in pol.reason


def test_implicit_meta_over_zero_subpolicies_reason():
    meta = policies_pb2.ImplicitMetaPolicy()
    meta.sub_policy = "Writers"
    meta.rule = policies_pb2.ImplicitMetaPolicy.ANY
    group = _group_with_policy(
        "Writers", policies_pb2.Policy.IMPLICIT_META,
        meta.SerializeToString(),
    )
    mgr = manager_from_config_group("Channel", group, deserializer=None)
    pol = mgr.get_policy("Writers")
    assert isinstance(pol, RejectPolicy)
    assert "zero sub-policies" in pol.reason


def test_missing_policy_default_reason():
    assert "not defined" in RejectPolicy("Readers").reason


@pytest.fixture()
def validation_log():
    """Capture fabric_tpu's validation logger directly: flogging's
    package root has propagate=False, so caplog's root handler never
    sees these records."""
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("fabric_tpu.peer.validation")
    handler = _Capture(level=logging.WARNING)
    logger.addHandler(handler)
    yield records
    logger.removeHandler(handler)


def test_fail_pending_carries_and_logs_reason(validation_log):
    pending = _FailPending("tx rwset for namespace 'cc' does not parse")
    assert pending.finish([]) is False
    assert pending.items == []
    assert "does not parse" in pending.reason
    assert any("validation action rejected" in m for m in validation_log)


def test_policy_provider_logs_unparsable_envelope(validation_log):
    provider = PolicyProvider(policy_manager=None, deserializer=None)
    pol = provider.from_signature_policy_bytes(GARBAGE)
    assert pol is None
    assert any("SignaturePolicyEnvelope" in m for m in validation_log)


def test_policy_provider_logs_unparsable_application_policy(validation_log):
    provider = PolicyProvider(policy_manager=None, deserializer=None)
    pol = provider.from_application_policy_bytes(GARBAGE)
    assert pol is None
    assert any("ApplicationPolicy" in m for m in validation_log)


def test_missing_cryptography_import_error_is_actionable():
    """On a minimal host the provider names must fail with an error that
    NAMES the missing dependency, not a bare 'cannot import name'."""
    import importlib.util

    if importlib.util.find_spec("cryptography") is not None:
        pytest.skip("cryptography installed; minimal-host path inactive")
    with pytest.raises(ImportError, match="cryptography"):
        from fabric_tpu.csp import SWCSP  # noqa: F401

def test_policy_provider_distinguishes_resolution_failure(validation_log):
    """A well-formed ApplicationPolicy whose channel-config reference
    cannot be resolved must not be reported as unparsable BYTES — the
    operator would debug a proto-encoding problem that doesn't exist."""
    from fabric_tpu.protos.peer import collection_pb2

    ap = collection_pb2.ApplicationPolicy(
        channel_config_policy_reference="/Channel/Application/Endorsement"
    )
    provider = PolicyProvider(policy_manager=None, deserializer=None)
    assert provider.from_application_policy_bytes(
        ap.SerializeToString()
    ) is None
    assert any("could not be resolved" in m for m in validation_log)
    assert not any("unparsable" in m for m in validation_log)
