"""Transient store: endorsement-time private-data staging.

Reference: core/transientstore/store.go — endorsers persist the cleartext
private write sets they produced (or received from other endorsers) keyed
by (txid, uuid, endorsement-block-height); the committer consumes them at
commit time and purges entries below a height watermark or by txid.
"""

from __future__ import annotations

import threading
import uuid as uuid_mod

from fabric_tpu.ledger.kvstore import KVStore, NamedDB


def _key(txid: str, height: int, uid: str) -> bytes:
    return b"%s\x00%016x\x00%s" % (txid.encode(), height, uid.encode())


class TransientStore:
    def __init__(self, kv: KVStore, ledger_id: str):
        self._db = NamedDB(kv, f"transient/{ledger_id}")
        self._lock = threading.Lock()

    def persist(self, txid: str, block_height: int, pvt_bytes: bytes) -> None:
        """Store one TxPvtReadWriteSet observed at endorsement height
        (reference store.go Persist)."""
        uid = uuid_mod.uuid4().hex
        with self._lock:
            self._db.put(_key(txid, block_height, uid), pvt_bytes)

    def get_tx_pvt_rwsets(self, txid: str) -> list[tuple[int, bytes]]:
        """All stored (endorsement_height, pvt_bytes) for a txid
        (reference GetTxPvtRWSetByTxid scanner)."""
        prefix = txid.encode() + b"\x00"
        out = []
        with self._lock:
            for key, value in self._db.iterate(prefix, prefix + b"\xff"):
                parts = key.split(b"\x00")
                out.append((int(parts[1], 16), value))
        return out

    def purge_by_txids(self, txids) -> None:
        """Remove entries for committed txs (reference PurgeByTxids)."""
        with self._lock:
            deletes = []
            for txid in txids:
                prefix = txid.encode() + b"\x00"
                deletes.extend(
                    key for key, _ in self._db.iterate(prefix, prefix + b"\xff")
                )
            if deletes:
                self._db.write_batch({}, deletes)

    def purge_below_height(self, height: int) -> None:
        """Drop entries endorsed below `height` (reference
        PurgeBelowHeight — reclaims data for txs that never committed)."""
        with self._lock:
            deletes = []
            for key, _ in self._db.iterate():
                parts = key.split(b"\x00")
                if len(parts) >= 2 and int(parts[1], 16) < height:
                    deletes.append(key)
            if deletes:
                self._db.write_batch({}, deletes)

    def min_height(self) -> int | None:
        with self._lock:
            heights = [
                int(key.split(b"\x00")[1], 16)
                for key, _ in self._db.iterate()
            ]
        return min(heights) if heights else None


__all__ = ["TransientStore"]
