"""Follower and inactive chains (reference orderer/consensus/follower +
orderer/consensus/inactive).

A node listed in a channel's config but NOT in its consenter set runs a
`FollowerChain`: it pulls blocks from the cluster (the onboarding
BlockPuller) and appends them to the local ledger until a config block
adds the node to the consenter set — then it halts so the registrar can
start the real consenter chain (reference follower_chain.go:15-31, a
skeleton in the snapshot; the pull loop matches
orderer/common/cluster/replication.go semantics).

`InactiveChain` is the placeholder registered for channels this node
tracks but does not serve: every `order`/`configure` fails with
NotServiced until activation (reference inactive/inactive_chain.go).
"""

from __future__ import annotations

import threading

from fabric_tpu.devtools.lockwatch import spawn_thread

from fabric_tpu.protos.common import common_pb2


class NotServicedError(Exception):
    """Raised for submissions to a channel this node does not service."""


class InactiveChain:
    """Reference inactive.Chain: errors until the chain is activated."""

    def __init__(self, channel_id: str):
        self.channel_id = channel_id

    def start(self) -> None:
        pass

    def halt(self) -> None:
        pass

    def wait_ready(self) -> None:
        raise NotServicedError(f"channel {self.channel_id!r} is not serviced")

    def order(self, env: common_pb2.Envelope, config_seq: int = 0) -> None:
        raise NotServicedError(f"channel {self.channel_id!r} is not serviced")

    def configure(self, env: common_pb2.Envelope, config_seq: int = 0) -> None:
        raise NotServicedError(f"channel {self.channel_id!r} is not serviced")

    def errored(self):
        return NotServicedError(self.channel_id)


class FollowerChain:
    """Pull blocks while outside the consenter set; signal when joined.

    puller: callable(height:int) -> Block | None — fetch the block at
        `height` from some cluster member (cluster onboarding transport).
    writer: callable(Block) -> None — append to the local ledger.
    in_consenter_set: callable(Block) -> bool — config-block predicate;
        when True the follower stops and `joined` is set so the
        registrar can switch to a consenter chain.
    """

    def __init__(self, channel_id: str, height, puller, writer,
                 in_consenter_set, poll_interval_s: float = 0.2):
        self.channel_id = channel_id
        self._height = height
        self._puller = puller
        self._writer = writer
        self._in_set = in_consenter_set
        self._poll = poll_interval_s
        self._stop = threading.Event()
        self.joined = threading.Event()
        self._thread: threading.Thread | None = None

    # consensus SPI: a follower accepts no submissions
    def wait_ready(self) -> None:
        raise NotServicedError(
            f"channel {self.channel_id!r}: this node is a follower"
        )

    order = InactiveChain.order
    configure = InactiveChain.configure

    def errored(self):
        return None

    def start(self) -> None:
        self._thread = spawn_thread(
            target=self._run, name=f"follower-{self.channel_id}",
            kind="service",
        )
        self._thread.start()

    def halt(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    @property
    def height(self) -> int:
        return self._height

    def _run(self) -> None:
        while not self._stop.is_set():
            blk = None
            try:
                blk = self._puller(self._height)
            except Exception:
                blk = None  # transient pull failure: retry after poll
            if blk is None:
                self._stop.wait(self._poll)
                continue
            self._writer(blk)
            self._height += 1
            if self._is_config(blk) and self._in_set(blk):
                self.joined.set()
                return

    @staticmethod
    def _is_config(blk: common_pb2.Block) -> bool:
        try:
            env = common_pb2.Envelope.FromString(blk.data.data[0])
            payload = common_pb2.Payload.FromString(env.payload)
            chdr = common_pb2.ChannelHeader.FromString(
                payload.header.channel_header
            )
            return chdr.type == common_pb2.CONFIG
        except Exception:
            return False


__all__ = ["FollowerChain", "InactiveChain", "NotServicedError"]
