"""X.509 identities (reference msp/identities.go).

`Identity.verify` is the single-signature call the reference issues per
endorsement (msp/identities.go:169-196: hash then bccsp.Verify).  The TPU
build adds `verification_item` so callers can *collect* instead of verify —
the whole block's items go to one `CSP.verify_batch` call (SURVEY.md §3.4).
"""

from __future__ import annotations

from cryptography import x509
from cryptography.hazmat.primitives import serialization
from cryptography.x509.oid import NameOID

from fabric_tpu.common.hashing import sha256 as _sha256
from fabric_tpu.csp import api as csp_api
from fabric_tpu.csp.api import ECDSAP256PublicKey, VerifyBatchItem
from fabric_tpu.protos.msp import identities_pb2


def cert_pubkey(cert: x509.Certificate) -> ECDSAP256PublicKey:
    der = cert.public_key().public_bytes(
        serialization.Encoding.DER, serialization.PublicFormat.SubjectPublicKeyInfo
    )
    return ECDSAP256PublicKey.from_der(der)


def cert_ous(cert: x509.Certificate) -> list[str]:
    return [
        a.value
        for a in cert.subject.get_attributes_for_oid(NameOID.ORGANIZATIONAL_UNIT_NAME)
    ]


class Identity:
    """A deserialized, not-necessarily-valid identity bound to its MSP."""

    def __init__(self, mspid: str, cert: x509.Certificate, csp):
        self.mspid = mspid
        self.cert = cert
        self._csp = csp
        self.public_key = cert_pubkey(cert)
        der = cert.public_bytes(serialization.Encoding.DER)
        # IdentityIdentifier: (mspid, hash of the raw cert) — reference
        # msp/mspimpl.go getIdentityFromConf.
        self.id = (mspid, _sha256(der).hex())
        self.ous = cert_ous(cert)

    def serialize(self) -> bytes:
        # memoized: the hot path (policy evaluation, cache keys) calls
        # this per endorsement and certs are immutable
        cached = getattr(self, "_serialized", None)
        if cached is None:
            cached = identities_pb2.SerializedIdentity(
                mspid=self.mspid,
                id_bytes=self.cert.public_bytes(serialization.Encoding.PEM),
            ).SerializeToString()
            self._serialized = cached
        return cached

    def expires_at(self):
        return self.cert.not_valid_after_utc

    # -- verification ------------------------------------------------------

    def verify(self, msg: bytes, sig: bytes) -> bool:
        """Hash + verify (single call; hot paths use verification_item)."""
        return self._csp.verify(self.public_key, sig, self._csp.hash(msg))

    def verification_item(self, msg: bytes, sig: bytes) -> VerifyBatchItem:
        """Deferred-verification triple for CSP.verify_batch."""
        return VerifyBatchItem(self.public_key, _sha256(msg), sig)


class SigningIdentity(Identity):
    def __init__(self, mspid: str, cert: x509.Certificate, private_key, csp):
        super().__init__(mspid, cert, csp)
        self._key = private_key  # csp_api.ECDSAP256PrivateKey

    def sign(self, msg: bytes) -> bytes:
        return self._csp.sign(self._key, self._csp.hash(msg))

    @classmethod
    def from_pem(cls, mspid: str, cert_pem: bytes, key_pem: bytes, csp):
        cert = x509.load_pem_x509_certificates(cert_pem)[0]
        key = csp_api.ECDSAP256PrivateKey.from_pem(key_pem)
        return cls(mspid, cert, key, csp)


__all__ = ["Identity", "SigningIdentity", "cert_pubkey", "cert_ous"]
