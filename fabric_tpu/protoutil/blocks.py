"""Block construction & hashing (reference protoutil/blockutils.go).

Header hashing is the consensus-critical part: the reference hashes the
DER (ASN.1) encoding of (Number, PreviousHash, DataHash) so independent
implementations agree byte-for-byte; we implement the same encoding with a
minimal DER writer (no external asn1 dependency).
"""

from __future__ import annotations

from fabric_tpu.common.hashing import sha256 as _sha256
from fabric_tpu.protos.common import common_pb2


def _der_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _der_integer(v: int) -> bytes:
    if v == 0:
        body = b"\x00"
    else:
        body = v.to_bytes((v.bit_length() + 8) // 8, "big")  # extra byte if MSB set
        if len(body) > 1 and body[0] == 0 and body[1] < 0x80:
            body = body[1:]
    return b"\x02" + _der_len(len(body)) + body


def _der_octets(b: bytes) -> bytes:
    return b"\x04" + _der_len(len(b)) + b


def block_header_bytes(header: common_pb2.BlockHeader) -> bytes:
    """ASN.1 SEQUENCE { number INTEGER, previous_hash OCTET STRING,
    data_hash OCTET STRING } — deterministic across implementations."""
    body = (
        _der_integer(header.number)
        + _der_octets(header.previous_hash)
        + _der_octets(header.data_hash)
    )
    return b"\x30" + _der_len(len(body)) + body


def block_header_hash(header: common_pb2.BlockHeader) -> bytes:
    # through the CSP hash seam: a TPU default provider digests headers
    # alongside the rest of the block's crypto, and the call site stays
    # visible to hash_batch batching (fabriclint csp-seam)
    return _sha256(block_header_bytes(header))


def block_data_hash(data: common_pb2.BlockData) -> bytes:
    """SHA-256 over the concatenation of the serialized envelopes."""
    return _sha256(b"".join(data.data))


def init_block_metadata(block: common_pb2.Block) -> None:
    while len(block.metadata.metadata) <= common_pb2.COMMIT_HASH:
        block.metadata.metadata.append(b"")


def new_block(seq: int, previous_hash: bytes) -> common_pb2.Block:
    blk = common_pb2.Block()
    blk.header.number = seq
    blk.header.previous_hash = previous_hash
    init_block_metadata(blk)
    return blk


def create_next_block(prev_header: common_pb2.BlockHeader, envelopes) -> common_pb2.Block:
    blk = new_block(prev_header.number + 1, block_header_hash(prev_header))
    for env in envelopes:
        blk.data.data.append(env.SerializeToString())
    blk.header.data_hash = block_data_hash(blk.data)
    return blk


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def serialize_block(
    block: common_pb2.Block, env_bytes=None
) -> bytes:
    """Serialize a Block by splicing its three fields instead of
    re-encoding the whole message: the envelope byte strings are stored
    verbatim inside BlockData, so the (megabytes of) data field is a
    pure framing exercise — ~7x faster than Message.SerializeToString
    on a 1000-tx block, byte-identical output (fields emitted in field
    order, exactly like upb).  `env_bytes` may pass an already
    materialized list of the envelope bytes (each repeated-field access
    copies); commit paths that walked the block earlier reuse theirs."""
    parts: list = []
    if block.HasField("header"):
        hb = block.header.SerializeToString()
        parts += [b"\x0a", _varint(len(hb)), hb]
    if block.HasField("data"):
        if env_bytes is None:
            env_bytes = block.data.data
        dparts: list = []
        ap = dparts.append
        for env in env_bytes:
            ap(b"\x0a")
            ap(_varint(len(env)))
            ap(env)
        db = b"".join(dparts)
        parts += [b"\x12", _varint(len(db)), db]
    if block.HasField("metadata"):
        mb = block.metadata.SerializeToString()
        parts += [b"\x1a", _varint(len(mb)), mb]
    return b"".join(parts)


def extract_envelope(block: common_pb2.Block, idx: int) -> common_pb2.Envelope:
    return common_pb2.Envelope.FromString(block.data.data[idx])


def tx_filter(block: common_pb2.Block) -> bytearray:
    """The per-tx validation-code byte array in block metadata
    (BlockMetadataIndex.TRANSACTIONS_FILTER)."""
    init_block_metadata(block)
    raw = block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER]
    if len(raw) != len(block.data.data):
        return bytearray(len(block.data.data))
    return bytearray(raw)


def set_tx_filter(block: common_pb2.Block, flags) -> None:
    init_block_metadata(block)
    block.metadata.metadata[common_pb2.TRANSACTIONS_FILTER] = bytes(flags)


def get_last_config_index(block: common_pb2.Block) -> int:
    meta = common_pb2.Metadata.FromString(
        block.metadata.metadata[common_pb2.SIGNATURES]
    )
    if not meta.value:
        return 0
    return common_pb2.OrdererBlockMetadata.FromString(meta.value).last_config.index
