"""Credential revocation information (reference idemix/revocation.go).

The reference supports pluggable revocation algorithms; this snapshot's
default — and only implemented — algorithm is ALG_NO_REVOCATION
(revocation.go RevocationAlgorithm): the CRI (credential revocation
information) is an epoch counter plus an epoch key, signed by the
revocation authority with ECDSA.  Verifiers check the CRI signature and
epoch freshness; unrevoked-ness proofs are vacuous under NO_REVOCATION.
The weak-BB primitives (weakbb.py) are in place for signature-based
revocation algorithms.
"""

from __future__ import annotations

import dataclasses
import json

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.exceptions import InvalidSignature

from fabric_tpu.idemix import bn254 as bn

ALG_NO_REVOCATION = 0


def generate_long_term_revocation_key() -> ec.EllipticCurvePrivateKey:
    """Reference uses ECDSA over P-384 for the revocation authority
    (revocation.go GenerateLongTermRevocationKey)."""
    return ec.generate_private_key(ec.SECP384R1())


@dataclasses.dataclass
class CredentialRevocationInformation:
    epoch: int
    revocation_alg: int
    epoch_pk: bytes  # serialized G2 point (epoch key)
    epoch_pk_sig: bytes  # RA signature over (epoch, alg, epoch_pk)

    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "epoch": self.epoch,
                "alg": self.revocation_alg,
                "epoch_pk": self.epoch_pk.hex(),
                "sig": self.epoch_pk_sig.hex(),
            }
        ).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "CredentialRevocationInformation":
        d = json.loads(raw)
        return cls(
            epoch=d["epoch"],
            revocation_alg=d["alg"],
            epoch_pk=bytes.fromhex(d["epoch_pk"]),
            epoch_pk_sig=bytes.fromhex(d["sig"]),
        )


def _cri_digest_material(epoch: int, alg: int, epoch_pk: bytes) -> bytes:
    return b"idemix-cri" + epoch.to_bytes(8, "big") + bytes([alg]) + epoch_pk


def create_cri(
    ra_key: ec.EllipticCurvePrivateKey,
    epoch: int,
    alg: int = ALG_NO_REVOCATION,
    rng=None,
) -> CredentialRevocationInformation:
    """Reference revocation.go CreateCRI."""
    if alg != ALG_NO_REVOCATION:
        raise NotImplementedError("only ALG_NO_REVOCATION is supported")
    epoch_sk = bn.rand_zr(rng)
    epoch_pk = bn.g2_to_bytes(bn.g2_mul(bn.G2_GEN, epoch_sk))
    sig = ra_key.sign(
        _cri_digest_material(epoch, alg, epoch_pk), ec.ECDSA(hashes.SHA256())
    )
    return CredentialRevocationInformation(
        epoch=epoch, revocation_alg=alg, epoch_pk=epoch_pk, epoch_pk_sig=sig
    )


def verify_epoch_pk(
    ra_pub: ec.EllipticCurvePublicKey,
    cri: CredentialRevocationInformation,
) -> bool:
    """Reference revocation.go VerifyEpochPK."""
    try:
        ra_pub.verify(
            cri.epoch_pk_sig,
            _cri_digest_material(
                cri.epoch, cri.revocation_alg, cri.epoch_pk
            ),
            ec.ECDSA(hashes.SHA256()),
        )
        bn.g2_from_bytes(cri.epoch_pk)
        return True
    except (InvalidSignature, ValueError):
        return False
