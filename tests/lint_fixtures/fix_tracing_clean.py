"""CLEAN TWIN of fix_tracing_dirty: the same commit-lock shape calling
the REAL tracing seam instead.  tracing.dump_to's flush only runs when
a caller explicitly dumps the armed flight recorder, and the module is
a reviewed chaos seam (dataflow._CHAOS_SEAM) — its blocking summary
must not propagate into lock-discipline for callers."""

from fabric_tpu.common import tracing


class Ledger:
    def __init__(self, lock):
        self.commit_lock = lock

    def commit(self):
        with self.commit_lock:
            tracing.instant("commit.mark", stage="fixture")
            tracing.dump_to("/tmp/fixture-trace.json")
