"""Membership service provider (X.509 identity layer).

Reference: msp/ (interfaces msp/msp.go:16,60,118,173; impl mspimpl.go).
Identities expose `verification_item` so signature checks batch onto the
TPU data plane instead of being verified one at a time.
"""

from fabric_tpu.msp.identity import Identity, SigningIdentity
from fabric_tpu.msp.msp import MSP, MSPError, MSPManager
from fabric_tpu.msp.config import msp_config_from_ca, load_msp_dir, write_msp_dir

__all__ = [
    "Identity",
    "SigningIdentity",
    "MSP",
    "MSPError",
    "MSPManager",
    "msp_config_from_ca",
    "load_msp_dir",
    "write_msp_dir",
]
