"""Hierarchical policy manager + implicit meta policies.

Reference: common/policies/policy.go:152 (Manager: path-addressed policy
namespace `/Channel/Application/Writers`), implicitmeta.go (ANY/ALL/
MAJORITY over the equally-named policy of each sub-group).

Every policy object implements the same two-phase protocol as
SignaturePolicy (`prepare` -> PendingEvaluation with batchable items) so a
caller can batch across policies — including across the sub-policies an
implicit meta policy fans out to.
"""

from __future__ import annotations

from fabric_tpu.protos.common import configtx_pb2, policies_pb2
from fabric_tpu.protoutil import SignedData
from fabric_tpu.policies.signature_policy import (
    PendingEvaluation,
    PolicyError,
    SignaturePolicy,
)

# Reserved policy names (reference common/policies/policy.go)
CHANNEL_READERS = "Readers"
CHANNEL_WRITERS = "Writers"
CHANNEL_ADMINS = "Admins"
BLOCK_VALIDATION = "BlockValidation"


class _MetaPending:
    def __init__(self, pendings: list[PendingEvaluation], threshold: int):
        self._pendings = pendings
        self._threshold = threshold
        self.items = [it for p in pendings for it in p.items]

    def finish(self, mask) -> bool:
        if len(mask) != len(self.items):
            raise PolicyError("mask length mismatch")
        satisfied = 0
        off = 0
        for p in self._pendings:
            n = len(p.items)
            if p.finish(mask[off : off + n]):
                satisfied += 1
            off += n
        return satisfied >= self._threshold


class ImplicitMetaPolicy:
    """ANY/ALL/MAJORITY of the same-named policy across sub-managers."""

    def __init__(self, sub_policies: list, rule: int):
        self._subs = sub_policies
        R = policies_pb2.ImplicitMetaPolicy
        if rule == R.ANY:
            self._threshold = min(1, len(sub_policies))
        elif rule == R.ALL:
            self._threshold = len(sub_policies)
        elif rule == R.MAJORITY:
            self._threshold = len(sub_policies) // 2 + 1
        else:
            raise PolicyError(f"unknown implicit meta rule {rule}")

    def prepare(self, signed_data: list[SignedData]):
        return _MetaPending([p.prepare(signed_data) for p in self._subs], self._threshold)

    def evaluate_signed_data(self, signed_data: list[SignedData], csp) -> bool:
        pending = self.prepare(signed_data)
        mask = csp.verify_batch(pending.items)
        return pending.finish(mask)


class RejectPolicy:
    """Stand-in for unparsable/absent policies: always rejects (the
    reference routes unknown policies to an implicit deny).  `reason`
    records WHY the deny exists — an unparsable policy and a missing
    path are different operator problems, and a silent always-False
    object made them indistinguishable."""

    def __init__(self, name: str, reason: str = ""):
        self.name = name
        self.reason = reason or f"policy {name!r} is not defined"

    def prepare(self, signed_data):
        return _MetaPending([], 1)

    def evaluate_signed_data(self, signed_data, csp) -> bool:
        return False


class Manager:
    """A node in the policy namespace tree."""

    def __init__(self, path: str, policies: dict, sub_managers: dict):
        self.path = path
        self._policies = policies
        self._subs = sub_managers

    def manager(self, relpath: list[str]) -> "Manager | None":
        m = self
        for seg in relpath:
            m = m._subs.get(seg)
            if m is None:
                return None
        return m

    def get_policy(self, name: str):
        """Accepts relative names ("Writers"), absolute paths
        ("/Channel/Application/Writers"), and slashed relative paths."""
        if name.startswith("/"):
            segs = [s for s in name.split("/") if s]
            # absolute paths are rooted at the channel manager; tolerate a
            # leading "Channel" segment matching this manager's root
            m = self
            if segs and segs[0] == "Channel" and self.path in ("Channel", ""):
                segs = segs[1:]
            for seg in segs[:-1]:
                m = m._subs.get(seg)
                if m is None:
                    return RejectPolicy(name)
            return m._policies.get(segs[-1], RejectPolicy(name)) if segs else RejectPolicy(name)
        if "/" in name:
            segs = [s for s in name.split("/") if s]
            m = self.manager(segs[:-1])
            if m is None:
                return RejectPolicy(name)
            return m._policies.get(segs[-1], RejectPolicy(name))
        return self._policies.get(name, RejectPolicy(name))


def manager_from_config_group(
    path: str, group: configtx_pb2.ConfigGroup, deserializer
) -> Manager:
    """Build the manager tree from a channel config group (reference
    NewManagerImpl walking ConfigGroup.policies/groups)."""
    subs = {
        name: manager_from_config_group(f"{path}/{name}" if path else name, g, deserializer)
        for name, g in group.groups.items()
    }
    policies: dict[str, object] = {}
    metas: list[tuple[str, policies_pb2.ImplicitMetaPolicy]] = []
    for name, cfg_policy in group.policies.items():
        pol = cfg_policy.policy
        if pol.type == policies_pb2.Policy.SIGNATURE:
            try:
                env = policies_pb2.SignaturePolicyEnvelope.FromString(pol.value)
                policies[name] = SignaturePolicy(env, deserializer)
            except Exception as exc:
                # structured deny: the config carried a SIGNATURE policy
                # that does not parse — evaluations fail closed AND the
                # reject records what broke (reference logs + implicit
                # deny for unknown policy types)
                policies[name] = RejectPolicy(
                    name, reason=f"unparsable SIGNATURE policy: {exc}"
                )
        elif pol.type == policies_pb2.Policy.IMPLICIT_META:
            metas.append((name, policies_pb2.ImplicitMetaPolicy.FromString(pol.value)))
        else:
            policies[name] = RejectPolicy(
                name, reason=f"unsupported policy type {pol.type}"
            )
    # implicit metas resolve against sub-managers' policies after they exist
    for name, meta in metas:
        sub_pols = []
        for sm in subs.values():
            p = sm._policies.get(meta.sub_policy)
            if p is not None and not isinstance(p, RejectPolicy):
                sub_pols.append(p)
        if sub_pols:
            policies[name] = ImplicitMetaPolicy(sub_pols, meta.rule)
        else:
            policies[name] = RejectPolicy(
                name,
                reason=f"implicit meta policy over {meta.sub_policy!r} "
                       f"resolved zero sub-policies",
            )
    return Manager(path, policies, subs)


__all__ = [
    "Manager",
    "ImplicitMetaPolicy",
    "RejectPolicy",
    "manager_from_config_group",
    "CHANNEL_READERS",
    "CHANNEL_WRITERS",
    "CHANNEL_ADMINS",
    "BLOCK_VALIDATION",
]
