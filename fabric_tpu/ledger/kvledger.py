"""The peer ledger: block store + state DB + history DB orchestration.

Reference: core/ledger/kvledger/kv_ledger.go:447-530 CommitLegacy
(ValidateAndPrepare -> block store -> state DB -> history DB), provider in
kv_ledger_provider.go, recovery-on-open (state/history DBs replay blocks
newer than their savepoints), ledgermgmt/ledger_mgmt.go lifecycle.
"""

from __future__ import annotations

import os

from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.ledger.history import HistoryDB
from fabric_tpu.ledger.kvstore import KVStore, MemKVStore, open_kvstore
from fabric_tpu.ledger.statedb import Height, VersionedDB
from fabric_tpu.ledger.txmgmt import MVCCValidator, TxSimulator, VALID
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.ledger.rwset import rwset_pb2
from fabric_tpu.protos.ledger.rwset.kvrwset import kv_rwset_pb2
from fabric_tpu import protoutil


def extract_rwsets(block: common_pb2.Block) -> list[bytes | None]:
    """Per-tx marshaled TxReadWriteSet for endorser txs (None otherwise)."""
    out: list[bytes | None] = []
    for i in range(len(block.data.data)):
        raw = None
        try:
            env = protoutil.extract_envelope(block, i)
            payload = common_pb2.Payload.FromString(env.payload)
            chdr = common_pb2.ChannelHeader.FromString(payload.header.channel_header)
            if chdr.type == common_pb2.ENDORSER_TRANSACTION:
                _, action = protoutil.get_action_from_envelope(env)
                raw = action.results
        except Exception:
            raw = None
        out.append(raw)
    return out


def _history_writes(rwsets: list[bytes | None], flags: list[int]):
    """Per-tx (ns, key) write lists for the history index (valid txs only)."""
    writes_per_tx: list[list[tuple[str, str]]] = [[] for _ in flags]
    for tx_num, raw in enumerate(rwsets):
        if flags[tx_num] != VALID or raw is None:
            continue
        try:
            txrw = rwset_pb2.TxReadWriteSet.FromString(raw)
            for nsrw in txrw.ns_rwset:
                kvrw = kv_rwset_pb2.KVRWSet.FromString(nsrw.rwset)
                writes_per_tx[tx_num].extend(
                    (nsrw.namespace, w.key) for w in kvrw.writes
                )
        except Exception:
            continue
    return writes_per_tx


class KVLedger:
    """One channel's ledger (reference ledger.PeerLedger,
    core/ledger/ledger_interface.go:142)."""

    def __init__(self, ledger_id: str, block_store: BlockStore, kv: KVStore):
        self.ledger_id = ledger_id
        self._blocks = block_store
        self._state = VersionedDB(kv, f"statedb/{ledger_id}")
        self._history = HistoryDB(kv, f"historydb/{ledger_id}")
        self._mvcc = MVCCValidator(self._state)
        self._recover()

    # -- recovery (reference recoverDBs / syncStateAndHistoryDBWithBlockstore)

    def _recover(self) -> None:
        height = self._blocks.height
        sp = self._state.savepoint()
        first = 0 if sp is None else sp.block_num + 1
        for num in range(first, height):
            block = self._blocks.get_block_by_number(num)
            self._apply_state_updates(block)

    def _apply_state_updates(self, block: common_pb2.Block) -> None:
        flags = list(protoutil.tx_filter(block))
        rwsets = extract_rwsets(block)
        # replay trusts the recorded validation flags; MVCC re-application
        # is deterministic because only VALID txs contribute writes
        batch = self._mvcc.validate_and_prepare(block.header.number, rwsets, flags)
        self._state.apply_updates(batch, Height(block.header.number, len(flags)))
        self._history.commit(
            block.header.number, _history_writes(rwsets, flags)
        )

    # -- commit path (reference kv_ledger.go:447 CommitLegacy) -------------

    def commit(self, block: common_pb2.Block) -> None:
        """MVCC-validate (updating the tx filter), persist block, apply
        state + history.  Signature/policy flags must already be set by the
        txvalidator; this adds the MVCC codes."""
        flags = list(protoutil.tx_filter(block))
        rwsets = extract_rwsets(block)
        batch = self._mvcc.validate_and_prepare(block.header.number, rwsets, flags)
        protoutil.set_tx_filter(block, flags)
        self._blocks.add_block(block)
        self._state.apply_updates(batch, Height(block.header.number, len(flags)))
        self._history.commit(
            block.header.number, _history_writes(rwsets, flags)
        )

    # -- queries -----------------------------------------------------------

    @property
    def height(self) -> int:
        return self._blocks.height

    def get_blockchain_info(self):
        return self._blocks.info()

    def get_block_by_number(self, num: int):
        return self._blocks.get_block_by_number(num)

    def get_block_by_hash(self, h: bytes):
        return self._blocks.get_block_by_hash(h)

    def get_tx_by_id(self, txid: str):
        return self._blocks.get_tx_by_id(txid)

    def get_tx_validation_code(self, txid: str):
        return self._blocks.get_tx_validation_code(txid)

    def tx_id_exists(self, txid: str) -> bool:
        return self._blocks.get_tx_loc(txid) is not None

    def new_tx_simulator(self) -> TxSimulator:
        return TxSimulator(self._state)

    def get_state(self, ns: str, key: str) -> bytes | None:
        vv = self._state.get_state(ns, key)
        return vv.value if vv else None

    def get_state_range(self, ns: str, start: str, end: str):
        for key, vv in self._state.get_state_range(ns, start, end):
            yield key, vv.value

    def get_history_for_key(self, ns: str, key: str):
        return self._history.get_history_for_key(ns, key)


class LedgerProvider:
    """Opens/creates per-channel ledgers under one root (reference
    kv_ledger_provider.go + ledgermgmt)."""

    def __init__(self, root_dir: str | None = None):
        self._root = root_dir
        if root_dir is None:
            self._kv = MemKVStore()
        else:
            os.makedirs(root_dir, exist_ok=True)
            self._kv = open_kvstore(os.path.join(root_dir, "index.sqlite"))
        self._ledgers: dict[str, KVLedger] = {}

    def create(self, genesis_block: common_pb2.Block) -> KVLedger:
        """Create from a genesis block (ledger id = channel id inside)."""
        env = protoutil.extract_envelope(genesis_block, 0)
        payload = common_pb2.Payload.FromString(env.payload)
        chdr = common_pb2.ChannelHeader.FromString(payload.header.channel_header)
        ledger = self.open(chdr.channel_id)
        if ledger.height == 0:
            ledger.commit(genesis_block)
        return ledger

    def open(self, ledger_id: str) -> KVLedger:
        if ledger_id in self._ledgers:
            return self._ledgers[ledger_id]
        block_dir = (
            None if self._root is None else os.path.join(self._root, ledger_id, "chains")
        )
        store = BlockStore(block_dir, self._kv, name=ledger_id)
        ledger = KVLedger(ledger_id, store, self._kv)
        self._ledgers[ledger_id] = ledger
        return ledger

    def list(self) -> list[str]:
        return sorted(self._ledgers)

    def close(self) -> None:
        self._kv.close()


__all__ = ["KVLedger", "LedgerProvider", "extract_rwsets"]
