"""ACL management: resource name -> policy evaluation at API entry.

Reference: core/aclmgmt — aclmgmt.go:15 ACLProvider, resources.go (the
resource-name catalog), defaultaclprovider.go (defaults mapping each
resource to /Channel/Application/{Readers,Writers,Admins}, with local
MSP fallbacks for channel-less resources), resourceprovider.go (config
overrides via the channel's ACLs config value).

`check_acl(resource, channel_policy_manager, signed_data)` raises
ACLError when the policy is not satisfied.
"""

from __future__ import annotations

from fabric_tpu.protos.peer import configuration_pb2 as peer_configuration_pb2


class ACLError(Exception):
    pass


# Resource names (reference resources.go).
LSCC_GET_CC_DATA = "lscc/GetChaincodeData"
LSCC_GET_CHAINCODES = "lscc/GetInstantiatedChaincodes"
LSCC_CC_EXISTS = "lscc/ChaincodeExists"
LSCC_GET_DEP_SPEC = "lscc/GetDeploymentSpec"
QSCC_GET_CHAIN_INFO = "qscc/GetChainInfo"
QSCC_GET_BLOCK_BY_NUMBER = "qscc/GetBlockByNumber"
QSCC_GET_BLOCK_BY_HASH = "qscc/GetBlockByHash"
QSCC_GET_TX_BY_ID = "qscc/GetTransactionByID"
QSCC_GET_BLOCK_BY_TX_ID = "qscc/GetBlockByTxID"
CSCC_GET_CONFIG_BLOCK = "cscc/GetConfigBlock"
CSCC_GET_CHANNEL_CONFIG = "cscc/GetChannelConfig"
CSCC_JOIN_CHAIN = "cscc/JoinChain"
CSCC_GET_CHANNELS = "cscc/GetChannels"
LSCC_INSTALL = "lscc/Install"
LSCC_GET_INSTALLED_CC = "lscc/GetInstalledChaincodes"
LIFECYCLE_INSTALL = "_lifecycle/InstallChaincode"
LIFECYCLE_QUERY_INSTALLED = "_lifecycle/QueryInstalledChaincodes"
LIFECYCLE_GET_PACKAGE = "_lifecycle/GetInstalledChaincodePackage"
LIFECYCLE_APPROVE = "_lifecycle/ApproveChaincodeDefinitionForMyOrg"
LIFECYCLE_COMMIT = "_lifecycle/CommitChaincodeDefinition"
LIFECYCLE_CHECK_READINESS = "_lifecycle/CheckCommitReadiness"
LIFECYCLE_QUERY_COMMITTED = "_lifecycle/QueryChaincodeDefinition"
LIFECYCLE_QUERY_COMMITTED_ALL = "_lifecycle/QueryChaincodeDefinitions"
PEER_PROPOSE = "peer/Propose"
PEER_CC2CC = "peer/ChaincodeToChaincode"
EVENT_BLOCK = "event/Block"
EVENT_FILTERED_BLOCK = "event/FilteredBlock"
GOSSIP_PRIVATE_DATA = "gossip/PrivateData"

_READERS = "/Channel/Application/Readers"
_WRITERS = "/Channel/Application/Writers"
_ADMINS = "/Channel/Application/Admins"

DEFAULT_POLICIES: dict[str, str] = {
    LSCC_GET_CC_DATA: _READERS,
    LSCC_GET_CHAINCODES: _READERS,
    LSCC_CC_EXISTS: _READERS,
    LSCC_GET_DEP_SPEC: _READERS,
    QSCC_GET_CHAIN_INFO: _READERS,
    QSCC_GET_BLOCK_BY_NUMBER: _READERS,
    QSCC_GET_BLOCK_BY_HASH: _READERS,
    QSCC_GET_TX_BY_ID: _READERS,
    QSCC_GET_BLOCK_BY_TX_ID: _READERS,
    CSCC_GET_CONFIG_BLOCK: _READERS,
    CSCC_GET_CHANNEL_CONFIG: _READERS,
    CSCC_GET_CHANNELS: _READERS,  # channel-less in practice
    CSCC_JOIN_CHAIN: _ADMINS,  # local admin in the reference
    LSCC_INSTALL: _ADMINS,  # local admin in the reference
    LSCC_GET_INSTALLED_CC: _ADMINS,
    LIFECYCLE_INSTALL: _ADMINS,
    LIFECYCLE_QUERY_INSTALLED: _ADMINS,
    LIFECYCLE_GET_PACKAGE: _ADMINS,
    LIFECYCLE_QUERY_COMMITTED_ALL: _READERS,
    LIFECYCLE_APPROVE: _WRITERS,
    LIFECYCLE_COMMIT: _WRITERS,
    LIFECYCLE_CHECK_READINESS: _WRITERS,
    LIFECYCLE_QUERY_COMMITTED: _READERS,
    PEER_PROPOSE: _WRITERS,
    PEER_CC2CC: _WRITERS,
    EVENT_BLOCK: _READERS,
    EVENT_FILTERED_BLOCK: _READERS,
    GOSSIP_PRIVATE_DATA: _READERS,
}


# System-chaincode function -> resource mapping.  The reference checks
# these inside each SCC, where the stub exposes the SignedProposal
# (qscc/query.go:112 fn->resource switch, cscc/configure.go:163-186,
# lifecycle/scc.go:209 "_lifecycle/<FuncName>"); here the enforcement
# point is the endorser entry, the one place this build has the signed
# proposal, the channel policy manager, and the chaincode name+function
# together.
SCC_FUNCTION_RESOURCES: dict[tuple[str, str], str] = {
    ("qscc", "GetChainInfo"): QSCC_GET_CHAIN_INFO,
    ("qscc", "GetBlockByNumber"): QSCC_GET_BLOCK_BY_NUMBER,
    ("qscc", "GetBlockByHash"): QSCC_GET_BLOCK_BY_HASH,
    ("qscc", "GetTransactionByID"): QSCC_GET_TX_BY_ID,
    ("qscc", "GetBlockByTxID"): QSCC_GET_BLOCK_BY_TX_ID,
    ("cscc", "GetConfigBlock"): CSCC_GET_CONFIG_BLOCK,
    ("cscc", "GetChannelConfig"): CSCC_GET_CHANNEL_CONFIG,
    ("cscc", "GetChannels"): CSCC_GET_CHANNELS,
    ("cscc", "JoinChain"): CSCC_JOIN_CHAIN,
    # fn names as the lscc dispatch spells them (chaincode/lscc.py:58-70)
    ("lscc", "getccdata"): LSCC_GET_CC_DATA,
    ("lscc", "getchaincodes"): LSCC_GET_CHAINCODES,
    # the dispatch's GetChaincodesResult alias of getchaincodes
    # (chaincode/lscc.py:66) must satisfy the same resource — an
    # uncataloged alias used to skip the check entirely (ADVICE r5)
    ("lscc", "GetChaincodesResult"): LSCC_GET_CHAINCODES,
    ("lscc", "getid"): LSCC_CC_EXISTS,
    ("lscc", "getdepspec"): LSCC_GET_DEP_SPEC,
    ("lscc", "install"): LSCC_INSTALL,
    ("lscc", "getinstalledchaincodes"): LSCC_GET_INSTALLED_CC,
    # deploy/upgrade: "ACL check covered by PROPOSAL" in the reference
    # (defaultaclprovider.go:69-70) — the channel Writers gate applies
    ("lscc", "deploy"): PEER_PROPOSE,
    ("lscc", "upgrade"): PEER_PROPOSE,
    ("_lifecycle", "InstallChaincode"): LIFECYCLE_INSTALL,
    ("_lifecycle", "QueryInstalledChaincodes"): LIFECYCLE_QUERY_INSTALLED,
    ("_lifecycle", "GetInstalledChaincodePackage"): LIFECYCLE_GET_PACKAGE,
    ("_lifecycle", "ApproveChaincodeDefinitionForMyOrg"): LIFECYCLE_APPROVE,
    ("_lifecycle", "CommitChaincodeDefinition"): LIFECYCLE_COMMIT,
    ("_lifecycle", "CheckCommitReadiness"): LIFECYCLE_CHECK_READINESS,
    ("_lifecycle", "QueryChaincodeDefinition"): LIFECYCLE_QUERY_COMMITTED,
    ("_lifecycle", "QueryChaincodeDefinitions"): LIFECYCLE_QUERY_COMMITTED_ALL,
}

SYSTEM_CHAINCODES = frozenset({"qscc", "cscc", "lscc", "_lifecycle"})


def resource_for_chaincode(cc_name: str, fn: str) -> str:
    """Resource an on-channel proposal must satisfy: the per-function
    SCC resource, or peer/Propose for application chaincodes.

    FAIL-CLOSED: a system-chaincode function with NO catalog entry is
    denied outright (raises ACLError) instead of skipping the check —
    the old skip meant any SCC function added without a catalog entry
    (install, query-installed, a dispatch alias) was world-invocable
    until someone noticed (ADVICE r5).  The SCC's own unknown-function
    rejection still covers truly nonexistent names, but names it DOES
    serve must be cataloged here."""
    if cc_name in SYSTEM_CHAINCODES:
        res = SCC_FUNCTION_RESOURCES.get((cc_name, fn))
        if res is None:
            raise ACLError(
                f"access denied: no ACL catalog entry for system "
                f"chaincode function {cc_name}/{fn!r}"
            )
        return res
    return PEER_PROPOSE


class ACLProvider:
    """Evaluates resource ACLs against a channel's policy manager, with
    per-channel overrides from the ACLs config value (reference
    resourceprovider.go wrapping defaultaclprovider.go)."""

    def __init__(self, overrides: dict[str, str] | None = None,
                 csp=None):
        self._overrides = dict(overrides or {})
        self._csp = csp

    @classmethod
    def from_acls_config(cls, raw: bytes, csp=None) -> "ACLProvider":
        """Parse a peer.ACLs config value (peer/configuration.proto)."""
        acls = peer_configuration_pb2.ACLs.FromString(raw)
        return cls(
            {name: a.policy_ref for name, a in acls.acls.items()}, csp=csp
        )

    def policy_ref(self, resource: str) -> str:
        ref = self._overrides.get(resource) or DEFAULT_POLICIES.get(resource)
        if ref is None:
            raise ACLError(f"no ACL policy for resource {resource!r}")
        if not ref.startswith("/"):
            # a non-fully-qualified ref is relative to the Application
            # group (reference aclmgmtimpl newACLMgmt policy resolution)
            ref = "/Channel/Application/" + ref
        return ref

    def check_acl(
        self, resource: str, policy_manager, signed_data
    ) -> None:
        """Raise ACLError unless the resource's policy passes (reference
        aclmgmt CheckACL)."""
        ref = self.policy_ref(resource)
        pol = policy_manager.get_policy(ref)
        if not pol.evaluate_signed_data(
            signed_data if isinstance(signed_data, list) else [signed_data],
            self._csp,
        ):
            raise ACLError(
                f"access denied: resource {resource!r} requires {ref!r}"
            )


__all__ = [
    "ACLProvider",
    "ACLError",
    "DEFAULT_POLICIES",
    "SCC_FUNCTION_RESOURCES",
    "SYSTEM_CHAINCODES",
    "resource_for_chaincode",
]
