"""netnode — one node of a netharness topology, run as its own OS
process (``python -m fabric_tpu.devtools.netnode <config.json>``).

The multi-process sibling of ``node/peer_node.py`` / ``node/
orderer_node.py`` for hosts without the ``cryptography`` package: the
identity plane comes from :mod:`fabric_tpu.devtools.netident`, but the
machinery under test is the production stack —

  orderer role: raft consensus over ``TCPTransport`` (WAL recovery on
    restart), blockcutter/blockwriter, ``ab.Broadcast``/``ab.Deliver``
    over the framed RPC transport;
  peer role: ``LedgerProvider`` (sqlite + block files, REAL crash
    recovery after kill -9), ``TxValidator`` -> ``Committer``, gossip
    over ``TCPGossipComm`` (push/pull/state transfer/leader election),
    the ``DeliverClient`` pulling from the orderer cluster, snapshot
    generation/serving, and the operations endpoint (``/traces``).

Lifecycle contract (what the harness relies on):

- startup is CRASH-TOLERANT: a peer restarted after SIGKILL reopens its
  ledger through normal recovery; a half-finished snapshot import is
  discarded (``discard_failed_import``) and the node rejoins from its
  configured snapshot;
- SIGTERM is a CLEAN stop: every component's stop path runs and the
  process exits 0 (the harness's graceful-stop schedule entries);
- SIGKILL needs no cooperation, which is the point.

The control surface rides the same RPC server the data plane uses:
``net.Status`` (readiness + heights), ``net.Check`` (the invariants
oracle run in-process, over THIS node's stores), ``net.TraceDump``,
``admin.SnapshotSubmit``/``admin.SnapshotFetch``/``admin.Height``.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading


def _configure_env(cfg: dict) -> None:
    """Arm per-node chaos/trace seams BEFORE fabric imports: the
    harness plumbs per-node FABRIC_TPU_FAULTLINE / FABRIC_TPU_TRACE
    through the child environment (faultfuzz's multi-process mode), and
    those modules read the environment at import time."""
    for key, val in (cfg.get("env") or {}).items():
        os.environ.setdefault(key, str(val))


def main(argv: list[str]) -> int:
    with open(argv[0], "r", encoding="utf-8") as f:
        cfg = json.load(f)
    _configure_env(cfg)

    # imports AFTER env plumbing (faultline/tracing arm from env)
    from fabric_tpu.common import tracing
    from fabric_tpu.devtools import invariants, netident, netsplit

    # this process's vantage point for the netsplit seam — a plan
    # pushed later over net.Netsplit then judges links without having
    # to carry a per-node "node" field itself
    netsplit.set_local_node(cfg["name"])

    if cfg.get("trace"):
        tracing.arm(int(cfg["trace"]))
        # per-node id bases keep span/trace ids globally unique across
        # the topology, so merged network traces stay causally linked
        # instead of colliding at id 1 in every process
        tracing.reset_ids(int(cfg.get("trace_id_base", 0)))

    stop_evt = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_evt.set())
    signal.signal(signal.SIGINT, lambda *_: stop_evt.set())

    role = cfg["role"]
    node = (
        _build_orderer(cfg, netident)
        if role == "orderer"
        else _build_peer(cfg, netident, invariants, tracing)
    )
    try:
        node.start()
        _touch(cfg.get("ready_file"))
        stop_evt.wait()
    finally:
        node.stop()
    return 0


def _touch(path: str | None) -> None:
    if path:
        with open(path, "w", encoding="utf-8") as f:
            f.write("ready\n")


def _netsplit_handler(body: bytes, stream) -> bytes:
    """``net.Netsplit``: arm/replace/heal this node's partition plan.
    Body: a netsplit plan JSON to arm; empty / ``null`` / ``{}`` heals
    (deactivates).  Shared by both roles — the harness's partition
    executor pushes per-node plan updates through this."""
    from fabric_tpu.devtools import netsplit

    raw = body.decode("utf-8").strip() if body else ""
    if not raw or raw in ("null", "{}"):
        netsplit.deactivate()
        return json.dumps({"armed": False}, sort_keys=True).encode()
    plan = netsplit.activate(raw)
    return json.dumps({
        "armed": True,
        "label": plan.label,
        "mode": plan.mode,
        "groups": [list(g) for g in plan.groups],
    }, sort_keys=True).encode()


# -- orderer role -------------------------------------------------------------


class _OrdererSupport:
    """chain_getter target for the deliver service: the raft-ordered
    block store behind the fake channel bundle."""

    def __init__(self, store, bundle):
        self.store = store
        self.bundle = bundle


class NetOrderer:
    def __init__(self, cfg: dict):
        from fabric_tpu.comm import RPCServer
        from fabric_tpu.common.deliver import BlockNotifier, DeliverService
        from fabric_tpu.devtools import netident
        from fabric_tpu.ledger.blkstorage import BlockStore
        from fabric_tpu.ledger.kvstore import open_kvstore
        from fabric_tpu.orderer.blockcutter import BlockCutter
        from fabric_tpu.orderer.blockwriter import BlockWriter
        from fabric_tpu.orderer.raft import RaftChain
        from fabric_tpu.orderer.raft.transport import TCPTransport
        from fabric_tpu.protos.orderer import ab_pb2, raft_pb2 as rpb
        from fabric_tpu.protos.common import common_pb2

        self._ab = ab_pb2
        self._common = common_pb2
        self.cfg = cfg
        self.channel = cfg["channel"]
        root = cfg["root"]
        os.makedirs(root, exist_ok=True)
        # operations endpoint FIRST: the raft chain + WAL take their
        # metrics bundle at construction
        self.operations = None
        raft_metrics = None
        if cfg.get("ops_port") is not None:
            from fabric_tpu.common.operations import System

            self.operations = System(
                ("127.0.0.1", int(cfg["ops_port"])), process_metrics=True
            )
            raft_metrics = self.operations.raft_metrics()
            from fabric_tpu.common import profile

            if profile.enabled():
                profile.set_lock_metrics(self.operations.lock_metrics())
        self.kv = open_kvstore(os.path.join(root, "index.sqlite"))
        self.store = BlockStore(
            os.path.join(root, "chains"), self.kv, name=self.channel
        )
        genesis = netident.make_genesis(self.channel)
        if self.store.height == 0:
            self.store.add_block(genesis)
        self.writer = BlockWriter(self.store)
        node_id = int(cfg["node_id"])
        self.transport = TCPTransport(
            node_id, ("127.0.0.1", int(cfg["raft_port"])),
            metrics=raft_metrics,
        )
        consenters = []
        for cid, addr in sorted(
            cfg["consenters"].items(), key=lambda kv: int(kv[0])
        ):
            cid = int(cid)
            consenters.append(rpb.Consenter(id=cid))
            if cid != node_id:
                self.transport.set_peer(cid, (addr[0], int(addr[1])))
        notifier = BlockNotifier()
        self.chain = RaftChain(
            self.channel,
            node_id,
            consenters,
            BlockCutter(
                max_message_count=int(cfg.get("max_message_count", 10))
            ),
            self.writer,
            self.transport,
            wal_dir=os.path.join(root, "wal"),
            batch_timeout_s=float(cfg.get("batch_timeout_s", 0.2)),
            tick_interval_s=float(cfg.get("tick_interval_s", 0.02)),
            on_block=lambda blk: (notifier.notify(),
                                  self._publish_height()),
            metrics=raft_metrics,
        )
        if self.operations is not None:
            # the orderer's height rides the same per-channel gauge
            # name the peers use, so netscope's lag/stall view sees
            # the ordering tip beside every peer's commit tip
            self._ledger_metrics = self.operations.ledger_metrics()
            self._publish_height()
            self.operations.register_checker(
                "raft", lambda: not self.chain._halted.is_set()
            )
        else:
            self._ledger_metrics = None
        self.transport.set_handler(self.chain.handle_step)
        bundle = netident.FakeBundle(k=1)
        self.deliver = DeliverService(
            lambda ch: (
                _OrdererSupport(self.store, bundle)
                if ch == self.channel else None
            ),
            netident.FakeCSP(),
            notifier=notifier,
        )
        self.rpc = RPCServer("127.0.0.1", int(cfg["rpc_port"]))
        if self.operations is not None:
            # same shape as the reference's grpc server interceptors:
            # per-method completed/duration series on the ops registry
            from fabric_tpu.comm.instrument import instrument

            instrument(self.rpc, self.operations.metrics_provider)
        self.rpc.register("ab.Broadcast", self._broadcast)
        self.rpc.register("ab.BroadcastStream", self._broadcast_stream)
        self.rpc.register("ab.Deliver", self._deliver)
        self.rpc.register("net.Status", self._status)
        self.rpc.register("net.Netsplit", _netsplit_handler)
        self.rpc.register("net.TraceDump", self._trace_dump)

    def _publish_height(self) -> None:
        """The ordering tip on the same per-channel ``ledger_height``
        gauge the peers publish: netscope's derived lag then measures
        orderer tip minus slowest peer, and the stall detector covers
        orderers as subjects too."""
        lm = self._ledger_metrics
        if lm is not None:
            lm.height.With("channel", self.channel).set(
                self.store.height
            )

    def start(self) -> None:
        self.chain.start()
        self.rpc.start()
        if self.operations is not None:
            self.operations.start()

    def stop(self) -> None:
        self.rpc.stop()
        self.deliver.stop()
        self.chain.halt()
        self.transport.close()
        if self.operations is not None:
            self.operations.stop()
        self.kv.close()

    def _broadcast(self, body: bytes, stream) -> bytes:
        env = self._common.Envelope.FromString(body)
        self.chain.order(env)
        return self._ab.BroadcastResponse(
            status=self._common.SUCCESS
        ).SerializeToString()

    def _broadcast_stream(self, body: bytes, stream):
        """The gateway's pipelined submission path: client-streamed
        envelopes, one ack frame per ordered envelope (FIFO credits,
        not per-txid receipts), an empty frame ends the stream.  An
        ordering failure surfaces as the connection's ERR frame — the
        gateway fails over and resubmits its unresolved window."""
        ack = self._ab.BroadcastResponse(
            status=self._common.SUCCESS
        ).SerializeToString()
        while True:
            frame = stream.recv()
            if not frame:
                return None
            env = self._common.Envelope.FromString(frame)
            self.chain.order(env)
            stream.send(ack)

    def _deliver(self, body: bytes, stream):
        from fabric_tpu.common.deliver import deliver_response_frames

        return deliver_response_frames(self.deliver, body)

    def _status(self, body: bytes, stream) -> bytes:
        return json.dumps({
            "role": "orderer",
            "name": self.cfg["name"],
            "height": self.store.height,
            "is_leader": self.chain.is_leader,
            "leader": self.chain.leader,
        }, sort_keys=True).encode()

    def _trace_dump(self, body: bytes, stream) -> bytes:
        from fabric_tpu.common import tracing

        return json.dumps(tracing.export(), sort_keys=True).encode()


def _build_orderer(cfg: dict, netident) -> NetOrderer:
    return NetOrderer(cfg)


# -- peer role ----------------------------------------------------------------


class _PeerDeliverStore:
    """Durable-height view of the peer ledger for the deliver service:
    a gateway tailing this peer for commit statuses must only see
    blocks that are flushed and announced — a buffered group-commit
    block is neither readable nor guaranteed to survive a crash."""

    def __init__(self, ledger):
        self._ledger = ledger

    @property
    def height(self) -> int:
        return getattr(
            self._ledger, "durable_height", self._ledger.height
        )

    def get_block_by_number(self, num: int):
        return self._ledger.get_block_by_number(num)


class NetPeer:
    def __init__(self, cfg: dict, invariants, tracing):
        from fabric_tpu.comm import RPCClient, RPCServer
        from fabric_tpu.common.deliver import make_seek_info_envelope
        from fabric_tpu.devtools import netident
        from fabric_tpu.gossip import GossipRunner, GossipService
        from fabric_tpu.gossip.comm import TCPGossipComm
        from fabric_tpu.ledger import LedgerProvider, snapshot as snap
        from fabric_tpu.peer.committer import Committer
        from fabric_tpu.peer.deliverclient import DeliverClient
        from fabric_tpu.peer.txvalidator import TxValidator
        from fabric_tpu.protos.common import common_pb2
        from fabric_tpu.protos.orderer import ab_pb2

        self._invariants = invariants
        self._tracing = tracing
        self._netident = netident
        self._common = common_pb2
        self.cfg = cfg
        self.channel = cfg["channel"]
        self.name = cfg["name"]
        root = cfg["root"]
        os.makedirs(root, exist_ok=True)
        # operations endpoint FIRST (peer_node's ordering): the ledger
        # provider and validator take their metric bundles at
        # construction, and the checkers give /healthz?detail=1 real
        # per-component inputs for netscope's health timeline
        self.operations = None
        if cfg.get("ops_port") is not None:
            from fabric_tpu.common import workpool
            from fabric_tpu.common.operations import System

            self.operations = System(
                ("127.0.0.1", int(cfg["ops_port"])), process_metrics=True
            )
            workpool.set_metrics(self.operations.workpool_metrics())
            from fabric_tpu.common import profile

            if profile.enabled():
                profile.set_lock_metrics(self.operations.lock_metrics())
            self.operations.register_checker(
                "workpool", workpool.health_checker()
            )
        self.provider = LedgerProvider(
            root,
            commit_metrics=(
                self.operations.commit_metrics()
                if self.operations is not None else None
            ),
            ledger_metrics=(
                self.operations.ledger_metrics()
                if self.operations is not None else None
            ),
        )
        genesis = netident.make_genesis(self.channel)
        join_dir = cfg.get("join_snapshot")
        try:
            if join_dir:
                self.ledger = self.provider.create_from_snapshot(join_dir)
            else:
                self.ledger = self.provider.create(genesis)
        except snap.SnapshotError:
            # crash-tolerant reopen: a kill -9 mid-import leaves the
            # half-import marker; discard the debris and retry (from
            # the snapshot when one is configured, else from genesis)
            self.provider.discard_failed_import(self.channel)
            self.ledger = (
                self.provider.create_from_snapshot(join_dir)
                if join_dir else self.provider.create(genesis)
            )
        orgs = int(cfg.get("orgs", 1))
        self.csp = netident.FakeCSP()
        bundle = netident.FakeBundle(k=1 if orgs < 2 else 2)
        self.validator = TxValidator(
            self.channel, self.ledger, bundle, self.csp,
            metrics=(
                self.operations.validate_metrics()
                if self.operations is not None else None
            ),
        )
        self.committer = Committer(self.validator, self.ledger)

        # deliver client over the orderer cluster's ab.Deliver, signed
        # with this node's fake identity (the orderer's deliver policy
        # verifies it)
        signer = netident.sign_as
        ident = b"cre:" + self.name.encode()

        class _Signer:
            def serialize(self):
                return ident

            def sign(self, msg: bytes) -> bytes:
                from fabric_tpu.common.hashing import sha256

                return signer(ident, sha256(msg))

        def connect_fn(endpoint):
            def connect(start_num: int):
                client = RPCClient(endpoint[0], int(endpoint[1]),
                                   timeout=10.0)
                env = make_seek_info_envelope(
                    self.channel, start_num, 0x7FFFFFFFFFFFFFFF,
                    signer=_Signer(),
                )
                for raw in client.stream("ab.Deliver",
                                         env.SerializeToString()):
                    resp = ab_pb2.DeliverResponse.FromString(raw)
                    if resp.WhichOneof("Type") == "block":
                        yield resp.block
                    else:
                        return

            return connect

        self.deliver_client = DeliverClient(
            self.channel,
            [connect_fn(ep) for ep in cfg["orderer_endpoints"]],
            endpoint_addrs=[
                f"{ep[0]}:{int(ep[1])}"
                for ep in cfg["orderer_endpoints"]
            ],
            height_fn=lambda: self.ledger.height,
            sink=self._receive_block,
            max_backoff_s=2.0,
            metrics=(
                self.operations.deliver_metrics()
                if self.operations is not None else None
            ),
        )

        self.comm = TCPGossipComm(
            ("127.0.0.1", int(cfg["gossip_port"])),
            self.name.encode(),
            mcs=netident.NetMCS(bytes.fromhex(cfg["secret"])),
        )
        self.gossip = GossipService(
            self.comm, list(cfg.get("gossip_bootstrap") or [])
        )
        if self.operations is not None:
            self.gossip.set_metrics(self.operations.gossip_metrics())
        self.handle = self.gossip.join_channel(
            self.channel, self.committer,
            deliver_client=self.deliver_client,
        )
        self.runner = GossipRunner(
            self.gossip, float(cfg.get("gossip_tick_s", 0.1))
        )

        # peer-served ab.Deliver: the gateway's commit-status tail
        # reads blocks HERE, not from the orderer — peer blocks carry
        # the post-validation flags a VALID/INVALID verdict needs.
        # Access is 1-of-any (k=1) like the orderer's deliver gate;
        # the notifier fires from the commit listener, which runs
        # post-flush, so BLOCK_UNTIL_READY wakes only for durable
        # blocks (matching _PeerDeliverStore's height).
        from fabric_tpu.common.deliver import BlockNotifier, DeliverService

        self._deliver_notifier = BlockNotifier()
        self.committer.add_commit_listener(
            lambda blk, flags: self._deliver_notifier.notify()
        )
        deliver_support = _OrdererSupport(
            _PeerDeliverStore(self.ledger), netident.FakeBundle(k=1)
        )
        self.deliver_service = DeliverService(
            lambda ch: deliver_support if ch == self.channel else None,
            self.csp,
            notifier=self._deliver_notifier,
        )

        self.rpc = RPCServer("127.0.0.1", int(cfg["rpc_port"]))
        if self.operations is not None:
            from fabric_tpu.comm.instrument import instrument

            instrument(self.rpc, self.operations.metrics_provider)
        self.rpc.register("ab.Deliver", self._deliver)
        self.rpc.register("net.Status", self._status)
        self.rpc.register("net.Netsplit", _netsplit_handler)
        self.rpc.register("net.Check", self._check)
        self.rpc.register("net.TraceDump", self._trace_dump)
        self.rpc.register("admin.Height", self._height)
        self.rpc.register("admin.SnapshotSubmit", self._snapshot_submit)
        self.rpc.register("admin.SnapshotList", self._snapshot_list)
        self.rpc.register("admin.SnapshotCompleted", self._snapshot_completed)
        self.rpc.register("admin.SnapshotFetch", self._snapshot_fetch)

    def _receive_block(self, seq: int, block_bytes: bytes) -> None:
        self.handle.state.add_payload(seq, block_bytes, from_orderer=True)

    def _deliver(self, body: bytes, stream):
        from fabric_tpu.common.deliver import deliver_response_frames

        return deliver_response_frames(self.deliver_service, body)

    def start(self) -> None:
        self.runner.start()
        self.rpc.start()
        if self.operations is not None:
            self.operations.start()

    def stop(self) -> None:
        self.rpc.stop()
        self.deliver_service.stop()
        self.runner.stop()
        self.deliver_client.stop()
        self.comm.close()
        if self.operations is not None:
            self.operations.stop()
        if self.ledger.snapshots is not None:
            self.ledger.snapshots.wait_idle(timeout=5.0)
        self.provider.close()

    # -- control surface ---------------------------------------------------

    def _status(self, body: bytes, stream) -> bytes:
        dc = self.deliver_client
        return json.dumps({
            "role": "peer",
            "name": self.name,
            "height": self.ledger.height,
            "durable_height": getattr(
                self.ledger, "durable_height", self.ledger.height
            ),
            "gossip_endpoint": self.comm.endpoint,
            "alive_peers": sorted(
                p.endpoint for p in self.gossip.discovery.alive_peers()
            ),
            "election_leader": self.handle.election.is_leader,
            "deliver_running": bool(
                dc._thread is not None and dc._thread.is_alive()
                and not dc._stop.is_set()
            ),
            "delivered": dc.delivered,
        }, sort_keys=True).encode()

    def _height(self, body: bytes, stream) -> bytes:
        return str(self.ledger.height).encode()

    def _check(self, body: bytes, stream) -> bytes:
        """The invariants oracle over THIS node's stores, plus a
        canonical state digest for cross-peer agreement and presence
        probes for harness-sampled keys."""
        req = json.loads(body.decode("utf-8")) if body else {}
        violations = self._invariants.check_ledger(self.ledger)
        missing = []
        for ns, key, value in req.get("expect", []):
            got = self.ledger.get_state(ns, key)
            if got != value.encode("utf-8"):
                missing.append([ns, key, repr(got)])
        return json.dumps({
            "name": self.name,
            "height": self.ledger.height,
            "violations": [v.as_dict() for v in violations],
            "missing": missing,
            "state_digest": self._invariants.state_digest(self.ledger),
        }, sort_keys=True).encode()

    def _trace_dump(self, body: bytes, stream) -> bytes:
        return json.dumps(
            self._tracing.export(), sort_keys=True
        ).encode()

    def _snapshot_submit(self, body: bytes, stream) -> bytes:
        req = json.loads(body.decode("utf-8"))
        res = self.ledger.snapshots.submit_request(
            int(req.get("block_number", 0))
        )
        return json.dumps(res).encode()

    def _snapshot_list(self, body: bytes, stream) -> bytes:
        return json.dumps(self.ledger.snapshots.list_pending()).encode()

    def _snapshot_completed(self, body: bytes, stream) -> bytes:
        from fabric_tpu.ledger import snapshot as snap

        return json.dumps(snap.list_completed(
            self.provider.snapshots_root, self.channel
        )).encode()

    def _snapshot_fetch(self, body: bytes, stream):
        from fabric_tpu.ledger import snapshot as snap

        req = json.loads(body.decode("utf-8"))
        sdir = snap.completed_snapshot_dir(
            self.provider.snapshots_root, self.channel,
            int(req["block_number"]),
        )
        return snap.stream_snapshot_dir(sdir)


def _build_peer(cfg: dict, netident, invariants, tracing) -> NetPeer:
    return NetPeer(cfg, invariants, tracing)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
