"""Raft consensus tests: core protocol, WAL recovery, and the consenter
chain on an in-process 3-node cluster (the reference tests etcdraft the
same way — fake network, deterministic clocks; orderer/consensus/etcdraft
chain_test.go)."""

import os
import threading
import time

import pytest

from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.orderer.blockcutter import BlockCutter
from fabric_tpu.orderer.blockwriter import BlockWriter
from fabric_tpu.orderer.raft import (
    InProcTransport,
    MemoryLog,
    RaftChain,
    RaftNode,
    WAL,
)
from fabric_tpu.orderer.raft.raftcore import LEADER
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.orderer import raft_pb2 as rpb
from fabric_tpu import protoutil


# ---------------------------------------------------------------------------
# deterministic in-test cluster harness for the raw state machine
# ---------------------------------------------------------------------------

class Cluster:
    def __init__(self, n: int, seed: int = 7):
        import random

        self.nodes = {
            i: RaftNode(i, set(range(1, n + 1)), rng=random.Random(seed + i))
            for i in range(1, n + 1)
        }
        self.dropped: set[int] = set()  # node ids cut off from the network
        self.applied: dict[int, list[bytes]] = {i: [] for i in self.nodes}

    def flush(self, rounds: int = 20) -> None:
        """Deliver messages until quiescent."""
        for _ in range(rounds):
            moved = False
            for nid, node in self.nodes.items():
                rd = node.ready()
                for e in rd.committed:
                    if e.type == rpb.ENTRY_CONF_CHANGE:
                        cc = rpb.ConfChange.FromString(e.data)
                        node.apply_conf_change(cc)
                    elif e.data:
                        self.applied[nid].append(e.data)
                for m in rd.messages:
                    moved = True
                    if nid in self.dropped or m.to in self.dropped:
                        continue
                    if m.to in self.nodes:
                        self.nodes[m.to].step(m)
            if not moved:
                return

    def tick_all(self, n: int = 1) -> None:
        for _ in range(n):
            for nid, node in self.nodes.items():
                if nid not in self.dropped:
                    node.tick()
            self.flush()

    def elect(self, max_ticks: int = 200) -> RaftNode:
        for _ in range(max_ticks):
            self.tick_all()
            leaders = [
                n
                for i, n in self.nodes.items()
                if n.state == LEADER and i not in self.dropped
            ]
            if leaders:
                return leaders[0]
        raise AssertionError("no leader elected")


def test_single_node_self_elects_and_commits():
    c = Cluster(1)
    leader = c.elect()
    assert leader.propose(b"tx1")
    c.flush()
    assert c.applied[leader.id] == [b"tx1"]


def test_three_node_election_and_replication():
    c = Cluster(3)
    leader = c.elect()
    for i in range(5):
        assert leader.propose(b"tx%d" % i)
    c.flush()
    want = [b"tx%d" % i for i in range(5)]
    for nid in c.nodes:
        assert c.applied[nid] == want


def test_leader_failure_reelection_preserves_log():
    c = Cluster(3)
    leader = c.elect()
    leader.propose(b"before")
    c.flush()
    c.dropped.add(leader.id)
    new_leader = c.elect()
    assert new_leader.id != leader.id
    new_leader.propose(b"after")
    c.flush()
    for nid in c.nodes:
        if nid not in c.dropped:
            assert c.applied[nid] == [b"before", b"after"]
    # old leader rejoins and catches up
    c.dropped.clear()
    c.tick_all(5)
    assert c.applied[leader.id] == [b"before", b"after"]


def test_stale_leader_proposal_discarded_on_rejoin():
    c = Cluster(3)
    leader = c.elect()
    leader.propose(b"committed")
    c.flush()
    # partition the leader, let it append an entry nobody sees
    c.dropped.add(leader.id)
    leader.propose(b"lost")
    new_leader = c.elect()
    new_leader.propose(b"won")
    c.flush()
    c.dropped.clear()
    c.tick_all(10)
    want = [b"committed", b"won"]
    for nid in c.nodes:
        assert c.applied[nid] == want, f"node {nid}"


def test_conf_change_add_and_remove_node():
    c = Cluster(3)
    leader = c.elect()
    cc = rpb.ConfChange(action=rpb.ConfChange.ADD_NODE)
    cc.consenter.id = 4
    assert leader.propose_conf_change(cc)
    c.flush()
    assert 4 in leader.voters
    # quorum is now 3 of 4
    cc2 = rpb.ConfChange(action=rpb.ConfChange.REMOVE_NODE)
    cc2.consenter.id = 4
    leader.propose_conf_change(cc2)
    c.flush()
    assert 4 not in leader.voters


def test_quorum_loss_blocks_commit():
    c = Cluster(3)
    leader = c.elect()
    c.dropped.update(set(c.nodes) - {leader.id})
    leader.propose(b"stuck")
    c.tick_all(5)
    assert c.applied[leader.id] == []  # cannot commit without quorum


# ---------------------------------------------------------------------------
# WAL
# ---------------------------------------------------------------------------

def test_wal_roundtrip_and_torn_tail(tmp_path):
    w = WAL(str(tmp_path))
    hs, log, snap = w.load()
    assert hs.term == 0 and log.last_index == 0 and snap is None
    entries = [
        rpb.Entry(index=1, term=1, data=b"a"),
        rpb.Entry(index=2, term=1, data=b"b"),
    ]
    w.save(rpb.HardState(term=1, voted_for=2, commit=2), entries)
    w.close()
    # simulate a torn final write
    path = os.path.join(str(tmp_path), "raft.wal")
    with open(path, "ab") as f:
        f.write(b"\x00\x00\x00\xffgarbage")
    w2 = WAL(str(tmp_path))
    hs2, log2, _ = w2.load()
    assert hs2.term == 1 and hs2.voted_for == 2 and hs2.commit == 2
    assert [e.data for e in log2.entries] == [b"a", b"b"]
    w2.close()


def test_wal_snapshot_compacts_replay(tmp_path):
    w = WAL(str(tmp_path))
    w.load()
    w.save(None, [rpb.Entry(index=i, term=1, data=b"e%d" % i) for i in (1, 2, 3)])
    snap = rpb.Snapshot()
    snap.meta.index = 2
    snap.meta.term = 1
    snap.meta.voters.extend([1, 2, 3])
    snap.block_number = 7
    w.save_snapshot(snap)
    w.close()
    w2 = WAL(str(tmp_path))
    hs, log, snap2 = w2.load()
    assert snap2.block_number == 7
    assert log.snap_index == 2
    assert [e.data for e in log.entries] == [b"e3"]
    w2.close()


# ---------------------------------------------------------------------------
# RaftChain: 3 ordering nodes, in-process transport, real block stores
# ---------------------------------------------------------------------------

def _mk_chain(nid, transport, tmp_path, consenters, genesis, **kw):
    store = BlockStore(None, name=f"orderer{nid}")
    store.add_block(genesis)
    writer = BlockWriter(store)
    delivered = []
    chain = RaftChain(
        "testchannel",
        nid,
        consenters,
        BlockCutter(max_message_count=2),
        writer,
        transport,
        wal_dir=str(tmp_path / f"wal{nid}"),
        batch_timeout_s=0.2,
        tick_interval_s=0.01,
        on_block=delivered.append,
        **kw,
    )
    transport.register(nid, chain.handle_step)
    return chain, store, delivered


def _genesis():
    blk = protoutil.new_block(0, b"")
    blk.data.data.append(b"genesis-config")
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    return blk


def _env(data: bytes) -> common_pb2.Envelope:
    return common_pb2.Envelope(payload=data)


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


@pytest.fixture
def chain_cluster(tmp_path):
    transport = InProcTransport()
    consenters = [rpb.Consenter(id=i) for i in (1, 2, 3)]
    genesis = _genesis()
    chains = {}
    for nid in (1, 2, 3):
        chains[nid] = _mk_chain(nid, transport, tmp_path, consenters, genesis)
    for c, _, _ in chains.values():
        c.start()
    yield transport, chains
    for c, _, _ in chains.values():
        if not c._halted.is_set():
            c.halt()


def _leader(chains, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for nid, (c, _, _) in chains.items():
            if c.is_leader:
                return nid
        time.sleep(0.02)
    raise AssertionError("no chain leader")


def test_chain_orders_and_replicates_blocks(chain_cluster):
    transport, chains = chain_cluster
    lead = _leader(chains)
    leader_chain = chains[lead][0]
    for i in range(4):
        leader_chain.order(_env(b"tx-%d" % i))
    # 4 txs, cutter max 2 -> blocks 1 and 2 on every node
    for nid, (c, store, delivered) in chains.items():
        _wait(lambda s=store: s.height == 3, msg=f"height 3 on node {nid}")
    blk1 = chains[1][1].get_block_by_number(1)
    assert list(blk1.data.data) == [
        _env(b"tx-0").SerializeToString(),
        _env(b"tx-1").SerializeToString(),
    ]
    # all stores identical
    h1 = protoutil.block_header_hash(blk1.header)
    for nid in (2, 3):
        assert (
            protoutil.block_header_hash(
                chains[nid][1].get_block_by_number(1).header
            )
            == h1
        )


def test_chain_follower_forwards_to_leader(chain_cluster):
    transport, chains = chain_cluster
    lead = _leader(chains)
    follower = next(nid for nid in chains if nid != lead)
    chains[follower][0].order(_env(b"via-follower"))
    chains[follower][0].order(_env(b"via-follower-2"))
    for nid, (c, store, _) in chains.items():
        _wait(lambda s=store: s.height == 2, msg=f"block on node {nid}")


def test_chain_batch_timeout_cuts_partial_block(chain_cluster):
    transport, chains = chain_cluster
    lead = _leader(chains)
    chains[lead][0].order(_env(b"lonely"))
    _wait(lambda: chains[lead][1].height == 2, msg="timeout cut")


def test_chain_restart_recovers_from_wal(tmp_path):
    transport = InProcTransport()
    consenters = [rpb.Consenter(id=1)]
    genesis = _genesis()
    chain, store, _ = _mk_chain(1, transport, tmp_path, consenters, genesis)
    chain.start()
    chain.order(_env(b"a"))
    chain.order(_env(b"b"))
    _wait(lambda: store.height == 2, msg="block before restart")
    chain.halt()
    transport.unregister(1)

    # "restart": same WAL dir, fresh empty-but-genesis block store replays
    # committed raft entries into the writer
    store2 = BlockStore(None, name="orderer1-restarted")
    store2.add_block(genesis)
    writer2 = BlockWriter(store2)
    chain2 = RaftChain(
        "testchannel",
        1,
        consenters,
        BlockCutter(max_message_count=2),
        writer2,
        transport,
        wal_dir=str(tmp_path / "wal1"),
        batch_timeout_s=0.2,
        tick_interval_s=0.01,
    )
    transport.register(1, chain2.handle_step)
    chain2.start()
    _wait(lambda: store2.height == 2, msg="block replayed from WAL")
    assert (
        store2.get_block_by_number(1).SerializeToString()
        == store.get_block_by_number(1).SerializeToString()
    )
    chain2.order(_env(b"c"))
    chain2.order(_env(b"d"))
    _wait(lambda: store2.height == 3, msg="new block after restart")
    chain2.halt()
