"""Device-batched idemix Schnorr recomputation vs the host path.

The XLA program in csp/tpu/bn254_batch.py must produce bit-identical
T1/T2/T3 commitments to signature._relations +
schnorr.recompute_commitments for every disclosure shape, and the
device-backed verify_batch must agree with the host verify mask on
valid, tampered, and malformed signatures."""

from __future__ import annotations

import pytest

from fabric_tpu.idemix import bn254 as bn


@pytest.fixture(autouse=True)
def _pin_xla_engine(monkeypatch):
    """This module tests the XLA scan engine; the fused Pallas ladder
    (now the preferred engine) has its own parity suite in
    tests/test_pallas_bn254.py."""
    monkeypatch.setenv("FABRIC_BN254_NO_PALLAS", "1")
from fabric_tpu.idemix import schnorr, signature
from fabric_tpu.idemix.credential import new_cred_request, new_credential
from fabric_tpu.idemix.issuer import IssuerKey


@pytest.fixture(scope="module")
def world():
    isk = IssuerKey.generate(["a0", "a1", "a2"])
    sk = bn.rand_zr()
    req = new_cred_request(sk, b"nonce", isk.ipk)
    attrs = [11, 22, 33]
    cred = new_credential(isk, req, attrs)
    return isk, sk, cred, attrs


def _sigs(world, n=6):
    isk, sk, cred, attrs = world
    out = []
    for i in range(n):
        disclosure = [
            [False, False, False],
            [True, False, True],
            [True, True, True],
        ][i % 3]
        msg = b"msg-%d" % i
        sig = signature.new_signature(
            cred, sk, isk.ipk, msg, disclosure=disclosure
        )
        out.append((sig, msg))
    return out


def _host_commitments(sig, ipk):
    rels = signature._relations(
        ipk, sig.a_prime, sig.a_bar, sig.b_prime, sig.nym,
        sig.disclosure, sig.disclosed_attrs,
    )
    return schnorr.recompute_commitments(rels, sig.challenge, sig.responses)


def test_device_commitments_match_host(world):
    from fabric_tpu.csp.tpu import bn254_batch

    isk, *_ = world
    pairs = _sigs(world)
    got = bn254_batch.schnorr_commitments_batch(
        [s for s, _ in pairs], isk.ipk
    )
    for j, (sig, _msg) in enumerate(pairs):
        want = _host_commitments(sig, isk.ipk)
        assert got[j] is not None
        assert list(got[j]) == list(want), f"sig {j} commitments diverge"


def test_device_verify_batch_mask(world):
    from fabric_tpu.idemix.signature import verify_batch_device

    isk, sk, cred, attrs = world
    pairs = _sigs(world)
    sigs = [s for s, _ in pairs]
    msgs = [m for _, m in pairs]
    # tamper: wrong message for #1, wrong challenge for #3
    msgs = list(msgs)
    msgs[1] = b"not-the-message"
    import dataclasses

    sigs[3] = dataclasses.replace(
        sigs[3], challenge=(sigs[3].challenge + 1) % bn.R
    )
    want = signature.verify_batch(list(sigs), isk.ipk, list(msgs))
    got = verify_batch_device(list(sigs), isk.ipk, list(msgs))
    assert got == want
    assert got[1] is False and got[3] is False
    assert got[0] and got[2]


def test_device_malformed_inputs_never_throw(world):
    from fabric_tpu.idemix.signature import verify_batch_device

    isk, *_ = world
    pairs = _sigs(world, 2)
    good_sig, good_msg = pairs[0]
    import dataclasses

    off_curve = dataclasses.replace(
        good_sig, a_prime=(good_sig.a_prime[0], good_sig.a_prime[1] + 1)
    )
    missing = dataclasses.replace(
        good_sig, responses={k: v for k, v in good_sig.responses.items()
                             if k != "sk"}
    )
    bad_len = dataclasses.replace(good_sig, disclosure=[True])
    sigs = [good_sig, off_curve, missing, bad_len]
    msgs = [good_msg] * 4
    got = verify_batch_device(sigs, isk.ipk, msgs)
    assert got == [True, False, False, False]
