"""State-based (key-level) endorsement tests.

Reference coverage model: integration/sbe/sbe_test.go — set a key-level
policy via SetStateValidationParameter, then writes to that key require
the key's policy instead of the chaincode-level policy; changing the
policy is itself gated by the current policy.
"""

import pytest

from fabric_tpu.chaincode.statebased import KeyEndorsementPolicy, ROLE_MEMBER
from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.msp import msp_config_from_ca
from fabric_tpu.node.devnode import DevNode
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.protos.peer import proposal_pb2, transaction_pb2
from fabric_tpu import protoutil

from orgfix import make_org

V = transaction_pb2


def sbecc(sim, args):
    """Chaincode exercising key-level endorsement."""
    op = args[0]
    if op == b"put":
        sim.set_state("sbecc", args[1].decode(), args[2])
        return 200, "", b""
    if op == b"setpol":  # attach a key-level policy
        pol = KeyEndorsementPolicy()
        pol.add_orgs(ROLE_MEMBER, *[m.decode() for m in args[2:]])
        sim.set_state_metadata(
            "sbecc", args[1].decode(),
            {"VALIDATION_PARAMETER": pol.policy()},
        )
        return 200, "", b""
    if op == b"rawpol":  # write raw (possibly broken) policy bytes
        sim.set_state_metadata(
            "sbecc", args[1].decode(),
            {"VALIDATION_PARAMETER": args[2]},
        )
        return 200, "", b""
    return 500, f"unknown op {op!r}", b""


@pytest.fixture(scope="module")
def net():
    org1 = make_org("Org1MSP")
    org2 = make_org("Org2MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {
            "Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org1.ca, "Org1MSP")),
            "Org2": ctx.org_group("Org2MSP", msp_config_from_ca(org2.ca, "Org2MSP")),
        }
    )
    ordg = ctx.orderer_group(
        {"OrdererOrg": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
        max_message_count=10,
    )
    genesis = ctx.genesis_block("sbechannel", ctx.channel_group(app, ordg))
    peer1 = org1.signer("peer0.org1", role_ou="peer")
    peer2 = org2.signer("peer0.org2", role_ou="peer")
    node = DevNode(
        genesis,
        csp=org1.csp,
        peer_signer=peer1,
        chaincodes={"sbecc": sbecc},
        batch_timeout_s=0.25,
    )
    endorser2 = Endorser(
        node.channel_id, node.ledger, node.bundle, peer2, {"sbecc": sbecc},
        node.csp,
    )
    client = org1.signer("user1", role_ou="client")
    yield node, endorser2, client
    node.shutdown()


def _endorse(node, endorser2, client, args, endorsers):
    prop, txid = protoutil.create_chaincode_proposal(
        client.serialize(), node.channel_id, "sbecc", args
    )
    signed = proposal_pb2.SignedProposal(
        proposal_bytes=prop.SerializeToString(),
        signature=client.sign(prop.SerializeToString()),
    )
    responses = []
    if "org1" in endorsers:
        responses.append(node.endorser.process_proposal(signed))
    if "org2" in endorsers:
        responses.append(endorser2.process_proposal(signed))
    return protoutil.create_signed_tx(prop, client, responses), txid


def _commit_one(node, env):
    node.broadcast(env)
    _, flags = node.wait_commit()
    return flags


def test_key_level_policy_overrides_chaincode_policy(net):
    node, endorser2, client = net
    # seed the key under the default (MAJORITY both-orgs) policy
    env, _ = _endorse(node, endorser2, client, [b"put", b"k", b"v0"],
                      ("org1", "org2"))
    assert _commit_one(node, env) == [V.VALID]

    # attach a key-level policy: Org2 only (needs both orgs to pass the
    # current default policy on the metadata write)
    env, _ = _endorse(
        node, endorser2, client, [b"setpol", b"k", b"Org2MSP"],
        ("org1", "org2"),
    )
    assert _commit_one(node, env) == [V.VALID]

    # now an Org2-only endorsement suffices for this key (the chaincode
    # default MAJORITY policy would have rejected a single endorsement)
    env, _ = _endorse(node, endorser2, client, [b"put", b"k", b"v1"],
                      ("org2",))
    assert _commit_one(node, env) == [V.VALID]
    assert node.ledger.get_state("sbecc", "k") == b"v1"

    # ...and an Org1-only endorsement is rejected by the key's policy
    env, _ = _endorse(node, endorser2, client, [b"put", b"k", b"v2"],
                      ("org1",))
    assert _commit_one(node, env) == [V.ENDORSEMENT_POLICY_FAILURE]
    assert node.ledger.get_state("sbecc", "k") == b"v1"

    # metadata RETENTION: the value-only write of v1 must not have erased
    # the key's policy — a second Org2-only write still passes (it would
    # fail the chaincode-level MAJORITY policy if the policy were gone)
    env, _ = _endorse(node, endorser2, client, [b"put", b"k", b"v3"],
                      ("org2",))
    assert _commit_one(node, env) == [V.VALID]
    assert node.ledger.get_state("sbecc", "k") == b"v3"

    # keys WITHOUT a key-level policy still use the chaincode policy
    env, _ = _endorse(node, endorser2, client, [b"put", b"other", b"x"],
                      ("org2",))
    assert _commit_one(node, env) == [V.ENDORSEMENT_POLICY_FAILURE]


def test_same_block_policy_change_gates_later_tx(net):
    node, endorser2, client = net
    # seed key "q" and give it an Org1-only policy
    env, _ = _endorse(node, endorser2, client, [b"put", b"q", b"0"],
                      ("org1", "org2"))
    assert _commit_one(node, env) == [V.VALID]
    env, _ = _endorse(
        node, endorser2, client, [b"setpol", b"q", b"Org1MSP"],
        ("org1", "org2"),
    )
    # in the SAME block: a write endorsed by Org2 only — must fail once
    # the new Org1-only policy lands (in-block overlay ordering)
    env2, _ = _endorse(node, endorser2, client, [b"put", b"q", b"1"],
                       ("org2",))
    node.broadcast(env)
    node.broadcast(env2)
    _, flags = node.wait_commit()
    if len(flags) == 1:  # raced into two blocks
        _, flags2 = node.wait_commit()
        flags = flags + flags2
    assert flags == [V.VALID, V.ENDORSEMENT_POLICY_FAILURE]
    # an Org1-only write now passes
    env, _ = _endorse(node, endorser2, client, [b"put", b"q", b"2"],
                      ("org1",))
    assert _commit_one(node, env) == [V.VALID]
    assert node.ledger.get_state("sbecc", "q") == b"2"


def _raw_block(node, envs):
    """Hand-build a block so multi-tx ordering is deterministic (the
    batch timeout can otherwise split broadcasts across blocks)."""
    from fabric_tpu.protos.common import common_pb2

    blk = common_pb2.Block()
    blk.header.number = 1
    blk.data.data.extend(e.SerializeToString() for e in envs)
    while len(blk.metadata.metadata) < 3:
        blk.metadata.metadata.append(b"")
    return blk


def test_inblock_conflict_invalidates_even_when_new_policy_satisfied(net):
    """Reference vpmanagerimpl.go:219 ValidationParameterUpdatedError: a
    tx touching a key whose VALIDATION_PARAMETER an earlier VALID tx in
    the block rewrote is invalid, even if its endorsements satisfy both
    the old and the new policy."""
    node, endorser2, client = net
    env, _ = _endorse(node, endorser2, client, [b"put", b"w", b"0"],
                      ("org1", "org2"))
    assert _commit_one(node, env) == [V.VALID]

    env1, _ = _endorse(node, endorser2, client,
                       [b"setpol", b"w", b"Org2MSP"], ("org1", "org2"))
    # endorsed by BOTH orgs: satisfies MAJORITY (old) and Org2-only (new)
    env2, _ = _endorse(node, endorser2, client, [b"put", b"w", b"1"],
                       ("org1", "org2"))
    flags = node.validator.validate(_raw_block(node, [env1, env2]))
    assert flags == [V.VALID, V.ENDORSEMENT_POLICY_FAILURE]

    # order matters: the put BEFORE the setpol is untouched by the rule
    env3, _ = _endorse(node, endorser2, client, [b"put", b"w2", b"x"],
                       ("org1", "org2"))
    env4, _ = _endorse(node, endorser2, client,
                       [b"setpol", b"w2", b"Org1MSP"], ("org1", "org2"))
    flags = node.validator.validate(_raw_block(node, [env3, env4]))
    assert flags == [V.VALID, V.VALID]


def test_conflict_with_invalid_first_tx_does_not_gate(net):
    """An INVALID metadata write introduces no dependency
    (waitForValidationResults only errors when the dep tx validated)."""
    node, endorser2, client = net
    env, _ = _endorse(node, endorser2, client, [b"put", b"z", b"0"],
                      ("org1", "org2"))
    assert _commit_one(node, env) == [V.VALID]
    # setpol endorsed by org1 only -> fails MAJORITY -> invalid
    env1, _ = _endorse(node, endorser2, client,
                       [b"setpol", b"z", b"Org1MSP"], ("org1",))
    env2, _ = _endorse(node, endorser2, client, [b"put", b"z", b"1"],
                       ("org1", "org2"))
    flags = node.validator.validate(_raw_block(node, [env1, env2]))
    assert flags == [V.ENDORSEMENT_POLICY_FAILURE, V.VALID]


def test_unparseable_key_policy_invalidates_writes(net):
    """A key whose committed VALIDATION_PARAMETER does not unmarshal
    invalidates txs writing the key (reference policyErr on a broken
    vp), rather than silently falling back to the chaincode policy."""
    node, endorser2, client = net
    env, _ = _endorse(node, endorser2, client, [b"put", b"bad", b"0"],
                      ("org1", "org2"))
    assert _commit_one(node, env) == [V.VALID]
    # the metadata write itself is gated by the PRE-write policy
    # (chaincode MAJORITY), so it commits fine
    env, _ = _endorse(node, endorser2, client,
                      [b"rawpol", b"bad", b"\x08"], ("org1", "org2"))
    assert _commit_one(node, env) == [V.VALID]
    env, _ = _endorse(node, endorser2, client, [b"put", b"bad", b"v"],
                      ("org1", "org2"))
    assert _commit_one(node, env) == [V.ENDORSEMENT_POLICY_FAILURE]


@pytest.fixture(scope="module")
def ccnet():
    """Network with committed chaincode definitions: cc1 (Org1-only EP,
    collection collA with an Org2-only collection EP) and cc2
    (Org2-only EP)."""
    from fabric_tpu.common.privdata import (
        collection_package,
        static_collection,
    )
    from fabric_tpu.policies.signature_policy import signed_by_msp_role
    from fabric_tpu.protos.msp import msp_principal_pb2
    from fabric_tpu.protos.peer import collection_pb2

    org1 = make_org("Org1MSP")
    org2 = make_org("Org2MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {
            "Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org1.ca, "Org1MSP")),
            "Org2": ctx.org_group("Org2MSP", msp_config_from_ca(org2.ca, "Org2MSP")),
        }
    )
    ordg = ctx.orderer_group(
        {"OrdererOrg": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
        max_message_count=10,
    )
    genesis = ctx.genesis_block("ccchannel", ctx.channel_group(app, ordg))

    def role(mspid):
        return signed_by_msp_role(mspid, msp_principal_pb2.MSPRole.MEMBER)

    def app_policy_bytes(env):
        ap = collection_pb2.ApplicationPolicy()
        ap.signature_policy.CopyFrom(env)
        return ap.SerializeToString()

    colls = collection_package(
        static_collection("collA", ["Org1MSP", "Org2MSP"],
                          endorsement_policy=role("Org2MSP"))
    )

    class Defs:
        _params = {
            "cc1": app_policy_bytes(role("Org1MSP")),
            "cc2": app_policy_bytes(role("Org2MSP")),
        }

        def validation_info(self, name):
            p = self._params.get(name)
            return ("vscc", p) if p is not None else None

        def collection_config(self, name, coll):
            if name != "cc1":
                return None
            for c in colls.config:
                if c.static_collection_config.name == coll:
                    return c.static_collection_config
            return None

    def cc1(sim, args):
        op = args[0]
        if op == b"own":
            sim.set_state("cc1", args[1].decode(), args[2])
        elif op == b"xns":  # cross-namespace write (cc2cc)
            sim.set_state("cc1", args[1].decode(), args[2])
            sim.set_state("cc2", args[1].decode(), args[2])
        elif op == b"pvt":  # collection write
            sim.set_private_data("cc1", "collA", args[1].decode(), args[2])
        else:
            return 500, f"unknown op {args[0]!r}", b""
        return 200, "", b""

    peer1 = org1.signer("peer0.org1", role_ou="peer")
    peer2 = org2.signer("peer0.org2", role_ou="peer")
    node = DevNode(
        genesis,
        csp=org1.csp,
        peer_signer=peer1,
        chaincodes={"cc1": cc1},
        batch_timeout_s=0.25,
        definition_provider=Defs(),
    )
    endorser2 = Endorser(
        node.channel_id, node.ledger, node.bundle, peer2, {"cc1": cc1},
        node.csp,
    )
    client = org1.signer("user1", role_ou="client")
    yield node, endorser2, client
    node.shutdown()


def _cc1_tx(node, endorser2, client, args, endorsers):
    prop, _ = protoutil.create_chaincode_proposal(
        client.serialize(), node.channel_id, "cc1", args
    )
    signed = proposal_pb2.SignedProposal(
        proposal_bytes=prop.SerializeToString(),
        signature=client.sign(prop.SerializeToString()),
    )
    responses = []
    if "org1" in endorsers:
        responses.append(node.endorser.process_proposal(signed))
    if "org2" in endorsers:
        responses.append(endorser2.process_proposal(signed))
    env = protoutil.create_signed_tx(prop, client, responses)
    node.broadcast(env)
    _, flags = node.wait_commit()
    return flags


def test_cc2cc_write_gated_by_target_namespace_policy(ccnet):
    """A tx whose rwset spans namespaces is validated against EACH
    written namespace's endorsement policy (dispatcher.go:190)."""
    node, endorser2, client = ccnet
    # own-namespace write: Org1's endorsement suffices (cc1 EP)
    assert _cc1_tx(node, endorser2, client, [b"own", b"a", b"1"],
                   ("org1",)) == [V.VALID]
    # cross-namespace write endorsed by Org1 only: cc2's Org2-only EP fails
    assert _cc1_tx(node, endorser2, client, [b"xns", b"b", b"1"],
                   ("org1",)) == [V.ENDORSEMENT_POLICY_FAILURE]
    assert node.ledger.get_state("cc2", "b") is None
    # endorsed by both orgs: both namespace policies pass
    assert _cc1_tx(node, endorser2, client, [b"xns", b"b", b"2"],
                   ("org1", "org2")) == [V.VALID]
    assert node.ledger.get_state("cc2", "b") == b"2"


def test_collection_level_endorsement_policy(ccnet):
    """Collection writes without key-level policies are gated by the
    collection EP when one is defined, INSTEAD of the chaincode EP
    (v20.go CheckCCEPIfNotChecked)."""
    node, endorser2, client = ccnet
    # Org1 satisfies cc1's chaincode EP but NOT collA's Org2-only EP
    assert _cc1_tx(node, endorser2, client, [b"pvt", b"p", b"1"],
                   ("org1",)) == [V.ENDORSEMENT_POLICY_FAILURE]
    # Org2 alone satisfies the collection EP (which replaces the cc EP
    # for collection keys)
    assert _cc1_tx(node, endorser2, client, [b"pvt", b"p", b"2"],
                   ("org2",)) == [V.VALID]
