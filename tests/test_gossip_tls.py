"""TCPGossipComm over mutual TLS: delivery works, and the ConnEstablish
handshake is bound to the TLS session — an unsigned handshake and a
validly-signed handshake claiming a different cert's hash are both
rejected (reference gossip/comm/crypto.go:20-40 binding)."""

from __future__ import annotations

import hashlib
import socket
import struct
import time

import pytest

from fabric_tpu.comm.tls import credentials_from_ca
from fabric_tpu.common.crypto import CA
from fabric_tpu.gossip.comm import MessageCryptoService, TCPGossipComm
from fabric_tpu.protos.gossip import message_pb2 as gpb

_LEN = struct.Struct(">I")


class _ToyMCS(MessageCryptoService):
    """Deterministic shared-secret signer so handshake signatures are
    real (and verifiable) without standing up MSPs."""

    def sign(self, payload: bytes) -> bytes:
        return hashlib.sha256(b"toy-secret" + payload).digest()

    def verify(self, identity: bytes, signature: bytes, payload: bytes) -> bool:
        return signature == hashlib.sha256(b"toy-secret" + payload).digest()


@pytest.fixture(scope="module")
def ca():
    return CA("tlsca.gossip", "org1")


def _wait(pred, timeout=5.0):
    end = time.time() + timeout
    while time.time() < end:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _data_msg(payload: bytes) -> gpb.GossipMessage:
    m = gpb.GossipMessage()
    m.data_msg.block = payload
    m.data_msg.seq_num = 1
    return m


def test_tls_gossip_delivery(ca):
    a = TCPGossipComm(("127.0.0.1", 0), b"idA", mcs=_ToyMCS(),
                      tls=credentials_from_ca(ca, "peerA"))
    b = TCPGossipComm(("127.0.0.1", 0), b"idB", mcs=_ToyMCS(),
                      tls=credentials_from_ca(ca, "peerB"))
    got = []
    b.subscribe(lambda rm: got.append(rm.msg.data_msg.block))
    try:
        a.send(b.endpoint, _data_msg(b"hello-tls"))
        assert _wait(lambda: got == [b"hello-tls"])
        # B learned A's gossip identity through the bound handshake
        assert b.identity_of(a.pki_id) == b"idA"
    finally:
        a.close()
        b.close()


def test_plaintext_sender_rejected_by_tls_listener(ca):
    b = TCPGossipComm(("127.0.0.1", 0), b"idB", mcs=_ToyMCS(),
                      tls=credentials_from_ca(ca, "peerB"))
    a = TCPGossipComm(("127.0.0.1", 0), b"idA", mcs=_ToyMCS())  # no TLS
    got = []
    b.subscribe(lambda rm: got.append(rm.msg))
    try:
        a.send(b.endpoint, _data_msg(b"plaintext"))
        assert not _wait(lambda: got, timeout=1.5)
    finally:
        a.close()
        b.close()


def test_require_client_auth_enforced(ca):
    with pytest.raises(ValueError):
        TCPGossipComm(
            ("127.0.0.1", 0), b"idX",
            tls=credentials_from_ca(ca, "x", require_client_auth=False),
        )


def _raw_tls_handshake(b_endpoint: str, creds, ce: gpb.ConnEstablish):
    ctx = creds.client_context()
    host, port = b_endpoint.rsplit(":", 1)
    sock = ctx.wrap_socket(
        socket.create_connection((host, int(port)), timeout=3),
        server_hostname=host,
    )
    raw = ce.SerializeToString()
    sock.sendall(_LEN.pack(len(raw)) + raw)
    signed = gpb.SignedGossipMessage(
        payload=_data_msg(b"forged").SerializeToString()
    ).SerializeToString()
    sock.sendall(_LEN.pack(len(signed)) + signed)
    return sock


def test_unsigned_handshake_rejected(ca):
    b = TCPGossipComm(("127.0.0.1", 0), b"idB", mcs=_ToyMCS(),
                      tls=credentials_from_ca(ca, "peerB"))
    got = []
    b.subscribe(lambda rm: got.append(rm.msg))
    mallory = credentials_from_ca(ca, "mallory")
    mcs = _ToyMCS()
    try:
        ce = gpb.ConnEstablish(
            pki_id=mcs.get_pki_id(b"idA"), identity=b"idA",
            tls_cert_hash=mallory.cert_hash,  # even the honest hash
        )
        # ... but no signature: must be dropped under TLS
        _raw_tls_handshake(b.endpoint, mallory, ce)
        assert not _wait(lambda: got, timeout=1.5)
    finally:
        b.close()


def test_handshake_not_bound_to_session_rejected(ca):
    """Mallory authenticates with her own cert but replays a handshake
    whose tls_cert_hash (and valid signature!) belong to a different TLS
    identity — the session-binding check must drop it."""
    b = TCPGossipComm(("127.0.0.1", 0), b"idB", mcs=_ToyMCS(),
                      tls=credentials_from_ca(ca, "peerB"))
    got = []
    b.subscribe(lambda rm: got.append(rm.msg))

    mallory = credentials_from_ca(ca, "mallory")
    victim = credentials_from_ca(ca, "victimA")
    mcs = _ToyMCS()
    try:
        ce = gpb.ConnEstablish(
            pki_id=mcs.get_pki_id(b"idA"), identity=b"idA",
            tls_cert_hash=victim.cert_hash,
        )
        ce.signature = mcs.sign(bytes(ce.pki_id) + bytes(ce.tls_cert_hash))
        _raw_tls_handshake(b.endpoint, mallory, ce)
        assert not _wait(lambda: got, timeout=1.5)
    finally:
        b.close()
