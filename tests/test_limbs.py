"""Parity tests: vectorized limb arithmetic vs python ints.

The TPU field arithmetic must agree with arbitrary-precision host math on
random and adversarial values (SURVEY.md section 7 step 9: crypto parity
vectors against a software oracle)."""

import random

import numpy as np
import pytest

from fabric_tpu.csp import api
from fabric_tpu.csp.tpu import limbs


P = api.P256_P
N = api.P256_N


def rand_invariant(rng, bound=1 << 257):
    """Random value satisfying the lazy invariant (< 2**257)."""
    return rng.randrange(bound)


@pytest.mark.parametrize("m", [P, N])
def test_mod_ops_parity(m):
    rng = random.Random(1234 + m % 97)
    ctx = limbs.mod_ctx(m)
    edge = [0, 1, m - 1, m, m + 1, (1 << 256) - 1, (1 << 257) - 1, m // 2]
    vals_a = edge + [rand_invariant(rng) for _ in range(56)]
    vals_b = list(reversed(edge)) + [rand_invariant(rng) for _ in range(56)]
    a = np.asarray(limbs.ints_to_limbs(vals_a))
    b = np.asarray(limbs.ints_to_limbs(vals_b))

    got_add = limbs.limbs_to_ints(np.asarray(ctx.add(a, b)))
    got_sub = limbs.limbs_to_ints(np.asarray(ctx.sub(a, b)))
    got_mul = limbs.limbs_to_ints(np.asarray(ctx.mul(a, b)))
    got_sqr = limbs.limbs_to_ints(np.asarray(ctx.sqr(a)))
    got_canon = limbs.limbs_to_ints(np.asarray(ctx.canon(a)))
    got_k3 = limbs.limbs_to_ints(np.asarray(ctx.mul_const(a, 3)))
    got_k8 = limbs.limbs_to_ints(np.asarray(ctx.mul_const(a, 8)))

    for i, (x, y) in enumerate(zip(vals_a, vals_b)):
        assert got_add[i] % m == (x + y) % m, ("add", i)
        assert got_sub[i] % m == (x - y) % m, ("sub", i)
        assert got_mul[i] % m == (x * y) % m, ("mul", i)
        assert got_sqr[i] % m == (x * x) % m, ("sqr", i)
        assert got_canon[i] == x % m, ("canon", i)
        assert got_k3[i] % m == (3 * x) % m, ("k3", i)
        assert got_k8[i] % m == (8 * x) % m, ("k8", i)
        # invariant maintained: results below 2**257
        assert got_add[i] < 1 << 257
        assert got_sub[i] < 1 << 257
        assert got_mul[i] < 1 << 257


@pytest.mark.parametrize("m", [P, N])
def test_mod_chain_stress(m):
    """Long randomly-interleaved op chains keep parity and the invariant."""
    rng = random.Random(77)
    ctx = limbs.mod_ctx(m)
    vals = [rng.randrange(1 << 256) for _ in range(8)]
    dev = np.asarray(limbs.ints_to_limbs(vals))
    ref = list(vals)
    for step in range(60):
        op = rng.choice(["add", "sub", "mul", "sqr"])
        j = rng.randrange(8)
        other = np.roll(dev, j, axis=0)
        ref_other = ref[-j:] + ref[:-j]
        if op == "add":
            dev = np.asarray(ctx.add(dev, other))
            ref = [(x + y) % m for x, y in zip(ref, ref_other)]
        elif op == "sub":
            dev = np.asarray(ctx.sub(dev, other))
            ref = [(x - y) % m for x, y in zip(ref, ref_other)]
        elif op == "mul":
            dev = np.asarray(ctx.mul(dev, other))
            ref = [(x * y) % m for x, y in zip(ref, ref_other)]
        else:
            dev = np.asarray(ctx.sqr(dev))
            ref = [(x * x) % m for x in ref]
        got = limbs.limbs_to_ints(dev)
        for i in range(8):
            assert got[i] < 1 << 257, (step, op, i)
            assert got[i] % m == ref[i], (step, op, i)


def test_eq_is_zero():
    ctx = limbs.mod_ctx(P)
    vals = [0, P, 2 * P - 1, 5, P + 5]
    a = np.asarray(limbs.ints_to_limbs(vals))
    z = np.asarray(ctx.is_zero(a))
    assert list(z) == [True, True, False, False, False]
    b = np.asarray(limbs.ints_to_limbs([P, 0, P - 2, 5 + P, 5]))
    e = np.asarray(ctx.eq(a, b))
    # 2P-1 ≡ P-1 ≢ P-2 (mod P)
    assert list(e) == [True, True, False, True, True]


def test_mul_wide_parity():
    rng = random.Random(5)
    xs = [rng.randrange(1 << 272) for _ in range(16)]
    ys = [rng.randrange(1 << 272) for _ in range(16)]
    a = np.asarray(limbs.ints_to_limbs(xs, 17))
    b = np.asarray(limbs.ints_to_limbs(ys, 17))
    got = limbs.limbs_to_ints(np.asarray(limbs.mul_wide(a, b)))
    for i in range(16):
        assert got[i] == xs[i] * ys[i]


def test_mul_low_parity():
    rng = random.Random(6)
    xs = [0, 1, (1 << 272) - 1] + [rng.randrange(1 << 272) for _ in range(13)]
    ys = [(1 << 272) - 1, 0, (1 << 272) - 1] + [
        rng.randrange(1 << 272) for _ in range(13)
    ]
    a = np.asarray(limbs.ints_to_limbs(xs, 17))
    b = np.asarray(limbs.ints_to_limbs(ys, 17))
    got = limbs.limbs_to_ints(np.asarray(limbs.mul_low(a, b, 17)))
    for i in range(len(xs)):
        assert got[i] == (xs[i] * ys[i]) % (1 << 272), i


BN_P = 21888242871839275222246405745257275088696311157297823662689037894645226208583


@pytest.mark.parametrize("m", [BN_P, P, N])
def test_mont_ops_parity(m):
    """MontMod keeps exact parity mod m with plain-int math: elements in
    Montgomery form x·R, REDC-based mul/sqr, inherited add/sub/canon."""
    rng = random.Random(4321 + m % 89)
    ctx = limbs.mont_ctx(m)
    r = ctx.r
    vals_a = [0, 1, m - 1, m // 3] + [rng.randrange(m) for _ in range(28)]
    vals_b = [m - 1, 1, 0, m // 7] + [rng.randrange(m) for _ in range(28)]
    a = np.asarray(limbs.ints_to_limbs([ctx.to_mont_int(x) for x in vals_a]))
    b = np.asarray(limbs.ints_to_limbs([ctx.to_mont_int(x) for x in vals_b]))

    got_mul = limbs.limbs_to_ints(np.asarray(ctx.mul(a, b)))
    got_sqr = limbs.limbs_to_ints(np.asarray(ctx.sqr(a)))
    got_add = limbs.limbs_to_ints(np.asarray(ctx.add(a, b)))
    got_sub = limbs.limbs_to_ints(np.asarray(ctx.sub(a, b)))
    got_k3 = limbs.limbs_to_ints(np.asarray(ctx.mul_const(a, 3)))
    got_canon = limbs.limbs_to_ints(np.asarray(ctx.canon(a)))
    got_plain = limbs.limbs_to_ints(np.asarray(ctx.from_mont(a)))

    for i, (x, y) in enumerate(zip(vals_a, vals_b)):
        assert got_mul[i] % m == (x * y) % m * r % m, ("mul", i)
        assert got_mul[i] < 2 * m, ("mul bound", i)
        assert got_sqr[i] % m == (x * x) % m * r % m, ("sqr", i)
        assert got_add[i] % m == (x + y) % m * r % m, ("add", i)
        assert got_add[i] < 1 << 257, ("add bound", i)
        assert got_sub[i] % m == (x - y) % m * r % m, ("sub", i)
        assert got_k3[i] % m == 3 * x % m * r % m, ("k3", i)
        assert got_canon[i] == x * r % m, ("canon", i)
        assert got_plain[i] % m == x, ("from_mont", i)
        assert ctx.from_mont_int(got_canon[i]) == x, ("from_mont_int", i)


def test_mont_chain_stress():
    """Interleaved Montgomery op chains keep parity and the invariant."""
    m = BN_P
    rng = random.Random(88)
    ctx = limbs.mont_ctx(m)
    vals = [rng.randrange(m) for _ in range(8)]
    dev = np.asarray(limbs.ints_to_limbs([ctx.to_mont_int(x) for x in vals]))
    ref = list(vals)
    for step in range(48):
        op = rng.choice(["add", "sub", "mul", "sqr"])
        j = rng.randrange(8)
        other = np.roll(dev, j, axis=0)
        ref_other = ref[-j:] + ref[:-j]
        if op == "add":
            dev = np.asarray(ctx.add(dev, other))
            ref = [(x + y) % m for x, y in zip(ref, ref_other)]
        elif op == "sub":
            dev = np.asarray(ctx.sub(dev, other))
            ref = [(x - y) % m for x, y in zip(ref, ref_other)]
        elif op == "mul":
            dev = np.asarray(ctx.mul(dev, other))
            ref = [(x * y) % m for x, y in zip(ref, ref_other)]
        else:
            dev = np.asarray(ctx.sqr(dev))
            ref = [(x * x) % m for x in ref]
        got = limbs.limbs_to_ints(dev)
        for i in range(8):
            assert got[i] < 1 << 257, (step, op, i)
            assert got[i] % m == ref[i] * ctx.r % m, (step, op, i)
