"""Profscope acceptance: the zero-overhead disarmed contract, the env
knob, sampler capture with source-site frames, per-span CPU attribution
joined to tracelens' critical path, lock-contention roles mirrored into
lock_wait_seconds{role} on /metrics (and visible to a netscope scrape),
workpool chunk queue-wait/run attribution, profiled-vs-unprofiled
commit parity under the invariants oracle, faultfuzz profile artifacts,
and the scripts/profile.py CLI line."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import urllib.request

import pytest

from fabric_tpu.common import profile, tracing, workpool
from fabric_tpu.common.operations import System
from fabric_tpu.comm.rpc import RPCClient, RPCServer
from fabric_tpu.devtools import faultfuzz, invariants, lockwatch

CHANNEL = faultfuzz.CHANNEL


# -- disarmed: the zero-overhead contract ------------------------------------


def test_disarmed_profile_entry_points_are_noops():
    """FABRIC_TPU_PROFILE unset (tier-1 default): no profiler exists,
    every entry point no-ops, and a real RPC round trip plus a pooled
    fan-out (both of which cross watched locks and run_chunked's feed
    point) never touch the armed path."""
    assert not profile.enabled()
    assert profile.profiler() is None
    before = profile.lookup_count()

    # every feed/control point, disarmed
    profile.note_lock_wait("kvledger.commit_lock", 0.5)
    profile.note_lock_hold("kvledger.commit_lock", 0.5)
    profile.note_chunk(0.1, 0.2)
    profile.reset()
    doc = profile.export()
    assert doc["$schema"] == profile.SPEEDSCOPE_SCHEMA
    assert doc["profiles"] == []
    assert doc["otherData"]["armed"] is False

    # a live RPC round trip and a pooled fan-out, fully disarmed
    srv = RPCServer()
    srv.register("echo", lambda body, stream: body)
    srv.start()
    try:
        assert RPCClient(*srv.addr, timeout=5.0).call(
            "echo", b"hi"
        ) == b"hi"
    finally:
        srv.stop()
    with workpool.scoped_pool(2) as pool:
        out = workpool.run_chunked(
            pool, lambda off, chunk: [v * 2 for v in chunk],
            list(range(10)), 2,
        )
    assert out == [v * 2 for v in range(10)]

    # nothing above consulted the armed path, and no sampler exists
    assert profile.lookup_count() == before
    assert profile.profiler() is None


def test_env_knob_arms_and_sizes_the_sampler(monkeypatch):
    for falsy in ("0", "false", "off", "no", ""):
        monkeypatch.setenv("FABRIC_TPU_PROFILE", falsy)
        profile._init_from_env()
        assert not profile.enabled(), falsy
    monkeypatch.setenv("FABRIC_TPU_PROFILE", "1")
    profile._init_from_env()
    try:
        assert profile.enabled()
        assert profile.profiler().interval_s == profile.DEFAULT_INTERVAL_S
        assert profile.profiler().running
    finally:
        profile.disarm()
    # a number > 1 is a sampling rate in Hz (the FABRIC_TPU_TRACE
    # sizing convention)
    monkeypatch.setenv("FABRIC_TPU_PROFILE", "250")
    profile._init_from_env()
    try:
        assert profile.profiler().interval_s == pytest.approx(1 / 250)
    finally:
        profile.disarm()
    assert not profile.enabled()
    assert profile.profiler() is None


def test_scope_restores_previous_state_and_joins_sampler():
    assert not profile.enabled()
    with profile.scope(interval_s=0.002) as p:
        assert profile.enabled()
        assert profile.profiler() is p
        assert p.running
    assert not profile.enabled()
    assert not p.running  # the sampler service thread was joined


# -- sampling: source-site frames + CPU heuristic ----------------------------


def _spin_until(stop: threading.Event) -> None:
    # fresh call frames each iteration so consecutive samples see a
    # moved frame (the on-CPU heuristic)
    def burn(n):
        return sum(i * i for i in range(n))

    while not stop.is_set():
        burn(200)


def test_sampler_folds_spinning_thread_into_collapsed_stacks():
    stop = threading.Event()
    t = lockwatch.spawn_thread(
        lambda: _spin_until(stop), name="profscope-test-spin",
        kind="worker",
    )
    t.start()
    try:
        with profile.scope(sampler=False) as p:
            p.sample_rounds(6)
            doc = profile.export("test.session")
    finally:
        stop.set()
        t.join(timeout=10.0)

    assert doc["name"] == "test.session"
    assert doc["otherData"]["samples"] == 6
    frames = [f["name"] for f in doc["shared"]["frames"]]
    # frame names carry the source site: "fn (file.py:NN)"
    assert any(f.startswith("_spin_until (") for f in frames)
    (prof,) = doc["profiles"]
    assert prof["type"] == "sampled"
    assert prof["unit"] == "seconds"
    assert len(prof["samples"]) == len(prof["weights"])
    assert prof["endValue"] == pytest.approx(sum(prof["weights"]))
    # collapsed rows are "a;b;c N" and their counts sum to the wall
    # samples attributed across stacks
    for row in doc["otherData"]["collapsed"]:
        stack, _, count = row.rpartition(" ")
        assert int(count) >= 1
        assert ";" in stack or stack


def test_span_self_cpu_attribution_joins_critical_path():
    """Samples landing inside a live tracelens span are charged to it:
    self_cpu_ms keys are span names that also appear in the trace's
    critical path — busy-CPU read next to wall-gating per stage."""
    stop = threading.Event()
    started = threading.Event()

    def staged():
        with tracing.span("hot.stage", cat="stage", block=0):
            started.set()
            _spin_until(stop)

    with tracing.scope() as rec:
        with profile.scope(sampler=False) as p:
            t = lockwatch.spawn_thread(
                staged, name="profscope-test-stage", kind="worker",
            )
            t.start()
            try:
                assert started.wait(timeout=10.0)
                p.sample_rounds(6)
            finally:
                stop.set()
                t.join(timeout=10.0)
            prof_doc = profile.export()
        trace_doc = tracing.export(rec)

    od = prof_doc["otherData"]
    assert "hot.stage" in od["self_cpu_ms"]
    (row,) = [r for r in od["span_cpu"] if r["name"] == "hot.stage"]
    assert row["cat"] == "stage"
    assert row["wall_samples"] >= 1
    assert row["cpu_samples"] >= 1  # fresh frames each burn() => on-CPU
    assert row["self_cpu_ms"] == od["self_cpu_ms"]["hot.stage"]
    # the join: every CPU-attributed span is a critical-path stage
    cp = tracing.critical_path_ms(trace_doc["traceEvents"])
    assert set(od["self_cpu_ms"]) <= set(cp)


# -- lock contention + workpool attribution ----------------------------------


def test_lock_wait_lands_in_export_metrics_and_netscope_scrape():
    """A contended watched lock feeds profscope per-role aggregates,
    mirrors into lock_wait_seconds{role} on the operations /metrics
    page, and a netscope scrape of that endpoint carries the series."""
    sys_ = System(("127.0.0.1", 0))
    sys_.start()
    try:
        with profile.scope(sampler=False):
            profile.set_lock_metrics(sys_.lock_metrics())
            try:
                lock = lockwatch.named_lock("test.contend")
                held = threading.Event()
                done = threading.Event()

                def holder():
                    with lock:
                        held.set()
                        done.wait(timeout=10.0)

                t = lockwatch.spawn_thread(
                    holder, name="profscope-test-holder", kind="worker",
                )
                t.start()
                try:
                    assert held.wait(timeout=10.0)
                    done.set()  # waiter below blocks until holder exits
                    with lock:
                        pass
                finally:
                    t.join(timeout=10.0)
                doc = profile.export()
            finally:
                profile.set_lock_metrics(None)

        locks = doc["otherData"]["locks"]
        assert "test.contend" in locks
        assert locks["test.contend"]["wait_count"] >= 2
        assert locks["test.contend"]["hold_count"] >= 2
        assert locks["test.contend"]["wait_s"] >= 0.0
        assert (
            locks["test.contend"]["max_wait_s"]
            >= locks["test.contend"]["wait_s"]
            / locks["test.contend"]["wait_count"]
        )

        host, port = sys_.addr
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=5
        ) as r:
            exposed = r.read().decode("utf-8")
        assert 'lock_wait_seconds_count{role="test.contend"}' in exposed
        assert 'lock_hold_seconds_count{role="test.contend"}' in exposed

        from fabric_tpu.devtools.netscope import Netscope

        scope = Netscope({"n0": sys_.addr}, seed=1)
        scope.run_rounds(1)
        names = {name for (_, name, _) in scope.series_keys()}
        assert any(n.startswith("lock_wait_seconds") for n in names)
    finally:
        sys_.stop()


def test_workpool_chunk_queue_wait_vs_run_attribution():
    with profile.scope(sampler=False):
        with workpool.scoped_pool(2) as pool:
            out = workpool.run_chunked(
                pool, lambda off, chunk: [v + 1 for v in chunk],
                list(range(20)), 4,
            )
        doc = profile.export()
    assert out == [v + 1 for v in range(20)]
    wp = doc["otherData"]["workpool"]
    assert wp["chunks"] == 4
    assert wp["queue_wait_s"] >= 0.0
    assert wp["run_s"] > 0.0


# -- profiled vs unprofiled commit parity ------------------------------------


def _run_commit_workload(root: str, blocks: int = 3):
    """Commit the canned per-block writes; returns (block bytes list,
    state records, last hash) with the provider closed after."""
    from fabric_tpu.ledger import LedgerProvider

    provider = LedgerProvider(root)
    ledger = provider.open(CHANNEL)
    writes = faultfuzz.workload_writes(blocks)
    try:
        for n in range(blocks + 2):
            ledger.commit(
                faultfuzz._endorsed_block(ledger, n, writes[n])
            )
        blocks_raw = [
            ledger.get_block_by_number(n).SerializeToString()
            for n in range(blocks + 2)
        ]
        state = list(ledger.state_db.export_records())
        return blocks_raw, state, ledger.block_store.last_block_hash
    finally:
        provider.close()


def test_profiled_commit_stream_is_byte_identical_to_unprofiled(tmp_path):
    """The parity acceptance: the sampler observes, never participates
    — committed blocks, exported state records, and the chain head
    hash are byte-identical with and without a live background sampler,
    and the invariants oracle passes the profiled ledger."""
    plain = _run_commit_workload(str(tmp_path / "plain"))
    with profile.scope(interval_s=0.002):
        profiled = _run_commit_workload(str(tmp_path / "profiled"))
        doc = profile.export()
        # the sampler really ran over the workload (it always takes at
        # least one sweep on start)
        assert doc["otherData"]["samples"] >= 1
    assert profiled[0] == plain[0]  # every block, byte for byte
    assert profiled[1] == plain[1]  # every state record
    assert profiled[2] == plain[2]  # chain head

    from fabric_tpu.ledger import LedgerProvider

    provider = LedgerProvider(str(tmp_path / "profiled"))
    try:
        vs = invariants.check_ledger(
            provider.open(CHANNEL), faultfuzz.workload_writes(3)
        )
        assert vs == []
    finally:
        provider.close()


# -- faultfuzz: profile artifact beside the repro ----------------------------


def test_campaign_writes_profile_artifact_next_to_repro(
    tmp_path, monkeypatch,
):
    """A failing campaign plan leaves <repro>.profile.json beside the
    repro JSON when profscope is armed (the trace-artifact contract)."""
    seeded = {
        "faults": [
            {"point": "store.shard_flush", "action": "crash",
             "ctx": {"stage": "apply"}, "count": 1},
            {"point": "store.shard_recover", "action": "skip",
             "count": 5},
        ],
    }
    monkeypatch.setattr(
        faultfuzz, "generate_plan",
        lambda rng, registry, label, tripped=frozenset():
            {**seeded, "label": label, "seed": 3},
    )
    out_dir = tmp_path / "artifacts"
    with profile.scope(sampler=False):
        summary = faultfuzz.Campaign(
            seed=11, plans=1, out_dir=str(out_dir),
            workdir=str(tmp_path / "work"), shrink=False, comm=False,
        ).run()
    assert summary["failures"] == 1
    (repro,) = summary["repro"]
    (prof_path,) = summary["profile"]
    assert prof_path == repro[: -len(".json")] + ".profile.json"
    with open(prof_path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["$schema"] == profile.SPEEDSCOPE_SCHEMA
    # the run's workpool/lock aggregates rode along with the stacks
    assert "workpool" in doc["otherData"]
    assert "locks" in doc["otherData"]


# -- scripts/profile.py: the CLI line ----------------------------------------


def test_profile_cli_emits_bench_style_line_and_artifact(tmp_path):
    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts", "profile.py",
    )
    out = tmp_path / "profscope.json"
    env = dict(os.environ)
    env.pop("FABRIC_TPU_PROFILE", None)  # the CLI arms its own scope
    res = subprocess.run(
        [sys.executable, script, "--blocks", "2", "--hz", "400",
         "--out", str(out)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert res.returncode == 0, res.stderr
    line = json.loads(res.stdout.strip().splitlines()[-1])
    assert line["experiment"] == "profscope"
    assert line["final_height"] == 4  # the blocks + 2 workload commits
    assert line["samples"] >= 1
    assert line["top_frames"], "hot frames must be attributed"
    assert all(
        set(f) == {"frame", "samples"} for f in line["top_frames"]
    )
    assert isinstance(line["lock_wait_ms"], dict)
    assert line["artifact"] == str(out)
    with open(out, "r", encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["$schema"] == profile.SPEEDSCOPE_SCHEMA
    assert doc["otherData"]["collapsed"]
