"""Seeded violation (rpc-conformance): the client calls
``fix.Missing`` but NO component registers that method — the call can
only ever raise method-not-found.  ``fix.Ping`` is registered AND
called, so the only violation is the orphan call site."""


class FixServer:
    def __init__(self, rpc):
        self.rpc = rpc
        self.rpc.register("fix.Ping", self._ping)

    def _ping(self, body, stream):
        return b"pong"


def probe(conn):
    conn.call("fix.Ping", b"")
    return conn.call("fix.Missing", b"")  # <- orphan call site: HERE
