"""Seeded violations (knob-conformance): one read of a FABRIC_TPU_*
name that has NO knob_registry entry, and one read of a registered
name that BYPASSES the registry helper with a raw ``os.environ.get``.
Expected: both fire, each at its read site."""

import os

from fabric_tpu.devtools import knob_registry


def tuning():
    ghost = knob_registry.raw("FABRIC_TPU_FIXTURE_GHOST")  # <- unregistered
    raw = os.environ.get("FABRIC_TPU_TRACE", "")  # <- helper bypass
    return ghost, raw
