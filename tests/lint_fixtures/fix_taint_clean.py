"""CLEAN TWIN of fix_taint_dirty: identical shape, but the timestamp is
threaded in as an argument — every peer marshals the same bytes."""

from fabric_tpu.protos.common import common_pb2


def build_header(number: int, timestamp: float) -> bytes:
    stamp = int(timestamp)
    seconds = stamp + 0
    hdr = common_pb2.BlockHeader(number=number)
    hdr.timestamp = seconds
    return hdr.SerializeToString()
