"""Multi-device sharding in the TPU CSP provider.

Conftest forces an 8-virtual-device CPU mesh, so these tests exercise
the provider's production scaling axis (SURVEY.md §2.9): when more than
one device is visible, verify chunks are placed round-robin across the
mesh — verification is embarrassingly parallel, so data-parallel chunk
placement (no collectives, no global barrier) is the TPU-idiomatic
layout, and each chunk's host marshalling overlaps other chunks'
device time.
"""

from __future__ import annotations

import hashlib

import jax
import pytest

from fabric_tpu.csp import SWCSP
from fabric_tpu.csp.api import VerifyBatchItem
from fabric_tpu.csp.tpu.provider import TPUCSP


@pytest.fixture(scope="module")
def items():
    sw = SWCSP()
    keys = [sw.key_gen() for _ in range(4)]
    out = []
    for i in range(700):
        d = hashlib.sha256(b"md-%d" % i).digest()
        k = keys[i % 4]
        out.append(VerifyBatchItem(k.public_key(), d, sw.sign(k, d)))
    # one tampered lane
    out[13] = VerifyBatchItem(
        out[13].key, hashlib.sha256(b"other").digest(), out[13].signature
    )
    return out


def test_mesh_is_visible():
    assert len(jax.devices()) == 8  # conftest's virtual mesh


def test_chunks_spread_across_devices(items):
    # small chunks force a multi-chunk dispatch even at 700 lanes
    csp = TPUCSP(min_device_batch=1, max_chunk=128, coalesce_lanes=1)
    mask = csp.verify_batch(items)
    assert mask[13] is False
    assert all(v for i, v in enumerate(mask) if i != 13)
    used = csp.last_dispatch_devices
    assert len(used) >= 2, f"expected spread over devices, got {used}"


def test_multidevice_matches_single_device(items):
    multi = TPUCSP(min_device_batch=1, max_chunk=128, coalesce_lanes=1)
    single = TPUCSP(min_device_batch=1)
    assert multi.verify_batch(items) == single.verify_batch(items)


def test_async_coalesced_multidevice(items):
    csp = TPUCSP(min_device_batch=1, max_chunk=256)
    c1 = csp.verify_batch_async(items[:400])
    c2 = csp.verify_batch_async(items[400:])
    m = c1() + c2()
    assert m[13] is False and sum(m) == len(items) - 1
    assert len(csp.last_dispatch_devices) >= 2


def test_concurrent_submitters_stress(items):
    """Race-detector stand-in for the coalescer (SURVEY.md §5): many
    threads concurrently submit overlapping async batches of random
    sizes against ONE provider and collect in random order.  Every
    caller must get exactly its own mask — the historical bug classes
    here were double-consumed chunk collectors and double-materialized
    flushes (commits de34221, ef06d45), both only visible under
    contention.  Seeded, so failures reproduce."""
    import random
    import threading

    rng = random.Random(4242)
    csp = TPUCSP(min_device_batch=1, max_chunk=128, coalesce_lanes=8)
    jobs = []  # (start, size) into the 700-item pool; expected via index
    for _ in range(24):
        # a few odd sizes (not a new compile per job): padding and
        # coalescing still vary per flush, which is what races
        size = rng.choice((5, 17, 33))
        start = rng.randrange(0, len(items) - size)
        jobs.append((start, size))
    results: list = [None] * len(jobs)
    errors: list = []
    barrier = threading.Barrier(8)

    def worker(w):
        try:
            barrier.wait()
            for j in range(w, len(jobs), 8):
                start, size = jobs[j]
                collect = csp.verify_batch_async(
                    items[start:start + size]
                )
                if j % 3 == 0:  # some collect immediately, some defer
                    results[j] = collect()
                else:
                    results[j] = ("defer", collect)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(w,)) for w in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for j, r in enumerate(results):
        if isinstance(r, tuple) and r and r[0] == "defer":
            results[j] = r[1]()
    for j, (start, size) in enumerate(jobs):
        want = [i != 13 for i in range(start, start + size)]
        assert results[j] == want, (j, start, size)
