"""Deterministic config-transaction engine.

Reference: common/configtx — validator.go:103 NewValidatorImpl /
:133 ProposeConfigUpdate, update.go (read/write-set verification and
policy gathering), compare.go (element equality), and the
configtxlator-side delta computation (internal/configtxlator/update).

Semantics (mirroring the reference):

- The channel config is a versioned tree (ConfigGroup / ConfigValue /
  ConfigPolicy, each with a version and a mod_policy).
- A ConfigUpdate carries a read_set and a write_set.  Every element in
  the read_set must exist at exactly the stated version (stale reads are
  rejected).  Elements in the write_set at their current version are
  carried through unchanged; an element whose version is bumped by
  exactly one is a modification and requires its CURRENT mod_policy to
  be satisfied by the update's signatures (for brand-new elements the
  enclosing group's mod_policy gates the change).
- The proposed config is the current tree with the write_set applied,
  at sequence+1.
"""

from __future__ import annotations

from fabric_tpu.protos.common import common_pb2, configtx_pb2
from fabric_tpu.protoutil.common import SignedData


class ConfigtxError(Exception):
    pass


# ---------------------------------------------------------------------------
# element comparison helpers
# ---------------------------------------------------------------------------


def _values_equal(a: configtx_pb2.ConfigValue, b: configtx_pb2.ConfigValue):
    return a.value == b.value and a.mod_policy == b.mod_policy


def _policies_equal(a: configtx_pb2.ConfigPolicy, b: configtx_pb2.ConfigPolicy):
    return (
        a.policy.SerializeToString() == b.policy.SerializeToString()
        and a.mod_policy == b.mod_policy
    )


def _group_shallow_equal(a: configtx_pb2.ConfigGroup, b: configtx_pb2.ConfigGroup):
    return (
        a.mod_policy == b.mod_policy
        and set(a.groups) == set(b.groups)
        and set(a.values) == set(b.values)
        and set(a.policies) == set(b.policies)
    )


# ---------------------------------------------------------------------------
# validator
# ---------------------------------------------------------------------------


class ConfigtxValidator:
    """Per-channel config state machine (reference ValidatorImpl)."""

    def __init__(
        self,
        channel_id: str,
        config: configtx_pb2.Config,
        policy_manager=None,
        csp=None,
    ):
        if not channel_id:
            raise ConfigtxError("empty channel id")
        self.channel_id = channel_id
        self.config = config
        self._pm = policy_manager
        self._csp = csp

    @property
    def sequence(self) -> int:
        return self.config.sequence

    # -- entry point -------------------------------------------------------

    def propose_config_update(
        self, update_env: configtx_pb2.ConfigUpdateEnvelope
    ) -> configtx_pb2.ConfigEnvelope:
        """Validate a signed update against the current config and return
        the resulting ConfigEnvelope (reference ProposeConfigUpdate)."""
        update = configtx_pb2.ConfigUpdate.FromString(
            update_env.config_update
        )
        if update.channel_id != self.channel_id:
            raise ConfigtxError(
                f"update for channel {update.channel_id!r}, "
                f"validator is {self.channel_id!r}"
            )
        current = self.config.channel_group
        self._verify_read_set(current, update.read_set, path="Channel")
        signed_data = self._signed_data(update_env)
        new_group = configtx_pb2.ConfigGroup()
        new_group.CopyFrom(current)
        self._apply_write_set(
            new_group, current, update.write_set, signed_data,
            path="Channel", parent_mod_policy=current.mod_policy,
        )
        result = configtx_pb2.Config(sequence=self.config.sequence + 1)
        result.channel_group.CopyFrom(new_group)
        return configtx_pb2.ConfigEnvelope(config=result)

    def commit(self, env: configtx_pb2.ConfigEnvelope) -> None:
        """Adopt a validated config (after ordering)."""
        if env.config.sequence != self.config.sequence + 1:
            raise ConfigtxError(
                f"out-of-order config sequence {env.config.sequence}"
            )
        self.config = env.config

    # -- read set ----------------------------------------------------------

    def _verify_read_set(self, current, read_set, path: str) -> None:
        if read_set.version != current.version:
            raise ConfigtxError(
                f"read_set {path}: version {read_set.version} != current "
                f"{current.version}"
            )
        for name, g in read_set.groups.items():
            if name not in current.groups:
                raise ConfigtxError(f"read_set group {path}/{name} not found")
            self._verify_read_set(
                current.groups[name], g, f"{path}/{name}"
            )
        for name, v in read_set.values.items():
            if name not in current.values:
                raise ConfigtxError(f"read_set value {path}/{name} not found")
            if current.values[name].version != v.version:
                raise ConfigtxError(
                    f"read_set value {path}/{name}: stale version"
                )
        for name, p in read_set.policies.items():
            if name not in current.policies:
                raise ConfigtxError(
                    f"read_set policy {path}/{name} not found"
                )
            if current.policies[name].version != p.version:
                raise ConfigtxError(
                    f"read_set policy {path}/{name}: stale version"
                )

    # -- write set ---------------------------------------------------------

    def _check_policy(self, mod_policy: str, path: str, signed_data) -> None:
        if self._pm is None:
            return  # unwired (tests/tools): policy gating disabled
        if not mod_policy:
            raise ConfigtxError(f"{path}: empty mod_policy rejects changes")
        pol = self._pm.get_policy(
            mod_policy if mod_policy.startswith("/")
            else self._relative(path, mod_policy)
        )
        if not pol.evaluate_signed_data(signed_data, self._csp):
            raise ConfigtxError(
                f"{path}: mod_policy {mod_policy!r} not satisfied"
            )

    @staticmethod
    def _relative(path: str, mod_policy: str) -> str:
        # mod_policy names resolve relative to the element's enclosing
        # group; path is "Channel[/seg...]" and the manager tree is rooted
        # at Channel.
        segs = path.split("/")[1:]  # drop leading "Channel"
        return "/".join(segs[:-1] + [mod_policy]) if len(segs) > 0 else mod_policy

    def _apply_write_set(
        self, target, current, write, signed_data, path, parent_mod_policy
    ) -> None:
        """Recursively apply `write` over `target` (a copy of `current`),
        enforcing version arithmetic and mod policies."""
        if write.version == current.version + 1:
            # group itself modified (membership / mod_policy change)
            self._check_policy(
                current.mod_policy or parent_mod_policy, path, signed_data
            )
            target.version = write.version
            target.mod_policy = write.mod_policy or current.mod_policy
            # element removal: anything absent from the write set goes
            for name in list(target.groups):
                if name not in write.groups:
                    del target.groups[name]
            for name in list(target.values):
                if name not in write.values:
                    del target.values[name]
            for name in list(target.policies):
                if name not in write.policies:
                    del target.policies[name]
        elif write.version != current.version:
            raise ConfigtxError(
                f"write_set {path}: version {write.version} not in "
                f"{{{current.version}, {current.version + 1}}}"
            )

        for name, wv in write.values.items():
            cur = current.values.get(name)
            p = f"{path}/{name}"
            if cur is None:
                if wv.version != 0:
                    raise ConfigtxError(f"new value {p} must be version 0")
                self._check_policy(
                    current.mod_policy or parent_mod_policy, p, signed_data
                )
                target.values[name].CopyFrom(wv)
            elif wv.version == cur.version:
                if not _values_equal(wv, cur):
                    raise ConfigtxError(
                        f"value {p} changed without version bump"
                    )
            elif wv.version == cur.version + 1:
                self._check_policy(cur.mod_policy, p, signed_data)
                target.values[name].CopyFrom(wv)
            else:
                raise ConfigtxError(f"value {p}: bad version {wv.version}")

        for name, wp in write.policies.items():
            cur = current.policies.get(name)
            p = f"{path}/{name}"
            if cur is None:
                if wp.version != 0:
                    raise ConfigtxError(f"new policy {p} must be version 0")
                self._check_policy(
                    current.mod_policy or parent_mod_policy, p, signed_data
                )
                target.policies[name].CopyFrom(wp)
            elif wp.version == cur.version:
                if not _policies_equal(wp, cur):
                    raise ConfigtxError(
                        f"policy {p} changed without version bump"
                    )
            elif wp.version == cur.version + 1:
                self._check_policy(cur.mod_policy, p, signed_data)
                target.policies[name].CopyFrom(wp)
            else:
                raise ConfigtxError(f"policy {p}: bad version {wp.version}")

        for name, wg in write.groups.items():
            cur = current.groups.get(name)
            p = f"{path}/{name}"
            if cur is None:
                if wg.version != 0:
                    raise ConfigtxError(f"new group {p} must be version 0")
                self._check_policy(
                    current.mod_policy or parent_mod_policy, p, signed_data
                )
                target.groups[name].CopyFrom(wg)
            else:
                self._apply_write_set(
                    target.groups[name], cur, wg, signed_data, p,
                    current.mod_policy or parent_mod_policy,
                )

    # -- signatures --------------------------------------------------------

    def _signed_data(self, update_env) -> list[SignedData]:
        out = []
        for cs in update_env.signatures:
            shdr = common_pb2.SignatureHeader.FromString(cs.signature_header)
            out.append(
                SignedData(
                    data=bytes(cs.signature_header)
                    + bytes(update_env.config_update),
                    identity=bytes(shdr.creator),
                    signature=bytes(cs.signature),
                )
            )
        return out


# ---------------------------------------------------------------------------
# delta computation (configtxlator's compute-update)
# ---------------------------------------------------------------------------


def compute_update(
    channel_id: str,
    original: configtx_pb2.Config,
    updated: configtx_pb2.Config,
) -> configtx_pb2.ConfigUpdate:
    """Minimal ConfigUpdate turning `original` into `updated` (reference
    internal/configtxlator/update/update.go Compute)."""
    read, write, changed = _compute_group_delta(
        original.channel_group, updated.channel_group
    )
    if not changed:
        raise ConfigtxError("no differences between original and updated")
    upd = configtx_pb2.ConfigUpdate(channel_id=channel_id)
    upd.read_set.CopyFrom(read)
    upd.write_set.CopyFrom(write)
    return upd


def _compute_group_delta(orig, new):
    """Returns (read_group, write_group, changed)."""
    read = configtx_pb2.ConfigGroup(version=orig.version)
    write = configtx_pb2.ConfigGroup(
        version=orig.version, mod_policy=orig.mod_policy
    )
    members_changed = (
        set(orig.groups) != set(new.groups)
        or set(orig.values) != set(new.values)
        or set(orig.policies) != set(new.policies)
        or orig.mod_policy != new.mod_policy
    )
    changed = members_changed

    for name, ov in orig.values.items():
        nv = new.values.get(name)
        if nv is None:
            changed = True
            continue
        if not _values_equal(ov, nv):
            changed = True
            w = write.values[name]
            w.CopyFrom(nv)
            w.version = ov.version + 1
    for name, nv in new.values.items():
        if name not in orig.values:
            changed = True
            w = write.values[name]
            w.CopyFrom(nv)
            w.version = 0
        elif _values_equal(orig.values[name], nv):
            # unchanged: carried in the write set at current version
            w = write.values[name]
            w.CopyFrom(nv)
            w.version = orig.values[name].version

    for name, op in orig.policies.items():
        np = new.policies.get(name)
        if np is None:
            changed = True
        elif not _policies_equal(op, np):
            changed = True
            w = write.policies[name]
            w.CopyFrom(np)
            w.version = op.version + 1
    for name, np in new.policies.items():
        if name not in orig.policies:
            changed = True
            w = write.policies[name]
            w.CopyFrom(np)
            w.version = 0
        elif _policies_equal(orig.policies[name], np):
            w = write.policies[name]
            w.CopyFrom(np)
            w.version = orig.policies[name].version

    for name, og in orig.groups.items():
        ng = new.groups.get(name)
        if ng is None:
            changed = True
            continue
        sub_read, sub_write, sub_changed = _compute_group_delta(og, ng)
        if sub_changed:
            changed = True
            write.groups[name].CopyFrom(sub_write)
            # the read set references the group at its current version
            read.groups[name].version = og.version
        else:
            write.groups[name].version = og.version
    for name, ng in new.groups.items():
        if name not in orig.groups:
            changed = True
            g = write.groups[name]
            g.CopyFrom(ng)
            g.version = 0

    if members_changed:
        write.version = orig.version + 1
        write.mod_policy = new.mod_policy or orig.mod_policy
        # re-add unchanged members so removal semantics don't fire
        for name, ov in orig.values.items():
            if name in new.values and name not in write.values:
                w = write.values[name]
                w.CopyFrom(new.values[name])
                w.version = ov.version
    return read, write, changed


__all__ = ["ConfigtxValidator", "ConfigtxError", "compute_update"]
