"""Registrar + broadcast + deliver service tests (reference
orderer/common/multichannel, broadcast, common/deliver test strategy:
in-process fakes, real block stores)."""

import threading
import time

import pytest

from fabric_tpu.common.deliver import DeliverService, make_seek_info_envelope
from fabric_tpu.orderer.broadcast import BroadcastHandler
from fabric_tpu.orderer.multichannel import Registrar
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.orderer import ab_pb2
from fabric_tpu import protoutil

from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.msp import msp_config_from_ca

from orgfix import make_org


class _OrgSetup:
    def __init__(self):
        self.org1 = make_org("Org1MSP")
        oorg = make_org("OrdererMSP")
        app = ctx.application_group(
            {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(self.org1.ca, "Org1MSP"))}
        )
        ordg = ctx.orderer_group(
            {
                "OrdererOrg": ctx.org_group(
                    "OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP")
                )
            },
            consensus_type="solo",
            max_message_count=2,
            batch_timeout="250ms",
        )
        self.channel_id = "testchannel"
        self.genesis = ctx.genesis_block(
            self.channel_id, ctx.channel_group(app, ordg)
        )
        self.csp = self.org1.csp
        self.admin = self.org1.signer("admin", role_ou="admin")


@pytest.fixture(scope="module")
def org():
    return _OrgSetup()


@pytest.fixture
def registrar(org, tmp_path):
    reg = Registrar(str(tmp_path), org.csp)
    reg.startup([org.genesis])
    yield reg
    reg.halt_all()


def _tx_env(org, data: bytes) -> common_pb2.Envelope:
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel_id=org.channel_id
    )
    shdr = protoutil.make_signature_header(
        org.admin.serialize(), protoutil.random_nonce()
    )
    payload = common_pb2.Payload(data=data)
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = shdr.SerializeToString()
    raw = payload.SerializeToString()
    return common_pb2.Envelope(payload=raw, signature=org.admin.sign(raw))


def test_broadcast_orders_into_blocks(registrar, org):
    h = BroadcastHandler(registrar)
    cs = registrar.get_chain(org.channel_id)
    notifier_fired = threading.Event()
    registrar.add_block_listener(lambda ch, blk: notifier_fired.set())
    for i in range(3):
        assert h.process_message(_tx_env(org, b"d%d" % i)) == common_pb2.SUCCESS
    deadline = time.monotonic() + 10
    while cs.store.height < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert cs.store.height >= 2
    assert notifier_fired.is_set()


def test_broadcast_unknown_channel(registrar, org):
    h = BroadcastHandler(registrar)
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel_id="no-such-channel"
    )
    payload = common_pb2.Payload(data=b"x")
    payload.header.channel_header = chdr.SerializeToString()
    env = common_pb2.Envelope(payload=payload.SerializeToString())
    assert h.process_message(env) == common_pb2.NOT_FOUND


def test_broadcast_rejects_unsigned(registrar, org):
    h = BroadcastHandler(registrar)
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel_id=org.channel_id
    )
    shdr = protoutil.make_signature_header(b"not-an-identity", b"nonce")
    payload = common_pb2.Payload(data=b"x")
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = shdr.SerializeToString()
    env = common_pb2.Envelope(payload=payload.SerializeToString())
    assert h.process_message(env) == common_pb2.FORBIDDEN


def test_deliver_streams_existing_and_new_blocks(registrar, org):
    h = BroadcastHandler(registrar)
    svc = DeliverService(registrar.get_chain, org.csp)
    registrar.add_block_listener(lambda ch, blk: svc.notifier.notify())
    for i in range(3):
        h.process_message(_tx_env(org, b"d%d" % i))
    cs = registrar.get_chain(org.channel_id)
    deadline = time.monotonic() + 10
    while cs.store.height < 2 and time.monotonic() < deadline:
        time.sleep(0.02)

    env = make_seek_info_envelope(
        org.channel_id, 0, cs.store.height - 1, signer=org.admin,
        behavior=ab_pb2.SeekInfo.FAIL_IF_NOT_READY,
    )
    events = list(svc.deliver(env))
    kinds = [k for k, _ in events]
    assert kinds[-1] == "status" and events[-1][1] == common_pb2.SUCCESS
    blocks = [b for k, b in events if k == "block"]
    assert [b.header.number for b in blocks] == list(range(cs.store.height))
    assert blocks[0].header.number == 0  # genesis


def test_deliver_block_until_ready_waits(registrar, org):
    svc = DeliverService(registrar.get_chain, org.csp)
    registrar.add_block_listener(lambda ch, blk: svc.notifier.notify())
    h = BroadcastHandler(registrar)
    got: list = []

    def consume():
        env = make_seek_info_envelope(org.channel_id, 1, 1, signer=org.admin)
        for kind, item in svc.deliver(env):
            got.append((kind, item))

    from fabric_tpu.devtools.lockwatch import spawn_thread

    t = spawn_thread(target=consume, name="deliver-consume", kind="worker")
    t.start()
    time.sleep(0.2)
    assert not got  # waiting for block 1
    for i in range(3):
        h.process_message(_tx_env(org, b"w%d" % i))
    t.join(timeout=10)
    assert got and got[0][0] == "block" and got[0][1].header.number == 1


def test_deliver_forbidden_without_signature(registrar, org):
    svc = DeliverService(registrar.get_chain, org.csp)
    env = make_seek_info_envelope(org.channel_id, 0, 0, signer=None)
    events = list(svc.deliver(env))
    assert events == [("status", common_pb2.FORBIDDEN)]


def test_deliver_unknown_channel(registrar, org):
    svc = DeliverService(registrar.get_chain, org.csp)
    env = make_seek_info_envelope("ghost", 0, 0, signer=org.admin)
    assert list(svc.deliver(env)) == [("status", common_pb2.NOT_FOUND)]


# -- maintenance mode + consensus-type migration ---------------------------
# (reference orderer/common/msgprocessor/maintenancefilter.go:31-44)


class _MigrationWorld:
    """A solo channel whose admins can drive config updates end to end."""

    def __init__(self, tmp_path):
        from fabric_tpu.common import configtx_builder as cb

        self.org1 = make_org("Org1MSP")
        self.oorg = make_org("OrdererMSP")
        app = ctx.application_group(
            {"Org1": ctx.org_group(
                "Org1MSP", msp_config_from_ca(self.org1.ca, "Org1MSP"))}
        )
        ordg = ctx.orderer_group(
            {"OrdererOrg": ctx.org_group(
                "OrdererMSP", msp_config_from_ca(self.oorg.ca, "OrdererMSP"))},
            consensus_type="solo",
            max_message_count=1,
            batch_timeout="200ms",
        )
        self.channel_id = "migrch"
        self.genesis = ctx.genesis_block(
            self.channel_id, ctx.channel_group(app, ordg)
        )
        self.csp = self.org1.csp
        self.client = self.org1.signer("client", role_ou="client")
        self.orderer_admin = self.oorg.signer("oadmin", role_ou="admin")
        from fabric_tpu.orderer.kafka import InProcBroker

        self.registrar = Registrar(
            str(tmp_path), self.csp,
            signer=self.oorg.signer("orderer0", role_ou="orderer"),
            consenter_overrides={"broker": InProcBroker()},
        )
        self.registrar.startup([self.genesis])
        self.handler = BroadcastHandler(self.registrar)

    def current_config(self):
        return self.registrar.get_chain(self.channel_id).bundle.config

    def update_env(self, mutate):
        """Signed CONFIG_UPDATE envelope transforming the current config
        with `mutate(updated_config)`."""
        from fabric_tpu.common.configtx import compute_update
        from fabric_tpu.protos.common import configtx_pb2

        cur = self.current_config()
        upd_cfg = configtx_pb2.Config()
        upd_cfg.CopyFrom(cur)
        mutate(upd_cfg)
        update = compute_update(self.channel_id, cur, upd_cfg)
        ue = configtx_pb2.ConfigUpdateEnvelope(
            config_update=update.SerializeToString()
        )
        shdr = protoutil.make_signature_header(
            self.orderer_admin.serialize(), protoutil.random_nonce()
        ).SerializeToString()
        ue.signatures.add(
            signature_header=shdr,
            signature=self.orderer_admin.sign(
                shdr + ue.config_update
            ),
        )
        chdr = protoutil.make_channel_header(
            common_pb2.CONFIG_UPDATE, channel_id=self.channel_id
        )
        payload = protoutil.make_payload_bytes(
            chdr,
            protoutil.make_signature_header(
                self.orderer_admin.serialize(), protoutil.random_nonce()
            ),
            ue.SerializeToString(),
        )
        return protoutil.make_envelope(payload, signer=self.orderer_admin)

    def set_consensus(self, cfg, ctype=None, state=None):
        from fabric_tpu.common import configtx_builder as cb
        from fabric_tpu.protos.orderer import configuration_pb2 as ocp

        og = cfg.channel_group.groups["Orderer"]
        cur = ocp.ConsensusType.FromString(
            og.values[cb.CONSENSUS_TYPE_KEY].value
        )
        if ctype is not None:
            cur.type = ctype
        if state is not None:
            cur.state = state
        og.values[cb.CONSENSUS_TYPE_KEY].value = cur.SerializeToString()

    def normal_tx(self, signer, data=b"tx"):
        chdr = protoutil.make_channel_header(
            common_pb2.ENDORSER_TRANSACTION, channel_id=self.channel_id
        )
        shdr = protoutil.make_signature_header(
            signer.serialize(), protoutil.random_nonce()
        )
        payload = common_pb2.Payload(data=data)
        payload.header.channel_header = chdr.SerializeToString()
        payload.header.signature_header = shdr.SerializeToString()
        raw = payload.SerializeToString()
        return common_pb2.Envelope(payload=raw, signature=signer.sign(raw))

    def wait_height(self, h, timeout=10.0):
        cs = self.registrar.get_chain(self.channel_id)
        deadline = time.time() + timeout
        while cs.store.height < h and time.time() < deadline:
            time.sleep(0.02)
        return cs.store.height


def test_consensus_migration_through_maintenance_mode(tmp_path):
    """Full migration flow: type change rejected in NORMAL; enter
    maintenance; client txs rejected while orderer admins still write;
    type change accepted in maintenance; exit maintenance; the channel
    orders through the NEW consenter."""
    from fabric_tpu.orderer.msgprocessor import (
        STATE_MAINTENANCE,
        STATE_NORMAL,
    )

    w = _MigrationWorld(tmp_path)
    try:
        reg, h = w.registrar, w.handler
        # 0) type change outside maintenance is FORBIDDEN
        env = w.update_env(
            lambda c: w.set_consensus(c, ctype="kafka")
        )
        assert h.process_message(env) == common_pb2.FORBIDDEN

        # 1) enter maintenance (type unchanged) — accepted
        env = w.update_env(
            lambda c: w.set_consensus(c, state=STATE_MAINTENANCE)
        )
        assert h.process_message(env) == common_pb2.SUCCESS
        hh = w.wait_height(2)
        assert hh == 2
        cs = reg.get_chain(w.channel_id)
        assert cs.processor.in_maintenance()

        # 2) while in maintenance, client txs are rejected...
        assert (
            h.process_message(w.normal_tx(w.client))
            == common_pb2.FORBIDDEN
        )
        # ...and entering again with a simultaneous exit+type change fails
        env = w.update_env(
            lambda c: w.set_consensus(c, ctype="kafka", state=STATE_NORMAL)
        )
        assert h.process_message(env) == common_pb2.FORBIDDEN

        # 3) change the consensus type INSIDE maintenance — accepted;
        #    the registrar swaps the consenter (solo -> kafka)
        env = w.update_env(lambda c: w.set_consensus(c, ctype="kafka"))
        assert h.process_message(env) == common_pb2.SUCCESS
        assert w.wait_height(3) == 3
        deadline = time.time() + 5
        from fabric_tpu.orderer.kafka import KafkaChain

        while time.time() < deadline and not isinstance(
            reg.get_chain(w.channel_id).chain, KafkaChain
        ):
            time.sleep(0.05)
        assert isinstance(reg.get_chain(w.channel_id).chain, KafkaChain)

        # 4) exit maintenance (type now stays kafka) — accepted
        env = w.update_env(
            lambda c: w.set_consensus(c, state=STATE_NORMAL)
        )
        assert h.process_message(env) == common_pb2.SUCCESS
        assert w.wait_height(4) == 4
        assert not reg.get_chain(w.channel_id).processor.in_maintenance()

        # 5) normal client traffic orders through the NEW consenter
        assert (
            h.process_message(w.normal_tx(w.client)) == common_pb2.SUCCESS
        )
        assert w.wait_height(5) == 5
    finally:
        w.registrar.halt_all()


def test_maintenance_filter_unit_rules(tmp_path):
    """Filter matrix at the unit level (the e2e migration test covers
    the happy path): every NORMAL-state type change is rejected, both
    maintenance transitions keep the type, removal of the Orderer group
    is rejected."""
    from fabric_tpu.orderer.msgprocessor import (
        MsgProcessorError,
        STATE_MAINTENANCE,
        STATE_NORMAL,
    )

    w = _MigrationWorld(tmp_path)
    try:
        cs = w.registrar.get_chain(w.channel_id)
        proc = cs.processor
        from fabric_tpu.protos.common import configtx_pb2

        def cfg_with(ctype=None, state=None, drop_orderer=False):
            c = configtx_pb2.Config()
            c.CopyFrom(w.current_config())
            c.sequence += 1
            if drop_orderer:
                del c.channel_group.groups["Orderer"]
            else:
                w.set_consensus(c, ctype=ctype, state=state)
            return c

        # NORMAL -> type change: rejected
        with pytest.raises(MsgProcessorError):
            proc._maintenance_filter(cfg_with(ctype="kafka"))
        # NORMAL -> enter maintenance, same type: allowed
        proc._maintenance_filter(cfg_with(state=STATE_MAINTENANCE))
        # Orderer group removal: rejected
        with pytest.raises(MsgProcessorError):
            proc._maintenance_filter(cfg_with(drop_orderer=True))
        # while IN maintenance: type change allowed; exit+change rejected
        import dataclasses

        oc = cs.bundle.orderer_config
        cs.bundle.orderer_config = dataclasses.replace(
            oc, consensus_state=STATE_MAINTENANCE
        )
        proc._maintenance_filter(
            cfg_with(ctype="kafka", state=STATE_MAINTENANCE)
        )
        with pytest.raises(MsgProcessorError):
            proc._maintenance_filter(
                cfg_with(ctype="kafka", state=STATE_NORMAL)
            )
        # while IN maintenance: touching anything OUTSIDE the Orderer
        # group rides along a migration update — rejected
        # (maintenancefilter.go ensures only-Orderer changes)
        tainted = cfg_with(ctype="kafka", state=STATE_MAINTENANCE)
        tainted.channel_group.groups["Application"].version += 1
        with pytest.raises(MsgProcessorError):
            proc._maintenance_filter(tainted)
        cs.bundle.orderer_config = oc
    finally:
        w.registrar.halt_all()
