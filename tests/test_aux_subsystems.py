"""Auxiliary subsystems: weighted semaphore, thread-dump diagnostics,
config history store, chaincode event manager."""

import io
import struct

from fabric_tpu.common.diag import dump_threads
from fabric_tpu.common.semaphore import Semaphore
from fabric_tpu.ledger.cceventmgmt import ChaincodeEventMgr
from fabric_tpu.ledger.confighistory import ConfigHistoryMgr
from fabric_tpu.ledger.kvstore import MemKVStore


def test_semaphore_limits_concurrency():
    sem = Semaphore(2)
    assert sem.try_acquire() and sem.try_acquire()
    assert not sem.try_acquire()
    sem.release()
    with sem:
        assert not sem.try_acquire()
    assert sem.try_acquire()


def test_thread_dump_lists_main_thread():
    buf = io.StringIO()
    text = dump_threads(buf)
    assert "MainThread" in text
    assert "test_thread_dump_lists_main_thread" in text


def test_confighistory_most_recent_below():
    mgr = ConfigHistoryMgr(MemKVStore(), "ch")
    mgr.handle_commit(5, {"cc1": b"cfg@5"})
    mgr.handle_commit(12, {"cc1": b"cfg@12", "cc2": b"other@12"})
    r = mgr.retriever()
    assert r.most_recent_below("cc1", 6) == (5, b"cfg@5")
    assert r.most_recent_below("cc1", 5) is None
    assert r.most_recent_below("cc1", 100) == (12, b"cfg@12")
    assert r.most_recent_below("cc2", 13) == (12, b"other@12")
    assert r.most_recent_below("cc3", 100) is None


def test_cceventmgmt_dispatch_and_isolation():
    mgr = ChaincodeEventMgr()
    got = []
    mgr.register("ch1", got.append)
    mgr.register(None, lambda e: got.append(("global", e.name)))
    mgr.register("ch1", lambda e: 1 / 0)  # broken listener is isolated
    mgr.handle_definition_committed("ch1", "mycc", "1.0", 3)
    mgr.handle_definition_committed("ch2", "othercc", "1.0", 1)
    names = [e.name if hasattr(e, "name") else e for e in got]
    assert ("global", "mycc") in got and ("global", "othercc") in got
    assert any(getattr(e, "channel_id", None) == "ch1" for e in got)
    assert not any(getattr(e, "channel_id", None) == "ch2" for e in got
                   if hasattr(e, "channel_id"))


# -- profiling endpoints (profscope on the operations System; the old
# standalone ProfileServer/pprof listener is retired) ----------------------


def test_profile_endpoints_on_operations_system():
    import json
    import threading
    import urllib.request

    from fabric_tpu.common import profile
    from fabric_tpu.common.operations import System

    # a busy thread so the CPU profile has something to sample
    stop = threading.Event()

    def spin():
        # plain loop, no genexpr: the sampler must attribute the hot
        # frame to `spin` itself, not an inner <genexpr> frame (which
        # made the "spin in profile" assertion a coin flip)
        x = 0
        while not stop.is_set():
            for i in range(1000):
                x += i * i

    from fabric_tpu.devtools.lockwatch import spawn_thread

    t = spawn_thread(target=spin, name="busy-loop", kind="worker")
    t.start()
    sys_ = System()
    sys_.start()
    try:
        base = f"http://{sys_.addr[0]}:{sys_.addr[1]}"
        # disarmed: still a valid (empty) speedscope doc, armed: false
        doc = json.loads(
            urllib.request.urlopen(base + "/profile").read()
        )
        assert doc["otherData"]["armed"] is False
        assert doc["profiles"] == []
        # ?seconds=N samples inline in the handler thread — works with
        # no profiler armed, and the hot frame lands in the stacks
        doc = json.loads(
            urllib.request.urlopen(
                base + "/profile?seconds=0.3"
            ).read()
        )
        assert doc["$schema"] == profile.SPEEDSCOPE_SCHEMA
        # frame names carry the source site: "spin (test_aux_...py:N)"
        frames = [f["name"] for f in doc["shared"]["frames"]]
        assert any(f.startswith("spin ") for f in frames)
        assert doc["profiles"][0]["samples"]
        h = json.loads(
            urllib.request.urlopen(base + "/profile/heap").read()
        )
        assert "top" in h and "current_bytes" in h
    finally:
        stop.set()
        sys_.stop()
        t.join(timeout=5)


def test_peer_profile_config_knob_consumed():
    """core.yaml peer.profile.enabled still gates profiling when the
    peer CLI boots (the knob must not be dead now that it arms the
    profscope sampler instead of a standalone listener)."""
    from fabric_tpu.common.config import Config

    cfg = Config(
        {"peer": {"profile": {"enabled": True,
                              "listenAddress": "127.0.0.1:0"}}}
    )
    assert cfg.get_bool("peer.profile.enabled", False)


def test_cert_expiration_warnings():
    """Week-ahead expiry warnings (reference expiration.go
    TrackExpiration wired at peer/orderer start)."""
    import datetime

    from fabric_tpu.common.crypto import (
        CA,
        expiration_warning,
        track_expiration,
    )

    ca = CA("expwarn-ca", "org")
    soon = ca.issue(
        "dying",
        not_after=datetime.datetime.now(datetime.timezone.utc)
        + datetime.timedelta(days=3),
    )
    fine = ca.issue("healthy", validity_days=365)
    expired = ca.issue(
        "dead",
        not_after=datetime.datetime.now(datetime.timezone.utc)
        - datetime.timedelta(days=1),
    )
    assert "expires within" in expiration_warning(soon.cert_pem, "x")
    assert expiration_warning(fine.cert_pem, "x") is None
    assert "EXPIRED" in expiration_warning(expired.cert_pem, "x")
    got = []
    track_expiration(
        [("a", soon.cert_pem), ("b", fine.cert_pem), ("c", expired.cert_pem),
         ("d", b"")],
        got.append,
    )
    assert len(got) == 2 and "a" in got[0] and "c" in got[1]


def test_node_start_warns_on_expiring_certs(tmp_path, capsys):
    """A peer started with a nearly-expired TLS cert logs the warning."""
    import datetime
    import logging

    from fabric_tpu.common.crypto import CA
    from fabric_tpu.comm.tls import TLSCredentials
    from fabric_tpu.csp import SWCSP
    from fabric_tpu.node.peer_node import PeerNode

    ca = CA("expwarn-tls", "org")
    pair = ca.issue(
        "peer0", sans=["localhost", "127.0.0.1"], client=True, server=True,
        not_after=datetime.datetime.now(datetime.timezone.utc)
        + datetime.timedelta(days=2),
    )
    creds = TLSCredentials(
        cert_pem=pair.cert_pem, key_pem=pair.key_pem, ca_pems=[ca.cert_pem]
    )
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logging.getLogger("fabric_tpu.peer").addHandler(h := Capture())
    try:
        node = PeerNode(None, SWCSP(), None, port=0, tls=creds)
        node.start()
        node.stop()
    finally:
        logging.getLogger("fabric_tpu.peer").removeHandler(h)
    assert any("TLS certificate expires within" in m for m in records), records
