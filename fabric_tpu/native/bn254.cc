// Native BN254 (alt-bn128) G1 arithmetic for the idemix data plane.
//
// The reference's idemix math runs on pure-Go AMCL (fabric-amcl,
// SURVEY.md §2.1); the TPU build's Python bn254.py is the portable
// fallback and THIS file is the hot path: Montgomery Fp (4x64 limbs,
// __int128 products), Jacobian G1 (a = 0, y^2 = x^3 + 3), 4-bit
// windowed scalar multiplication, and batch APIs with one shared
// Montgomery inversion for the affine outputs.  Used by the Schnorr
// commitment recomputation in idemix signature verification
// (signature.go:243-relations equivalent) and the RLC accumulation in
// batched verification — the per-item cost that dominates once the
// pairings amortize to two per batch.
//
// All point/scalar I/O is 32-byte big-endian affine coordinates.

#include <cstdint>
#include <cstring>

typedef uint8_t u8;
typedef uint64_t u64;
typedef unsigned __int128 u128;

namespace {

// BN254 prime and Montgomery constants (little-endian 64-bit limbs).
static const u64 PRIME[4] = {0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                             0xb85045b68181585dULL, 0x30644e72e131a029ULL};
static const u64 N0INV = 0x87d20782e4866389ULL;  // -P^-1 mod 2^64
static const u64 R2[4] = {0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                          0x47ab1eff0a417ff6ULL, 0x06d89f71cab8351fULL};
static const u64 ONE_M[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                             0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};

struct Fp {
  u64 v[4];
};

inline bool is_zero(const Fp& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

inline int cmp_p(const u64* a) {
  for (int i = 3; i >= 0; --i) {
    if (a[i] != PRIME[i]) return a[i] < PRIME[i] ? -1 : 1;
  }
  return 0;
}

inline void sub_p(u64* a) {  // a -= P (caller ensures a >= P)
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a[i] - PRIME[i] - (u64)borrow;
    a[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

inline void fp_add(const Fp& a, const Fp& b, Fp* out) {
  u128 carry = 0;
  u64 t[4];
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a.v[i] + b.v[i] + (u64)carry;
    t[i] = (u64)s;
    carry = s >> 64;
  }
  if (carry || cmp_p(t) >= 0) sub_p(t);
  memcpy(out->v, t, sizeof(t));
}

inline void fp_sub(const Fp& a, const Fp& b, Fp* out) {
  u128 borrow = 0;
  u64 t[4];
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - b.v[i] - (u64)borrow;
    t[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {  // += P
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
      u128 s = (u128)t[i] + PRIME[i] + (u64)carry;
      t[i] = (u64)s;
      carry = s >> 64;
    }
  }
  memcpy(out->v, t, sizeof(t));
}

inline void fp_dbl(const Fp& a, Fp* out) { fp_add(a, a, out); }

// Montgomery CIOS multiplication: out = a*b*R^-1 mod P.
void fp_mul(const Fp& a, const Fp& b, Fp* out) {
  u64 t[5] = {0, 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    // t += a[i] * b
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 s = (u128)a.v[i] * b.v[j] + t[j] + (u64)carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u64 t4 = t[4] + (u64)carry;
    // m = t[0] * n0inv; t += m * P; t >>= 64
    u64 m = t[0] * N0INV;
    carry = ((u128)m * PRIME[0] + t[0]) >> 64;
    for (int j = 1; j < 4; ++j) {
      u128 s = (u128)m * PRIME[j] + t[j] + (u64)carry;
      t[j - 1] = (u64)s;
      carry = s >> 64;
    }
    u128 s = (u128)t4 + (u64)carry;
    t[3] = (u64)s;
    t[4] = (u64)(s >> 64);
  }
  if (t[4] || cmp_p(t) >= 0) sub_p(t);
  memcpy(out->v, t, 4 * sizeof(u64));
}

inline void fp_sqr(const Fp& a, Fp* out) { fp_mul(a, a, out); }

void to_mont(const Fp& a, Fp* out) {
  Fp r2;
  memcpy(r2.v, R2, sizeof(R2));
  fp_mul(a, r2, out);
}

void from_mont(const Fp& a, Fp* out) {
  Fp one = {{1, 0, 0, 0}};
  fp_mul(a, one, out);
}

// Montgomery inversion via Fermat: a^(P-2).  ~380 muls; used once per
// batch thanks to the shared batch-inversion trick.
void fp_inv(const Fp& a, Fp* out) {
  // exponent P-2, big-endian bit scan
  u64 e[4];
  memcpy(e, PRIME, sizeof(e));
  // subtract 2
  if (e[0] >= 2) {
    e[0] -= 2;
  } else {
    e[0] = e[0] - 2;  // wraps; borrow
    int i = 1;
    while (e[i] == 0) e[i++] -= 1;
    e[i] -= 1;
  }
  Fp result;
  memcpy(result.v, ONE_M, sizeof(ONE_M));
  bool started = false;
  for (int limb = 3; limb >= 0; --limb) {
    for (int bit = 63; bit >= 0; --bit) {
      if (started) fp_sqr(result, &result);
      if ((e[limb] >> bit) & 1) {
        if (!started) {
          result = a;
          started = true;
        } else {
          fp_mul(result, a, &result);
        }
      }
    }
  }
  *out = result;
}

// ---------------------------------------------------------------------------
// G1 Jacobian (Montgomery-domain coordinates).
// ---------------------------------------------------------------------------

struct G1 {
  Fp x, y, z;
  bool inf;
};

void g1_dbl(const G1& p, G1* out) {
  if (p.inf || is_zero(p.y)) {
    out->inf = true;
    return;
  }
  // dbl-2009-l (a = 0): A=X^2 B=Y^2 C=B^2 D=2((X+B)^2-A-C) E=3A F=E^2
  Fp A, B, C, D, E, F, t;
  fp_sqr(p.x, &A);
  fp_sqr(p.y, &B);
  fp_sqr(B, &C);
  fp_add(p.x, B, &t);
  fp_sqr(t, &t);
  fp_sub(t, A, &t);
  fp_sub(t, C, &t);
  fp_dbl(t, &D);
  fp_dbl(A, &E);
  fp_add(E, A, &E);
  fp_sqr(E, &F);
  G1 r;
  r.inf = false;
  fp_sub(F, D, &r.x);
  fp_sub(r.x, D, &r.x);               // X3 = F - 2D
  Fp c8;
  fp_dbl(C, &c8);
  fp_dbl(c8, &c8);
  fp_dbl(c8, &c8);                    // 8C
  fp_sub(D, r.x, &t);
  fp_mul(E, t, &r.y);
  fp_sub(r.y, c8, &r.y);              // Y3 = E(D - X3) - 8C
  fp_mul(p.y, p.z, &t);
  fp_dbl(t, &r.z);                    // Z3 = 2YZ
  *out = r;
}

void g1_add(const G1& p, const G1& q, G1* out) {
  if (p.inf) {
    *out = q;
    return;
  }
  if (q.inf) {
    *out = p;
    return;
  }
  // add-2007-bl
  Fp z1z1, z2z2, u1, u2, s1, s2, h, i, j, rr, v, t;
  fp_sqr(p.z, &z1z1);
  fp_sqr(q.z, &z2z2);
  fp_mul(p.x, z2z2, &u1);
  fp_mul(q.x, z1z1, &u2);
  fp_mul(p.y, q.z, &t);
  fp_mul(t, z2z2, &s1);
  fp_mul(q.y, p.z, &t);
  fp_mul(t, z1z1, &s2);
  fp_sub(u2, u1, &h);
  fp_sub(s2, s1, &rr);
  if (is_zero(h)) {
    if (is_zero(rr)) {
      g1_dbl(p, out);
      return;
    }
    out->inf = true;
    return;
  }
  fp_dbl(h, &t);
  fp_sqr(t, &i);
  fp_mul(h, i, &j);
  fp_dbl(rr, &rr);
  fp_mul(u1, i, &v);
  G1 r;
  r.inf = false;
  fp_sqr(rr, &r.x);
  fp_sub(r.x, j, &r.x);
  fp_sub(r.x, v, &r.x);
  fp_sub(r.x, v, &r.x);               // X3 = r^2 - J - 2V
  fp_sub(v, r.x, &t);
  fp_mul(rr, t, &r.y);
  Fp s1j;
  fp_mul(s1, j, &s1j);
  fp_dbl(s1j, &s1j);
  fp_sub(r.y, s1j, &r.y);             // Y3 = r(V - X3) - 2 S1 J
  fp_add(p.z, q.z, &t);
  fp_sqr(t, &t);
  fp_sub(t, z1z1, &t);
  fp_sub(t, z2z2, &t);
  fp_mul(t, h, &r.z);                 // Z3 = ((Z1+Z2)^2 - Z1Z1 - Z2Z2) H
  *out = r;
}

// 4-bit windowed scalar multiplication, MSB first.
void g1_mul(const G1& p, const u8* scalar_be, G1* out) {
  G1 table[16];
  table[0].inf = true;
  table[1] = p;
  for (int k = 2; k < 16; ++k) g1_add(table[k - 1], p, &table[k]);
  G1 acc;
  acc.inf = true;
  bool any = false;
  for (int i = 0; i < 32; ++i) {
    for (int half = 0; half < 2; ++half) {
      int d = half ? (scalar_be[i] & 0xf) : (scalar_be[i] >> 4);
      if (any) {
        g1_dbl(acc, &acc);
        g1_dbl(acc, &acc);
        g1_dbl(acc, &acc);
        g1_dbl(acc, &acc);
      }
      if (d) {
        g1_add(acc, table[d], &acc);
        any = true;
      } else if (any) {
        // nothing
      }
    }
  }
  *out = acc;
}

void load_fp_be(const u8* be, Fp* out) {
  for (int i = 0; i < 4; ++i) {
    u64 v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | be[(3 - i) * 8 + j];
    out->v[i] = v;
  }
}

void store_fp_be(const Fp& a, u8* be) {
  for (int i = 0; i < 4; ++i) {
    u64 v = a.v[3 - i];
    for (int j = 0; j < 8; ++j) be[i * 8 + j] = (u8)(v >> (56 - 8 * j));
  }
}

void load_point(const u8* x_be, const u8* y_be, G1* out) {
  Fp x, y;
  load_fp_be(x_be, &x);
  load_fp_be(y_be, &y);
  out->inf = is_zero(x) && is_zero(y);
  to_mont(x, &out->x);
  to_mont(y, &out->y);
  memcpy(out->z.v, ONE_M, sizeof(ONE_M));
}

}  // namespace

extern "C" {

// out = sum_i scalar_i * (x_i, y_i).  Inputs/outputs 32-byte big-endian
// affine; (0, 0) encodes infinity.  Returns 1 when the sum is infinity.
int bn254_g1_msm(int n, const u8* xs, const u8* ys, const u8* scalars,
                 u8* out_x, u8* out_y) {
  G1 acc;
  acc.inf = true;
  for (int i = 0; i < n; ++i) {
    G1 p, t;
    load_point(xs + 32 * i, ys + 32 * i, &p);
    if (p.inf) continue;
    g1_mul(p, scalars + 32 * i, &t);
    g1_add(acc, t, &acc);
  }
  if (acc.inf) {
    memset(out_x, 0, 32);
    memset(out_y, 0, 32);
    return 1;
  }
  Fp zinv, zinv2, zinv3, ax, ay;
  fp_inv(acc.z, &zinv);
  fp_sqr(zinv, &zinv2);
  fp_mul(zinv2, zinv, &zinv3);
  fp_mul(acc.x, zinv2, &ax);
  fp_mul(acc.y, zinv3, &ay);
  from_mont(ax, &ax);
  from_mont(ay, &ay);
  store_fp_be(ax, out_x);
  store_fp_be(ay, out_y);
  return 0;
}

// out_i = scalar_i * (x_i, y_i), independent muls; shared Montgomery
// batch inversion for the affine conversions.  inf_flags[i] set when
// the result is infinity.
int bn254_g1_mul_many(int n, const u8* xs, const u8* ys, const u8* scalars,
                      u8* out_xs, u8* out_ys, u8* inf_flags) {
  G1* res = new G1[n];
  for (int i = 0; i < n; ++i) {
    G1 p;
    load_point(xs + 32 * i, ys + 32 * i, &p);
    if (p.inf) {
      res[i].inf = true;
      continue;
    }
    g1_mul(p, scalars + 32 * i, &res[i]);
  }
  // batch inversion of all finite Z's
  Fp* prefix = new Fp[n + 1];
  memcpy(prefix[0].v, ONE_M, sizeof(ONE_M));
  for (int i = 0; i < n; ++i) {
    if (res[i].inf) {
      prefix[i + 1] = prefix[i];
    } else {
      fp_mul(prefix[i], res[i].z, &prefix[i + 1]);
    }
  }
  Fp inv;
  fp_inv(prefix[n], &inv);
  for (int i = n - 1; i >= 0; --i) {
    if (res[i].inf) {
      inf_flags[i] = 1;
      memset(out_xs + 32 * i, 0, 32);
      memset(out_ys + 32 * i, 0, 32);
      continue;
    }
    inf_flags[i] = 0;
    Fp zinv, zinv2, zinv3, ax, ay;
    fp_mul(inv, prefix[i], &zinv);
    fp_mul(inv, res[i].z, &inv);
    fp_sqr(zinv, &zinv2);
    fp_mul(zinv2, zinv, &zinv3);
    fp_mul(res[i].x, zinv2, &ax);
    fp_mul(res[i].y, zinv3, &ay);
    from_mont(ax, &ax);
    from_mont(ay, &ay);
    store_fp_be(ax, out_xs + 32 * i);
    store_fp_be(ay, out_ys + 32 * i);
  }
  delete[] res;
  delete[] prefix;
  return 0;
}

}  // extern "C"
