"""Ledger throughput harness (reference core/ledger/kvledger/benchmark:
BenchmarkInsertTxs / BenchmarkReadWriteTxs, driven by
scripts/runbenchmarks.sh).

Short-circuits chaincode exactly like the reference harness: drives the
TxSimulator + block commit directly — no endorsement, no crypto — to
measure the storage stack (MVCC validate + block store + state DB +
history DB) in isolation.

    python scripts/bench_ledger.py [--txs 10000] [--batch 100] \
        [--keys 4] [--value-size 64] [--disk]

Prints one JSON line per experiment.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mk_ledger(disk: bool):
    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"),
    )
    from orgfix import make_org

    from fabric_tpu.common import configtx_builder as ctx
    from fabric_tpu.ledger import LedgerProvider
    from fabric_tpu.msp import msp_config_from_ca

    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))}
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("benchledger", ctx.channel_group(app, ordg))
    root = tempfile.mkdtemp(prefix="bench-ledger-") if disk else None
    return LedgerProvider(root).create(genesis)


def _env_for(txid: str, rwset: bytes, channel: str) -> bytes:
    """Minimal unsigned endorser-tx envelope carrying one rwset (the
    reference harness also skips endorsement/signatures)."""
    from fabric_tpu import protoutil
    from fabric_tpu.protos.common import common_pb2
    from fabric_tpu.protos.peer import (
        proposal_pb2,
        proposal_response_pb2,
        transaction_pb2,
    )

    action = proposal_pb2.ChaincodeAction(results=rwset)
    prp = proposal_response_pb2.ProposalResponsePayload(
        extension=action.SerializeToString()
    )
    cap = transaction_pb2.ChaincodeActionPayload()
    cap.action.proposal_response_payload = prp.SerializeToString()
    tx = transaction_pb2.Transaction()
    tx.actions.add(payload=cap.SerializeToString())
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel, tx_id=txid
    )
    shdr = protoutil.make_signature_header(b"bench-creator", txid.encode())
    return common_pb2.Envelope(
        payload=protoutil.make_payload_bytes(chdr, shdr, tx.SerializeToString())
    ).SerializeToString()


def _block_of(ledger, num, writes, n_keys, vsize, read=False):
    """Simulate `len(writes)` txs -> one block, reference-harness style
    (pre-validated write sets, no signatures)."""
    from fabric_tpu import protoutil
    from fabric_tpu.protos.common import common_pb2

    blk = common_pb2.Block()
    blk.header.number = num
    for txid, keybase in writes:
        sim = ledger.new_tx_simulator()
        for k in range(n_keys):
            key = f"{keybase}-{k}"
            if read:
                sim.get_state("benchcc", key)
            sim.set_state("benchcc", key, os.urandom(vsize))
        blk.data.data.append(
            _env_for(txid, sim.get_tx_simulation_results(), "benchledger")
        )
    protoutil.init_block_metadata(blk)
    protoutil.set_tx_filter(blk, bytearray(len(writes)))
    return blk


def run_experiment(name, ledger, n_txs, batch, n_keys, vsize, read):
    t0 = time.perf_counter()
    height = ledger.height
    for off in range(0, n_txs, batch):
        writes = [
            (f"{name}-tx{off + i}", f"{name}-key{(off + i) % (n_txs // 2 or 1)}")
            for i in range(min(batch, n_txs - off))
        ]
        blk = _block_of(ledger, height, writes, n_keys, vsize, read)
        ledger.commit(blk)
        height += 1
    dt = time.perf_counter() - t0
    print(json.dumps({
        "experiment": name,
        "txs": n_txs,
        "batch": batch,
        "keys_per_tx": n_keys,
        "value_size": vsize,
        "seconds": round(dt, 3),
        "tx_per_s": round(n_txs / dt, 1),
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--txs", type=int, default=10000)
    ap.add_argument("--batch", type=int, default=100)
    ap.add_argument("--keys", type=int, default=4)
    ap.add_argument("--value-size", type=int, default=64)
    ap.add_argument("--disk", action="store_true",
                    help="sqlite-backed stores instead of in-memory")
    args = ap.parse_args()
    ledger = _mk_ledger(args.disk)
    run_experiment("insert", ledger, args.txs, args.batch, args.keys,
                   args.value_size, read=False)
    run_experiment("readwrite", ledger, args.txs, args.batch, args.keys,
                   args.value_size, read=True)


if __name__ == "__main__":
    main()
