"""Clean twin of fix_flow_loopstart_dirty: the shared field is fully
published BEFORE the loop, so every start() in the loop dominates no
later write — the CFG pass proves the publication and stays quiet."""

from fabric_tpu.devtools.lockwatch import spawn_thread


def handle(item):
    return item


class BatchPump:
    def __init__(self):
        self._batch = []
        self._threads = []

    def launch(self, specs):
        # publish once, before any worker exists: every path from a
        # start() sees only reads of the field
        self._batch = list(specs)
        for _spec in specs:
            t = spawn_thread(
                target=self._run, name="pump", kind="worker"
            )
            t.start()
            self._threads.append(t)
        for t in self._threads:
            t.join()

    def _run(self):
        for item in list(self._batch):
            handle(item)
