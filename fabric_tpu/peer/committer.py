"""Commit orchestration: validate -> commit -> notify.

Reference: gossip/privdata/coordinator.go:149 StoreBlock (txvalidator ->
pvtdata assembly -> CommitLegacy) + core/committer/committer_impl.go.
Private-data fetching slots in between validate and commit when the
pvtdata subsystem lands.
"""

from __future__ import annotations

import threading
import time


class Committer:
    def __init__(self, validator, ledger, metrics=None):
        self._validator = validator
        self._ledger = ledger
        self._listeners: list = []
        self._lock = threading.Lock()
        self.metrics = metrics

    def add_commit_listener(self, fn) -> None:
        self._listeners.append(fn)

    def store_block(self, block) -> list[int]:
        """The per-block pipeline; returns final validation flags."""
        t0 = time.perf_counter()
        self._validator.validate(block)  # sets sig/policy flags
        t_validate = time.perf_counter() - t0
        with self._lock:
            self._ledger.commit(block)  # MVCC + persist (updates flags again)
        if self.metrics is not None:
            self.metrics.observe(
                "validate_duration", t_validate, channel=self._validator.channel_id
            )
            self.metrics.observe(
                "commit_duration",
                time.perf_counter() - t0,
                channel=self._validator.channel_id,
            )
        from fabric_tpu import protoutil

        flags = list(protoutil.tx_filter(block))
        for fn in self._listeners:
            fn(block, flags)
        return flags

    @property
    def height(self) -> int:
        return self._ledger.height


__all__ = ["Committer"]
