"""Faultfuzz tests (ISSUE 8 tentpole): registry discovery over the
canned workload, fixed-seed campaign determinism (the acceptance pin:
two 25-plan seed-7 campaigns produce byte-identical verdicts and
canonical trip ledgers), an intentionally-seeded oracle violation
(shard-apply crash + skipped shard roll-forward) caught, shrunk to its
2-rule minimum, and replayable from the repro artifact, the snapshot
export/import fault points (torn manifest refused, half-import refused
loudly), and the tier-1 soak mode (slow): the commit+snapshot workload
under the low-probability background plan to a green oracle."""

import copy
import json
import os
import random

import pytest

from fabric_tpu.devtools import faultfuzz, faultline, invariants
from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.ledger import snapshot as snap


# -- workload + oracle baseline ----------------------------------------------


def test_workload_green_without_effective_faults(tmp_path):
    """The canned workload with a never-matching plan: all phases run,
    the oracle is green — the fuzzer's failures are real signals, not
    workload noise."""
    res = faultfuzz.run_plan(
        {"faults": [{"point": "no.such.point", "action": "delay",
                     "delay_s": 0.0}]},
        str(tmp_path / "w"),
    )
    assert res["violations"] == []
    assert res["trips"] == []
    assert res["stats"]["committed"] == faultfuzz.DEFAULT_BLOCKS + 2
    assert res["stats"]["import"] == "done"
    assert res["stats"]["rpc_ok"] == 3


def test_registry_discovery_enumerates_the_workload_surface(tmp_path):
    c = faultfuzz.Campaign(
        seed=1, plans=0, workdir=str(tmp_path), out_dir=str(tmp_path)
    )
    reg = c.discover(str(tmp_path))
    # the three layers the canned workload drives
    for point in (
        "commit.stage", "kvstore.txn", "blkstorage.file_append",
        "blkstorage.fsync", "snapshot.export.stage", "snapshot.manifest",
        "snapshot.import.stage", "rpc.accept", "rpc.client.read",
        "rpc.server.read",
    ):
        assert point in reg, sorted(reg)
    # ctx value samples give the generator concrete targets
    assert "mvcc" in reg["commit.stage"]["ctx"]["stage"]
    assert "write" in reg["snapshot.manifest"]["kinds"]
    assert "io" in reg["rpc.client.read"]["kinds"]


# -- determinism acceptance ---------------------------------------------------


def _strip_paths(summary: dict) -> dict:
    out = {k: v for k, v in summary.items() if k != "repro"}
    out["results"] = [
        {k: v for k, v in e.items() if k != "repro"}
        for e in summary["results"]
    ]
    return out


def test_campaign_25_plans_seed_7_is_deterministic(tmp_path):
    """The acceptance pin: the fixed-seed campaign
    (scripts/chaos.py --plans 25 --seed 7) run twice produces
    byte-identical trip ledgers and oracle verdicts."""
    runs = []
    for sub in ("r1", "r2"):
        c = faultfuzz.Campaign(
            seed=7, plans=25, workdir=str(tmp_path / sub),
            out_dir=str(tmp_path / sub / "out"),
        )
        runs.append(c.run())
    a, b = runs
    assert a["verdicts"] == b["verdicts"]
    assert json.dumps(a["trip_ledger"], sort_keys=True) == \
        json.dumps(b["trip_ledger"], sort_keys=True)
    assert _strip_paths(a) == _strip_paths(b)
    # the campaign actually injected faults (a dead campaign would be
    # vacuously deterministic)
    assert a["trips_total"] > 0
    assert a["registry_points"] >= 10


# -- the seeded oracle violation ---------------------------------------------


_SEEDED_PLAN = {
    "seed": 3,
    "label": "seeded",
    "faults": [
        # a crash at the first shard-apply: the coordinator txn
        # (savepoint + block index + epoch record) is already durable,
        # the shard's staged writes are not yet folded in...
        {"point": "store.shard_flush", "action": "crash",
         "ctx": {"stage": "apply"}, "count": 1},
        # ...and the reopen roll-forward guard is SKIPPED, so the
        # committed-but-unapplied pending writes are silently dropped
        # while the savepoint says the block committed — lost state
        # below the recovered height
        {"point": "store.shard_recover", "action": "skip", "count": 5},
    ],
}


def test_seeded_violation_caught_shrunk_and_replayable(tmp_path):
    """The full failure pipeline: the oracle catches the corruption,
    shrinking proves BOTH rules are load-bearing (the minimal plan is
    exactly the two of them), the repro artifact is written, and
    re-arming it reproduces the failure."""
    res = faultfuzz.run_plan(_SEEDED_PLAN, str(tmp_path / "run"))
    assert res["violations"], "the seeded violation was not caught"
    checks = {v["check"] for v in res["violations"]}
    assert checks & {"state", "reopen"}, res["violations"]

    # dropping either rule individually passes — the pair is minimal
    counter = [0]

    def still_fails(cand):
        counter[0] += 1
        return bool(faultfuzz.run_plan(
            cand, str(tmp_path / f"shrink{counter[0]}")
        )["violations"])

    shrunk, runs = faultfuzz.shrink_plan(_SEEDED_PLAN, still_fails)
    assert len(shrunk["faults"]) == 2
    assert {f["point"] for f in shrunk["faults"]} == {
        "store.shard_flush", "store.shard_recover",
    }
    assert runs >= 2  # it really tried to drop both

    path = faultfuzz.write_repro(
        str(tmp_path / "repro.json"), shrunk, _SEEDED_PLAN,
        res["violations"], res["trips"], seed=3, index=0,
    )
    doc = json.loads(open(path).read())
    assert doc["format"] == faultfuzz.REPRO_FORMAT
    replayed = faultfuzz.replay(path, str(tmp_path / "replay"))
    assert replayed["violations"], "the repro artifact did not reproduce"
    assert {v["check"] for v in replayed["violations"]} & \
        {"state", "reopen"}


def test_campaign_writes_repro_for_failing_plan(tmp_path):
    """End to end through Campaign: a campaign that happens to include
    the seeded failure writes a shrunk repro artifact and reports the
    failure in its summary (simulated by judging a single run_plan
    failure through the same artifact path chaos.py uses)."""
    res = faultfuzz.run_plan(_SEEDED_PLAN, str(tmp_path / "run"))
    out = str(tmp_path / ".faultfuzz")
    path = faultfuzz.write_repro(
        os.path.join(out, "repro_seed3_plan000.json"),
        _SEEDED_PLAN, _SEEDED_PLAN, res["violations"], res["trips"],
        seed=3, index=0,
    )
    assert os.path.isfile(path)


# -- single-edit mutants (ISSUE 19 satellite) ---------------------------------


def _seeded_registry():
    """Registry slice covering the seeded plan's two points, with the
    kinds the pinned faultmap carries — enough for mutate_plan's
    action-pool lookup."""
    return {
        "store.shard_flush": {"kinds": ["point"], "ctx": {}},
        "store.shard_recover": {"kinds": ["guard"], "ctx": {}},
    }


def test_mutate_plan_same_seed_same_single_edit_mutant():
    """A mutant is fully derived from its rng seed and differs from
    its parent by EXACTLY one edit: a dropped rule, a swapped action
    (from the point's own pool), or a re-sampled trigger.  The plan
    seed carries over, so a mutant run isolates one variable."""
    registry = _seeded_registry()
    snapshot = copy.deepcopy(_SEEDED_PLAN)
    parent = _SEEDED_PLAN["faults"]
    kinds_of_edit = set()
    for j in range(8):
        a = faultfuzz.mutate_plan(
            random.Random(f"3:0:m{j}"), _SEEDED_PLAN, registry,
            f"seeded:m{j}",
        )
        b = faultfuzz.mutate_plan(
            random.Random(f"3:0:m{j}"), _SEEDED_PLAN, registry,
            f"seeded:m{j}",
        )
        assert a == b  # same (seed, plan index, mutant index) -> same mutant
        assert a["label"] == f"seeded:m{j}"
        assert a["seed"] == _SEEDED_PLAN["seed"]
        faults = a["faults"]
        if len(faults) == len(parent) - 1:
            kinds_of_edit.add("drop")
            assert all(f in parent for f in faults)
        else:
            assert len(faults) == len(parent)
            diffs = [k for k in range(len(parent))
                     if faults[k] != parent[k]]
            assert len(diffs) == 1, (faults, parent)
            f, p = faults[diffs[0]], parent[diffs[0]]
            assert f["point"] == p["point"]  # the rule kept its target
            if f["action"] != p["action"]:
                kinds_of_edit.add("action")
                assert f["action"] in faultfuzz._action_pool(
                    f["point"], registry[f["point"]]["kinds"]
                )
            else:
                kinds_of_edit.add("trigger")
    # all three edit kinds show up across the first 8 seeds, and the
    # parent plan itself is never touched (deepcopy, not aliasing)
    assert kinds_of_edit == {"drop", "action", "trigger"}
    assert _SEEDED_PLAN == snapshot


def test_campaign_mutants_ride_the_repro_path_and_stay_deterministic(
        tmp_path, monkeypatch):
    """Campaign-level mutant plumbing.  Generated plans at test sizes
    never fail the oracle, so the failing-plan mutant path is pinned
    by making the generator emit the seeded failure: the campaign
    derives K seed-addressed mutants, judges each, writes a repro for
    the still-failing one (mutant m5's trigger tweak keeps the
    shard-apply crash live), counts it in the summary, and two
    same-seed campaigns agree byte-for-byte once artifact paths are
    stripped."""
    def seeded_generator(rng, registry, label, tripped=frozenset()):
        plan = copy.deepcopy(_SEEDED_PLAN)
        plan["label"] = label
        return plan

    monkeypatch.setattr(faultfuzz, "generate_plan", seeded_generator)

    def strip(summary):
        out = {k: v for k, v in summary.items()
               if k not in ("repro", "trace", "profile")}
        out["results"] = [
            {
                **{k: v for k, v in e.items()
                   if k not in ("repro", "trace", "profile", "mutants")},
                "mutants": [
                    {k: v for k, v in m.items() if k != "repro"}
                    for m in e.get("mutants", ())
                ],
            }
            for e in summary["results"]
        ]
        return out

    runs = []
    for sub in ("r1", "r2"):
        c = faultfuzz.Campaign(
            seed=3, plans=1, mutants=6, shrink=False,
            workdir=str(tmp_path / sub),
            out_dir=str(tmp_path / sub / "out"),
        )
        runs.append(c.run())
    a, b = runs
    assert strip(a) == strip(b)

    assert a["mutants_per_failure"] == 6
    assert a["mutant_failures"] == 1
    [entry] = a["results"]
    assert entry["verdict"] == "fail"
    muts = entry["mutants"]
    assert [m["index"] for m in muts] == list(range(6))
    # each mutant label is addressable back to (seed, plan, mutant)
    assert muts[5]["plan"]["label"] == "fuzz:3:0:m5"
    assert [m["verdict"] for m in muts] == \
        ["pass", "pass", "pass", "pass", "pass", "fail"]
    # mutant trips feed the campaign's coverage ledger
    assert a["trips_total"] > len(entry["trips"])

    # the failing mutant wrote a repro through the same artifact path
    # as its parent, and that artifact replays to the same violation
    assert len(a["repro"]) == 2
    failing = muts[5]
    assert failing["repro"].endswith("repro_seed3_plan000_m5.json")
    assert os.path.isfile(failing["repro"])
    doc = json.loads(open(failing["repro"]).read())
    assert doc["format"] == faultfuzz.REPRO_FORMAT
    replayed = faultfuzz.replay(
        failing["repro"], str(tmp_path / "replay")
    )
    assert replayed["violations"], \
        "the mutant repro artifact did not reproduce"
    assert {v["check"] for v in replayed["violations"]} & {"state"}


# -- snapshot fault points ----------------------------------------------------


def _build_ledger(root, blocks=3):
    provider = LedgerProvider(str(root))
    ledger = provider.open(faultfuzz.CHANNEL)
    writes = faultfuzz.workload_writes(blocks)
    for n in range(blocks):
        ledger.commit(faultfuzz._endorsed_block(ledger, n, writes[n]))
    return provider, ledger


def test_torn_manifest_staging_dir_refuses_verification(tmp_path):
    """A torn write of the signable metadata mid-export: the crash
    leaves only the staging directory, nothing lands in completed/,
    and verify_snapshot refuses the torn directory — the oracle's
    rejection contract."""
    provider, ledger = _build_ledger(tmp_path / "src")
    with faultline.use_plan({"faults": [
        {"point": "snapshot.manifest", "action": "torn", "cut": 0.5},
    ]}):
        with pytest.raises(faultline.FaultCrash, match="torn write"):
            ledger.snapshots.generate()
        assert faultline.trips()
    provider.close()

    snaps = tmp_path / "src" / "snapshots"
    assert not os.path.isdir(str(snaps / "completed" / faultfuzz.CHANNEL))
    staging = snaps / "in_progress"
    [work] = os.listdir(str(staging))
    torn_dir = str(staging / work)
    # the torn manifest is really a strict prefix on disk
    raw = open(os.path.join(torn_dir, snap.METADATA_FILE), "rb").read()
    with pytest.raises(ValueError):
        json.loads(raw.decode("utf-8", "replace"))
    assert invariants.check_snapshot_rejected(torn_dir) == []
    with pytest.raises(Exception):
        snap.verify_snapshot(torn_dir)


def test_export_crash_before_rename_leaves_completed_clean(tmp_path):
    """A crash at the rename stage: the fully-written snapshot stays in
    staging, completed/ holds nothing — and a later export of the same
    height succeeds after the staging dir is reclaimed."""
    provider, ledger = _build_ledger(tmp_path / "src")
    with faultline.use_plan({"faults": [
        {"point": "snapshot.export.stage", "action": "crash",
         "ctx": {"stage": "rename"}},
    ]}):
        with pytest.raises(faultline.FaultCrash):
            ledger.snapshots.generate()
    # retry with no plan: generate_snapshot reclaims the staging dir
    path = ledger.snapshots.generate()
    assert os.path.isdir(path)
    assert invariants.check_snapshot_verifies(path) == []
    provider.close()


def test_partial_import_refused_loudly(tmp_path):
    """A crash mid-import (after txids, before state) leaves the
    half-import marker: both re-import and open() refuse the channel
    instead of serving partial state."""
    provider, ledger = _build_ledger(tmp_path / "src")
    export_dir = ledger.snapshots.generate()
    provider.close()

    dst_root = str(tmp_path / "dst")
    dst = LedgerProvider(dst_root)
    with faultline.use_plan({"faults": [
        {"point": "snapshot.import.stage", "action": "crash",
         "ctx": {"stage": "txids"}},
    ]}):
        with pytest.raises(faultline.FaultCrash):
            dst.create_from_snapshot(export_dir)
        assert faultline.trips()
    dst.close()

    dst2 = LedgerProvider(dst_root)
    try:
        assert snap.import_marker(dst2.kv, faultfuzz.CHANNEL) == \
            snap.IMPORT_IN_PROGRESS
        with pytest.raises(snap.SnapshotError, match="half-finished"):
            dst2.open(faultfuzz.CHANNEL)
        with pytest.raises(snap.SnapshotError, match="half-finished"):
            dst2.create_from_snapshot(export_dir)
        # the recovery path the refusal points at: discard the debris,
        # then the SAME provider re-imports the SAME snapshot cleanly
        deleted = dst2.discard_failed_import(faultfuzz.CHANNEL)
        assert deleted > 0  # the crashed import left real residue
        assert snap.import_marker(dst2.kv, faultfuzz.CHANNEL) is None
        with pytest.raises(snap.SnapshotError, match="no half-finished"):
            dst2.discard_failed_import(faultfuzz.CHANNEL)
        led2 = dst2.create_from_snapshot(export_dir)
        assert snap.import_marker(dst2.kv, faultfuzz.CHANNEL) == \
            snap.IMPORT_DONE
        assert invariants.check_import_state(led2, export_dir) == []
    finally:
        dst2.close()
    # and a FRESH destination imports the same snapshot cleanly
    dst3 = LedgerProvider(str(tmp_path / "dst3"))
    try:
        led3 = dst3.create_from_snapshot(export_dir)
        assert snap.import_marker(dst3.kv, faultfuzz.CHANNEL) == \
            snap.IMPORT_DONE
        assert invariants.check_import_state(led3, export_dir) == []
    finally:
        dst3.close()


def test_completed_import_marker_done_on_clean_path(tmp_path):
    provider, ledger = _build_ledger(tmp_path / "src")
    export_dir = ledger.snapshots.generate()
    provider.close()
    dst = LedgerProvider(str(tmp_path / "dst"))
    try:
        dst.create_from_snapshot(export_dir)
        assert snap.import_marker(dst.kv, faultfuzz.CHANNEL) == \
            snap.IMPORT_DONE
    finally:
        dst.close()


# -- soak mode ----------------------------------------------------------------


def test_soak_env_arms_background_plan(monkeypatch):
    monkeypatch.setattr(faultline, "_plan", None)
    monkeypatch.setattr(faultline, "_env_plan", None)
    monkeypatch.delenv("FABRIC_TPU_FAULTLINE", raising=False)
    monkeypatch.setenv("FABRIC_TPU_SOAK", "11")
    faultline._init_from_env()
    try:
        plan = faultline.current_plan()
        assert plan is not None and plan.label == "soak"
        assert any(r.wildcard for r in plan.rules)
    finally:
        faultline.deactivate()
        faultline.reset_trips()
    # an explicit FAULTLINE plan wins over SOAK
    monkeypatch.setenv(
        "FABRIC_TPU_FAULTLINE",
        '{"label": "explicit", "faults": [{"point": "x", '
        '"action": "delay", "delay_s": 0.0}]}',
    )
    faultline._init_from_env()
    try:
        assert faultline.current_plan().label == "explicit"
    finally:
        faultline.deactivate()
        faultline.reset_trips()
    with pytest.raises(faultline.PlanError):
        monkeypatch.delenv("FABRIC_TPU_FAULTLINE")
        monkeypatch.setenv("FABRIC_TPU_SOAK", "not-a-seed")
        faultline._init_from_env()


@pytest.mark.slow
def test_soak_tier1_workload_green_oracle(tmp_path):
    """Soak acceptance: the commit+snapshot workload (the tier-1
    subset) under the low-probability background plan finishes with a
    GREEN oracle — background chaos perturbs timing, never
    correctness — and the background delays really fired."""
    with faultline.use_plan(faultline.soak_plan(11)):
        stats = faultfuzz._drive(str(tmp_path), blocks=12)
        soak_trips = [
            t for t in faultline.trips() if t["plan"] == "soak"
        ]
    assert stats["committed"] == 14
    assert stats["import"] == "done"
    assert soak_trips, "the soak plan never fired in 14 commits"
    violations = faultfuzz._judge(
        str(tmp_path), stats, faultfuzz.workload_writes(12)
    )
    assert violations == [], [str(v) for v in violations]


# -- coverage-weighted generation (ISSUE 18 satellite) ------------------------


def test_generate_plan_prefers_cold_points_same_draw_count():
    """Selection is biased toward registry entries with zero trips so
    far: with every point but one marked tripped, every fault rule
    lands on the cold one — and the weighting consumes the same RNG
    draws as the unweighted path, so an empty tripped set reproduces
    the v4 stream exactly (the same-seed campaign byte-identity pin
    rides on this)."""
    import random

    reg = {
        "a.one": {"kinds": []},
        "b.two": {"kinds": []},
        "c.three": {"kinds": []},
    }
    for i in range(20):
        rng = random.Random(f"w:{i}")
        plan = faultfuzz.generate_plan(
            rng, reg, "w", tripped={"a.one", "c.three"}
        )
        assert all(f["point"] == "b.two" for f in plan["faults"])
    # empty tripped set == the unweighted stream, draw for draw
    for i in range(20):
        p0 = faultfuzz.generate_plan(
            random.Random(f"s:{i}"), reg, "s"
        )
        p1 = faultfuzz.generate_plan(
            random.Random(f"s:{i}"), reg, "s", tripped=frozenset()
        )
        assert p0 == p1
    # fully-tripped registry degrades to uniform, never to an error
    p = faultfuzz.generate_plan(
        random.Random("t"), reg, "t", tripped=set(reg)
    )
    assert all(f["point"] in reg for f in p["faults"])


# -- chaos-coverage registry cross-check (ISSUE 18 tentpole) ------------------


def test_pinned_registry_contains_fresh_discovery(tmp_path):
    """The pinned faultmap registry (fabric_tpu/devtools/
    faultmap_registry.json, refreshed via scripts/chaos.py
    --export-registry) must contain every point a fresh observer-plan
    discovery finds — discovery ⊆ registry, the runtime half of the
    containment chain (lint pins registry ⊆ static faultmap)."""
    from fabric_tpu.devtools.lint import load_faultmap_registry

    pinned = load_faultmap_registry()
    assert pinned, "faultmap_registry.json missing or empty"
    c = faultfuzz.Campaign(
        seed=1, plans=0, workdir=str(tmp_path), out_dir=str(tmp_path)
    )
    fresh = c.discover(str(tmp_path))
    for name, ent in fresh.items():
        assert name in pinned, (
            f"discovery found {name!r} missing from the pinned "
            "registry — refresh with scripts/chaos.py --export-registry"
        )
        assert set(ent["kinds"]) <= set(pinned[name]["kinds"]), name
