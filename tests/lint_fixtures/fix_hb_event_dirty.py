"""Seeded violation: re-arming a shared Event (clear()) on one thread
concurrently with another thread's set() — a waiter can miss the set
entirely (the lost-wakeup class behind the PR 11 deliver-client
wedge).  racecheck, v4 happens-before pass."""

import threading

from fabric_tpu.devtools.lockwatch import spawn_thread


class Gate:
    def __init__(self):
        self._pulse = threading.Event()
        self._a = spawn_thread(target=self._ping, name="a", kind="worker")
        self._b = spawn_thread(target=self._pong, name="b", kind="worker")

    def start(self):
        self._a.start()
        self._b.start()

    def _ping(self):
        self._pulse.set()

    def _pong(self):
        self._pulse.wait()
        self._pulse.clear()  # <- racecheck fires HERE
