"""Raft cluster TCP transport over pinned mutual TLS: consenter-set
members exchange Step frames; a node whose cert is not pinned cannot
deliver into the cluster (reference orderer/common/cluster/comm.go:116
VerifyConnection pinning)."""

from __future__ import annotations

import time

import pytest

from fabric_tpu.comm.tls import credentials_from_ca
from fabric_tpu.common.crypto import CA
from fabric_tpu.orderer.raft.transport import TCPTransport
from fabric_tpu.protos.orderer import raft_pb2 as rpb


@pytest.fixture(scope="module")
def ca():
    return CA("tlsca.orderer.example.com", "orderer")


def _wait(pred, timeout=5.0):
    end = time.time() + timeout
    while time.time() < end:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _step(frm: int, term: int = 7) -> rpb.StepRequest:
    req = rpb.StepRequest()
    req.channel = "tlsch"
    req.consensus.type = rpb.MSG_APPEND
    req.consensus.sender = frm
    req.consensus.term = term
    return req


def test_pinned_cluster_step(ca):
    creds = {i: credentials_from_ca(ca, f"orderer{i}") for i in (1, 2)}
    pinned = [c.cert_der for c in creds.values()]
    for c in creds.values():
        c.pinned_certs = list(pinned)

    t1 = TCPTransport(1, ("127.0.0.1", 0), tls=creds[1])
    t2 = TCPTransport(2, ("127.0.0.1", 0), tls=creds[2])
    got = []
    t2.set_handler(lambda req: got.append(req.consensus.sender))
    try:
        t1.set_peer(2, t2.addr)
        t1.send(1, 2, _step(1))
        assert _wait(lambda: got == [1])
    finally:
        t1.close()
        t2.close()


def test_unpinned_node_rejected(ca):
    creds = {i: credentials_from_ca(ca, f"orderer{i}") for i in (1, 2)}
    pinned = [c.cert_der for c in creds.values()]
    for c in creds.values():
        c.pinned_certs = list(pinned)

    t2 = TCPTransport(2, ("127.0.0.1", 0), tls=creds[2])
    got = []
    t2.set_handler(lambda req: got.append(req.consensus.sender))

    # same CA, valid chain — but not in the consenter allowlist
    rogue_creds = credentials_from_ca(ca, "rogue-orderer")
    rogue_creds.pinned_certs = list(pinned)  # it even pins the others
    rogue = TCPTransport(9, ("127.0.0.1", 0), tls=rogue_creds)
    try:
        rogue.set_peer(2, t2.addr)
        rogue.send(9, 2, _step(9))
        assert not _wait(lambda: got, timeout=1.5)
    finally:
        rogue.close()
        t2.close()


def test_set_pinned_admits_new_consenter(ca):
    creds = {i: credentials_from_ca(ca, f"orderer{i}") for i in (1, 2)}
    pinned = [creds[1].cert_der, creds[2].cert_der]
    for c in creds.values():
        c.pinned_certs = list(pinned)

    t2 = TCPTransport(2, ("127.0.0.1", 0), tls=creds[2])
    got = []
    t2.set_handler(lambda req: got.append(req.consensus.sender))

    c3 = credentials_from_ca(ca, "orderer3")
    c3.pinned_certs = list(pinned)
    t3 = TCPTransport(3, ("127.0.0.1", 0), tls=c3)
    try:
        t3.set_peer(2, t2.addr)
        t3.send(3, 2, _step(3))
        assert not _wait(lambda: got, timeout=1.0), "not yet admitted"
        # config change adds orderer3 to the consenter set
        t2.set_pinned(pinned + [c3.cert_der])
        t3.remove_peer(2)  # drop the sender's failed/cached socket
        t3.set_peer(2, t2.addr)
        t3.send(3, 2, _step(3))
        assert _wait(lambda: got == [3])
    finally:
        t3.close()
        t2.close()
