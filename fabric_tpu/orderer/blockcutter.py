"""Batching envelopes into blocks (reference
orderer/common/blockcutter/blockcutter.go:69 Ordered / :127 Cut).

Triggers: message count, preferred byte size, oversized-message isolation.
Timeout-based cutting is the consenter's job (it calls `cut()` on timer),
matching the reference's division of labor.
"""

from __future__ import annotations


class BlockCutter:
    def __init__(
        self,
        max_message_count: int = 500,
        preferred_max_bytes: int = 2 * 1024 * 1024,
        absolute_max_bytes: int = 10 * 1024 * 1024,
    ):
        self.max_message_count = max_message_count
        self.preferred_max_bytes = preferred_max_bytes
        self.absolute_max_bytes = absolute_max_bytes
        self._pending: list[bytes] = []
        self._pending_bytes = 0

    @classmethod
    def from_orderer_config(cls, oc) -> "BlockCutter":
        return cls(oc.max_message_count, oc.preferred_max_bytes, oc.absolute_max_bytes)

    def update_from_orderer_config(self, oc) -> None:
        """Adopt new BatchSize limits in place (a committed config
        update must take effect on the RUNNING chain, which holds this
        cutter; pending messages keep accumulating under the new
        limits)."""
        self.max_message_count = oc.max_message_count
        self.preferred_max_bytes = oc.preferred_max_bytes
        self.absolute_max_bytes = oc.absolute_max_bytes

    def ordered(self, env_bytes: bytes) -> tuple[list[list[bytes]], bool]:
        """Enqueue one message; returns (cut batches, pending remains)."""
        batches: list[list[bytes]] = []
        size = len(env_bytes)
        if size > self.preferred_max_bytes:
            # isolate oversized messages into their own block
            if self._pending:
                batches.append(self.cut())
            batches.append([env_bytes])
            return batches, False
        if self._pending_bytes + size > self.preferred_max_bytes and self._pending:
            batches.append(self.cut())
        self._pending.append(env_bytes)
        self._pending_bytes += size
        if len(self._pending) >= self.max_message_count:
            batches.append(self.cut())
        return batches, bool(self._pending)

    def cut(self) -> list[bytes]:
        batch = self._pending
        self._pending = []
        self._pending_bytes = 0
        return batch

    @property
    def pending(self) -> bool:
        return bool(self._pending)


__all__ = ["BlockCutter"]
