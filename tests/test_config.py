"""Config loading: YAML + env overrides + decode hooks (the reference's
viperutil/config_util.go behaviors)."""

import os

from fabric_tpu.common.config import (
    Config,
    parse_bytesize,
    parse_duration,
    resolve_file_ref,
)


def test_yaml_env_precedence(tmp_path, monkeypatch):
    (tmp_path / "core.yaml").write_text(
        "peer:\n  listenAddress: 1.2.3.4:7051\n  validatorPoolSize: 8\n"
    )
    monkeypatch.setenv("FABRIC_CFG_PATH", str(tmp_path))
    cfg = Config.load("core", "CORE")
    assert cfg.get("peer.listenAddress") == "1.2.3.4:7051"
    assert cfg.get_int("peer.validatorPoolSize") == 8
    # env override wins (viper CORE_PEER_LISTENADDRESS)
    monkeypatch.setenv("CORE_PEER_LISTENADDRESS", "9.9.9.9:1")
    cfg = Config.load("core", "CORE")
    assert cfg.get("peer.listenAddress") == "9.9.9.9:1"
    # case-insensitive dotted lookup
    assert cfg.get("PEER.VALIDATORPOOLSIZE") == 8
    # missing -> default
    assert cfg.get("peer.nope", 42) == 42


def test_decode_hooks(tmp_path):
    assert parse_bytesize("100 MB") == 100 << 20
    assert parse_bytesize("16k") == 16384
    assert parse_bytesize(512) == 512
    assert parse_duration("250ms") == 0.25
    assert parse_duration("2m") == 120.0
    assert parse_duration(1.5) == 1.5
    pem = tmp_path / "cert.pem"
    pem.write_bytes(b"PEMDATA")
    assert resolve_file_ref(f"file:{pem}") == b"PEMDATA"
    assert resolve_file_ref("plain-value") == "plain-value"


def test_typed_getters(tmp_path, monkeypatch):
    (tmp_path / "orderer.yaml").write_text(
        "general:\n  tickInterval: 500ms\nconsensus:\n"
        "  snapshotIntervalSize: 16 MB\ndebug:\n  enabled: 'yes'\n"
    )
    monkeypatch.setenv("FABRIC_CFG_PATH", str(tmp_path))
    cfg = Config.load("orderer", "ORDERER")
    assert cfg.get_duration("general.tickInterval") == 0.5
    assert cfg.get_bytesize("consensus.snapshotIntervalSize") == 16 << 20
    assert cfg.get_bool("debug.enabled") is True


def test_sampleconfig_parses():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    core = Config.load("core", "CORE",
                       os.path.join(root, "sampleconfig", "core.yaml"))
    assert core.get("bccsp.default") == "TPU"
    assert core.get_int("peer.limits.concurrency.endorserService") == 2500
    orderer = Config.load("orderer", "ORDERER",
                          os.path.join(root, "sampleconfig", "orderer.yaml"))
    assert orderer.get_int("general.listenPort") == 7050
    assert orderer.get_duration("consensus.tickInterval") == 0.5


def test_keepalive_options_from_config():
    """peer.keepalive / general.keepalive blocks feed the RPC
    connection-lifecycle knobs on both daemons."""
    from fabric_tpu.comm.rpc import KeepaliveOptions
    from fabric_tpu.common.config import Config

    cfg = Config(
        {
            "peer": {"keepalive": {"idleTimeout": 11, "interval": 5,
                                   "timeout": 7}},
            "general": {"keepalive": {"idleTimeout": 42}},
        }
    )
    ka = KeepaliveOptions.from_config(cfg)
    assert (ka.idle_timeout, ka.ping_interval, ka.ping_timeout) == (11, 5, 7)
    oka = KeepaliveOptions.from_config(cfg, prefix="general.keepalive")
    assert oka.idle_timeout == 42
    assert oka.ping_interval == KeepaliveOptions().ping_interval  # default
    # absent block -> all defaults
    dka = KeepaliveOptions.from_config(Config({}))
    assert dka == KeepaliveOptions()


def test_csp_from_config_selects_tpu_provider():
    from fabric_tpu.common.config import Config
    from fabric_tpu.csp import csp_from_config
    from fabric_tpu.csp.tpu.provider import TPUCSP

    csp = csp_from_config(
        Config({"bccsp": {"default": "TPU",
                          "tpu": {"minDeviceBatch": 7}}})
    )
    assert isinstance(csp, TPUCSP)
    assert csp._min_device_batch == 7
    # orderer-style nested prefix
    csp2 = csp_from_config(
        Config({"general": {"bccsp": {"default": "SW"}}}),
        prefix="general.bccsp",
    )
    from fabric_tpu.csp import SWCSP

    assert isinstance(csp2, SWCSP)
