"""BCCSP keystores: in-memory, SKI-keyed PEM file store, dummy.

Reference: bccsp/sw/inmemoryks.go, bccsp/sw/fileks.go:29
(fileBasedKeyStore — one PEM per key in a flat directory, file name
keyed by the hex SKI with a `_sk` / `_pk` suffix), bccsp/sw/dummyks.go.
The file store is what lets a node restart reuse its generated keys
instead of re-deriving state from MSP directories; the directory is
created 0700 and private-key files 0600, as the reference enforces.
"""

from __future__ import annotations

import os
import threading

from cryptography.hazmat.primitives import serialization as _ser

from fabric_tpu.csp.api import ECDSAP256PrivateKey, ECDSAP256PublicKey, Key


class InMemoryKeyStore:
    """Ephemeral SKI -> Key map (reference inmemoryks.go)."""

    read_only = False

    def __init__(self) -> None:
        self._keys: dict[bytes, Key] = {}
        self._lock = threading.Lock()

    def store_key(self, key: Key) -> None:
        with self._lock:
            self._keys[key.ski()] = key

    def get_key(self, ski: bytes) -> Key:
        with self._lock:
            key = self._keys.get(ski)
        if key is None:
            raise KeyError(f"no key for SKI {ski.hex()}")
        return key


class DummyKeyStore:
    """Stores nothing, returns nothing (reference dummyks.go) — for
    providers whose keys live elsewhere (e.g. imported per call)."""

    read_only = True

    def store_key(self, key: Key) -> None:
        pass

    def get_key(self, ski: bytes) -> Key:
        raise KeyError(f"dummy keystore holds no keys (SKI {ski.hex()})")


class FileKeyStore:
    """SKI-keyed PEM file keystore (reference fileks.go:29).

    Layout: `<dir>/<ski-hex>_sk.pem` (PKCS8 private) and
    `<dir>/<ski-hex>_pk.pem` (SubjectPublicKeyInfo).  Lookups hit an
    in-memory cache first and fall back to disk, so a restarted node
    finds every key a previous process generated.  `read_only=True`
    refuses stores (the reference supports this for pre-provisioned
    HSM-style directories)."""

    def __init__(self, path: str, read_only: bool = False) -> None:
        self.path = path
        self.read_only = read_only
        os.makedirs(path, exist_ok=True)
        os.chmod(path, 0o700)
        self._cache: dict[bytes, Key] = {}
        self._lock = threading.Lock()

    def _file(self, ski: bytes, private: bool) -> str:
        return os.path.join(
            self.path, f"{ski.hex()}_{'sk' if private else 'pk'}.pem"
        )

    def store_key(self, key: Key) -> None:
        if self.read_only:
            raise PermissionError("read-only keystore")
        private = isinstance(key, ECDSAP256PrivateKey)
        path = self._file(key.ski(), private)
        pem = (
            key.crypto_key.private_bytes(
                _ser.Encoding.PEM,
                _ser.PrivateFormat.PKCS8,
                _ser.NoEncryption(),
            )
            if private
            else key.pem()
        )
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            os.write(fd, pem)
        finally:
            os.close(fd)
        with self._lock:
            self._cache[key.ski()] = key

    def get_key(self, ski: bytes) -> Key:
        with self._lock:
            key = self._cache.get(ski)
        if key is not None:
            return key
        sk = self._file(ski, True)
        pk = self._file(ski, False)
        if os.path.exists(sk):
            with open(sk, "rb") as f:
                key = ECDSAP256PrivateKey.from_pem(f.read())
        elif os.path.exists(pk):
            with open(pk, "rb") as f:
                key = ECDSAP256PublicKey.from_pem(f.read())
        else:
            raise KeyError(f"no key for SKI {ski.hex()}")
        if key.ski() != ski:
            raise KeyError(
                f"keystore file for {ski.hex()} holds a different key"
            )
        with self._lock:
            self._cache[ski] = key
        return key


__all__ = ["InMemoryKeyStore", "FileKeyStore", "DummyKeyStore"]
