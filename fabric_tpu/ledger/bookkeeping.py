"""Internal bookkeeping provider (reference
core/ledger/kvledger/bookkeeping/provider.go).

Ledger-internal components (pvt-data expiry schedules, metadata hints,
snapshot bookkeeping) need durable key-value namespaces that are NOT
part of channel state.  The reference hands each category a leveldb
handle namespaced by ledger id + category; here each category is a
`NamespacedKV` view over the ledger's shared KVStore under the
"bookkeeping/<ledger>/<category>" prefix.
"""

from __future__ import annotations

from fabric_tpu.ledger.kvstore import KVStore, NamedDB

# reference bookkeeping.Category values
PVT_DATA_EXPIRY = "pvtdata-expiry"
METADATA_PRESENCE = "metadata-presence"
SNAPSHOT_REQUEST = "snapshot-request"


class BookkeepingProvider:
    """Per-ledger, per-category durable namespaces."""

    def __init__(self, store: KVStore):
        self._store = store

    def get_kv(self, ledger_id: str, category: str) -> NamedDB:
        return NamedDB(self._store, f"bookkeeping/{ledger_id}/{category}")


__all__ = [
    "BookkeepingProvider",
    "PVT_DATA_EXPIRY",
    "METADATA_PRESENCE",
    "SNAPSHOT_REQUEST",
]
