"""Clean twin of fix_rpc_shape_dirty: the client ``stream``s the
generator-backed method, so the verb matches the handler shape and
rpc-conformance stays quiet."""


class FixServer:
    def __init__(self, rpc):
        self.rpc = rpc
        self.rpc.register("fix.Feed", self._feed)

    def _feed(self, body, stream):
        for chunk in (b"a", b"b"):
            yield chunk


def drain(conn):
    return list(conn.stream("fix.Feed", b""))
