"""protoutil construction/extraction round trips (reference protoutil tests'
coverage model: tx id binding, header hashing determinism, signed-tx
assembly invariants)."""

import hashlib

import pytest

from fabric_tpu.csp import SWCSP
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.peer import chaincode_pb2, proposal_pb2
from fabric_tpu import protoutil


class LocalSigner:
    """Minimal signing identity for tests (MSP provides the real one)."""

    def __init__(self, mspid="Org1MSP"):
        from fabric_tpu.protos.msp import identities_pb2

        self.csp = SWCSP()
        self.key = self.csp.key_gen()
        self.sid = identities_pb2.SerializedIdentity(
            mspid=mspid, id_bytes=self.key.public_key().pem()
        ).SerializeToString()

    def serialize(self):
        return self.sid

    def sign(self, msg: bytes) -> bytes:
        return self.csp.sign(self.key, self.csp.hash(msg))


def test_tx_id_binding():
    nonce, creator = b"n" * 24, b"creator"
    txid = protoutil.compute_tx_id(nonce, creator)
    assert txid == hashlib.sha256(nonce + creator).hexdigest()
    assert protoutil.check_tx_id(txid, nonce, creator)
    assert not protoutil.check_tx_id(txid, b"x" * 24, creator)


def test_block_header_hash_asn1():
    hdr = common_pb2.BlockHeader(number=7, previous_hash=b"\xaa" * 32, data_hash=b"\xbb" * 32)
    raw = protoutil.block_header_bytes(hdr)
    # SEQUENCE(INTEGER 7, OCTET STRING (32), OCTET STRING (32))
    assert raw[0] == 0x30
    assert raw[2:5] == b"\x02\x01\x07"
    assert protoutil.block_header_hash(hdr) == hashlib.sha256(raw).digest()
    # large number needs the high-bit padding byte
    hdr2 = common_pb2.BlockHeader(number=0x80, previous_hash=b"", data_hash=b"")
    assert b"\x02\x02\x00\x80" in protoutil.block_header_bytes(hdr2)


def test_create_next_block_chain():
    genesis = protoutil.new_block(0, b"")
    genesis.header.data_hash = protoutil.block_data_hash(genesis.data)
    env = common_pb2.Envelope(payload=b"tx0")
    blk = protoutil.create_next_block(genesis.header, [env])
    assert blk.header.number == 1
    assert blk.header.previous_hash == protoutil.block_header_hash(genesis.header)
    assert protoutil.extract_envelope(blk, 0).payload == b"tx0"
    flags = protoutil.tx_filter(blk)
    assert len(flags) == 1
    flags[0] = 11
    protoutil.set_tx_filter(blk, flags)
    assert protoutil.tx_filter(blk)[0] == 11


def test_proposal_tx_roundtrip():
    signer = LocalSigner()
    prop, txid = protoutil.create_chaincode_proposal(
        signer.serialize(), "testchannel", "mycc", [b"invoke", b"a", b"b"],
        transient={"secret": b"s3cret"},
    )
    unpacked = protoutil.unpack_proposal(
        proposal_pb2.SignedProposal(proposal_bytes=prop.SerializeToString())
    )
    assert unpacked.chaincode_name == "mycc"
    assert list(unpacked.input.args) == [b"invoke", b"a", b"b"]
    assert protoutil.check_tx_id(
        txid, unpacked.signature_header.nonce, unpacked.signature_header.creator
    )

    resp = protoutil.create_proposal_response(
        prop,
        results=b"rwset-bytes",
        events=b"",
        response=proposal_pb2.Response(status=200),
        chaincode_id=chaincode_pb2.ChaincodeID(name="mycc", version="1.0"),
        endorser_signer=signer,
    )
    env = protoutil.create_signed_tx(prop, signer, [resp])
    tx = protoutil.unpack_transaction(env)
    assert tx.channel_header.tx_id == txid
    cap, action = protoutil.get_action_from_envelope(env)
    assert action.results == b"rwset-bytes"
    # transient data must have been stripped from the committed payload
    ccpp = proposal_pb2.ChaincodeProposalPayload.FromString(
        cap.chaincode_proposal_payload
    )
    assert not ccpp.TransientMap
    # proposal hash binds: recompute from tx parts equals endorsed hash
    from fabric_tpu.protos.peer import proposal_response_pb2

    prp = proposal_response_pb2.ProposalResponsePayload.FromString(
        cap.action.proposal_response_payload
    )
    recomputed = protoutil.proposal_hash(
        tx.payload.header.channel_header,
        tx.payload.header.signature_header,
        cap.chaincode_proposal_payload,
    )
    assert recomputed == prp.proposal_hash


def test_create_signed_tx_rejects_mismatches():
    signer = LocalSigner()
    other = LocalSigner()
    prop, _ = protoutil.create_chaincode_proposal(
        signer.serialize(), "ch", "cc", [b"x"]
    )
    resp = protoutil.create_proposal_response(
        prop, b"r", b"", proposal_pb2.Response(status=200),
        chaincode_pb2.ChaincodeID(name="cc"), signer,
    )
    with pytest.raises(ValueError, match="creator"):
        protoutil.create_signed_tx(prop, other, [resp])
    bad = proposal_pb2.Response(status=500)
    resp2 = protoutil.create_proposal_response(
        prop, b"r", b"", bad, chaincode_pb2.ChaincodeID(name="cc"), signer
    )
    resp2.response.status = 500
    with pytest.raises(ValueError, match="not successful"):
        protoutil.create_signed_tx(prop, signer, [resp2])
    resp3 = protoutil.create_proposal_response(
        prop, b"other-rwset", b"", proposal_pb2.Response(status=200),
        chaincode_pb2.ChaincodeID(name="cc"), signer,
    )
    with pytest.raises(ValueError, match="do not match"):
        protoutil.create_signed_tx(prop, signer, [resp, resp3])
