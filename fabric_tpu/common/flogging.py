"""Structured logging with runtime per-logger level specs.

Reference: common/flogging — zap-based global registry (logging.go:60-200,
global.go), per-logger level specs parsed from strings like
"gossip=debug:warning" (loggerlevels.go), the /logspec HTTP admin
(httpadmin/) served by the operations endpoint, and a metrics observer
counting emitted entries (metrics/observer.go).

Built on the stdlib logging module: one shared handler, a level registry
that applies spec rules by longest-prefix logger-name match, and an
optional metrics hook.
"""

from __future__ import annotations

import logging
import sys
import threading

_NAME = "fabric_tpu"

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "warn": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
    "panic": logging.CRITICAL,
    "fatal": logging.CRITICAL,
}
_LEVEL_NAMES = {
    logging.DEBUG: "debug",
    logging.INFO: "info",
    logging.WARNING: "warning",
    logging.ERROR: "error",
    logging.CRITICAL: "critical",
}


class LogSpecError(ValueError):
    pass


def parse_spec(spec: str) -> tuple[int, dict[str, int]]:
    """Parse "logger1,logger2=level:logger3=level:defaultlevel" into
    (default_level, {prefix: level}) (reference loggerlevels.go
    ActivateSpec)."""
    default = logging.INFO
    overrides: dict[str, int] = {}
    for field in (spec or "").split(":"):
        field = field.strip()
        if not field:
            continue
        if "=" in field:
            names, _, lvl = field.partition("=")
            level = _LEVELS.get(lvl.strip().lower())
            if level is None:
                raise LogSpecError(f"invalid log level {lvl!r}")
            for name in names.split(","):
                name = name.strip().rstrip(".")
                if name:
                    overrides[name] = level
        else:
            level = _LEVELS.get(field.lower())
            if level is None:
                raise LogSpecError(f"invalid log level {field!r}")
            default = level
    return default, overrides


class LoggerLevels:
    """Longest-prefix level resolution (reference loggerlevels.go)."""

    def __init__(self):
        self._default = logging.INFO
        self._overrides: dict[str, int] = {}
        self._lock = threading.Lock()
        self._spec = "info"

    def activate_spec(self, spec: str) -> None:
        default, overrides = parse_spec(spec)
        with self._lock:
            self._default = default
            self._overrides = overrides
            self._spec = spec or "info"

    def spec(self) -> str:
        with self._lock:
            return self._spec

    def level_for(self, name: str) -> int:
        with self._lock:
            best, best_len = self._default, -1
            for prefix, lvl in self._overrides.items():
                if (
                    name == prefix or name.startswith(prefix + ".")
                ) and len(prefix) > best_len:
                    best, best_len = lvl, len(prefix)
            return best


class _LevelFilter(logging.Filter):
    def __init__(self, registry: "Registry"):
        super().__init__()
        self._registry = registry

    def filter(self, record: logging.LogRecord) -> bool:
        name = record.name
        if name.startswith(_NAME + "."):
            name = name[len(_NAME) + 1 :]
        ok = record.levelno >= self._registry.levels.level_for(name)
        if ok and self._registry.observer is not None:
            self._registry.observer(record)
        return ok


class _TraceFormatter(logging.Formatter):
    """The standard line format, plus ``trace=<id> span=<id>`` when
    tracelens is armed and the emitting thread has an active span — so
    ``/logspec``-tuned debug logs join against ``/traces`` dumps by id.
    Disarmed, the emitted bytes are identical to before."""

    def format(self, record: logging.LogRecord) -> str:
        line = super().format(record)
        from fabric_tpu.common import tracing

        ctx = tracing.current()
        if ctx is not None:
            # ids go on the HEADER line, not after an exc_info
            # traceback — line-oriented joins grep the message line
            suffix = f" trace={ctx.trace_id:x} span={ctx.span_id:x}"
            head, nl, rest = line.partition("\n")
            line = head + suffix + nl + rest
        return line


class Registry:
    """Global logging state (reference global.go / logging.go Logging)."""

    def __init__(self):
        self.levels = LoggerLevels()
        self.observer = None  # callable(record), e.g. metrics counter
        self._root = logging.getLogger(_NAME)
        self._root.setLevel(logging.DEBUG)  # filtering happens in _LevelFilter
        self._root.propagate = False
        self._handler = logging.StreamHandler(sys.stderr)
        self._handler.setFormatter(
            _TraceFormatter(
                "%(asctime)s %(levelname).4s [%(name)s] %(message)s",
                "%Y-%m-%d %H:%M:%S",
            )
        )
        self._handler.addFilter(_LevelFilter(self))
        self._root.addHandler(self._handler)

    def logger(self, name: str) -> logging.Logger:
        return logging.getLogger(f"{_NAME}.{name}")

    def activate_spec(self, spec: str) -> None:
        self.levels.activate_spec(spec)

    def spec(self) -> str:
        return self.levels.spec()

    def set_writer(self, stream) -> None:
        self._handler.setStream(stream)

    def set_observer_counter(self, counter) -> None:
        """Count emitted entries per level (reference metrics/observer.go
        CheckedEntry counter with a level label)."""

        def observe(record: logging.LogRecord) -> None:
            counter.with_labels(
                "level", _LEVEL_NAMES.get(record.levelno, "info")
            ).add(1)

        self.observer = observe


_registry = Registry()


def must_get_logger(name: str) -> logging.Logger:
    """The module-level entry point (reference flogging.MustGetLogger)."""
    return _registry.logger(name)


def activate_spec(spec: str) -> None:
    _registry.activate_spec(spec)


def spec() -> str:
    return _registry.spec()


def global_registry() -> Registry:
    return _registry


__all__ = [
    "must_get_logger",
    "activate_spec",
    "spec",
    "parse_spec",
    "LoggerLevels",
    "LogSpecError",
    "Registry",
    "global_registry",
]
