"""Mutual TLS on the RPC substrate: handshake, client-auth enforcement,
wrong-CA rejection, pinned-cert allowlists, peer-cert exposure."""

from __future__ import annotations

import pytest

from fabric_tpu.comm.rpc import RPCClient, RPCError, RPCServer
from fabric_tpu.comm.tls import TLSCredentials, credentials_from_ca
from fabric_tpu.common.crypto import CA


@pytest.fixture(scope="module")
def cas():
    return CA("tlsca.org1.example.com", "org1"), CA(
        "tlsca.org2.example.com", "org2"
    )


def _server(creds):
    srv = RPCServer(tls=creds)
    srv.register("echo", lambda body, stream: b"ok:" + body)
    srv.start()
    return srv


def test_mutual_tls_roundtrip(cas):
    ca, _ = cas
    srv = _server(credentials_from_ca(ca, "server.org1"))
    try:
        cli = RPCClient(*srv.addr, tls=credentials_from_ca(ca, "client.org1"))
        assert cli.call("echo", b"hi") == b"ok:hi"
    finally:
        srv.stop()


def test_client_without_cert_rejected(cas):
    ca, _ = cas
    srv = _server(credentials_from_ca(ca, "server.org1"))
    try:
        # TLS context with trust but *no* client certificate
        import socket
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(cadata=ca.cert_pem.decode())
        sock = socket.create_connection(srv.addr, timeout=5)
        with pytest.raises((ssl.SSLError, ConnectionError, OSError)):
            tls_sock = ctx.wrap_socket(sock)
            # server requires a client cert: handshake or first read fails
            tls_sock.sendall(b"x" * 8)
            tls_sock.recv(1)
            tls_sock.recv(1)
            raise ConnectionError("server accepted an unauthenticated client")
    finally:
        srv.stop()


def test_wrong_ca_client_rejected(cas):
    ca1, ca2 = cas
    srv = _server(credentials_from_ca(ca1, "server.org1"))
    try:
        # client cert from a CA the server does not trust
        pair = ca2.issue("evil.org2", client=True, server=True)
        wrong = TLSCredentials(
            cert_pem=pair.cert_pem, key_pem=pair.key_pem,
            ca_pems=[ca1.cert_pem],
        )
        cli = RPCClient(*srv.addr, tls=wrong, timeout=5)
        with pytest.raises((RPCError, ConnectionError, OSError)):
            cli.call("echo", b"hi")
    finally:
        srv.stop()


def test_plaintext_client_to_tls_server_fails(cas):
    ca, _ = cas
    srv = _server(credentials_from_ca(ca, "server.org1"))
    try:
        cli = RPCClient(*srv.addr, timeout=5)
        with pytest.raises((RPCError, ConnectionError, OSError)):
            cli.call("echo", b"hi")
    finally:
        srv.stop()


def test_pinned_cert_allowlist(cas):
    ca, _ = cas
    good = credentials_from_ca(ca, "client.good")
    other = credentials_from_ca(ca, "client.other")
    server_creds = credentials_from_ca(ca, "server.org1")
    server_creds.pinned_certs = [good.cert_der]  # only `good` may connect
    srv = _server(server_creds)
    try:
        cli = RPCClient(*srv.addr, tls=good)
        assert cli.call("echo", b"hi") == b"ok:hi"
        bad = RPCClient(*srv.addr, tls=other, timeout=5)
        with pytest.raises((RPCError, ConnectionError, OSError)):
            bad.call("echo", b"hi")
    finally:
        srv.stop()


def test_peer_cert_exposed_to_handler(cas):
    ca, _ = cas
    seen: list = []
    srv = RPCServer(tls=credentials_from_ca(ca, "server.org1"))

    def capture(body, stream):
        seen.append(stream.peer_cert)
        return b"ok"

    srv.register("cap", capture)
    srv.start()
    try:
        client_creds = credentials_from_ca(ca, "client.org1")
        RPCClient(*srv.addr, tls=client_creds).call("cap")
        assert seen and seen[0] == client_creds.cert_der
    finally:
        srv.stop()


def test_streaming_over_tls(cas):
    ca, _ = cas
    srv = RPCServer(tls=credentials_from_ca(ca, "server.org1"))
    srv.register("count", lambda body, stream: (b"%d" % i for i in range(5)))
    srv.start()
    try:
        cli = RPCClient(*srv.addr, tls=credentials_from_ca(ca, "client.org1"))
        assert list(cli.stream("count")) == [b"0", b"1", b"2", b"3", b"4"]
    finally:
        srv.stop()


def test_server_name_verified_by_default(cas):
    """A cert from the right CA but without the dialed address in its
    SANs must NOT pass as a server endpoint (advisor round-2 medium:
    otherwise any org-issued client cert can impersonate any peer or
    orderer).  Mirrors gRPC transport-credential SAN verification."""
    ca, _ = cas
    rogue_pair = ca.issue(
        "user1@org1", sans=["user1.example.com"], client=True, server=True
    )
    rogue = TLSCredentials(
        cert_pem=rogue_pair.cert_pem,
        key_pem=rogue_pair.key_pem,
        ca_pems=[ca.cert_pem],
    )
    srv = _server(rogue)  # "server" presenting a user cert
    try:
        cli = RPCClient(*srv.addr, tls=credentials_from_ca(ca, "client.org1"))
        with pytest.raises(RPCError, match="tls"):
            cli.call("echo", b"hi")
    finally:
        srv.stop()


def test_server_name_verification_opt_out(cas):
    ca, _ = cas
    pair = ca.issue(
        "node.org1", sans=["node.example.com"], client=True, server=True
    )
    srv_creds = TLSCredentials(
        cert_pem=pair.cert_pem, key_pem=pair.key_pem, ca_pems=[ca.cert_pem]
    )
    srv = _server(srv_creds)
    try:
        cli_creds = credentials_from_ca(ca, "client.org1")
        cli_creds.verify_server_name = False  # pin-protected transports
        cli = RPCClient(*srv.addr, tls=cli_creds)
        assert cli.call("echo", b"hi") == b"ok:hi"
    finally:
        srv.stop()


# -- keepalive / connection lifecycle --------------------------------------


def test_hung_peer_reaped_by_idle_timeout():
    """A client that connects and never sends a request is reaped after
    the idle window (reference keepalive semantics: silent connections
    must not hold server resources forever)."""
    import socket
    import time

    from fabric_tpu.comm.rpc import KeepaliveOptions, RPCServer

    srv = RPCServer(
        keepalive=KeepaliveOptions(idle_timeout=0.3, ping_interval=0.2)
    )
    srv.register("echo", lambda body, stream: b"ok")
    srv.start()
    try:
        sock = socket.create_connection(srv.addr, timeout=5)
        deadline = time.time() + 5
        while srv.connection_count == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert srv.connection_count >= 1
        # the server closes it without us ever sending a byte
        sock.settimeout(5)
        assert sock.recv(1) == b""
        deadline = time.time() + 5
        while srv.connection_count and time.time() < deadline:
            time.sleep(0.02)
        assert srv.connection_count == 0
        sock.close()
    finally:
        srv.stop()


def test_live_idle_stream_survives_keepalive():
    """A streaming handler with gaps longer than the ping interval is
    NOT torn down: PING frames keep the read deadline fresh and the
    client still sees every item."""
    import time

    from fabric_tpu.comm.rpc import KeepaliveOptions, RPCClient, RPCServer

    ka = KeepaliveOptions(
        idle_timeout=5.0, ping_interval=0.15, ping_timeout=0.2
    )

    def slow(body, stream):
        yield b"a"
        time.sleep(0.6)  # several ping intervals of silence
        yield b"b"

    srv = RPCServer(keepalive=ka)
    srv.register("slow", slow)
    srv.start()
    try:
        cli = RPCClient(*srv.addr, timeout=5, keepalive=ka)
        assert list(cli.stream("slow")) == [b"a", b"b"]
    finally:
        srv.stop()


def test_dead_server_detected_on_stream():
    """Silence past ping_interval + ping_timeout on a stream raises
    instead of hanging forever (dead-peer detection)."""
    import threading
    import time

    from fabric_tpu.comm.rpc import (
        KeepaliveOptions,
        RPCClient,
        RPCError,
        RPCServer,
    )

    # a server whose keepalive never fires (huge interval) simulates a
    # peer that froze mid-stream
    srv = RPCServer(keepalive=KeepaliveOptions(ping_interval=60.0))
    hang = threading.Event()

    def frozen(body, stream):
        yield b"first"
        hang.wait(10)  # never yields again, never ends

    srv.register("frozen", frozen)
    srv.start()
    try:
        ka = KeepaliveOptions(ping_interval=0.2, ping_timeout=0.2)
        cli = RPCClient(*srv.addr, timeout=5, keepalive=ka)
        it = cli.stream("frozen")
        assert next(it) == b"first"
        t0 = time.time()
        try:
            next(it)
            raise AssertionError("frozen stream must raise")
        except RPCError:
            pass
        assert time.time() - t0 < 5
    finally:
        hang.set()
        srv.stop()
