"""fabric-custody: run a key-custody daemon (csp/custody.py).

The process-isolation analogue of the reference's PKCS#11 HSM seam
(bccsp/pkcs11): peers configured with `bccsp.default: CUSTODY` route
key generation and signing here; private keys live ONLY under this
process's keystore directory.

    fabric-custody --keystore /var/fabric/keys --token-file /etc/ct \
                   --listen 127.0.0.1:7599 [--tls-cert c --tls-key k \
                   --tls-ca ca]

The token file is the PIN analogue: provision the same file to the
daemon and to the peers' core.yaml `bccsp.custody.tokenFile`.
"""

from __future__ import annotations

import argparse
import signal
import threading

from fabric_tpu.cmd.common import parse_endpoint
from fabric_tpu.csp.custody import KeyCustodyServer, load_token


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fabric-custody", description=__doc__)
    ap.add_argument("--keystore", required=True,
                    help="directory holding the PEM keystore (0700)")
    ap.add_argument("--token-file", required=True,
                    help="shared-token file (the PIN analogue)")
    ap.add_argument("--listen", default="127.0.0.1:7599")
    ap.add_argument("--tls-cert")
    ap.add_argument("--tls-key")
    ap.add_argument("--tls-ca")
    args = ap.parse_args(argv)

    tls = None
    if args.tls_cert or args.tls_key or args.tls_ca:
        if not (args.tls_cert and args.tls_key):
            ap.error(
                "--tls-cert and --tls-key must be given together "
                "(a partial TLS config would silently serve plaintext)"
            )
        from fabric_tpu.comm.tls import credentials_from_files

        tls = credentials_from_files(
            args.tls_cert, args.tls_key,
            [args.tls_ca] if args.tls_ca else [],
            require_client_auth=bool(args.tls_ca),
        )
    host, port = parse_endpoint(args.listen)
    srv = KeyCustodyServer(
        args.keystore, load_token(args.token_file),
        host=host, port=port, tls=tls,
    )
    srv.start()
    print(f"custody daemon on {srv.addr[0]}:{srv.addr[1]}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
