"""idemixgen: generate idemix issuer keys and signer credentials
(reference cmd/idemixgen + msp idemix config generation).

    idemixgen ca-keygen --output idemix-config
    idemixgen signerconfig --output idemix-config \
        --org-unit org1 --enrollment-id user1 [--admin]
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys

from fabric_tpu.msp.idemixmsp import generate_issuer, issue_signer_config


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="idemixgen")
    sub = ap.add_subparsers(dest="cmd", required=True)
    ca = sub.add_parser("ca-keygen")
    ca.add_argument("--output", default="idemix-config")
    sc = sub.add_parser("signerconfig")
    sc.add_argument("--output", default="idemix-config")
    sc.add_argument("--org-unit", default="")
    sc.add_argument("--enrollment-id", default="user")
    sc.add_argument("--admin", action="store_true")
    args = ap.parse_args(argv)

    ca_dir = os.path.join(args.output, "ca")
    if args.cmd == "ca-keygen":
        os.makedirs(ca_dir, exist_ok=True)
        issuer = generate_issuer()
        with open(os.path.join(ca_dir, "IssuerKey.pkl"), "wb") as f:
            pickle.dump(issuer, f)
        print(f"issuer key material written to {ca_dir}")
        return 0

    from fabric_tpu.msp.idemixmsp import ROLE_ADMIN, ROLE_MEMBER

    with open(os.path.join(ca_dir, "IssuerKey.pkl"), "rb") as f:
        issuer = pickle.load(f)
    conf = issue_signer_config(
        issuer,
        mspid="IdemixMSP",
        ou=args.org_unit,
        role=ROLE_ADMIN if args.admin else ROLE_MEMBER,
        enrollment_id=args.enrollment_id,
    )
    user_dir = os.path.join(args.output, "user")
    os.makedirs(user_dir, exist_ok=True)
    with open(os.path.join(user_dir, "SignerConfig.pb"), "wb") as f:
        f.write(conf.SerializeToString())
    print(f"signer config written to {user_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
