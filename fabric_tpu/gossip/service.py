"""Gossip service glue: one node's gossip stack, joined per channel.

Capability parity with the reference's gossip/service
(gossip_service.go:162 New, :205 InitializeChannel): binds comm +
discovery once per node, then per channel wires ChannelGossip + leader
election + state provider, and (when elected) runs the deliver client
that pulls blocks from the orderer into the channel.
"""

from __future__ import annotations

import threading

from fabric_tpu.devtools.lockwatch import spawn_thread
from fabric_tpu.gossip.certstore import CertStore
from fabric_tpu.gossip.core import ChannelGossip
from fabric_tpu.gossip.discovery import DiscoveryCore
from fabric_tpu.gossip.election import LeaderElection
from fabric_tpu.gossip.identity import IdentityMapper
from fabric_tpu.gossip.state import StateProvider


class ChannelHandle:
    def __init__(self, gossip, election, state):
        self.gossip = gossip
        self.election = election
        self.state = state

    def tick(self) -> None:
        self.gossip.tick()
        self.election.tick()
        self.state.tick()


class GossipService:
    def __init__(
        self,
        comm,
        bootstrap: list[str],
        alive_expiration_ticks: int = 5,
        identity_ttl_s: float = 3600.0,
    ):
        self._comm = comm
        self.discovery = DiscoveryCore(
            comm, bootstrap, expiration_ticks=alive_expiration_ticks
        )
        # identity dissemination: expiration-aware mapper + pull-based
        # certstore (reference gossip/identity + gossip/gossip/certstore)
        self.identities = IdentityMapper(
            comm.mcs, comm.identity,
            default_ttl_s=identity_ttl_s,
            on_purge=comm.forget_identity,
        )
        self.certstore = CertStore(
            comm, self.identities,
            lambda: [p.endpoint for p in self.discovery.alive_peers()],
        )
        self.certstore.endpoint_lookup = self.discovery.endpoint_of
        self._channels: dict[str, ChannelHandle] = {}
        self._lock = threading.Lock()
        self._deliver_starters: dict[str, tuple] = {}
        self._metrics = None

    def set_metrics(self, metrics) -> None:
        """Bind a common.metrics.GossipMetrics bundle across the whole
        gossip stack: comm message flow, every channel's state-transfer
        counters, and the membership gauge this service keeps current
        per tick."""
        self._metrics = metrics
        self._comm.set_metrics(metrics)
        with self._lock:
            handles = list(self._channels.values())
        for h in handles:
            h.state.set_metrics(metrics)

    @property
    def endpoint(self) -> str:
        return self._comm.endpoint

    def join_channel(
        self,
        channel_id: str,
        committer,
        deliver_client=None,  # object with .start()/.stop(), run by the leader
        fanout: int = 3,
        store_capacity: int = 200,
    ) -> ChannelHandle:
        membership = lambda: [p.endpoint for p in self.discovery.alive_peers()]
        gossip = ChannelGossip(
            channel_id, self._comm, membership, fanout=fanout,
            store_capacity=store_capacity,
        )
        gossip.endpoint_lookup = self.discovery.endpoint_of
        state = StateProvider(channel_id, gossip, committer, self._comm)
        if self._metrics is not None:
            state.set_metrics(self._metrics)

        def on_leadership(is_leader: bool) -> None:
            if deliver_client is None:
                return
            if is_leader:
                deliver_client.start()
            else:
                deliver_client.stop()

        election = LeaderElection(
            channel_id, self._comm, membership, on_leadership_change=on_leadership
        )
        handle = ChannelHandle(gossip, election, state)
        with self._lock:
            self._channels[channel_id] = handle
        return handle

    def channel(self, channel_id: str) -> ChannelHandle | None:
        with self._lock:
            return self._channels.get(channel_id)

    def tick(self) -> None:
        """One logical round for the whole node: discovery, identity
        pull + expiration sweep, then all channels."""
        self.discovery.tick()
        self.certstore.tick()
        self.identities.sweep()
        m = self._metrics
        if m is not None:
            m.membership.set(len(self.discovery.alive_peers()))
        with self._lock:
            handles = list(self._channels.values())
        for h in handles:
            h.tick()


class GossipRunner:
    """Thread driver for production: ticks a GossipService on an interval."""

    def __init__(self, service: GossipService, tick_interval_s: float = 1.0):
        self._svc = service
        self._interval = tick_interval_s
        self._stop = threading.Event()
        self._thread = spawn_thread(
            target=self._run, name="gossip-ticker", kind="service"
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=3)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._svc.tick()


__all__ = ["GossipService", "GossipRunner", "ChannelHandle"]
