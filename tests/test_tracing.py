"""Tracelens acceptance: the zero-overhead disarmed contract, span
nesting across an RPC hop and a pooled (run_chunked) fan-out, byte-
deterministic traces under a virtual clock, the /traces endpoint,
flight-recorder dumps on injected crashes, faultfuzz trace artifacts
with same-seed determinism, and traced-vs-untraced commit parity under
the invariants oracle."""

from __future__ import annotations

import json
import logging
import urllib.request

import pytest

from fabric_tpu.common import flogging, tracing, workpool
from fabric_tpu.common.operations import System
from fabric_tpu.comm.rpc import RPCClient, RPCServer
from fabric_tpu.devtools import clockskew, faultfuzz, faultline, invariants

CHANNEL = faultfuzz.CHANNEL


# -- disarmed: the zero-overhead contract ------------------------------------


def test_disarmed_span_entry_points_are_noops(tmp_path):
    """FABRIC_TPU_TRACE unset (tier-1 default): no recorder exists,
    every entry point returns the shared no-op singleton, and a real
    RPC round trip plus a pooled fan-out never touch the armed path."""
    assert not tracing.enabled()
    assert tracing.recorder() is None
    before = tracing.lookup_count()

    s = tracing.span("x", anything=1)
    assert s is tracing._NOOP
    assert tracing.begin("y") is tracing._NOOP
    assert s.ctx is None
    s.annotate(a=1)
    s.end()
    assert tracing.current() is None
    assert tracing.wire_token() is None
    assert tracing.attached(None) is tracing._NOOP
    tracing.instant("nope")
    tracing.annotate(z=1)

    # a live RPC round trip and a pooled fan-out, fully disarmed
    srv = RPCServer()
    srv.register("echo", lambda body, stream: body)
    srv.start()
    try:
        assert RPCClient(*srv.addr, timeout=5.0).call(
            "echo", b"hi"
        ) == b"hi"
    finally:
        srv.stop()
    with workpool.scoped_pool(2) as pool:
        out = workpool.run_chunked(
            pool, lambda off, chunk: [v * 2 for v in chunk],
            list(range(10)), 2,
        )
    assert out == [v * 2 for v in range(10)]

    # nothing above consulted the armed path, and no ring buffer exists
    assert tracing.lookup_count() == before
    assert tracing.recorder() is None


def test_env_knob_arms_and_sizes_the_recorder(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_TRACE", "0")
    tracing._init_from_env()
    assert not tracing.enabled()
    monkeypatch.setenv("FABRIC_TPU_TRACE", "1")
    tracing._init_from_env()
    try:
        assert tracing.enabled()
        assert tracing.recorder().capacity == tracing.DEFAULT_CAPACITY
    finally:
        tracing.disarm()
    monkeypatch.setenv("FABRIC_TPU_TRACE", "256")
    tracing._init_from_env()
    try:
        assert tracing.recorder().capacity == 256
    finally:
        tracing.disarm()
    assert not tracing.enabled()


# -- nesting: RPC hop + pooled fan-out ---------------------------------------


def _by_name(doc, name):
    return [e for e in doc["traceEvents"] if e["name"] == name]


def test_span_nesting_across_rpc_round_trip():
    """The server's rpc.serve span must nest under the client's
    rpc.call span (same trace, parent=call span id), which itself
    nests under the caller's span — context crossed the wire inside
    the frame."""
    with tracing.scope() as rec:
        srv = RPCServer()
        srv.register("echo", lambda body, stream: body)
        srv.start()
        try:
            with tracing.span("client.work") as outer:
                cli = RPCClient(*srv.addr, timeout=5.0)
                assert cli.call("echo", b"ping") == b"ping"
        finally:
            srv.stop()
        doc = tracing.export(rec)

    (serve,) = _by_name(doc, "rpc.serve")
    (call,) = _by_name(doc, "rpc.call")
    (work,) = _by_name(doc, "client.work")
    assert serve["args"]["method"] == "echo"
    assert serve["args"]["trace"] == call["args"]["trace"]
    assert serve["args"]["parent"] == call["args"]["span"]
    assert call["args"]["parent"] == work["args"]["span"]
    assert call["args"]["trace"] == work["args"]["trace"]
    # the hop really crossed threads
    assert serve["tid"] != call["tid"]


@pytest.mark.parametrize("width", [1, 2, 8])
def test_pooled_fanout_nests_under_caller(width):
    """run_chunked flows the caller's span into every chunk: results
    stay identical to serial at every width, and (at width > 1) each
    chunk span parents under the calling span on a pool thread."""
    items = list(range(40))
    serial = [v * 3 for v in items]
    with tracing.scope() as rec:
        with workpool.scoped_pool(4) as pool:
            with tracing.span("fanout.caller") as caller:
                got = workpool.run_chunked(
                    pool, lambda off, chunk: [v * 3 for v in chunk],
                    items, width,
                )
        doc = tracing.export(rec)
    assert got == serial
    chunks = _by_name(doc, "workpool.chunk")
    (call_ev,) = _by_name(doc, "fanout.caller")
    if width <= 1:
        assert chunks == []  # serial short-circuit: no fan-out spans
        return
    assert len(chunks) == width
    assert sorted(c["args"]["offset"] for c in chunks) == [
        i * (len(items) // width) for i in range(width)
    ]
    for c in chunks:
        assert c["args"]["trace"] == call_ev["args"]["trace"]
        assert c["args"]["parent"] == call_ev["args"]["span"]


def test_exception_mid_span_repairs_the_stack():
    """A BaseException (FaultCrash) escaping an explicit begin() must
    not corrupt later parenting: ending an outer span closes abandoned
    children and pops them."""
    with tracing.scope() as rec:
        outer = tracing.begin("outer")
        inner = tracing.begin("inner")
        assert tracing.current() == inner.ctx
        # simulate a crash path that never reached inner.end()
        outer.end()
        with tracing.span("after") as after:
            assert after.parent_id is None  # outer is gone from stack
        doc = tracing.export(rec)
    (inner_ev,) = _by_name(doc, "inner")
    assert inner_ev["args"].get("abandoned") is True


# -- determinism under VirtualClock ------------------------------------------


def _clocked_workload():
    with tracing.span("root", cat="pipeline", block=0):
        with tracing.span("stage.a", cat="stage", block=0):
            clockskew.sleep(0.010)
        with tracing.span("stage.b", cat="stage", block=0):
            clockskew.sleep(0.020)
        tracing.instant("mark", k=1)


def test_virtual_clock_traces_are_byte_identical():
    runs = []
    for _ in range(2):
        with clockskew.use_virtual(clockskew.VirtualClock(start=500.0)):
            with tracing.scope() as rec:
                _clocked_workload()
                runs.append(tracing.export(rec))
    assert runs[0]["traceEvents"] == runs[1]["traceEvents"]
    # ...including timestamps: the virtual clock IS the time base
    (a,) = _by_name(runs[0], "stage.a")
    assert a["dur"] == 10_000  # exactly the virtual 10ms, in µs


def test_critical_path_over_stage_spans():
    with clockskew.use_virtual(clockskew.VirtualClock(start=500.0)):
        with tracing.scope() as rec:
            _clocked_workload()
            doc = tracing.export(rec)
    cp = tracing.critical_path_ms(doc["traceEvents"])
    # sequential stages: each contributes its full duration; the
    # "root" span is cat=pipeline and must not appear
    assert cp == {"stage.a": pytest.approx(10.0), "stage.b": pytest.approx(20.0)}


# -- /traces endpoint --------------------------------------------------------


def _get(addr, path):
    host, port = addr
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=5
    ) as r:
        return r.status, r.read()


def test_traces_endpoint_serves_flight_recorder():
    sys_ = System(("127.0.0.1", 0))
    sys_.start()
    try:
        # disarmed: valid, empty, explicitly not armed
        status, body = _get(sys_.addr, "/traces")
        assert status == 200
        doc = json.loads(body)
        assert doc["traceEvents"] == []
        assert doc["otherData"]["armed"] is False

        # armed: drive one RPC hop and one pooled fan-out, then assert
        # the NESTED spans straight off the endpoint's JSON
        with tracing.scope():
            srv = RPCServer()
            srv.register("echo", lambda body_, stream: body_)
            srv.start()
            try:
                with tracing.span("ops.probe", block=7, cat="stage"):
                    RPCClient(*srv.addr, timeout=5.0).call("echo", b"x")
                    with workpool.scoped_pool(2) as pool:
                        workpool.run_chunked(
                            pool, lambda off, chunk: list(chunk),
                            list(range(8)), 2,
                        )
            finally:
                srv.stop()
            status, body = _get(sys_.addr, "/traces")
        assert status == 200
        doc = json.loads(body)
        assert doc["otherData"]["armed"] is True
        (probe,) = _by_name(doc, "ops.probe")
        assert probe["ph"] == "X"
        assert probe["args"]["block"] == 7
        # RPC hop: serve nests under call nests under ops.probe
        (serve,) = _by_name(doc, "rpc.serve")
        (call,) = _by_name(doc, "rpc.call")
        assert serve["args"]["parent"] == call["args"]["span"]
        assert call["args"]["parent"] == probe["args"]["span"]
        # pooled fan-out: every chunk nests under ops.probe
        chunks = _by_name(doc, "workpool.chunk")
        assert len(chunks) == 2
        assert all(
            c["args"]["parent"] == probe["args"]["span"] for c in chunks
        )
    finally:
        sys_.stop()


# -- flight recorder + faultline ---------------------------------------------


def test_injected_crash_annotates_span_and_dumps(tmp_path):
    """An injected FaultCrash mid-commit lands an instant 'fault' mark,
    annotates the stage span it interrupted, and the recorder dumps to
    a loadable Chrome trace file."""
    from fabric_tpu.ledger import LedgerProvider

    provider = LedgerProvider(str(tmp_path / "src"))
    ledger = provider.open(CHANNEL)
    writes = faultfuzz.workload_writes(1)
    try:
        with tracing.scope() as rec:
            with faultline.use_plan({"faults": [
                {"point": "commit.stage", "ctx": {"stage": "pvt"},
                 "action": "crash", "nth": 1},
            ]}):
                blk = faultfuzz._endorsed_block(ledger, 0, writes[0])
                with pytest.raises(faultline.FaultCrash):
                    ledger.commit(blk)
            doc = tracing.export(rec)
            path = tracing.dump_to(
                str(tmp_path / "crash.trace.json"), rec
            )
    finally:
        provider.close()

    (fault,) = _by_name(doc, "fault")
    assert fault["args"]["point"] == "commit.stage"
    assert fault["args"]["action"] == "crash"
    (pvt,) = _by_name(doc, "pvt")
    assert pvt["args"]["fault"] == "commit.stage"
    assert fault["args"]["parent"] == pvt["args"]["span"]
    with open(path, "r", encoding="utf-8") as f:
        loaded = json.load(f)
    assert loaded["traceEvents"] == doc["traceEvents"]


def test_failing_faultfuzz_plan_ships_trace_and_replays_identically(
    tmp_path,
):
    """The seeded acceptance violation under an armed tracer: run_plan
    returns the flight-recorder export alongside the violations, and
    two same-seed runs produce identical span sequences (timestamps
    aside)."""
    seeded = {
        "seed": 3,
        "label": "seeded",
        "faults": [
            {"point": "store.shard_flush", "action": "crash",
             "ctx": {"stage": "apply"}, "count": 1},
            {"point": "store.shard_recover", "action": "skip",
             "count": 5},
        ],
    }
    seqs = []
    for i in range(2):
        with tracing.scope():
            res = faultfuzz.run_plan(
                seeded, str(tmp_path / f"run{i}"), comm=False
            )
        assert res["violations"], "seeded violation must fail the oracle"
        assert res["trace"]["traceEvents"]
        seqs.append(tracing.span_sequence(res["trace"]))
    assert seqs[0] == seqs[1]


def test_campaign_writes_trace_artifact_next_to_repro(
    tmp_path, monkeypatch,
):
    """A failing campaign plan leaves <repro>.trace.json beside the
    repro JSON when tracelens is armed."""
    seeded = {
        "faults": [
            {"point": "store.shard_flush", "action": "crash",
             "ctx": {"stage": "apply"}, "count": 1},
            {"point": "store.shard_recover", "action": "skip",
             "count": 5},
        ],
    }
    monkeypatch.setattr(
        faultfuzz, "generate_plan",
        lambda rng, registry, label, tripped=frozenset():
            {**seeded, "label": label, "seed": 3},
    )
    out_dir = tmp_path / "artifacts"
    with tracing.scope():
        summary = faultfuzz.Campaign(
            seed=11, plans=1, out_dir=str(out_dir),
            workdir=str(tmp_path / "work"), shrink=False, comm=False,
        ).run()
    assert summary["failures"] == 1
    (repro,) = summary["repro"]
    (trace,) = summary["trace"]
    assert trace == repro[: -len(".json")] + ".trace.json"
    with open(trace, "r", encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["traceEvents"]
    # the dump shows the injected faults in causal context
    assert any(e["name"] == "fault" for e in doc["traceEvents"])


# -- traced vs untraced commit parity ----------------------------------------


def _run_commit_workload(root: str, blocks: int = 3):
    """Commit the canned per-block writes; returns (block bytes list,
    state records, last hash) with the provider closed after."""
    from fabric_tpu.ledger import LedgerProvider

    provider = LedgerProvider(root)
    ledger = provider.open(CHANNEL)
    writes = faultfuzz.workload_writes(blocks)
    try:
        for n in range(blocks + 2):
            ledger.commit(
                faultfuzz._endorsed_block(ledger, n, writes[n])
            )
        blocks_raw = [
            ledger.get_block_by_number(n).SerializeToString()
            for n in range(blocks + 2)
        ]
        state = list(ledger.state_db.export_records())
        return blocks_raw, state, ledger.block_store.last_block_hash
    finally:
        provider.close()


def test_traced_commit_stream_is_byte_identical_to_untraced(tmp_path):
    """The parity acceptance: tracing observes, never participates —
    committed blocks, exported state records, and the chain head hash
    are byte-identical with and without an armed tracer, and the
    invariants oracle passes the traced ledger."""
    plain = _run_commit_workload(str(tmp_path / "plain"))
    with tracing.scope() as rec:
        traced = _run_commit_workload(str(tmp_path / "traced"))
        assert len(rec) > 0  # the tracer really was recording
    assert traced[0] == plain[0]  # every block, byte for byte
    assert traced[1] == plain[1]  # every state record
    assert traced[2] == plain[2]  # chain head

    from fabric_tpu.ledger import LedgerProvider

    provider = LedgerProvider(str(tmp_path / "traced"))
    try:
        vs = invariants.check_ledger(
            provider.open(CHANNEL), faultfuzz.workload_writes(3)
        )
        assert vs == []
    finally:
        provider.close()


# -- satellites: log correlation + workpool metrics --------------------------


def test_flogging_emits_trace_ids_when_armed():
    fmt = flogging._TraceFormatter("%(message)s")
    record = logging.LogRecord(
        "fabric_tpu.test", logging.INFO, __file__, 1, "hello", (), None
    )
    assert fmt.format(record) == "hello"  # disarmed: unchanged bytes
    with tracing.scope():
        with tracing.span("logged.work") as sp:
            line = fmt.format(record)
            assert f"trace={sp.trace_id:x}" in line
            assert f"span={sp.span_id:x}" in line
        assert fmt.format(record) == "hello"  # no active span
    assert fmt.format(record) == "hello"


def test_workpool_metrics_gauges_and_stats():
    from fabric_tpu.common.metrics import PrometheusProvider, WorkpoolMetrics

    prov = PrometheusProvider()
    workpool.reset_stats()
    workpool.set_metrics(WorkpoolMetrics(prov))
    try:
        with workpool.scoped_pool(2) as pool:
            out = workpool.run_chunked(
                pool, lambda off, chunk: [v + 1 for v in chunk],
                list(range(20)), 4,
            )
        assert out == [v + 1 for v in range(20)]
        stats = workpool.stats()
        assert stats["chunks"] == 4
        assert 1 <= stats["max_in_flight"] <= 4
        exposed = prov.registry.expose()
        assert "workpool_in_flight_chunks 0" in exposed
        assert "workpool_worker_saturation" in exposed
        assert "workpool_queue_depth" in exposed
    finally:
        workpool.set_metrics(None)
        workpool.reset_stats()


def test_operations_system_builds_workpool_metrics_lazily():
    sys_ = System(("127.0.0.1", 0), provider="disabled")
    m = sys_.workpool_metrics()
    assert m is sys_.workpool_metrics()  # memoized
    sys_._server.server_close()
