"""Seeded violation: the thread target is a closure that calls a
SIBLING closure, and the sibling performs the unguarded write —
reachable only through closure-to-closure call resolution (the v4
dataflow satellite).  v3 lost the call edge and stayed silent."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class Roller:
    def __init__(self):
        self._lock = named_lock("fixture.roller")
        self._height = 0

    def launch(self):
        def bump():
            self._height += 1  # <- racecheck fires HERE

        def pump_loop():
            for _ in range(4):
                bump()

        t = spawn_thread(target=pump_loop, name="roller", kind="worker")
        t.start()
        return t

    def read(self):
        with self._lock:
            return self._height

    def write(self, h):
        with self._lock:
            self._height = h
