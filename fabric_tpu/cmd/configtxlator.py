"""configtxlator: proto <-> JSON translation + config update computation
(reference internal/configtxlator + cmd/configtxlator).

    configtxlator proto_decode --type common.Block --input b.pb [--output j]
    configtxlator proto_encode --type common.Config --input j.json --output p
    configtxlator compute_update --channel_id ch --original a.pb \
        --updated b.pb --output update.pb
"""

from __future__ import annotations

import argparse
import sys

from google.protobuf import json_format

from fabric_tpu.protos.common import common_pb2, configtx_pb2, policies_pb2
from fabric_tpu.protos.msp import msp_config_pb2
from fabric_tpu.protos.orderer import ab_pb2

_TYPES = {
    "common.Block": common_pb2.Block,
    "common.Envelope": common_pb2.Envelope,
    "common.Payload": common_pb2.Payload,
    "common.Config": configtx_pb2.Config,
    "common.ConfigEnvelope": configtx_pb2.ConfigEnvelope,
    "common.ConfigUpdate": configtx_pb2.ConfigUpdate,
    "common.ConfigUpdateEnvelope": configtx_pb2.ConfigUpdateEnvelope,
    "common.Policy": policies_pb2.Policy,
    "common.SignaturePolicyEnvelope": policies_pb2.SignaturePolicyEnvelope,
    "msp.MSPConfig": msp_config_pb2.MSPConfig,
    "msp.FabricMSPConfig": msp_config_pb2.FabricMSPConfig,
    "orderer.SeekInfo": ab_pb2.SeekInfo,
}


def _read(path):
    if path in (None, "-"):
        return sys.stdin.buffer.read()
    with open(path, "rb") as f:
        return f.read()


def _write(path, data: bytes):
    if path in (None, "-"):
        sys.stdout.buffer.write(data)
    else:
        with open(path, "wb") as f:
            f.write(data)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="configtxlator")
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("proto_decode", "proto_encode"):
        p = sub.add_parser(name)
        p.add_argument("--type", required=True, choices=sorted(_TYPES))
        p.add_argument("--input", default="-")
        p.add_argument("--output", default="-")
    cu = sub.add_parser("compute_update")
    cu.add_argument("--channel_id", required=True)
    cu.add_argument("--original", required=True)
    cu.add_argument("--updated", required=True)
    cu.add_argument("--output", default="-")
    args = ap.parse_args(argv)

    if args.cmd == "proto_decode":
        msg = _TYPES[args.type].FromString(_read(args.input))
        out = json_format.MessageToJson(
            msg, preserving_proto_field_name=True
        )
        _write(args.output, out.encode("utf-8"))
        return 0
    if args.cmd == "proto_encode":
        msg = json_format.Parse(
            _read(args.input).decode("utf-8"), _TYPES[args.type]()
        )
        _write(args.output, msg.SerializeToString())
        return 0

    from fabric_tpu.common.configtx import compute_update

    original = configtx_pb2.Config.FromString(_read(args.original))
    updated = configtx_pb2.Config.FromString(_read(args.updated))
    upd = compute_update(args.channel_id, original, updated)
    _write(args.output, upd.SerializeToString())
    return 0


if __name__ == "__main__":
    sys.exit(main())
