"""Host ("software") CSP provider.

Equivalent of the reference's pure-Go `sw` provider (bccsp/sw/impl.go:36-47,
ecdsa.go:27-57): OpenSSL-backed ECDSA-P256 via `cryptography`, SHA-256 via
hashlib.  Serves two roles: (a) the host fallback provider, and (b) the
parity oracle the TPU provider is tested against.

Verify semantics match the reference exactly (bccsp/sw/ecdsa.go:41-57):
DER-unmarshal, reject r/s <= 0, reject high-S, then curve verify.
"""

from __future__ import annotations

import hashlib
from typing import Sequence

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import Prehashed

from fabric_tpu.csp import api
from fabric_tpu.csp.api import (
    CSP,
    ECDSAP256PrivateKey,
    ECDSAP256PublicKey,
    Key,
    VerifyBatchItem,
)

_PREHASHED_SHA256 = ec.ECDSA(Prehashed(hashes.SHA256()))


class SWCSP(CSP):
    """Host crypto over a pluggable keystore (reference bccsp/sw/impl.go;
    keystores: inmemoryks.go default, fileks.go via csp.keystore)."""

    def __init__(self, keystore=None) -> None:
        from fabric_tpu.csp.keystore import InMemoryKeyStore

        self._ks = keystore if keystore is not None else InMemoryKeyStore()

    # -- key management ----------------------------------------------------

    def key_gen(self) -> ECDSAP256PrivateKey:
        key = ECDSAP256PrivateKey.generate()
        self._store(key)
        return key

    def key_import(self, raw: bytes, private: bool = False) -> Key:
        key: Key
        if private:
            key = ECDSAP256PrivateKey.from_der(raw)
        elif raw[:1] == b"\x04" and len(raw) == 65:
            key = ECDSAP256PublicKey.from_point(
                int.from_bytes(raw[1:33], "big"), int.from_bytes(raw[33:65], "big")
            )
        else:
            key = ECDSAP256PublicKey.from_der(raw)
        self._store(key)
        return key

    def get_key(self, ski: bytes) -> Key:
        return self._ks.get_key(ski)

    def _store(self, key: Key) -> None:
        self._ks.store_key(key)

    # -- hashing -----------------------------------------------------------

    def hash(self, msg: bytes) -> bytes:
        return hashlib.sha256(msg).digest()

    def hash_batch(self, msgs: Sequence[bytes]) -> list[bytes]:
        return [hashlib.sha256(m).digest() for m in msgs]

    # -- sign / verify -----------------------------------------------------

    def sign(self, key: Key, digest: bytes) -> bytes:
        if not isinstance(key, ECDSAP256PrivateKey):
            raise TypeError("sign requires an ECDSA private key")
        sig = key.crypto_key.sign(digest, _PREHASHED_SHA256)
        # Reference always emits low-S (bccsp/utils/ecdsa.go ToLowS via
        # signECDSA, bccsp/sw/ecdsa.go:27-39).
        r, s = api.unmarshal_ecdsa_signature(sig)
        return api.marshal_ecdsa_signature(r, api.to_low_s(s))

    def verify(self, key: Key, signature: bytes, digest: bytes) -> bool:
        if isinstance(key, ECDSAP256PrivateKey):
            key = key.public_key()
        if not isinstance(key, ECDSAP256PublicKey):
            raise TypeError("verify requires an ECDSA key")
        return _verify_one(key, signature, digest)

    def verify_batch(self, items: Sequence[VerifyBatchItem]) -> list[bool]:
        return [_verify_one(it.key, it.signature, it.digest) for it in items]


def _verify_one(key: ECDSAP256PublicKey, signature: bytes, digest: bytes) -> bool:
    try:
        r, s = api.unmarshal_ecdsa_signature(signature)
    except ValueError:
        return False
    if r >= api.P256_N or s >= api.P256_N:
        return False
    # Reference rejects high-S before curve math (bccsp/sw/ecdsa.go:41-52).
    if not api.is_low_s(s):
        return False
    try:
        key.crypto_key.verify(
            api.marshal_ecdsa_signature(r, s), digest, _PREHASHED_SHA256
        )
        return True
    except InvalidSignature:
        return False
    except ValueError:
        return False  # e.g. digest length != 32: invalid, never a throw
