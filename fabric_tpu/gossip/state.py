"""Gossip state transfer: ordered block delivery into the commit pipeline.

Capability parity with the reference's gossip/state
(state.go:189 NewGossipStateProvider, :547 deliverPayloads, :591
antiAntropy, :750 AddPayload, :781 commitBlock): blocks arrive out of
order from gossip push/pull or in order from the deliver client; a
payload buffer holds them; a delivery loop commits strictly sequentially;
anti-entropy asks peers that advertise greater height for the missing
range (RemoteStateRequest/Response).
"""

from __future__ import annotations

import threading

from fabric_tpu.devtools.lockwatch import named_lock

from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.gossip import message_pb2 as gpb


class PayloadBuffer:
    def __init__(self):
        self._by_seq: dict[int, bytes] = {}
        self._lock = named_lock("gossip.state.buffer")

    def push(self, seq: int, block_bytes: bytes) -> None:
        with self._lock:
            self._by_seq.setdefault(seq, block_bytes)

    def pop(self, seq: int) -> bytes | None:
        with self._lock:
            return self._by_seq.pop(seq, None)

    def __contains__(self, seq: int) -> bool:
        with self._lock:
            return seq in self._by_seq


class StateProvider:
    def __init__(
        self,
        channel_id: str,
        channel_gossip,  # ChannelGossip
        committer,       # object with .store_block(Block) and .height
        comm,
        max_batch: int = 10,
    ):
        self.channel_id = channel_id
        self._chan = channel_id.encode()
        self._gossip = channel_gossip
        self._committer = committer
        self._comm = comm
        self._buffer = PayloadBuffer()
        self._max_batch = max_batch
        # watched under FABRIC_TPU_LOCKWATCH: ordered BEFORE the
        # ledger commit lock (store_block enters the committer/ledger
        # while holding it); nothing may take it while holding those
        self._commit_lock = named_lock("gossip.state.commit")
        channel_gossip.ledger_height = lambda: self._committer.height
        # blocks arriving via gossip land here
        self._gossip._on_block = self._on_gossip_block
        comm.subscribe(self._handle)

    # -- ingestion ---------------------------------------------------------

    def add_payload(self, seq: int, block_bytes: bytes, from_orderer: bool = False) -> None:
        """AddPayload: deliver-client (ordered) or gossip (unordered)."""
        if seq < self._committer.height:
            return  # already committed
        self._buffer.push(seq, block_bytes)
        if from_orderer:
            # teach the gossip layer so it disseminates to org peers
            self._gossip.add_block(seq, block_bytes)
        self._drain()

    def _on_gossip_block(self, seq: int, block_bytes: bytes) -> None:
        if seq < self._committer.height:
            return
        self._buffer.push(seq, block_bytes)
        self._drain()

    # -- ordered commit ----------------------------------------------------

    def _drain(self) -> None:
        with self._commit_lock:
            while True:
                nxt = self._committer.height
                raw = self._buffer.pop(nxt)
                if raw is None:
                    return
                blk = common_pb2.Block.FromString(raw)
                self._committer.store_block(blk)

    # -- anti-entropy ------------------------------------------------------

    def tick(self) -> None:
        """Request the missing range from the best-known peer if we lag."""
        ep, their_height = self._gossip.best_peer_height()
        my_height = self._committer.height
        if ep is None or their_height <= my_height:
            return
        req = gpb.GossipMessage(channel=self._chan)
        req.state_request.start_seq_num = my_height
        req.state_request.end_seq_num = min(
            their_height - 1, my_height + self._max_batch - 1
        )
        self._comm.send(ep, req)

    def _handle(self, rm) -> None:
        msg = rm.msg
        if bytes(msg.channel) != self._chan:
            return
        kind = msg.WhichOneof("content")
        if kind == "state_request":
            resp = gpb.GossipMessage(channel=self._chan)
            lo = msg.state_request.start_seq_num
            hi = msg.state_request.end_seq_num
            for seq in range(lo, hi + 1):
                raw = self._gossip.store.get(seq) or self._read_committed(seq)
                if raw is None:
                    break
                dm = resp.state_response.payloads.add()
                dm.seq_num = seq
                dm.block = raw
            ep = self._gossip._endpoint_for(rm.sender_pki)
            if ep and resp.state_response.payloads:
                self._comm.send(ep, resp)
        elif kind == "state_response":
            for dm in msg.state_response.payloads:
                self.add_payload(dm.seq_num, bytes(dm.block))

    def _read_committed(self, seq: int) -> bytes | None:
        reader = getattr(self._committer, "get_block_by_number", None)
        if reader is None:
            return None
        blk = reader(seq)
        return blk.SerializeToString() if blk is not None else None


__all__ = ["StateProvider", "PayloadBuffer"]
