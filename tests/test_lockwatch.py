"""Runtime lock-order watchdog tests (ISSUE 3).

fabriclint's static lock rule only sees lexically nested `with` blocks;
the watchdog closes the call-chain gap at runtime by recording the
process-wide acquisition-order graph over lock ROLES.  Here: an injected
A->B / B->A inversion across two threads is reported deterministically
(every attempt, with the full cycle and the offending thread), the clean
ledger commit + snapshot-export path does not trip it, and the
suppressed corner cases (RLock re-entrancy, two instances of one role)
stay quiet.
"""

import threading
import time

import pytest

from fabric_tpu.devtools import lockwatch
from fabric_tpu.devtools.lockwatch import (
    LockOrderError,
    WatchedLock,
    named_lock,
    named_rlock,
)


@pytest.fixture(autouse=True)
def _fresh_graph(monkeypatch):
    """Each test starts from an empty order graph (the suite-wide watch
    keeps accumulating before/after; edges only strengthen detection, so
    clearing them here cannot cause false positives elsewhere).  The
    violation ledger is SAVED and restored, not wiped: conftest's
    session-end soak gate asserts it empty, and an inversion recorded by
    an earlier test's background thread must still reach that gate."""
    monkeypatch.setenv("FABRIC_TPU_LOCKWATCH", "1")
    prior = list(lockwatch.violations)
    lockwatch.reset()
    yield
    lockwatch.reset()
    lockwatch.violations.extend(prior)


def _run_in_thread(fn, name="worker"):
    """Run fn in a thread, returning the exception it raised (or None)."""
    box = []

    def wrapper():
        try:
            fn()
        except BaseException as exc:  # noqa: BLE001 - test harness
            box.append(exc)

    t = threading.Thread(target=wrapper, name=name)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "watchdog test thread wedged"
    return box[0] if box else None


# -- injected inversion ------------------------------------------------------


def test_ab_ba_inversion_reported_deterministically():
    a, b = WatchedLock("A"), WatchedLock("B")

    def establish():
        with a:
            with b:
                pass

    assert _run_in_thread(establish, name="establisher") is None
    assert lockwatch.edges().get("A") == {"B"}

    # the inverse order must raise EVERY attempt, not just sometimes:
    # detection is against the persisted graph, not a lucky interleaving
    for attempt in range(3):
        def invert():
            with b:
                with a:
                    pass

        exc = _run_in_thread(invert, name=f"inverter-{attempt}")
        assert isinstance(exc, LockOrderError), f"attempt {attempt}"
        assert "'A'" in str(exc) and "'B'" in str(exc)

    v = lockwatch.violations[-1]
    assert v["acquiring"] == "A"
    assert v["holding"] == "B"
    assert v["cycle"] == ["A", "B", "A"]
    assert v["thread"] == "inverter-2"
    # the refused acquisition never took the inner lock: A is free
    assert a.acquire(blocking=False)
    a.release()


def test_contended_inversion_raises_instead_of_deadlocking():
    """A LIVE deadlock: T1 holds A and blocks acquiring B while T2
    holds B and attempts A.  The order check runs BEFORE the blocking
    inner acquire, so T2 raises (unwedging T1) rather than both
    threads inheriting the deadlock the watchdog exists to catch."""
    a, b = WatchedLock("A"), WatchedLock("B")
    both_held = threading.Barrier(2, timeout=5)
    errs: list[BaseException] = []

    def t1():
        with a:
            both_held.wait()
            with b:  # blocks until t2's refused attempt releases B
                pass

    def t2():
        with b:
            both_held.wait()
            time.sleep(0.05)  # let t1 record A->B and block on B
            try:
                with a:
                    pass
            except LockOrderError as exc:
                errs.append(exc)

    th1 = threading.Thread(target=t1, name="holder-A")
    th2 = threading.Thread(target=t2, name="holder-B")
    th1.start()
    th2.start()
    th2.join(timeout=5)
    th1.join(timeout=5)
    assert not th1.is_alive() and not th2.is_alive(), "deadlocked"
    assert len(errs) == 1 and isinstance(errs[0], LockOrderError)


def test_transitive_cycle_detected():
    a, b, c = WatchedLock("A"), WatchedLock("B"), WatchedLock("C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass

    def close_cycle():
        with c:
            with a:
                pass

    exc = _run_in_thread(close_cycle)
    assert isinstance(exc, LockOrderError)
    assert lockwatch.violations[-1]["cycle"] == ["A", "B", "C", "A"]


def test_record_mode_logs_without_raising(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_LOCKWATCH", "record")
    a, b = WatchedLock("A"), WatchedLock("B")
    with a:
        with b:
            pass

    def invert():
        with b:
            with a:
                pass

    assert _run_in_thread(invert) is None
    assert lockwatch.violations[-1]["cycle"] == ["A", "B", "A"]


# -- cases that must stay quiet ---------------------------------------------


def test_consistent_order_never_trips():
    a, b = WatchedLock("A"), WatchedLock("B")

    def nest():
        for _ in range(20):
            with a:
                with b:
                    pass

    threads = [
        threading.Thread(target=nest, name=f"nester-{i}") for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert not lockwatch.violations


def test_rlock_reentrancy_is_not_an_inversion():
    r = named_rlock("R")
    assert isinstance(r, WatchedLock)
    with r:
        with r:
            pass
    assert not lockwatch.violations


def test_two_instances_of_one_role_are_unordered():
    # per-channel locks share a role name; role-level ordering cannot
    # rank an instance against itself (documented approximation)
    l1, l2 = WatchedLock("chan"), WatchedLock("chan")
    with l1:
        with l2:
            pass
    assert not lockwatch.violations


def test_failed_try_lock_does_not_poison_the_graph():
    # a non-blocking acquire that loses the race cannot deadlock, so it
    # must not record an ordering edge — otherwise the later legitimate
    # B -> A nesting would raise a false LockOrderError
    a, b = WatchedLock("A"), WatchedLock("B")
    held, release = threading.Event(), threading.Event()

    def holder():
        with b:
            held.set()
            release.wait(5)

    t = threading.Thread(target=holder, name="B-holder")
    t.start()
    assert held.wait(5)
    with a:
        assert not b.acquire(blocking=False)  # busy: must leave no edge
        assert not b.acquire(True, 0.05)      # timed wait: same rule
    release.set()
    t.join(5)
    assert "A" not in lockwatch.edges()
    with b:
        with a:
            pass
    assert not lockwatch.violations


def test_blocking_self_reacquire_of_plain_lock_is_diagnosed():
    # a blocking re-acquire of a non-reentrant lock by the SAME thread
    # can never succeed: the watchdog must raise deterministically, not
    # wedge inside the wrapper; a non-blocking try stays a plain False
    lk = named_lock("gossip.net")
    assert isinstance(lk, WatchedLock)
    with lk:
        assert not lk.acquire(blocking=False)  # try-lock: quiet False
        assert not lockwatch.violations
        with pytest.raises(LockOrderError, match="self-deadlock"):
            # fabriclint: allow[lock-discipline] deliberate blocking
            # re-acquire: the raised self-deadlock IS the assertion
            lk.acquire()
    assert lockwatch.violations[-1]["cycle"] == ["gossip.net", "gossip.net"]
    lockwatch.reset()
    # and a watched RLock keeps full re-entrancy
    r = named_rlock("mgr")
    with r:
        assert r.acquire()
        r.release()
    assert not lockwatch.violations


def test_successful_try_lock_records_order():
    a, b = WatchedLock("A"), WatchedLock("B")
    with a:
        assert b.acquire(blocking=False)
        b.release()
    assert lockwatch.edges().get("A") == {"B"}

    def invert():
        with b:
            with a:
                pass

    assert isinstance(_run_in_thread(invert), LockOrderError)


def test_cross_thread_release_is_refused():
    # threading.Lock permits release on another thread (handoff), but
    # under watch that would leave a stale held-entry in the acquirer's
    # stack and later record bogus edges — must refuse, not rot
    lk = named_lock("handoff")
    # fabriclint: allow[lock-discipline] deliberately unpaired acquire:
    # the release happens on ANOTHER thread to probe handoff refusal
    lk.acquire()

    def release_elsewhere():
        lk.release()

    exc = _run_in_thread(release_elsewhere)
    assert isinstance(exc, LockOrderError)
    assert "cross-thread release" in str(exc)
    lk.release()  # same-thread release still fine
    assert lk.acquire(blocking=False)
    lk.release()


def test_named_lock_returns_plain_lock_when_disabled(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_LOCKWATCH", "0")
    assert not isinstance(named_lock("x"), WatchedLock)
    monkeypatch.setenv("FABRIC_TPU_LOCKWATCH", "1")
    assert isinstance(named_lock("x"), WatchedLock)


# -- the real commit + snapshot path ----------------------------------------


def test_clean_commit_and_snapshot_path_does_not_trip(tmp_path):
    """The production path the watchdog exists to protect: group
    commits interleaved with a commit-time snapshot auto-trigger whose
    export runs on a background thread, plus a foreground generate() —
    commit_lock -> manager _lock everywhere, so the graph must stay
    acyclic and the violation list empty."""
    import test_snapshot as ts

    provider, ledger = ts._source_ledger(tmp_path, 6)
    mgr = ledger.snapshots
    mgr.submit_request(8)
    ts._commit_blocks(ledger, 6, 3)  # crosses height 8 -> auto-trigger
    assert mgr.wait_idle(timeout=30)
    ts._commit_blocks(ledger, 9, 2)
    mgr.generate()
    assert not lockwatch.violations
    assert isinstance(ledger.commit_lock, WatchedLock)
    assert isinstance(mgr._lock, WatchedLock)
    provider.close()


def test_runtime_lock_graph_is_subgraph_of_static(tmp_path):
    """ISSUE 13 cross-check (runtime ⊆ static): every acquisition-order
    edge the runtime watchdog observes during a live commit+snapshot
    session must be present in fabriclint's whole-program lock-order
    graph — so the static pass provably covers what tier-1 exercises,
    and a call-chain ordering the static analysis cannot see would
    fail HERE instead of silently narrowing the lock-order rule's
    coverage."""
    import test_snapshot as ts

    from fabric_tpu.devtools.lint import lint_tree

    assert lockwatch.enabled()  # conftest arms tier-1
    provider, ledger = ts._source_ledger(tmp_path, 6)
    mgr = ledger.snapshots
    mgr.submit_request(8)
    ts._commit_blocks(ledger, 6, 3)  # crosses height 8 -> auto-trigger
    assert mgr.wait_idle(timeout=30)
    ts._commit_blocks(ledger, 9, 2)
    mgr.generate()
    provider.close()
    runtime = lockwatch.edges()
    observed = [(s, d) for s, ds in sorted(runtime.items())
                for d in sorted(ds)]
    # the session really exercised the commit -> snapshot ordering
    assert ("kvledger.commit_lock", "snapshot.manager") in observed
    static = lint_tree().lock_graph()["edges"]
    missing = [
        (s, d) for s, d in observed if d not in static.get(s, {})
    ]
    assert not missing, (
        f"runtime lockwatch edges missing from the static graph: "
        f"{missing} — the static pass lost a call chain the runtime "
        f"exercises"
    )


def test_refused_acquisition_leaves_no_partial_edges():
    # holding A then B with X->B established: acquiring X is refused at
    # the B check, and the A->X edge scanned BEFORE the violation must
    # not be committed — else the safe X->A nesting below would raise
    a, b, x = WatchedLock("A"), WatchedLock("B"), WatchedLock("X")
    with x:
        with b:
            pass

    def refused():
        with a:
            with b:
                with x:
                    pass

    assert isinstance(_run_in_thread(refused), LockOrderError)
    assert "X" not in lockwatch.edges().get("A", set())

    def safe():
        with x:
            with a:
                pass

    assert _run_in_thread(safe) is None
    assert len(lockwatch.violations) == 1  # only the injected refusal


def test_record_mode_performs_cross_thread_handoff():
    import os

    os.environ["FABRIC_TPU_LOCKWATCH"] = "record"
    try:
        lk = WatchedLock("handoff-rec")
        # fabriclint: allow[lock-discipline] deliberately unpaired acquire:
        # record-mode handoff releases on another thread by design
        lk.acquire()
        assert _run_in_thread(lambda: lk.release()) is None  # no raise
        assert lockwatch.violations[-1]["event"] == "cross-thread-release"
        assert lk.acquire(blocking=False)  # inner really was released
        lk.release()
    finally:
        os.environ["FABRIC_TPU_LOCKWATCH"] = "1"
        # the handoff leaves the documented stale held-entry on THIS
        # thread (observe-only mode doesn't fix the stack); scrub it so
        # later main-thread acquisitions/waits don't see a phantom hold
        st = lockwatch._held()
        st[:] = [e for e in st if e[0] is not lk]


# -- condition-variable wait ordering (ISSUE 4 satellite) --------------------


def test_wait_while_holding_order_predecessor_raises():
    # establish commit -> idle (the canonical snapshot ordering), then
    # wait on idle while HOLDING commit: the waker needs commit first,
    # which the waiter holds — a deadlock-capable wait
    from fabric_tpu.devtools.lockwatch import named_condition

    commit = named_lock("cw.commit")
    idle = named_condition("cw.idle")
    assert isinstance(idle, lockwatch.WatchedCondition)

    def establish():
        with commit:
            with idle:
                pass

    assert _run_in_thread(establish) is None

    def bad_wait():
        with commit:
            with idle:
                idle.wait(timeout=0.01)

    exc = _run_in_thread(bad_wait)
    assert isinstance(exc, LockOrderError)
    assert "order-predecessor" in str(exc)
    bad = lockwatch.violations[-1]
    assert bad["event"] == "wait-while-holding-predecessor"
    assert bad["condition"] == "cw.idle"
    assert bad["holding"] == "cw.commit"
    lockwatch.reset()


def test_wait_without_predecessor_is_quiet_and_wakes():
    from fabric_tpu.devtools.lockwatch import named_condition

    cond = named_condition("cw.plain")
    got = []

    def waiter():
        with cond:
            got.append(cond.wait(timeout=5))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive() and got == [True]
    assert not lockwatch.violations


def test_wait_for_uses_watched_wait():
    from fabric_tpu.devtools.lockwatch import named_condition

    cond = named_condition("cw.waitfor")
    state = {"ready": False}

    def setter():
        time.sleep(0.05)
        with cond:
            state["ready"] = True
            cond.notify_all()

    t = threading.Thread(target=setter)
    t.start()
    with cond:
        assert cond.wait_for(lambda: state["ready"], timeout=5)
    t.join(timeout=5)


def test_named_condition_plain_when_disabled(monkeypatch):
    from fabric_tpu.devtools.lockwatch import named_condition

    monkeypatch.setenv("FABRIC_TPU_LOCKWATCH", "")
    cond = named_condition("cw.off")
    assert isinstance(cond, threading.Condition)


# -- guarded(): the runtime half of racecheck (ISSUE 7) ----------------------


class _Obj:
    pass


def test_guarded_quiet_while_role_held():
    lock = named_lock("guard.role")
    with lock:
        lockwatch.guarded(_Obj(), "field", by="guard.role")
    assert lockwatch.violations == []
    # condition roles count too: holding the condition holds its lock
    from fabric_tpu.devtools.lockwatch import named_condition

    cond = named_condition("guard.cond")
    with cond:
        lockwatch.guarded(_Obj(), "field", by="guard.cond")
    assert lockwatch.violations == []


def test_guarded_violation_raises_and_lands_in_drained_ledger():
    """ISSUE 7 acceptance: an injected unguarded access fails
    DETERMINISTICALLY — guarded() raises on the spot AND records into
    lockwatch.violations, the very ledger conftest's session-end soak
    gate asserts empty, so even a violation swallowed by a broad
    handler on a background thread still fails the session."""
    named_lock("guard.other")  # role exists, but is not held
    with pytest.raises(LockOrderError, match="unguarded access"):
        lockwatch.guarded(_Obj(), "_peers", by="guard.role")
    assert len(lockwatch.violations) == 1
    bad = lockwatch.violations[0]
    assert bad["event"] == "unguarded-access"
    assert bad["field"] == "_peers"
    assert bad["required"] == "guard.role"
    assert bad["object"] == "_Obj"
    lockwatch.violations.clear()  # examined: keep the session gate green


def test_guarded_wrong_lock_held_still_fires():
    other = named_lock("guard.wrong")
    with other:
        with pytest.raises(LockOrderError, match="unguarded access"):
            lockwatch.guarded(_Obj(), "field", by="guard.right")
    lockwatch.violations.clear()


def test_guarded_record_mode_observes_without_raising(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_LOCKWATCH", "record")
    lockwatch.guarded(_Obj(), "field", by="guard.role")
    assert lockwatch.violations[-1]["event"] == "unguarded-access"
    lockwatch.violations.clear()


def test_guarded_noop_when_disabled(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_LOCKWATCH", "")
    lockwatch.guarded(_Obj(), "field", by="guard.role")
    assert lockwatch.violations == []


def test_guarded_sites_in_production_hold_their_declared_roles():
    """The wired hot sites really run under their guards in tier-1: a
    discovery _learn and a snapshot submit both pass through guarded()
    without tripping (the e2e suites exercise the rest)."""
    from fabric_tpu.gossip.discovery import DiscoveryCore

    class _Comm:
        endpoint = "h:1"
        pki_id = b"pki-self"
        identity = b"id-self"

        def subscribe(self, fn):
            pass

        def learn_identity(self, ident):
            pass

    core = DiscoveryCore(_Comm(), bootstrap=[])

    class _AM:
        class membership:
            pki_id = b"pki-peer"
            endpoint = "h:2"
            identity = b""

        inc_number = 1
        seq_num = 1

    assert core._learn(_AM()) is True
    assert lockwatch.violations == []


@pytest.fixture()
def _threadwatch(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_THREADWATCH", "1")
    prior = list(lockwatch.thread_violations)
    lockwatch.reset_threads()
    yield
    lockwatch.reset_threads()
    lockwatch.thread_violations.extend(prior)


def test_spawn_thread_registers_and_deregisters(_threadwatch):
    gate = threading.Event()
    release = threading.Event()

    def job():
        gate.set()
        release.wait(5)

    t = lockwatch.spawn_thread(target=job, name="tw-job", kind="worker")
    t.start()
    assert gate.wait(5)
    alive = lockwatch.threads_alive(kinds=("worker",))
    assert any(i["name"] == "tw-job" for i in alive)
    release.set()
    t.join(5)
    assert not any(
        i["name"] == "tw-job" for i in lockwatch.threads_alive()
    )
    assert not lockwatch.thread_violations


def test_spawn_thread_records_unhandled_exception(
    _threadwatch, monkeypatch
):
    def boom():
        raise RuntimeError("silent death")

    # the re-raise after recording is deliberate; keep the default
    # excepthook (and pytest's unhandled-thread warning) out of the way
    monkeypatch.setattr(threading, "excepthook", lambda args: None)
    t = lockwatch.spawn_thread(target=boom, name="tw-boom", kind="worker")
    t.start()
    t.join(5)
    assert any(
        v["event"] == "unhandled-exception" and v["thread"] == "tw-boom"
        for v in lockwatch.thread_violations
    )
    lockwatch.reset_threads()


def test_drain_joins_workers_and_flags_stragglers(_threadwatch):
    release = threading.Event()
    lockwatch.spawn_thread(
        target=lambda: release.wait(0.2), name="tw-quick", kind="worker"
    ).start()
    # a worker that exits inside the timeout drains cleanly
    release.set()
    assert lockwatch.drain_threads(timeout=5.0) == []
    assert not lockwatch.thread_violations

    # one that outlives the deadline is recorded as a straggler
    wedge = threading.Event()
    t = lockwatch.spawn_thread(
        target=lambda: wedge.wait(10), name="tw-wedged", kind="worker"
    )
    t.start()
    time.sleep(0.05)
    stragglers = lockwatch.drain_threads(timeout=0.1)
    assert stragglers == ["tw-wedged"]
    assert lockwatch.thread_violations[-1]["event"] == "drain-timeout"
    wedge.set()
    t.join(5)
    lockwatch.reset_threads()


def test_drain_skips_service_threads(_threadwatch):
    stop = threading.Event()
    t = lockwatch.spawn_thread(
        target=lambda: stop.wait(10), name="tw-service", kind="service"
    )
    t.start()
    time.sleep(0.05)
    assert lockwatch.drain_threads(timeout=0.1) == []  # workers only
    assert not lockwatch.thread_violations
    stop.set()
    t.join(5)


def test_spawn_thread_plain_when_disabled(monkeypatch):
    monkeypatch.setenv("FABRIC_TPU_THREADWATCH", "")
    t = lockwatch.spawn_thread(target=lambda: None, name="tw-plain")
    assert isinstance(t, threading.Thread) and t.daemon
    t.start()
    t.join(5)
    assert not any(
        i["name"] == "tw-plain" for i in lockwatch.threads_alive()
    )


def test_spawn_timer_fires_and_cancelled_timer_prunes(_threadwatch):
    fired = threading.Event()
    t = lockwatch.spawn_timer(0.05, fired.set, name="tw-timer")
    assert t.daemon
    t.start()
    assert fired.wait(5)
    t.join(5)
    assert not any(
        i["name"] == "tw-timer" for i in lockwatch.threads_alive()
    )
    # a timer cancelled after start() skips its callback, so the
    # wrapper's deregistration never runs — the registry must prune
    # the dead entry on the next read instead of leaking it
    t2 = lockwatch.spawn_timer(30.0, fired.set, name="tw-timer-cancel")
    t2.start()
    t2.cancel()
    t2.join(5)
    assert not any(
        i["name"] == "tw-timer-cancel"
        for i in lockwatch.threads_alive()
    )
    assert not lockwatch.thread_violations


def test_spawn_thread_visible_to_drain_immediately_after_start(
    _threadwatch,
):
    # registration happens-before start() returns: a drain sweep racing
    # a just-started worker must SEE it (the gate's whole guarantee)
    gate = threading.Event()
    t = lockwatch.spawn_thread(
        target=gate.wait, args=(5,), name="tw-early", kind="worker"
    )
    t.start()
    assert any(
        i["name"] == "tw-early"
        for i in lockwatch.threads_alive(kinds=("worker",))
    )
    gate.set()
    t.join(5)


def test_double_start_does_not_evict_live_registry_entry(_threadwatch):
    # a second start() raises, but its rollback must not deregister the
    # RUNNING thread — that would hide it from the drain gate
    gate = threading.Event()
    t = lockwatch.spawn_thread(
        target=gate.wait, args=(5,), name="tw-double", kind="worker"
    )
    t.start()
    with pytest.raises(RuntimeError):
        t.start()
    assert any(
        i["name"] == "tw-double" for i in lockwatch.threads_alive()
    )
    gate.set()
    t.join(5)
    assert not lockwatch.thread_violations


# -- threadwatch: concurrent.futures executors (ISSUE 6 satellite) -----------


def test_tracked_executor_workers_visible_to_drain_gate(_threadwatch):
    gate = threading.Event()
    release = threading.Event()
    ex = lockwatch.tracked_executor(2, name="tw-pool")
    try:
        fut = ex.submit(lambda: (gate.set(), release.wait(5)))
        assert gate.wait(5)
        # the pool worker registered itself through the initializer
        names = [
            i["name"] for i in lockwatch.threads_alive(kinds=("worker",))
        ]
        assert any(n.startswith("tw-pool") for n in names)
        release.set()
        assert fut.result(timeout=5)[1] is True
    finally:
        release.set()
        ex.shutdown(wait=True)
    # after shutdown the workers are dead; the registry prunes on read
    assert not any(
        i["name"].startswith("tw-pool")
        for i in lockwatch.threads_alive(kinds=("worker",))
    )


def test_tracked_executor_chains_caller_initializer(_threadwatch):
    seen = []
    ex = lockwatch.tracked_executor(
        1, name="tw-init", initializer=seen.append, initargs=("hello",)
    )
    try:
        assert ex.submit(lambda: 42).result(timeout=5) == 42
        assert seen == ["hello"]
    finally:
        ex.shutdown(wait=True)


def test_tracked_executor_rejects_unknown_kind(_threadwatch):
    with pytest.raises(ValueError, match="unknown thread kind"):
        lockwatch.tracked_executor(1, kind="demon")


def test_tracked_executor_plain_without_threadwatch(monkeypatch):
    from concurrent.futures import ThreadPoolExecutor

    monkeypatch.setenv("FABRIC_TPU_THREADWATCH", "0")
    ex = lockwatch.tracked_executor(1, name="tw-off")
    try:
        assert type(ex) is ThreadPoolExecutor
        assert ex.submit(lambda: 1).result(timeout=5) == 1
    finally:
        ex.shutdown(wait=True)
