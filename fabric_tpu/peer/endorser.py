"""Endorsement: simulate a proposal and sign the result.

Reference: core/endorser/endorser.go (:296 ProcessProposal -> :250
preProcess -> :178 SimulateProposal -> :106 callChaincode) +
plugin_endorser.go (EndorseWithPlugin) + the builtin plugin
(core/handlers/endorsement/builtin/default_endorsement.go:36).

Chaincodes here are in-process callables (the system-chaincode execution
model, core/scc/inprocstream.go); the external chaincode runtime plugs
into the same `chaincodes` registry when it lands.
"""

from __future__ import annotations

from fabric_tpu.peer import aclmgmt
from fabric_tpu.protos.peer import chaincode_pb2, proposal_pb2, proposal_response_pb2
from fabric_tpu import protoutil
from fabric_tpu.protoutil import SignedData


class EndorserError(Exception):
    pass


class ACLDeniedError(EndorserError):
    pass


class Endorser:
    def __init__(self, channel_id: str, ledger, bundle, signer, chaincodes: dict, csp,
                 acl_provider: aclmgmt.ACLProvider | None = None,
                 pvt_handoff=None):
        """chaincodes: name -> fn(tx_simulator, args: list[bytes]) ->
        (status:int, message:str, payload:bytes).

        `acl_provider` defaults to one built from the channel config's
        ACLs value (Bundle.acls) — enforcement is on by default, like
        the reference peer (endorser.go:286 CheckACL before simulating;
        per-function SCC resources per aclmgmt.SCC_FUNCTION_RESOURCES).

        `pvt_handoff(txid, pvt_bytes)`: receives the CLEARTEXT private
        simulation results before the endorsement is returned — node
        assemblies wire it to transient-store persist + gossip push
        (reference endorser.go:234 DistributePrivateData); its failure
        fails the endorsement.  A bare Endorser (auxiliary signer in
        tests, no node attached) has nowhere to persist, so None drops
        the cleartext — the PUBLIC response still carries the hashed
        rwsets either way."""
        self.channel_id = channel_id
        self._ledger = ledger
        self._bundle = bundle
        self._signer = signer
        self._chaincodes = chaincodes
        self._csp = csp
        self._acl = acl_provider or aclmgmt.ACLProvider(
            getattr(bundle, "acls", None), csp=csp
        )
        self._pvt_handoff = pvt_handoff

    def _check_acl(self, up, signed) -> None:
        """peer/Propose for application chaincodes (reference
        endorser.go:284-290 via support.go:137); the cataloged
        per-function resource for system chaincodes (checked inside each
        SCC in the reference — qscc/query.go:112, cscc/configure.go:163,
        lifecycle/scc.go:209 — here at the endorser entry, where the
        SignedProposal is in scope)."""
        fn = up.input.args[0].decode("utf-8", "replace") if up.input.args else ""
        try:
            # fail-closed: an uncataloged SCC function raises here
            resource = aclmgmt.resource_for_chaincode(up.chaincode_name, fn)
        except aclmgmt.ACLError as exc:
            raise ACLDeniedError(str(exc)) from exc
        sd = SignedData(
            signed.proposal_bytes,
            up.signature_header.creator,
            signed.signature,
        )
        try:
            self._acl.check_acl(resource, self._bundle.policy_manager, sd)
        except aclmgmt.ACLError as exc:
            raise ACLDeniedError(str(exc)) from exc

    def process_proposal(
        self, signed: proposal_pb2.SignedProposal
    ) -> proposal_response_pb2.ProposalResponse:
        # -- preProcess: structural checks + creator auth ------------------
        up = protoutil.unpack_proposal(signed)
        if up.channel_header.channel_id != self.channel_id:
            raise EndorserError("wrong channel")
        if not protoutil.check_tx_id(
            up.channel_header.tx_id,
            up.signature_header.nonce,
            up.signature_header.creator,
        ):
            raise EndorserError("tx id does not bind to nonce+creator")
        try:
            creator = self._bundle.msp_manager.deserialize_identity(
                up.signature_header.creator
            )
            self._bundle.msp_manager.validate(creator)
        except Exception as exc:
            raise EndorserError(f"creator identity invalid: {exc}") from exc
        if not creator.verify(signed.proposal_bytes, signed.signature):
            raise EndorserError("invalid creator signature on proposal")
        self._check_acl(up, signed)

        # -- simulate ------------------------------------------------------
        cc = self._chaincodes.get(up.chaincode_name)
        if cc is None:
            raise EndorserError(f"chaincode {up.chaincode_name!r} not installed")
        sim = self._ledger.new_tx_simulator()
        status, message, payload = cc(sim, list(up.input.args))
        if status >= 400:
            # simulation failure: no endorsement, return the error response
            return proposal_response_pb2.ProposalResponse(
                response=proposal_pb2.Response(status=status, message=message)
            )
        results = sim.get_tx_simulation_results()

        # -- private-data handoff (endorser.go:220-240): cleartext
        # collection writes go to the transient store and eligible peers
        # BEFORE the endorsement is returned; only the hashed rwsets
        # ride the public response.  A failed handoff (e.g. a
        # collection's required_peer_count unmet) fails the endorsement,
        # as the reference does.
        pvt = (
            sim.get_pvt_simulation_results()
            if hasattr(sim, "get_pvt_simulation_results")
            else None
        )
        if pvt is not None and self._pvt_handoff is not None:
            try:
                self._pvt_handoff(up.channel_header.tx_id, pvt)
            except Exception as exc:
                raise EndorserError(
                    f"private data distribution failed: {exc}"
                ) from exc

        # -- endorse (default endorsement plugin) --------------------------
        return protoutil.create_proposal_response(
            up.proposal,
            results=results,
            events=b"",
            response=proposal_pb2.Response(status=status, message=message, payload=payload),
            chaincode_id=chaincode_pb2.ChaincodeID(name=up.chaincode_name),
            endorser_signer=self._signer,
        )


__all__ = ["Endorser", "EndorserError", "ACLDeniedError"]
