"""Block-validation pipeline benchmark (BASELINE.md configs #3/#4):
VALIDATED tx/s (no commit in the timed loop — bench.py owns the
committed-tx/s headline via Committer.store_stream) and per-block
validate latency for 1000-tx blocks at
1-of-1 and 3-of-5 endorsement, TPU batched verify vs host sw verify.

Prints one JSON line per configuration (bench.py stays the single-line
headline; this is the measurement matrix).
"""

from __future__ import annotations

import json
import os
import time



def _build_world(n_orgs: int):
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"),
    )
    from orgfix import make_org

    from fabric_tpu.common import configtx_builder as ctx
    from fabric_tpu.msp import msp_config_from_ca

    orgs = [make_org(f"Org{i+1}MSP") for i in range(n_orgs)]
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {
            f"Org{i+1}": ctx.org_group(
                o.mspid, msp_config_from_ca(o.ca, o.mspid)
            )
            for i, o in enumerate(orgs)
        }
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("benchch", ctx.channel_group(app, ordg))
    return orgs, genesis


def _make_blocks(orgs, genesis, csp, n_txs: int, endorsers: int,
                 n_blocks: int = 1):
    """`n_blocks` blocks of distinct endorsed txs (each endorsed by
    `endorsers` orgs)."""
    from fabric_tpu import protoutil
    from fabric_tpu.common.channelconfig import bundle_from_genesis
    from fabric_tpu.ledger import LedgerProvider
    from fabric_tpu.peer.endorser import Endorser
    from fabric_tpu.protos.common import common_pb2
    from fabric_tpu.protos.peer import proposal_pb2

    provider = LedgerProvider(None)
    ledger = provider.create(genesis)
    bundle = bundle_from_genesis(genesis, csp)

    def cc(sim, args):
        sim.set_state("benchcc", args[0].decode(), args[1])
        return 200, "", b""

    ends = [
        Endorser("benchch", ledger, bundle,
                 o.signer(f"peer{i}", role_ou="peer"), {"benchcc": cc}, csp)
        for i, o in enumerate(orgs[:endorsers])
    ]
    client = orgs[0].signer("client", role_ou="client")
    blocks = []
    for bno in range(n_blocks):
        envs = []
        for i in range(n_txs):
            prop, _ = protoutil.create_chaincode_proposal(
                client.serialize(), "benchch", "benchcc",
                [b"k%d-%d" % (bno, i), b"v%d" % i],
            )
            signed = proposal_pb2.SignedProposal(
                proposal_bytes=prop.SerializeToString(),
                signature=client.sign(prop.SerializeToString()),
            )
            resps = [e.process_proposal(signed) for e in ends]
            envs.append(protoutil.create_signed_tx(prop, client, resps))
        blk = common_pb2.Block()
        blk.header.number = 1 + bno
        blk.data.data.extend(e.SerializeToString() for e in envs)
        while len(blk.metadata.metadata) < 3:
            blk.metadata.metadata.append(b"")
        blocks.append(blk)
    return ledger, bundle, blocks


def bench_config(name: str, n_orgs: int, endorsers: int, n_txs: int,
                 repeats: int = 3):
    from fabric_tpu.csp import SWCSP
    from fabric_tpu.csp.tpu.provider import TPUCSP
    from fabric_tpu.peer.txvalidator import TxValidator
    from fabric_tpu.protos.common import common_pb2

    sw = SWCSP()
    n_blocks = 4
    orgs, genesis = _build_world(n_orgs)
    ledger, bundle, blocks = _make_blocks(
        orgs, genesis, sw, n_txs, endorsers, n_blocks
    )

    def copies(k):
        out = []
        for j in range(k):
            b = common_pb2.Block()
            b.CopyFrom(blocks[j % n_blocks])
            out.append(b)
        return out

    out = {"config": name, "txs": n_txs, "endorsements_per_tx": endorsers}
    for label, csp in (("sw", sw), ("tpu", TPUCSP(min_device_batch=1))):
        validator = TxValidator("benchch", ledger, bundle, csp)
        best = float("inf")
        for _ in range(repeats):
            (b,) = copies(1)
            t0 = time.perf_counter()
            flags = validator.validate(b)
            best = min(best, time.perf_counter() - t0)
            assert all(f == 0 for f in flags), "txs must validate"
        out[f"{label}_block_validate_s"] = round(best, 4)
        out[f"{label}_validated_tx_s"] = round(n_txs / best, 1)
        # steady-state throughput: a stream of distinct blocks through
        # the pipelined validator (collect(k+1) overlaps device
        # verify(k)); fresh validator per run so the pipeline's
        # duplicate-txid window starts empty.
        stream_best = float("inf")
        for _ in range(repeats):
            v2 = TxValidator("benchch", ledger, bundle, csp)
            bs = copies(n_blocks)
            t0 = time.perf_counter()
            for flags in v2.validate_pipeline(iter(bs), depth=3):
                assert all(f == 0 for f in flags)
            stream_best = min(stream_best, time.perf_counter() - t0)
        out[f"{label}_pipelined_tx_s"] = round(n_blocks * n_txs / stream_best, 1)
    out["speedup"] = round(
        out["tpu_validated_tx_s"] / out["sw_validated_tx_s"], 2
    )
    out["pipelined_speedup"] = round(
        out["tpu_pipelined_tx_s"] / out["sw_pipelined_tx_s"], 2
    )
    print(json.dumps(out))


def main():
    bench_config("1000tx_1of1", 1, 1, 1000)
    bench_config("1000tx_3of5", 5, 3, 1000)


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
