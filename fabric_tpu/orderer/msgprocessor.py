"""Broadcast-side message processing: classification + filter pipeline.

Reference: orderer/common/msgprocessor (standardchannel.go:100
ProcessNormalMsg runs the rule set; sigfilter.go evaluates the channel
Writers policy over the envelope signature; sizefilter.go enforces
absolute_max_bytes; expiration.go rejects expired creator certs;
maintenancefilter.go:31-44 gates consensus-type changes behind
STATE_MAINTENANCE and forbids type changes on entry/exit).

Config updates run the configtx engine (ProposeConfigUpdate), pass the
maintenance filter, and come back wrapped as an orderer-signed CONFIG
envelope for the consenter's configure() path — the reference's
StandardChannel.ProcessConfigUpdateMsg shape.
"""

from __future__ import annotations

import datetime
import enum

from cryptography import x509

from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.msp import identities_pb2
from fabric_tpu.protos.orderer import configuration_pb2 as orderer_cfg_pb2
from fabric_tpu.protoutil import SignedData

STATE_NORMAL = orderer_cfg_pb2.ConsensusType.STATE_NORMAL
STATE_MAINTENANCE = orderer_cfg_pb2.ConsensusType.STATE_MAINTENANCE


class Classification(enum.Enum):
    NORMAL = 0
    CONFIG_UPDATE = 1
    CONFIG = 2


class MsgProcessorError(Exception):
    pass


class StandardChannelProcessor:
    def __init__(self, channel_id: str, bundle, csp, signer=None):
        self.channel_id = channel_id
        self._bundle = bundle
        self._csp = csp
        self._signer = signer  # orderer identity wrapping CONFIG envelopes

    @property
    def bundle(self):
        return self._bundle

    def update_bundle(self, bundle) -> None:
        """Adopt the post-config-block resources (the reference swaps the
        channelconfig Bundle on the chain support after a config commit)."""
        self._bundle = bundle

    def in_maintenance(self) -> bool:
        oc = self._bundle.orderer_config
        return oc is not None and oc.consensus_state == STATE_MAINTENANCE

    def classify(self, env: common_pb2.Envelope) -> Classification:
        payload = common_pb2.Payload.FromString(env.payload)
        chdr = common_pb2.ChannelHeader.FromString(payload.header.channel_header)
        if chdr.type == common_pb2.CONFIG_UPDATE:
            return Classification.CONFIG_UPDATE
        if chdr.type == common_pb2.CONFIG:
            return Classification.CONFIG
        return Classification.NORMAL

    def process_normal_msg(self, env: common_pb2.Envelope) -> int:
        """Raises MsgProcessorError if rejected; returns the config sequence
        the message was validated against (for revalidation downstream)."""
        self._size_filter(env)
        payload = common_pb2.Payload.FromString(env.payload)
        chdr = common_pb2.ChannelHeader.FromString(payload.header.channel_header)
        if chdr.channel_id != self.channel_id:
            raise MsgProcessorError(
                f"message is for channel {chdr.channel_id!r}, this is {self.channel_id!r}"
            )
        shdr = common_pb2.SignatureHeader.FromString(payload.header.signature_header)
        self._expiration_filter(shdr.creator)
        self._sig_filter(env, shdr)
        return self._bundle.config.sequence

    def _size_filter(self, env: common_pb2.Envelope) -> None:
        oc = self._bundle.orderer_config
        size = len(env.SerializeToString())
        if oc and size > oc.absolute_max_bytes:
            raise MsgProcessorError(
                f"message size {size} exceeds absolute maximum {oc.absolute_max_bytes}"
            )

    def _expiration_filter(self, creator: bytes) -> None:
        try:
            sid = identities_pb2.SerializedIdentity.FromString(creator)
            certs = x509.load_pem_x509_certificates(sid.id_bytes)
        except Exception:
            return  # sig filter will reject undeserializable creators
        now = datetime.datetime.now(datetime.timezone.utc)
        if certs and certs[0].not_valid_after_utc < now:
            raise MsgProcessorError("creator certificate has expired")

    def _sig_filter(self, env: common_pb2.Envelope, shdr) -> None:
        # During maintenance the write gate tightens to the ORDERER
        # writers policy — application clients cannot submit while the
        # consensus type migrates (reference standardchannel.go NewSigFilter
        # with ChannelWriters/ChannelOrdererWriters pair).
        name = (
            "/Channel/Orderer/Writers"
            if self.in_maintenance()
            else "/Channel/Writers"
        )
        policy = self._bundle.policy_manager.get_policy(name)
        sd = [SignedData(env.payload, shdr.creator, env.signature)]
        if not policy.evaluate_signed_data(sd, self._csp):
            raise MsgProcessorError(
                f"message did not satisfy the {name} policy"
            )

    # -- config updates ----------------------------------------------------

    def process_config_update_msg(self, env: common_pb2.Envelope):
        """Run a CONFIG_UPDATE through the configtx engine + maintenance
        filter; returns (orderer-signed CONFIG envelope, config seq)
        for the consenter's configure() path (reference
        standardchannel.go ProcessConfigUpdateMsg)."""
        from fabric_tpu.common.configtx import ConfigtxValidator
        from fabric_tpu.protos.common import configtx_pb2
        from fabric_tpu import protoutil

        self._size_filter(env)
        payload = common_pb2.Payload.FromString(env.payload)
        chdr = common_pb2.ChannelHeader.FromString(
            payload.header.channel_header
        )
        if chdr.channel_id != self.channel_id:
            raise MsgProcessorError(
                f"config update for channel {chdr.channel_id!r}, "
                f"this is {self.channel_id!r}"
            )
        shdr = common_pb2.SignatureHeader.FromString(
            payload.header.signature_header
        )
        self._expiration_filter(shdr.creator)
        # same sigfilter pair as normal messages — during maintenance
        # this is the gate that keeps application admins from slipping
        # config updates into a live migration (reference applies the
        # filter chain to ProcessConfigUpdateMsg too)
        self._sig_filter(env, shdr)
        try:
            update_env = configtx_pb2.ConfigUpdateEnvelope.FromString(
                payload.data
            )
        except Exception as exc:
            raise MsgProcessorError(f"bad config update: {exc}") from exc
        validator = ConfigtxValidator(
            self.channel_id,
            self._bundle.config,
            policy_manager=self._bundle.policy_manager,
            csp=self._csp,
        )
        try:
            cfg_env = validator.propose_config_update(update_env)
        except Exception as exc:
            raise MsgProcessorError(str(exc)) from exc
        self._maintenance_filter(cfg_env.config)
        cfg_env.last_update.CopyFrom(env)
        if self._signer is None:
            # a creator-less CONFIG envelope would be committed with an
            # invalid tx flag downstream — fail loudly at the source
            raise MsgProcessorError(
                "node has no signing identity to wrap CONFIG envelopes"
            )
        import os

        creator = self._signer.serialize()
        payload_bytes = protoutil.make_payload_bytes(
            protoutil.make_channel_header(
                common_pb2.CONFIG, channel_id=self.channel_id
            ),
            protoutil.make_signature_header(creator, os.urandom(24)),
            cfg_env.SerializeToString(),
        )
        new_env = protoutil.make_envelope(payload_bytes, signer=self._signer)
        return new_env, self._bundle.config.sequence

    def _maintenance_filter(self, new_config) -> None:
        """Reference maintenancefilter.go:31-44 semantics: the consensus
        type may only change while the channel is in (and stays in)
        STATE_MAINTENANCE; entering or leaving maintenance must not
        itself change the type."""
        from fabric_tpu.common.channelconfig import Bundle

        cur = self._bundle.orderer_config
        if cur is None:
            return
        nxt = Bundle(
            self.channel_id, _config_copy(new_config), self._csp
        ).orderer_config
        if nxt is None:
            raise MsgProcessorError(
                "config update removes the Orderer group"
            )
        if cur.consensus_state == STATE_NORMAL:
            if nxt.consensus_type != cur.consensus_type:
                raise MsgProcessorError(
                    "attempted to change consensus type from "
                    f"{cur.consensus_type!r} to {nxt.consensus_type!r} "
                    "outside of maintenance mode"
                )
        else:  # currently in maintenance
            if (
                nxt.consensus_state == STATE_NORMAL
                and nxt.consensus_type != cur.consensus_type
            ):
                raise MsgProcessorError(
                    "attempted to change consensus type and exit "
                    "maintenance mode in the same update"
                )
            # While in maintenance, nothing OUTSIDE the Orderer group may
            # change (reference maintenancefilter.go ensureOnlyOrdererChange:
            # an admin must not slip Application/Consortiums edits into a
            # consensus migration window).
            cur_cg = _config_copy_group(self._bundle.config.channel_group)
            nxt_cg = _config_copy_group(new_config.channel_group)
            for cg in (cur_cg, nxt_cg):
                if "Orderer" in cg.groups:
                    del cg.groups["Orderer"]
            if cur_cg.SerializeToString(
                deterministic=True
            ) != nxt_cg.SerializeToString(deterministic=True):
                raise MsgProcessorError(
                    "config changes outside the Orderer group are not "
                    "permitted while the channel is in maintenance mode"
                )


def _config_copy(config):
    from fabric_tpu.protos.common import configtx_pb2

    out = configtx_pb2.Config()
    out.CopyFrom(config)
    return out


def _config_copy_group(group):
    from fabric_tpu.protos.common import configtx_pb2

    out = configtx_pb2.ConfigGroup()
    out.CopyFrom(group)
    return out


__all__ = [
    "StandardChannelProcessor",
    "MsgProcessorError",
    "Classification",
    "STATE_NORMAL",
    "STATE_MAINTENANCE",
]
