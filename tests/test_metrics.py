"""Prometheus exposition hardening (ISSUE 12 satellite): label-value
escaping, histogram bucket monotonicity + _sum/_count agreement, and
the netscope parser's expose -> parse -> samples round trip."""

from __future__ import annotations

import math
import re

from fabric_tpu.common.metrics import (
    CounterOpts,
    GaugeOpts,
    HistogramOpts,
    PrometheusProvider,
)
from fabric_tpu.devtools.netscope import parse_prometheus


def _sample_map(text):
    return {
        (name, labels): value
        for name, labels, value in parse_prometheus(text)
    }


class TestExpositionHardening:
    def test_label_value_escaping_round_trips(self):
        p = PrometheusProvider()
        g = p.new_gauge(GaugeOpts(namespace="t", name="g"))
        nasty = 'quote:" backslash:\\ newline:\nend'
        g.With("channel", nasty).set(3)
        text = p.registry.expose()
        # the exposition stays one-sample-per-line: the raw newline
        # must never split the sample line
        sample_lines = [
            ln for ln in text.splitlines()
            if ln.startswith("t_g{")
        ]
        assert len(sample_lines) == 1
        assert '\\"' in sample_lines[0]
        assert "\\n" in sample_lines[0]
        assert "\\\\" in sample_lines[0]
        samples = parse_prometheus(text)
        assert samples == [("t_g", (("channel", nasty),), 3.0)]

    def test_histogram_buckets_monotonic_and_sum_count_agree(self):
        p = PrometheusProvider()
        h = p.new_histogram(HistogramOpts(
            namespace="t", name="h", buckets=(0.1, 1.0, 10.0),
        ))
        hh = h.With("channel", "c1")
        observations = (0.05, 0.05, 0.5, 5.0, 50.0)  # one ABOVE +Inf
        for v in observations:
            hh.observe(v)
        text = p.registry.expose()
        buckets = {}
        for line in text.splitlines():
            m = re.match(r't_h_bucket\{.*le="([^"]+)"\} (\d+)', line)
            if m:
                buckets[m.group(1)] = int(m.group(2))
        # cumulative, monotone, exact
        assert buckets == {"0.1": 2, "1": 3, "10": 4, "+Inf": 5}
        counts = sorted(buckets.values())
        assert counts == [buckets["0.1"], buckets["1"], buckets["10"],
                          buckets["+Inf"]]
        samples = _sample_map(text)
        labels = (("channel", "c1"),)
        assert samples[("t_h_count", labels)] == len(observations)
        assert math.isclose(
            samples[("t_h_sum", labels)], sum(observations)
        )
        # every rendered bucket count is <= _count (the old exposition
        # double-cumulated: a single small observation rendered bucket
        # counts LARGER than _count)
        assert max(buckets.values()) <= samples[("t_h_count", labels)]

    def test_single_small_observation_regression(self):
        """One observation below every bucket used to render bucket
        counts 1,2,3,... (every bucket incremented AND re-cumulated at
        exposition) — non-monotonic against _bucket{+Inf} = 1."""
        p = PrometheusProvider()
        h = p.new_histogram(HistogramOpts(
            namespace="t", name="h1", buckets=(1, 2, 3),
        ))
        h.observe(0.5)
        text = p.registry.expose()
        vals = [
            int(m.group(1))
            for m in re.finditer(r"t_h1_bucket\{[^}]*\} (\d+)", text)
        ]
        assert vals == [1, 1, 1, 1]  # le=1, le=2, le=3, +Inf

    def test_parser_round_trip_is_value_faithful(self):
        """expose -> parse -> samples carries every series, labelset,
        and value exactly (the netscope scraper's fidelity contract)."""
        p = PrometheusProvider()
        c = p.new_counter(CounterOpts(
            namespace="ledger", name="transactions_total",
            help="help text with spaces # and hash",
        ))
        c.With("channel", "ch1").add(7)
        c.With("channel", "ch2").add(0.5)
        g = p.new_gauge(GaugeOpts(namespace="ledger", name="height"))
        g.With("channel", "ch1").set(42)
        g2 = p.new_gauge(GaugeOpts(namespace="gossip",
                                   name="membership_size"))
        g2.set(3)  # label-free sample line
        h = p.new_histogram(HistogramOpts(
            namespace="v", name="lat", buckets=(0.5, 2.0),
        ))
        h.With("stage", "collect").observe(0.25)
        h.With("stage", "collect").observe(1.5)
        samples = _sample_map(p.registry.expose())
        assert samples[
            ("ledger_transactions_total", (("channel", "ch1"),))
        ] == 7.0
        assert samples[
            ("ledger_transactions_total", (("channel", "ch2"),))
        ] == 0.5
        assert samples[("ledger_height", (("channel", "ch1"),))] == 42.0
        assert samples[("gossip_membership_size", ())] == 3.0
        st = (("stage", "collect"),)
        assert samples[("v_lat_count", st)] == 2.0
        assert math.isclose(samples[("v_lat_sum", st)], 1.75)
        assert samples[
            ("v_lat_bucket", (("le", "0.5"), ("stage", "collect")))
        ] == 1.0
        assert samples[
            ("v_lat_bucket", (("le", "2"), ("stage", "collect")))
        ] == 2.0
        assert samples[
            ("v_lat_bucket", (("le", "+Inf"), ("stage", "collect")))
        ] == 2.0

    def test_parser_skips_malformed_lines(self):
        text = (
            "# HELP x y\n# TYPE x counter\n"
            "x 1\n"
            "not a sample line at all with words\n"
            "y{a=\"b\"} notafloat\n"
            "z{a=\"b\"} 2\n"
        )
        assert parse_prometheus(text) == [
            ("x", (), 1.0),
            ("z", (("a", "b"),), 2.0),
        ]
