"""Native (C++) host-side components, loaded via ctypes.

`marshal_batch` is the batch signature marshaller feeding the TPU verify
kernel (SURVEY.md §7 native-components policy).  The shared library is
compiled on first use with the system g++ and cached next to the source;
callers fall back to the pure-Python path when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "marshal.cc")
_LIB = os.path.join(_DIR, "libfabricmarshal.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            ):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_LIB)
            fn = lib.fabric_marshal_batch
            fn.restype = ctypes.c_int
            fn.argtypes = [
                ctypes.c_int,
                ctypes.c_char_p,  # xs
                ctypes.c_char_p,  # ys
                ctypes.c_char_p,  # digests
                ctypes.c_char_p,  # sigs
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # qx
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # qy
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # d1
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # d2
                np.ctypeslib.ndpointer(np.uint32, flags="C"),  # c0
                np.ctypeslib.ndpointer(np.uint8, flags="C"),   # c1ok
                np.ctypeslib.ndpointer(np.uint8, flags="C"),   # valid
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def marshal_batch(xs: bytes, ys: bytes, digests: bytes, sigs: bytes,
                  sig_off: np.ndarray) -> dict | None:
    """One pass: DER parse + prechecks + batch inversion + packing.
    Inputs: concatenated 32-byte big-endian x/y/digest buffers and
    concatenated DER signatures with (n+1,) int32 offsets.  Returns the
    packed dict fabric_tpu.csp.tpu.pallas_ec.verify_packed consumes, or
    None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(sig_off) - 1
    qx = np.empty((8, n), np.uint32)
    qy = np.empty((8, n), np.uint32)
    d1 = np.empty((8, n), np.uint32)
    d2 = np.empty((8, n), np.uint32)
    c0 = np.empty((8, n), np.uint32)
    c1ok = np.empty(n, np.uint8)
    valid = np.empty(n, np.uint8)
    lib.fabric_marshal_batch(
        n, xs, ys, digests, sigs, np.ascontiguousarray(sig_off, np.int32),
        qx, qy, d1, d2, c0, c1ok, valid,
    )
    return {
        "qx": qx,
        "qy": qy,
        "d1": d1,
        "d2": d2,
        "cand0": c0,
        # c1 (r+n words) is no longer shipped: the kernel rebuilds cand1
        # on-device from cand0; only the admissibility flag travels.
        "cand1_ok": c1ok.astype(bool),
        "valid": valid.astype(bool),
    }


__all__ = ["available", "marshal_batch"]
