"""Seeded violation: the worker reads the guarded field lock-free and
WITHOUT a publication edge (no Event wait, no queue get), so the
inferred guard is really missed — racecheck fires exactly as in v3.
The clean twin adds the set()->wait() / put()->get() edges and v4
credits them."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


def use(x):
    return x


class Feed:
    def __init__(self):
        self._lock = named_lock("fixture.feed")
        self._snapshot = None
        self._thread = spawn_thread(
            target=self._consume, name="feed", kind="worker"
        )

    def start(self):
        self._thread.start()

    def refresh(self, rows):
        with self._lock:
            self._snapshot = rows

    def peek(self):
        with self._lock:
            return self._snapshot

    def _consume(self):
        use(self._snapshot)  # <- racecheck fires HERE
