"""CLEAN TWIN of fix_race_lockvar_dirty: the bare local alias binds the
CORRECT guard lock — the alias resolves to the field's role, so the
scope counts as guarded instead of degrading to UNKNOWN."""

from fabric_tpu.devtools.lockwatch import named_lock, spawn_thread


class SessionTable:
    def __init__(self):
        self._lock = named_lock("fixture.sessions")
        self._aux = named_lock("fixture.sessions.aux")
        self._sessions = {}

    def start(self):
        t = spawn_thread(
            target=self._expire, name="fixture-expire", kind="worker"
        )
        t.start()
        return t

    def _expire(self):
        lock = self._lock
        with lock:
            self._sessions["expired"] = True

    def put(self, key, value):
        with self._lock:
            self._sessions[key] = value

    def get(self, key):
        with self._lock:
            return self._sessions.get(key)
