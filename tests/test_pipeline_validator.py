"""validate_pipeline: ordered flags identical to sequential validate,
with duplicate-txid detection spanning in-flight blocks."""

from __future__ import annotations

import pytest

from orgfix import make_org

from fabric_tpu import protoutil
from fabric_tpu.common import configtx_builder as ctx
from fabric_tpu.common.channelconfig import bundle_from_genesis
from fabric_tpu.ledger import LedgerProvider
from fabric_tpu.msp import msp_config_from_ca
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.peer.txvalidator import TxValidator
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.peer import proposal_pb2, transaction_pb2

V = transaction_pb2


def _cc(sim, args):
    sim.set_state("pipecc", args[0].decode(), args[1])
    return 200, "", b""


@pytest.fixture(scope="module")
def world():
    org = make_org("Org1MSP")
    oorg = make_org("OrdererMSP")
    app = ctx.application_group(
        {"Org1": ctx.org_group("Org1MSP", msp_config_from_ca(org.ca, "Org1MSP"))}
    )
    ordg = ctx.orderer_group(
        {"O": ctx.org_group("OrdererMSP", msp_config_from_ca(oorg.ca, "OrdererMSP"))},
        consensus_type="solo",
    )
    genesis = ctx.genesis_block("pipech", ctx.channel_group(app, ordg))
    provider = LedgerProvider(None)
    ledger = provider.create(genesis)
    bundle = bundle_from_genesis(genesis, org.csp)
    endorser = Endorser(
        "pipech", ledger, bundle, org.signer("peer0", role_ou="peer"),
        {"pipecc": _cc}, org.csp,
    )
    client = org.signer("user1", role_ou="client")
    return org, ledger, bundle, endorser, client


def _tx(endorser, client, key: bytes, val: bytes):
    prop, txid = protoutil.create_chaincode_proposal(
        client.serialize(), "pipech", "pipecc", [key, val]
    )
    signed = proposal_pb2.SignedProposal(
        proposal_bytes=prop.SerializeToString(),
        signature=client.sign(prop.SerializeToString()),
    )
    resp = endorser.process_proposal(signed)
    return protoutil.create_signed_tx(prop, client, [resp])


def _block(num: int, envs) -> common_pb2.Block:
    blk = common_pb2.Block()
    blk.header.number = num
    blk.data.data.extend(e.SerializeToString() for e in envs)
    while len(blk.metadata.metadata) < 3:
        blk.metadata.metadata.append(b"")
    return blk


def test_pipeline_matches_sequential(world):
    org, ledger, bundle, endorser, client = world
    blocks = []
    for b in range(3):
        envs = []
        for i in range(4):
            env = _tx(endorser, client, b"k%d-%d" % (b, i), b"v")
            if i == 2:  # tamper one creator signature per block
                env = common_pb2.Envelope(
                    payload=env.payload, signature=env.signature[:-2] + b"xx"
                )
            envs.append(env)
        blocks.append(_block(b + 1, envs))

    def copies():
        out = []
        for blk in blocks:
            c = common_pb2.Block()
            c.CopyFrom(blk)
            out.append(c)
        return out

    seq = [
        TxValidator("pipech", ledger, bundle, org.csp).validate(b)
        for b in copies()
    ]
    piped = list(
        TxValidator("pipech", ledger, bundle, org.csp).validate_pipeline(
            copies(), depth=2
        )
    )
    assert piped == seq
    for flags in piped:
        assert flags[2] == V.BAD_CREATOR_SIGNATURE
        assert [flags[0], flags[1], flags[3]] == [V.VALID] * 3


def test_pipeline_catches_cross_block_duplicate_txid(world):
    org, ledger, bundle, endorser, client = world
    env = _tx(endorser, client, b"dupkey", b"v")
    b1 = _block(10, [env])
    b2 = _block(11, [env])  # same envelope (same txid) in the next block
    piped = list(
        TxValidator("pipech", ledger, bundle, org.csp).validate_pipeline(
            [b1, b2], depth=2
        )
    )
    assert piped[0] == [V.VALID]
    assert piped[1] == [V.DUPLICATE_TXID]
