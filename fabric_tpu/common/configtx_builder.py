"""Channel config-tree construction (configtxgen's encoder core).

Reference: internal/configtxgen/encoder (NewChannelGroup/NewOrdererGroup/
NewApplicationGroup build the ConfigGroup tree from configtx.yaml
profiles) + protoutil genesis assembly.  This is the programmatic
equivalent; the configtxgen CLI feeds parsed YAML profiles into it.
"""

from __future__ import annotations

from fabric_tpu.protos.common import common_pb2, configtx_pb2, configuration_pb2, policies_pb2
from fabric_tpu.protos.msp import msp_config_pb2
from fabric_tpu.protos.orderer import configuration_pb2 as orderer_config_pb2
from fabric_tpu import protoutil
from fabric_tpu.policies import from_string

# config value keys (reference common/channelconfig/*.go key constants)
MSP_KEY = "MSP"
HASHING_ALGORITHM_KEY = "HashingAlgorithm"
BLOCK_DATA_HASHING_STRUCTURE_KEY = "BlockDataHashingStructure"
ORDERER_ADDRESSES_KEY = "OrdererAddresses"
CONSENSUS_TYPE_KEY = "ConsensusType"
BATCH_SIZE_KEY = "BatchSize"
BATCH_TIMEOUT_KEY = "BatchTimeout"
CONSORTIUM_KEY = "Consortium"
ENDORSEMENT_POLICY_KEY = "Endorsement"
ACLS_KEY = "ACLs"


def _implicit_meta(group: configtx_pb2.ConfigGroup, name: str, rule, sub_policy: str | None = None):
    group.policies[name].policy.type = policies_pb2.Policy.IMPLICIT_META
    group.policies[name].policy.value = policies_pb2.ImplicitMetaPolicy(
        sub_policy=sub_policy or name, rule=rule
    ).SerializeToString()
    group.policies[name].mod_policy = "Admins"


def _signature_policy(group: configtx_pb2.ConfigGroup, name: str, dsl: str):
    group.policies[name].policy.type = policies_pb2.Policy.SIGNATURE
    group.policies[name].policy.value = from_string(dsl).SerializeToString()
    group.policies[name].mod_policy = "Admins"


def _set_value(group: configtx_pb2.ConfigGroup, key: str, msg, mod_policy="Admins"):
    group.values[key].value = msg.SerializeToString()
    group.values[key].mod_policy = mod_policy


def org_group(mspid: str, msp_conf: msp_config_pb2.MSPConfig, anchor=None) -> configtx_pb2.ConfigGroup:
    """An application/orderer org group: MSP value + org-scoped policies
    (reference encoder.NewOrdererOrgGroup / NewApplicationOrgGroup)."""
    g = configtx_pb2.ConfigGroup()
    g.mod_policy = "Admins"
    _set_value(g, MSP_KEY, msp_conf)
    _signature_policy(g, "Readers", f"'{mspid}.member'")
    _signature_policy(g, "Writers", f"'{mspid}.member'")
    _signature_policy(g, "Admins", f"'{mspid}.admin'")
    _signature_policy(g, ENDORSEMENT_POLICY_KEY, f"'{mspid}.peer'")
    return g


def application_group(
    orgs: dict[str, configtx_pb2.ConfigGroup],
    acls: dict[str, str] | None = None,
) -> configtx_pb2.ConfigGroup:
    """`acls` maps resource names (peer/aclmgmt catalog) to policy refs,
    emitted as the Application ACLs config value (reference
    encoder.NewApplicationGroup addValue(ACLValues), consumed by
    aclmgmt's resourceprovider)."""
    g = configtx_pb2.ConfigGroup()
    g.mod_policy = "Admins"
    R = policies_pb2.ImplicitMetaPolicy
    _implicit_meta(g, "Readers", R.ANY)
    _implicit_meta(g, "Writers", R.ANY)
    _implicit_meta(g, "Admins", R.MAJORITY)
    _implicit_meta(g, "Endorsement", R.MAJORITY, sub_policy=ENDORSEMENT_POLICY_KEY)
    _implicit_meta(g, "LifecycleEndorsement", R.MAJORITY, sub_policy=ENDORSEMENT_POLICY_KEY)
    if acls:
        from fabric_tpu.protos.peer import configuration_pb2 as peer_cfg

        msg = peer_cfg.ACLs()
        for name, ref in acls.items():
            msg.acls[name].policy_ref = ref
        _set_value(g, ACLS_KEY, msg)
    for name, org in orgs.items():
        g.groups[name].CopyFrom(org)
    return g


def orderer_group(
    orgs: dict[str, configtx_pb2.ConfigGroup],
    consensus_type: str = "solo",
    consensus_metadata: bytes = b"",
    max_message_count: int = 500,
    absolute_max_bytes: int = 10 * 1024 * 1024,
    preferred_max_bytes: int = 2 * 1024 * 1024,
    batch_timeout: str = "2s",
) -> configtx_pb2.ConfigGroup:
    g = configtx_pb2.ConfigGroup()
    g.mod_policy = "Admins"
    R = policies_pb2.ImplicitMetaPolicy
    _implicit_meta(g, "Readers", R.ANY)
    _implicit_meta(g, "Writers", R.ANY)
    _implicit_meta(g, "Admins", R.MAJORITY)
    _implicit_meta(g, "BlockValidation", R.ANY, sub_policy="Writers")
    _set_value(
        g, CONSENSUS_TYPE_KEY,
        orderer_config_pb2.ConsensusType(type=consensus_type, metadata=consensus_metadata),
    )
    _set_value(
        g, BATCH_SIZE_KEY,
        orderer_config_pb2.BatchSize(
            max_message_count=max_message_count,
            absolute_max_bytes=absolute_max_bytes,
            preferred_max_bytes=preferred_max_bytes,
        ),
    )
    _set_value(g, BATCH_TIMEOUT_KEY, orderer_config_pb2.BatchTimeout(timeout=batch_timeout))
    for name, org in orgs.items():
        g.groups[name].CopyFrom(org)
    return g


def channel_group(
    application: configtx_pb2.ConfigGroup | None,
    orderer: configtx_pb2.ConfigGroup | None,
    orderer_addresses: list[str] | None = None,
) -> configtx_pb2.ConfigGroup:
    g = configtx_pb2.ConfigGroup()
    g.mod_policy = "Admins"
    R = policies_pb2.ImplicitMetaPolicy
    _implicit_meta(g, "Readers", R.ANY)
    _implicit_meta(g, "Writers", R.ANY)
    _implicit_meta(g, "Admins", R.MAJORITY)
    _set_value(g, HASHING_ALGORITHM_KEY, configuration_pb2.HashingAlgorithm(name="SHA256"))
    _set_value(
        g, BLOCK_DATA_HASHING_STRUCTURE_KEY,
        configuration_pb2.BlockDataHashingStructure(width=0xFFFFFFFF),
    )
    if orderer_addresses:
        _set_value(
            g, ORDERER_ADDRESSES_KEY,
            configuration_pb2.OrdererAddresses(addresses=orderer_addresses),
            mod_policy="/Channel/Orderer/Admins",
        )
    if application is not None:
        g.groups["Application"].CopyFrom(application)
    if orderer is not None:
        g.groups["Orderer"].CopyFrom(orderer)
    return g


def genesis_block(channel_id: str, group: configtx_pb2.ConfigGroup) -> common_pb2.Block:
    """Block 0 wrapping the CONFIG envelope (reference protoutil genesis +
    encoder.NewBootstrapper)."""
    config_env = configtx_pb2.ConfigEnvelope(
        config=configtx_pb2.Config(sequence=0, channel_group=group)
    )
    chdr = protoutil.make_channel_header(common_pb2.CONFIG, channel_id, tx_id="")
    shdr = protoutil.make_signature_header(b"", protoutil.random_nonce())
    payload = protoutil.make_payload_bytes(chdr, shdr, config_env.SerializeToString())
    env = common_pb2.Envelope(payload=payload)
    blk = protoutil.new_block(0, b"")
    blk.data.data.append(env.SerializeToString())
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    protoutil.set_tx_filter(blk, b"\x00")
    return blk


__all__ = [
    "org_group",
    "application_group",
    "orderer_group",
    "channel_group",
    "genesis_block",
    "MSP_KEY",
    "CONSENSUS_TYPE_KEY",
    "BATCH_SIZE_KEY",
    "BATCH_TIMEOUT_KEY",
    "ENDORSEMENT_POLICY_KEY",
    "ACLS_KEY",
]
