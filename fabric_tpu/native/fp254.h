// Shared BN254 base-field layer: Montgomery Fp arithmetic over
// p = 21888242871839275222246405745257275088696311157297823662689037894645226208583
// (alt-bn128).  Single source of truth for the curve constants and the
// reduction code — included by bn254.cc (G1 scalar ops) and pairing.cc
// (tower/pairing); everything is `inline` so both TUs share one
// definition set with no ODR risk.

#ifndef FABRIC_TPU_NATIVE_FP254_H_
#define FABRIC_TPU_NATIVE_FP254_H_

#include <cstdint>
#include <cstring>

namespace fp254 {

typedef uint8_t u8;
typedef uint64_t u64;
typedef unsigned __int128 u128;

// little-endian 64-bit limbs
inline const u64 PRIME[4] = {0x3c208c16d87cfd47ULL, 0x97816a916871ca8dULL,
                             0xb85045b68181585dULL, 0x30644e72e131a029ULL};
inline const u64 N0INV = 0x87d20782e4866389ULL;  // -P^-1 mod 2^64
inline const u64 R2[4] = {0xf32cfc5b538afa89ULL, 0xb5e71911d44501fbULL,
                          0x47ab1eff0a417ff6ULL, 0x06d89f71cab8351fULL};
inline const u64 ONE_M[4] = {0xd35d438dc58f0d9dULL, 0x0a78eb28f5c70b3dULL,
                             0x666ea36f7879462cULL, 0x0e0a77c19a07df2fULL};

struct Fp {
  u64 v[4];
};

inline bool fp_is_zero(const Fp& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

inline int cmp_p(const u64* a) {
  for (int i = 3; i >= 0; --i)
    if (a[i] != PRIME[i]) return a[i] < PRIME[i] ? -1 : 1;
  return 0;
}

inline void sub_p(u64* a) {  // a -= P (caller ensures a >= P)
  u128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a[i] - PRIME[i] - (u64)borrow;
    a[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

inline void fp_add(const Fp& a, const Fp& b, Fp* out) {
  u128 carry = 0;
  u64 t[4];
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a.v[i] + b.v[i] + (u64)carry;
    t[i] = (u64)s;
    carry = s >> 64;
  }
  if (carry || cmp_p(t) >= 0) sub_p(t);
  memcpy(out->v, t, sizeof(t));
}

inline void fp_sub(const Fp& a, const Fp& b, Fp* out) {
  u128 borrow = 0;
  u64 t[4];
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - b.v[i] - (u64)borrow;
    t[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {  // += P
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
      u128 s = (u128)t[i] + PRIME[i] + (u64)carry;
      t[i] = (u64)s;
      carry = s >> 64;
    }
  }
  memcpy(out->v, t, sizeof(t));
}

inline void fp_neg(const Fp& a, Fp* out) {
  Fp z = {{0, 0, 0, 0}};
  fp_sub(z, a, out);
}

inline void fp_dbl(const Fp& a, Fp* out) { fp_add(a, a, out); }

// Montgomery CIOS multiplication: out = a*b*R^-1 mod P.
inline void fp_mul(const Fp& a, const Fp& b, Fp* out) {
  u64 t[5] = {0, 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 s = (u128)a.v[i] * b.v[j] + t[j] + (u64)carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u64 t4 = t[4] + (u64)carry;
    u64 m = t[0] * N0INV;
    carry = ((u128)m * PRIME[0] + t[0]) >> 64;
    for (int j = 1; j < 4; ++j) {
      u128 s = (u128)m * PRIME[j] + t[j] + (u64)carry;
      t[j - 1] = (u64)s;
      carry = s >> 64;
    }
    u128 s = (u128)t4 + (u64)carry;
    t[3] = (u64)s;
    t[4] = (u64)(s >> 64);
  }
  if (t[4] || cmp_p(t) >= 0) sub_p(t);
  memcpy(out->v, t, 4 * sizeof(u64));
}

inline void fp_sqr(const Fp& a, Fp* out) { fp_mul(a, a, out); }

// Montgomery inversion via Fermat: a^(P-2) (P odd and > 2: no borrow).
inline void fp_inv(const Fp& a, Fp* out) {
  u64 e[4];
  memcpy(e, PRIME, sizeof(e));
  e[0] -= 2;
  Fp result;
  bool started = false;
  for (int limb = 3; limb >= 0; --limb)
    for (int bit = 63; bit >= 0; --bit) {
      if (started) fp_sqr(result, &result);
      if ((e[limb] >> bit) & 1) {
        if (!started) {
          result = a;
          started = true;
        } else {
          fp_mul(result, a, &result);
        }
      }
    }
  *out = result;
}

inline void to_mont(const Fp& a, Fp* out) {
  Fp r2;
  memcpy(r2.v, R2, sizeof(R2));
  fp_mul(a, r2, out);
}

inline void from_mont(const Fp& a, Fp* out) {
  Fp one = {{1, 0, 0, 0}};
  fp_mul(a, one, out);
}

inline void load_fp_be(const u8* be, Fp* out) {
  for (int i = 0; i < 4; ++i) {
    u64 v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | be[(3 - i) * 8 + j];
    out->v[i] = v;
  }
}

inline void store_fp_be(const Fp& a, u8* be) {
  for (int i = 0; i < 4; ++i) {
    u64 v = a.v[3 - i];
    for (int j = 0; j < 8; ++j) be[i * 8 + j] = (u8)(v >> (56 - 8 * j));
  }
}

}  // namespace fp254

#endif  // FABRIC_TPU_NATIVE_FP254_H_
