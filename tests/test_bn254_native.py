"""Native BN254 G1 backend (native/bn254.cc): parity with the pure-
Python affine implementation on random, infinity, and edge inputs."""

import random

import pytest

from fabric_tpu import native
from fabric_tpu.idemix import bn254 as bn

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)

RNG = random.Random(99)


def _rand_points(n):
    return [bn._g1_mul_py(bn.G1_GEN, bn.rand_zr(RNG)) for _ in range(n)]


def test_msm_parity():
    pts = _rand_points(6)
    ks = [bn.rand_zr(RNG) for _ in range(6)]
    ref = None
    for p, k in zip(pts, ks):
        ref = bn.g1_add(ref, bn._g1_mul_py(p, k))
    assert native.bn254_msm(pts, ks) == ref


def test_msm_edge_scalars():
    p = _rand_points(1)[0]
    # k = 0, 1, R-1, R, R+5 (reduction mod R)
    for k in (0, 1, bn.R - 1, bn.R, bn.R + 5):
        ref = bn._g1_mul_py(p, k)
        assert native.bn254_msm([p], [k]) == ref


def test_msm_infinity_paths():
    p = _rand_points(1)[0]
    # cancellation -> infinity
    assert native.bn254_msm([p, bn.g1_neg(p)], [7, 7]) is None
    # infinity input skipped
    assert native.bn254_msm([None, p], [3, 2]) == bn._g1_mul_py(p, 2)
    # empty
    assert native.bn254_msm([], []) is None


def test_mul_many_parity():
    pts = _rand_points(5) + [None]
    ks = [bn.rand_zr(RNG) for _ in range(5)] + [11]
    ref = [bn._g1_mul_py(p, k) if p else None for p, k in zip(pts, ks)]
    assert native.bn254_mul_many(pts, ks) == ref


def test_doubling_chain_parity():
    # repeated doubling exercises g1_dbl + the add h==0 branch
    p = _rand_points(1)[0]
    assert native.bn254_msm([p, p], [3, 3]) == bn._g1_mul_py(p, 6)
    assert native.bn254_msm([p], [2]) == bn.g1_add(p, p)


def test_pairing_check_bilinearity():
    a, b = bn.rand_zr(RNG), bn.rand_zr(RNG)
    p1 = bn._g1_mul_py(bn.G1_GEN, a)
    q1 = bn.g2_mul(bn.G2_GEN, b)
    p2 = bn.g1_neg(bn._g1_mul_py(bn.G1_GEN, a * b % bn.R))
    assert native.bn254_pairing_check([(p1, q1), (p2, bn.G2_GEN)])
    # python oracle agrees
    assert bn.multi_pairing([(p1, q1), (p2, bn.G2_GEN)]) == bn.FP12_ONE
    # tampered pair fails
    assert not native.bn254_pairing_check([(p1, q1), (bn.g1_neg(p1), bn.G2_GEN)])


def test_pairing_check_identity_inputs():
    p = bn._g1_mul_py(bn.G1_GEN, 5)
    # infinity on either side contributes the identity factor
    assert native.bn254_pairing_check([(None, bn.G2_GEN)])
    assert native.bn254_pairing_check([(p, None)])
    assert native.bn254_pairing_check([])
    # a single non-degenerate pairing is NOT one
    assert not native.bn254_pairing_check([(p, bn.G2_GEN)])


def test_pairing_check_three_way_split():
    # e(aG,bQ) e(bG,cQ) e(-G, (ab+bc)Q) == 1
    a, b, c = (bn.rand_zr(RNG) for _ in range(3))
    pairs = [
        (bn._g1_mul_py(bn.G1_GEN, a), bn.g2_mul(bn.G2_GEN, b)),
        (bn._g1_mul_py(bn.G1_GEN, b), bn.g2_mul(bn.G2_GEN, c)),
        (bn.g1_neg(bn.G1_GEN), bn.g2_mul(bn.G2_GEN, (a * b + b * c) % bn.R)),
    ]
    assert native.bn254_pairing_check(pairs)
