"""Block storage: preallocated-segment block files + KV index.

Reference: common/ledger/blkstorage (blockfile_mgr.go append-only files,
blockindex.go number/hash/txid indexes, restart recovery via checkpoint +
tail scan, blocks_itr.go iterators).  Same design: length-prefixed
serialized blocks in rolling .dat files, an index in the KVStore SPI, and
crash recovery that re-indexes complete trailing records and erases a
torn final write.  `dir=None` keeps blocks in memory (test/ephemeral
ledgers, the reference's ramledger role).

Storage engine v2 (the segment writer): each .dat file is PREALLOCATED
to a fixed segment size (fallocate-style, FABRIC_TPU_STORE_SEGMENT) when
it is created — a temp-file + rename + directory fsync, the only
metadata fsync on the commit path.  Records then land INSIDE already-
allocated space at the checkpoint offset, so the group-boundary
durability barrier is fdatasync (data pages only; the inode's size never
moves per append), not the grow-on-append fsync stream the v1 writer
paid.  A zero length-header marks the clean preallocated tail during
recovery; segment roll trims the sealed file to its data and starts the
next preallocated segment.
"""

from __future__ import annotations

import os
import struct
import threading

from fabric_tpu.devtools import faultline, knob_registry
from fabric_tpu.ledger.kvstore import KVStore, MemKVStore, NamedDB
from fabric_tpu.protos.common import common_pb2
from fabric_tpu import protoutil

_LEN = struct.Struct(">I")

DEFAULT_SEGMENT = 16 * 1024 * 1024
_MIN_SEGMENT = 4096


def segment_size(override: int | None = None) -> int:
    """FABRIC_TPU_STORE_SEGMENT: block-file segment prealloc size in
    bytes (k/m suffixes accepted, e.g. ``64k`` / ``16m``; default
    16 MiB, floor 4 KiB).  Larger segments amortize the prealloc +
    rename metadata cost over more blocks; smaller ones bound the zero
    tail a mostly-idle channel keeps on disk."""
    if override is not None:
        return max(_MIN_SEGMENT, int(override))
    raw = knob_registry.raw("FABRIC_TPU_STORE_SEGMENT").strip().lower()
    if not raw:
        return DEFAULT_SEGMENT
    mult = 1
    if raw.endswith("k"):
        mult, raw = 1024, raw[:-1]
    elif raw.endswith("m"):
        mult, raw = 1024 * 1024, raw[:-1]
    try:
        n = int(raw) * mult
    except ValueError:
        raise ValueError(
            f"FABRIC_TPU_STORE_SEGMENT={raw!r} is not a byte size "
            "(integer, optionally with a k/m suffix)"
        ) from None
    return max(_MIN_SEGMENT, n)

# bootstrap-from-snapshot info: ">Q" last snapshot block number + its
# header hash (reference blkstorage bootstrappingSnapshotInfo)
_BSI_KEY = b"bsi"
# the channel's config block bytes for ledgers bootstrapped without
# blocks (join-by-snapshot peers rebuild their channel bundle from this)
_CFG_KEY = b"cfg"
# txid-index sentinel for transactions that predate the snapshot: the
# txid exists (duplicate-tx guard) but no block location does
_SNAPSHOT_TX_LOC = struct.pack(">QQ", 0xFFFFFFFFFFFFFFFF, 0xFFFFFFFFFFFFFFFF)


class BlockStoreError(Exception):
    pass


def _bsi_height(raw: bytes | None) -> int:
    return 0 if raw is None else struct.unpack(">Q", raw[:8])[0] + 1


def read_bootstrap_height(index_store: KVStore, name: str) -> int:
    """Snapshot-bootstrap height straight from a store's index WITHOUT
    constructing the BlockStore (no recovery file scan, no checkpoint
    write) — the cheap probe the repair-op guards use."""
    return _bsi_height(NamedDB(index_store, f"blkindex/{name}").get(_BSI_KEY))


class BlockStore:
    def __init__(self, dir: str | None, index_store: KVStore | None = None,
                 name: str = "chain", segment: int | None = None):
        self._dir = dir
        self._index = NamedDB(index_store or MemKVStore(), f"blkindex/{name}")
        self._lock = threading.RLock()
        self._mem_blocks: list[bytes] | None = [] if dir is None else None
        self._height = 0
        self._last_hash = b""
        self._segment = segment_size(segment)
        # cached writer handle for the active segment (r+b: writes land
        # inside preallocated space at the checkpoint offset)
        self._fh = None
        self._fh_idx = -1
        if dir is not None:
            os.makedirs(dir, exist_ok=True)
            self._recover()
        else:
            self._recover_index_only()

    # -- file plumbing -----------------------------------------------------

    def _file_path(self, idx: int) -> str:
        return os.path.join(self._dir, f"blocks_{idx:06d}.dat")

    def _checkpoint(self, index=None) -> tuple[int, int, int]:
        """(file_idx, offset_after_last_indexed, height); `index` may be
        an overlay view so grouped commits see their own buffered
        checkpoint advance."""
        raw = (index or self._index).get(b"cp")
        if raw is None:
            return (0, 0, 0)
        return struct.unpack(">QQQ", raw)  # type: ignore[return-value]

    def _recover_index_only(self) -> None:
        _, _, self._height = self._checkpoint()
        if self._height and not self._last_hash:
            raw = self._index.get(_BSI_KEY)
            if raw is not None:
                self._last_hash = raw[8:]

    def _recover(self) -> None:
        """Re-index any blocks appended after the last checkpoint; erase
        from the first damaged record on (reference blockfile_helper
        scanForLastCompleteBlock).  Group commits append several records
        between data barriers, so a crash can tear a NON-tail record
        (writeback order is not guaranteed): any record that fails to
        parse, or whose number breaks the contiguous chain (a hole's
        garbage can "parse" — e.g. zeroed pages decode to an empty
        block 0), ends the replayable prefix — everything from there on
        was never acknowledged durable and is dropped.  A ZERO length
        header is the clean preallocated tail (fallocated space no
        record ever reached), not damage: the scan stops there without
        erasing anything."""
        file_idx, offset, height = self._checkpoint()
        self._height = height
        scanned: set[int] = set()
        # stray prealloc temp: a crash between fallocate and rename
        # left a segment that never atomically appeared — discard it
        for fn in os.listdir(self._dir):
            if fn.endswith(".pre"):
                os.remove(os.path.join(self._dir, fn))
        while True:
            path = self._file_path(file_idx)
            if not os.path.exists(path):
                break
            size = os.path.getsize(path)
            torn = False
            with open(path, "rb") as f:
                f.seek(offset)
                while True:
                    hdr = f.read(_LEN.size)
                    if len(hdr) < _LEN.size:
                        torn = len(hdr) > 0
                        break
                    (n,) = _LEN.unpack(hdr)
                    if n == 0:
                        break  # clean preallocated tail
                    raw = f.read(n)
                    if len(raw) < n:
                        torn = True  # length header promises absent bytes
                        break
                    try:
                        blk = common_pb2.Block.FromString(raw)
                    except Exception:
                        torn = True
                        break  # torn mid-file record: prefix ends here
                    if blk.header.number != self._height:
                        torn = True
                        break  # non-contiguous: damaged or stale bytes
                    self._index_block(blk, file_idx, offset)
                    offset += _LEN.size + n
                    self._height = blk.header.number + 1
                    scanned.add(file_idx)
            if torn:
                # guard-style fault point: a faultfuzz "skip" rule
                # deletes this protection, leaving the torn bytes past
                # the checkpoint — defense in depth the campaign may
                # probe (the next in-segment write overwrites from the
                # checkpoint offset, so the scan never trusts them)
                if faultline.guard(
                    "blkstorage.recovery_truncate", file=file_idx
                ):
                    self._erase_tail(path, offset, size)
                scanned.add(file_idx)
            next_path = self._file_path(file_idx + 1)
            if os.path.exists(next_path):
                file_idx += 1
                offset = 0
            else:
                break
        # re-indexed records may never have been fsynced (group-commit
        # appends sync at flush boundaries only): make the scanned data
        # durable BEFORE the checkpoint/index below reference it, or a
        # second crash could leave a committed checkpoint pointing past
        # what the file actually holds
        self.sync_files(scanned)
        if self._height > 0:
            last = self.get_block_by_number(self._height - 1)
            if last is not None:
                self._last_hash = protoutil.block_header_hash(last.header)
            else:
                # snapshot-bootstrapped store with no appended blocks yet:
                # the last hash lives in the bootstrap info, not a file
                raw = self._index.get(_BSI_KEY)
                self._last_hash = raw[8:] if raw is not None else b""
        self._write_checkpoint(file_idx, offset)

    def _write_checkpoint(self, file_idx: int, offset: int) -> None:
        self._index.put(b"cp", struct.pack(">QQQ", file_idx, offset, self._height))

    # -- segment plumbing (storage engine v2) ------------------------------

    def _erase_tail(self, path: str, offset: int, size: int) -> None:
        """Zero a damaged tail: truncate away everything past the last
        complete record, then re-extend to the segment floor so the
        file stays preallocated (extension fills with zeros — the clean
        tail the scan recognizes)."""
        with open(path, "r+b") as f:
            f.truncate(offset)
            if offset < self._segment and size >= self._segment:
                f.truncate(self._segment)

    def _sync_dir(self) -> None:
        fd = os.open(self._dir, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _prealloc_segment(self, idx: int, size: int) -> None:
        """Create segment `idx` atomically: allocate + fsync a temp
        file, then rename it into place and fsync the directory — the
        only metadata fsync the write path ever pays.  A crash before
        the rename leaves a stray .pre that recovery discards; after
        it, an all-zero segment (a clean tail at offset 0)."""
        path = self._file_path(idx)
        tmp = path + ".pre"
        fd = os.open(tmp, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
        try:
            try:
                os.posix_fallocate(fd, 0, size)
            except (AttributeError, OSError):
                os.ftruncate(fd, size)  # sparse fallback
            os.fsync(fd)
        finally:
            os.close(fd)
        faultline.point("blkstorage.segment_prealloc", file=idx, size=size)
        os.rename(tmp, path)
        self._sync_dir()

    def _segment_fh(self, idx: int):
        """The cached r+b handle for segment `idx`, preallocating the
        file on first touch."""
        if self._fh is not None and self._fh_idx == idx:
            return self._fh
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        path = self._file_path(idx)
        if not os.path.exists(path):
            self._prealloc_segment(idx, self._segment)
        self._fh = open(path, "r+b")
        self._fh_idx = idx
        return self._fh

    def _seal_segment(self, idx: int, data_size: int) -> None:
        """Segment roll: trim the sealed file to exactly its records
        (dropping the preallocated zero tail) and make the new size
        durable; the successor segment is preallocated on first write.
        Crash-idempotent — rerolling recomputes the same trim from the
        committed checkpoint."""
        faultline.point("blkstorage.segment_roll", file=idx, size=data_size)
        f = self._segment_fh(idx)
        f.truncate(data_size)
        f.flush()
        os.fsync(f.fileno())  # size change: metadata must be durable
        self._fh.close()
        self._fh = None
        self._fh_idx = -1

    def close(self) -> None:
        """Release the cached segment writer handle (providers close
        their ledgers' stores on shutdown; in-memory stores no-op)."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
                self._fh_idx = -1

    @staticmethod
    def _parse_txid(raw_env: bytes) -> str | None:
        try:
            env = common_pb2.Envelope.FromString(raw_env)
            payload = common_pb2.Payload.FromString(env.payload)
            chdr = common_pb2.ChannelHeader.FromString(
                payload.header.channel_header
            )
            return chdr.tx_id or None
        except Exception:
            # fabriclint: allow[exception-discipline] None is the documented
            # sentinel: a non-endorser/garbled envelope has no txid to index
            return None

    def _index_block(
        self,
        blk: common_pb2.Block,
        file_idx: int,
        offset: int,
        txids: list | None = None,
        checkpoint: tuple[int, int] | None = None,
        index=None,
    ) -> None:
        """`txids` may carry the validator's per-position txids so the
        healthy path parses no envelopes; positions it has no txid for
        (early parse failures, config txs) fall back to a local parse —
        index contents are identical either way.  `checkpoint` rides the
        number/hash write batch so commit pays two index round-trips
        (txid insert-if-absent + everything else), not four."""
        num_b = struct.pack(">Q", blk.header.number)
        puts = {
            b"n" + num_b: struct.pack(">QQ", file_idx, offset),
            b"h" + protoutil.block_header_hash(blk.header): num_b,
        }
        if checkpoint is not None:
            puts[b"cp"] = struct.pack(
                ">QQQ", checkpoint[0], checkpoint[1], self._height
            )
        data = blk.data.data
        if txids is None or len(txids) != len(data):
            txids = [None] * len(data)
        tx_puts: dict[bytes, bytes] = {}
        loc = num_b  # block_num prefix shared by every tx loc value
        for pos, txid in enumerate(txids):
            if txid is None:
                txid = self._parse_txid(data[pos])
            if txid:
                # dict insertion keeps the FIRST in-block occurrence;
                # insert-if-absent keeps the first across blocks
                tx_puts.setdefault(
                    b"t" + txid.encode(), loc + struct.pack(">Q", pos)
                )
        index = index or self._index
        index.write_batch_if_absent(tx_puts)
        index.write_batch(puts)

    # -- public API --------------------------------------------------------

    @property
    def height(self) -> int:
        return self._height

    @property
    def last_block_hash(self) -> bytes:
        return self._last_hash

    def info(self):
        return {"height": self._height, "currentBlockHash": self._last_hash}

    # -- snapshot bootstrap (reference blkstorage BootstrapFromSnapshottedTxIDs)

    @property
    def bootstrap_height(self) -> int:
        """Chain height at snapshot bootstrap (0 when this store was not
        bootstrapped from a snapshot).  Blocks below this height do not
        exist locally and can never be replayed — repair ops must refuse
        to truncate through it (ledger/admin.py)."""
        return _bsi_height(self._index.get(_BSI_KEY))

    @property
    def bootstrap_hash(self) -> bytes:
        """The snapshot's last block hash recorded at bootstrap (b""
        when not bootstrapped) — the chain anchor the first appended
        block's previous_hash must match (the invariant oracle checks
        the join-by-snapshot seam against this)."""
        raw = self._index.get(_BSI_KEY)
        return raw[8:] if raw is not None else b""

    def bootstrap(
        self,
        last_block_num: int,
        last_block_hash: bytes,
        config_block: bytes | None = None,
    ) -> None:
        """Initialize an EMPTY store from snapshot bootstrap info: the
        store reports height last_block_num+1 and accepts the next block
        at that number, with no block files below it."""
        with self._lock:
            if self._height:
                raise BlockStoreError(
                    "cannot bootstrap a non-empty block store "
                    f"(height {self._height})"
                )
            self._height = last_block_num + 1
            self._last_hash = last_block_hash
            puts = {
                _BSI_KEY: struct.pack(">Q", last_block_num) + last_block_hash
            }
            if config_block is not None:
                puts[_CFG_KEY] = config_block
            self._index.write_batch(puts)
            self._write_checkpoint(0, 0)

    def config_block_bytes(self) -> bytes | None:
        """The config block stored at snapshot import (None for stores
        that keep their config in chain block 0)."""
        return self._index.get(_CFG_KEY)

    def import_snapshot_txids(self, txids) -> None:
        """Load the snapshot's committed-txid set into the txid index
        under a sentinel location: tx_ids_exist sees them (duplicate-tx
        rejection spans the snapshot boundary) while location queries
        report not-found, matching the reference's 'details not
        available from snapshot' semantics."""
        chunk: dict[bytes, bytes] = {}
        for txid in txids:
            chunk[b"t" + txid.encode()] = _SNAPSHOT_TX_LOC
            if len(chunk) >= 10000:
                self._index.write_batch_if_absent(chunk)
                chunk = {}
        if chunk:
            self._index.write_batch_if_absent(chunk)

    def export_txids(self):
        """Every indexed txid (appended blocks AND snapshot-imported
        ones, so chained snapshots stay complete), in index order."""
        for k, _ in self._index.iterate(b"t", b"u"):
            yield k[1:].decode()

    def add_block(
        self,
        blk: common_pb2.Block,
        txids: list | None = None,
        env_bytes: list | None = None,
        into=None,
        sync: bool = True,
    ) -> int | None:
        """Append + index; returns the block-file index written (None
        for in-memory stores).  `txids`/`env_bytes` are optional
        commit-path assists from the validator (see CommitAssist):
        known txids skip the per-envelope parse in the index, and the
        envelope bytes let serialization splice instead of re-encode.

        Group-commit seams: `into` (a WriteBatchCollector over the
        index's backing store) buffers the index + checkpoint writes
        into the block's shared KV transaction, and `sync=False` skips
        the per-block fsync — the caller then makes the appended data
        durable with one sync_files() call at the group boundary,
        BEFORE flushing the collector (block file first, then the
        all-or-nothing KV txn, the same crash-recovery invariant as
        per-block commits)."""
        with self._lock:
            if blk.header.number != self._height:
                raise BlockStoreError(
                    f"block number {blk.header.number} != expected {self._height}"
                )
            index = self._index if into is None else self._index.rebase(into)
            raw = protoutil.serialize_block(blk, env_bytes)
            if self._mem_blocks is not None:
                self._mem_blocks.append(raw)
                self._height += 1
                self._index_block(
                    blk, 0, len(self._mem_blocks) - 1, txids,
                    checkpoint=(0, len(self._mem_blocks)), index=index,
                )
                file_idx = None
            else:
                file_idx, offset, _ = self._checkpoint(index)
                rec = _LEN.size + len(raw)
                if offset > 0 and offset + rec > self._segment:
                    self._seal_segment(file_idx, offset)
                    file_idx += 1
                    offset = 0
                f = self._segment_fh(file_idx)
                f.seek(offset)
                # faultline seam: a 'torn' fault writes a prefix of
                # the record and crashes — the mid-record tear the
                # recovery scan must erase
                faultline.write(
                    "blkstorage.file_append", f,
                    _LEN.pack(len(raw)), raw,
                    block=blk.header.number,
                )
                f.flush()
                if sync:
                    os.fdatasync(f.fileno())
                self._height += 1
                self._index_block(
                    blk, file_idx, offset, txids,
                    checkpoint=(file_idx, offset + _LEN.size + len(raw)),
                    index=index,
                )
            self._last_hash = protoutil.block_header_hash(blk.header)
            return file_idx

    def truncate_to_checkpoint(self) -> None:
        """Undo appended-but-unindexed records: drop file data past the
        last COMMITTED checkpoint and restore in-memory height/hash from
        committed state.  The group-commit failure rollback — a flush
        that could not land its KV transaction must not leave the live
        store advertising heights whose blocks have no index (a crash at
        the same point is handled by _recover's tail scan instead, which
        REPLAYS the surviving records; here the buffered index data is
        already lost, so the appends are rolled back with it)."""
        with self._lock:
            file_idx, offset, height = self._checkpoint()
            if self._mem_blocks is not None:
                del self._mem_blocks[offset:]
            else:
                if self._fh is not None:
                    self._fh.close()
                    self._fh = None
                    self._fh_idx = -1
                i = file_idx + 1
                while os.path.exists(self._file_path(i)):
                    os.remove(self._file_path(i))
                    i += 1
                path = self._file_path(file_idx)
                if os.path.exists(path):
                    # zero the unindexed appends but keep the segment
                    # preallocated (re-extension fills with zeros)
                    self._erase_tail(
                        path, offset, os.path.getsize(path)
                    )
            self._height = height
            self._last_hash = b""
            if height > 0:
                last = self.get_block_by_number(height - 1)
                if last is not None:
                    self._last_hash = protoutil.block_header_hash(last.header)
                else:
                    raw = self._index.get(_BSI_KEY)
                    self._last_hash = raw[8:] if raw is not None else b""

    def sync_files(self, file_idxs) -> None:
        """Make every append since the last barrier durable — ONE
        coalesced fdatasync per touched segment per group (usually
        exactly one).  fdatasync suffices: appends land inside
        preallocated space, so the inode's size/metadata never moves on
        the commit path (prealloc and roll pay the metadata fsyncs)."""
        if self._mem_blocks is not None:
            return
        for idx in sorted(file_idxs):
            faultline.point("blkstorage.fsync", file=idx)
            fd = os.open(self._file_path(idx), os.O_RDONLY)
            try:
                os.fdatasync(fd)
            finally:
                os.close(fd)

    def get_block_by_number(self, num: int) -> common_pb2.Block | None:
        if num >= self._height:
            return None
        loc = self._index.get(b"n" + struct.pack(">Q", num))
        if loc is None:
            return None
        file_idx, offset = struct.unpack(">QQ", loc)
        if self._mem_blocks is not None:
            return common_pb2.Block.FromString(self._mem_blocks[offset])
        with open(self._file_path(file_idx), "rb") as f:
            f.seek(offset)
            (n,) = _LEN.unpack(f.read(_LEN.size))
            return common_pb2.Block.FromString(f.read(n))

    def get_block_by_hash(self, block_hash: bytes) -> common_pb2.Block | None:
        raw = self._index.get(b"h" + block_hash)
        if raw is None:
            return None
        return self.get_block_by_number(struct.unpack(">Q", raw)[0])

    def get_tx_loc(self, txid: str) -> tuple[int, int] | None:
        raw = self._index.get(b"t" + txid.encode())
        if raw is None or raw == _SNAPSHOT_TX_LOC:
            return None  # sentinel: committed before the snapshot
        num, pos = struct.unpack(">QQ", raw)
        return num, pos

    def tx_ids_exist(self, txids) -> set[str]:
        """Subset of `txids` already present in the txid index — ONE
        index round-trip for a whole block's duplicate check (the
        reference pays a leveldb get per tx, validator.go:459)."""
        got = self._index.get_many([b"t" + t.encode() for t in txids])
        return {k[1:].decode() for k in got}

    def get_tx_by_id(self, txid: str) -> common_pb2.Envelope | None:
        loc = self.get_tx_loc(txid)
        if loc is None:
            return None
        blk = self.get_block_by_number(loc[0])
        return protoutil.extract_envelope(blk, loc[1])

    def get_tx_validation_code(self, txid: str) -> int | None:
        loc = self.get_tx_loc(txid)
        if loc is None:
            return None
        blk = self.get_block_by_number(loc[0])
        flags = protoutil.tx_filter(blk)
        return flags[loc[1]]

    def iterator(self, start: int = 0):
        """Blocking-free iterator over existing blocks from `start`."""
        num = start
        while num < self._height:
            yield self.get_block_by_number(num)
            num += 1


__all__ = [
    "BlockStore",
    "BlockStoreError",
    "read_bootstrap_height",
    "segment_size",
    "DEFAULT_SEGMENT",
]
