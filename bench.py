"""fabric-tpu benchmark entry point.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

North-star metric (BASELINE.md): committed tx/s at 1000-tx blocks with a
3-of-5 endorsement policy, batched TPU verify vs per-signature host verify.
Falls back through the implemented pipeline stages as the framework grows:
currently benches the batched crypto data plane directly.
"""

from __future__ import annotations

import json
import time


def bench_sw_verify(n: int = 256) -> float:
    """Host baseline: per-signature ECDSA-P256 verify throughput (sigs/s).

    Equivalent of `go test -bench` over the reference bccsp/sw
    (bccsp/sw/ecdsa.go:41)."""
    from fabric_tpu.csp import SWCSP, VerifyBatchItem

    csp = SWCSP()
    key = csp.key_gen()
    items = []
    for i in range(n):
        d = csp.hash(b"bench-tx-%d" % i)
        items.append(VerifyBatchItem(key.public_key(), d, csp.sign(key, d)))
    t0 = time.perf_counter()
    ok = csp.verify_batch(items)
    dt = time.perf_counter() - t0
    assert all(ok)
    return n / dt


def main() -> None:
    baseline = bench_sw_verify()
    # Until the TPU batched pipeline lands, value == host baseline.
    value = baseline
    print(
        json.dumps(
            {
                "metric": "ecdsa_p256_verify_throughput",
                "value": round(value, 2),
                "unit": "sigs/s",
                "vs_baseline": round(value / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
