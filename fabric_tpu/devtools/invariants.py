"""invariants — the reusable consistency oracle behind chaos fuzzing.

PR 6/7 asserted recovery correctness with per-test asserts; faultfuzz
needs the same judgments as DATA, reusable across thousands of generated
fault schedules.  Each check here returns a list of :class:`Violation`
records (empty = invariant holds) instead of raising, so a fuzzing
campaign can attribute failures to plans, shrink them, and serialize the
verdict into a repro artifact.

The checks (the consistency contracts the ledger/snapshot stack already
documents, PR 1/2/6 — now machine-checkable):

- **chain**: every block below the advertised height is readable from
  the store, numbered contiguously, hash-chained (``previous_hash`` =
  the previous header's hash), and the store's ``last_block_hash``
  matches the tail — the block-file-first invariant made observable (a
  skipped recovery truncation or an index pointing into torn bytes
  surfaces here as an unreadable/mischained block).
- **heights**: ``durable_height`` ≤ ``height`` = block-store height,
  with the state savepoint at ``height - 1`` — and, fed a sequence of
  watermark samples from the workload, ``durable_height`` monotonicity.
- **workload state**: given the per-block write model the workload
  committed, state/history must agree with the RECOVERED height h:
  every modeled write below h present (with its history entry at
  ``(n, 0)``), every write at or above h absent — torn state is a
  violation regardless of where recovery landed.
- **snapshot**: a completed snapshot directory must verify
  (``verify_snapshot``); a torn/partial staging directory must REFUSE
  to verify (the export-side tamper contract).
- **import**: a channel whose snapshot-import marker is mid-flight must
  refuse to open; a completed import must agree with the source
  snapshot's state records byte-for-byte.
- **breaker**: TPUCSP circuit-breaker metrics sanity (state is a known
  value, counters non-negative and ordered).
- **partition**: the split-brain contract over a netsplit episode
  (``partition_violations``): the quorum side keeps committing, the
  quorum-less side stalls WITHOUT forking (per-height digest
  agreement), judged on evidence sampled just before the heal.
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class Violation:
    """One broken invariant: which check, and a human-readable detail
    (deterministic content only — repro artifacts embed these)."""

    check: str
    detail: str

    def as_dict(self) -> dict:
        return {"check": self.check, "detail": self.detail}

    def __str__(self) -> str:  # pragma: no cover - repr convenience
        return f"[{self.check}] {self.detail}"


# -- chain integrity ----------------------------------------------------------


def check_chain(ledger) -> list[Violation]:
    from fabric_tpu import protoutil

    out: list[Violation] = []
    height = ledger.height
    prev = None
    for num in range(height):
        try:
            blk = ledger.get_block_by_number(num)
        except Exception as exc:
            out.append(Violation(
                "chain",
                f"block {num} unreadable below height {height}: "
                f"{type(exc).__name__}: {exc}",
            ))
            return out
        if blk is None:
            # snapshot-bootstrapped ledgers legitimately have no blocks
            # below their bootstrap height
            boot = getattr(ledger.block_store, "bootstrap_height", 0)
            if num < boot:
                continue
            out.append(Violation(
                "chain", f"block {num} missing below height {height}"
            ))
            return out
        if blk.header.number != num:
            out.append(Violation(
                "chain",
                f"block at index {num} carries number {blk.header.number}",
            ))
            return out
        if prev is not None and blk.header.previous_hash != \
                protoutil.block_header_hash(prev.header):
            out.append(Violation(
                "chain", f"hash chain broken between {num - 1} and {num}"
            ))
            return out
        if prev is None and num > 0:
            # the first present block after a snapshot bootstrap: its
            # previous_hash must anchor on the bootstrap record's hash,
            # or the oracle would be blind at exactly the
            # join-by-snapshot seam
            boot_hash = getattr(
                ledger.block_store, "bootstrap_hash", b""
            )
            if boot_hash and blk.header.previous_hash != boot_hash:
                out.append(Violation(
                    "chain",
                    f"block {num} does not chain onto the snapshot "
                    "bootstrap hash",
                ))
                return out
        prev = blk
    if prev is not None:
        tail = protoutil.block_header_hash(prev.header)
        if ledger.block_store.last_block_hash != tail:
            out.append(Violation(
                "chain",
                "store last_block_hash disagrees with the tail header "
                f"at height {height}",
            ))
    return out


# -- heights & durability -----------------------------------------------------


def check_heights(ledger, watermarks=None) -> list[Violation]:
    out: list[Violation] = []
    height = ledger.height
    durable = getattr(ledger, "durable_height", height)
    if durable > height:
        out.append(Violation(
            "heights", f"durable_height {durable} > height {height}"
        ))
    if height != ledger.block_store.height:
        out.append(Violation(
            "heights",
            f"ledger height {height} != block store height "
            f"{ledger.block_store.height}",
        ))
    sp = ledger.state_db.savepoint()
    if height > 0:
        if sp is None:
            out.append(Violation(
                "heights", f"no state savepoint at height {height}"
            ))
        elif sp.block_num != height - 1:
            out.append(Violation(
                "heights",
                f"state savepoint at block {sp.block_num}, height is "
                f"{height}",
            ))
    if watermarks:
        last = None
        for i, w in enumerate(watermarks):
            if last is not None and w < last:
                out.append(Violation(
                    "heights",
                    f"durable_height regressed at sample {i}: "
                    f"{last} -> {w}",
                ))
                break
            last = w
    return out


# -- workload state/history agreement ----------------------------------------


def check_workload_state(ledger, writes_by_block) -> list[Violation]:
    """``writes_by_block[n]`` = [(ns, key, value)] the workload's block
    `n` wrote.  Judged against the RECOVERED height: below it every
    write is present with a matching history entry; at/above it absent
    (recovery must never keep half a block)."""
    out: list[Violation] = []
    height = ledger.height
    for n, writes in enumerate(writes_by_block):
        expected_present = n < height
        for ns, key, value in writes:
            got = ledger.get_state(ns, key)
            if expected_present and got != value:
                out.append(Violation(
                    "state",
                    f"block {n} write {ns}/{key} expected "
                    f"{value!r} below height {height}, got {got!r}",
                ))
            elif not expected_present and got is not None:
                out.append(Violation(
                    "state",
                    f"block {n} write {ns}/{key} present at {got!r} "
                    f"but block is AT/ABOVE recovered height {height}",
                ))
            hist = ledger.get_history_for_key(ns, key)
            saw = [h for h in hist if h[0] == n]
            if expected_present and not saw:
                out.append(Violation(
                    "history",
                    f"no history entry for {ns}/{key} at block {n} "
                    f"(height {height})",
                ))
            elif not expected_present and saw:
                out.append(Violation(
                    "history",
                    f"history entry {saw} for {ns}/{key} above the "
                    f"recovered height {height}",
                ))
    return out


# -- snapshots ---------------------------------------------------------------


def check_snapshot_verifies(snapshot_dir: str, csp=None) -> list[Violation]:
    """A COMPLETED snapshot directory must verify."""
    from fabric_tpu.ledger import snapshot as snap

    try:
        snap.verify_snapshot(snapshot_dir, csp=csp)
    except Exception as exc:
        return [Violation(
            "snapshot",
            f"completed snapshot {os.path.basename(snapshot_dir)!r} "
            f"fails verification: {type(exc).__name__}: {exc}",
        )]
    return []


def check_completed_snapshots(snapshots_root: str, csp=None) -> list[Violation]:
    """Every snapshot under <root>/completed/ must verify — staging
    (in_progress/) directories are exempt: a crash may legitimately
    leave torn files there, and verify_snapshot REFUSING them is the
    contract (see check_snapshot_rejected)."""
    out: list[Violation] = []
    completed = os.path.join(snapshots_root, "completed")
    if not os.path.isdir(completed):
        return out
    for lid in sorted(os.listdir(completed)):
        ldir = os.path.join(completed, lid)
        for h in sorted(os.listdir(ldir)):
            out.extend(check_snapshot_verifies(os.path.join(ldir, h), csp))
    return out


def check_snapshot_rejected(snapshot_dir: str, csp=None) -> list[Violation]:
    """The inverse contract: a tampered/torn directory must NOT verify
    — verification succeeding on it is the violation."""
    from fabric_tpu.ledger import snapshot as snap

    try:
        snap.verify_snapshot(snapshot_dir, csp=csp)
    except Exception:
        return []
    return [Violation(
        "snapshot",
        f"torn/tampered snapshot {os.path.basename(snapshot_dir)!r} "
        "passed verification",
    )]


def check_import_state(ledger, snapshot_dir: str) -> list[Violation]:
    """A COMPLETED import must agree with the source snapshot's state
    records byte-for-byte: the imported ledger's raw export stream must
    contain every (key, value) record of the snapshot's public + hashed
    files (capped at 5 reported mismatches)."""
    from fabric_tpu.ledger import snapshot as snap

    out: list[Violation] = []
    imported = dict(ledger.state_db.export_records())
    for fname in (snap.PUBLIC_STATE_FILE, snap.PVT_HASHES_FILE):
        path = os.path.join(snapshot_dir, fname)
        if not os.path.isfile(path):
            continue
        for raw_key, raw_val in snap.read_records(path):
            if imported.get(raw_key) != raw_val:
                out.append(Violation(
                    "import",
                    f"imported ledger disagrees with snapshot record "
                    f"{raw_key!r} from {fname}",
                ))
                if len(out) >= 5:
                    return out
    return out


# -- cross-peer agreement -----------------------------------------------------


def state_digest(ledger) -> str:
    """Canonical sha256 over the ledger's raw state export — the
    cross-peer agreement probe: two peers that committed the same chain
    must produce the identical digest, regardless of which of them was
    killed and caught up via state transfer or join-by-snapshot (the
    netharness oracle compares this across every node)."""
    from fabric_tpu.common.hashing import sha256

    parts = []
    for k, v in sorted(ledger.state_db.export_records()):
        parts.append(len(k).to_bytes(4, "big"))
        parts.append(k)
        parts.append(len(v).to_bytes(4, "big"))
        parts.append(v)
    return sha256(b"".join(parts)).hex()


def partition_violations(
    mode: str,
    split_tip: int,
    pre_heal_heights: dict | None,
    minority_digests: dict | None,
    majority: list,
    minority: list,
    orderer_names: list,
    peer_names: list,
    slack: int = 1,
    expect_progress: bool = True,
    stall_tip: int | None = None,
) -> list[Violation]:
    """The split-brain judgment over one netsplit episode, evaluated
    on evidence sampled just BEFORE the heal (netharness's partition
    executor collects it; see ``run_stream``):

    - ``partition.majority_stalled`` — under ``full``/``oneway`` the
      side holding raft quorum must have committed PAST the tip
      observed at the split (skipped when ``expect_progress`` is
      False: a partition fired after the stream quiesced has no
      traffic to prove progress with).
    - ``partition.minority_progressed`` — under ``full`` a minority
      peer committing more than ``slack`` blocks past ``stall_tip``
      (the minority's height sampled right AFTER the cut landed;
      falls back to ``split_tip``) means the quorum-less side kept
      ordering.  Blocks replicated in the fire→cut window plus one
      fully in-flight block are legitimate, hence the post-cut
      baseline and the one-block slack.  ``oneway``/``flaky`` leave
      paths open by design, so no stall contract there.
    - ``partition.minority_forked`` — the NO-FORK invariant, every
      mode: minority peers sampled at the SAME height must agree on
      their state digest.  Comparing per-height keeps a one-block
      delivery skew from masquerading as a fork.
    - ``partition.sample`` — the evidence itself is missing (the
      pre-heal probe failed); the episode cannot be judged green.
    """
    out: list[Violation] = []
    if pre_heal_heights is None:
        return [Violation(
            "partition.sample", "no pre-heal height sample recorded"
        )]
    orderer_set = set(orderer_names)
    peer_set = set(peer_names)
    if mode in ("full", "oneway") and expect_progress:
        maj_ord = [n for n in majority if n in orderer_set]
        maj_tip = max(
            (pre_heal_heights.get(n, 0) for n in maj_ord), default=0
        )
        if maj_tip <= split_tip:
            out.append(Violation(
                "partition.majority_stalled",
                f"majority tip {maj_tip} never passed the split tip "
                f"{split_tip} (quorum side must keep committing)",
            ))
    if mode == "full":
        base = split_tip if stall_tip is None else stall_tip
        for n in sorted(minority):
            if n not in peer_set:
                continue
            h = pre_heal_heights.get(n)
            if h is not None and h > base + slack:
                out.append(Violation(
                    "partition.minority_progressed",
                    f"{n} reached height {h} > stall tip {base} "
                    f"+ slack {slack} on the quorum-less side",
                ))
    by_height: dict[int, dict] = {}
    for name, rec in sorted((minority_digests or {}).items()):
        h, digest = rec[0], rec[1]
        if h is None:
            out.append(Violation(
                "partition.sample", f"{name}: {digest}"
            ))
            continue
        by_height.setdefault(int(h), {})[name] = digest
    for h, members in sorted(by_height.items()):
        if len(set(members.values())) > 1:
            out.append(Violation(
                "partition.minority_forked",
                f"minority peers at height {h} disagree on state "
                f"digest: {sorted(members)}",
            ))
    return out


# -- TPU breaker sanity -------------------------------------------------------


def check_breaker(csp) -> list[Violation]:
    """Degraded-mode circuit-breaker sanity on a TPUCSP (or anything
    exposing its metrics shape); no-op for providers without one."""
    out: list[Violation] = []
    breaker = getattr(csp, "_breaker", None)
    if breaker is None:
        return out
    state = getattr(breaker, "state", None)
    if state not in ("open", "closed", None):
        out.append(Violation("breaker", f"unknown breaker state {state!r}"))
    for name in ("trips", "failures", "probes"):
        v = getattr(breaker, name, 0)
        if isinstance(v, int) and v < 0:
            out.append(Violation("breaker", f"negative counter {name}={v}"))
    return out


# -- aggregate ----------------------------------------------------------------


def check_ledger(ledger, writes_by_block=None,
                 watermarks=None) -> list[Violation]:
    """The standard post-chaos judgment over one reopened ledger."""
    out = check_chain(ledger)
    out.extend(check_heights(ledger, watermarks))
    if writes_by_block is not None:
        out.extend(check_workload_state(ledger, writes_by_block))
    return out


__all__ = [
    "Violation",
    "check_chain",
    "check_heights",
    "check_workload_state",
    "check_snapshot_verifies",
    "check_completed_snapshots",
    "check_snapshot_rejected",
    "check_import_state",
    "check_breaker",
    "check_ledger",
    "partition_violations",
    "state_digest",
]
