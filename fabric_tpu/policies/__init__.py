"""Policy engine: signature policies, text DSL, hierarchical manager.

Reference: common/cauthdsl (compiler/evaluator), common/policydsl (text
parser), common/policies (manager + implicit meta).  All policies speak the
two-phase prepare/finish protocol so signature verification batches onto
the TPU data plane (SURVEY.md §7 step 3).
"""

from fabric_tpu.policies.signature_policy import (
    PendingEvaluation,
    PolicyError,
    SignaturePolicy,
    n_out_of,
    signed_by,
    signed_by_any_member,
    signed_by_msp_role,
)
from fabric_tpu.policies.policydsl import DSLError, from_string
from fabric_tpu.policies.manager import (
    BLOCK_VALIDATION,
    CHANNEL_ADMINS,
    CHANNEL_READERS,
    CHANNEL_WRITERS,
    ImplicitMetaPolicy,
    Manager,
    RejectPolicy,
    manager_from_config_group,
)

__all__ = [
    "PendingEvaluation",
    "PolicyError",
    "SignaturePolicy",
    "n_out_of",
    "signed_by",
    "signed_by_any_member",
    "signed_by_msp_role",
    "DSLError",
    "from_string",
    "Manager",
    "ImplicitMetaPolicy",
    "RejectPolicy",
    "manager_from_config_group",
    "BLOCK_VALIDATION",
    "CHANNEL_ADMINS",
    "CHANNEL_READERS",
    "CHANNEL_WRITERS",
]
