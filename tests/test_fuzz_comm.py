"""Seeded wire fuzzers for the two remaining transport surfaces
(round-4 verdict #8, same harness style as test_fuzz_envelopes): the
framed RPC transport (comm/rpc.py) and the TCP gossip comm
(gossip/comm.py).  The reference covers this layer with its
race-detector/sanitizer CI (scripts/run-unit-tests.sh); here the
properties are behavioral: no abuse kills the server, no malformed
frame kills a serving loop, declared lengths never buy unbounded
allocation, and unauthenticated/unsigned gossip never reaches
subscribers.

Findings this suite pinned when first written:
  - a client declaring a ~100MB frame pinned a ~100MB recv() buffer
    per connection (comm/rpc.py _read_exact now caps recv chunks);
  - a malformed SignedGossipMessage killed the TCP serving thread
    (DecodeError escaped the loop);
  - an UNSIGNED gossip message from a handshaken peer dispatched
    without the MCS ever seeing a signature;
  - one raising subscriber starved every later subscriber.
"""

from __future__ import annotations

import hashlib
import random
import socket
import struct
import threading
import time

import pytest

from fabric_tpu.comm import RPCClient, RPCServer
from fabric_tpu.comm.rpc import KIND_DATA, KIND_END, KIND_ERR
from fabric_tpu.gossip.comm import MessageCryptoService, TCPGossipComm
from fabric_tpu.protos.gossip import message_pb2 as gpb

_LEN = struct.Struct(">I")


def _wait(pred, timeout=5.0):
    end = time.time() + timeout
    while time.time() < end:
        if pred():
            return True
        time.sleep(0.02)
    return False


# ---------------------------------------------------------------------------
# RPC framing
# ---------------------------------------------------------------------------


@pytest.fixture()
def rpc_server():
    srv = RPCServer("127.0.0.1", 0)
    srv.register("echo.Echo", lambda body, stream: b"ok:" + body)
    srv.start()
    yield srv
    srv.stop()


def _alive(srv) -> bool:
    """The liveness oracle: a well-formed request round-trips."""
    return RPCClient(*srv.addr, timeout=5.0).call("echo.Echo", b"ping") == b"ok:ping"


def _send_raw(addr, payload: bytes, close_early: bool = False) -> bytes:
    """Open a raw connection, send bytes, read whatever comes back.  A
    reset mid-send/receive is a legitimate server response to abuse."""
    s = socket.create_connection(addr, timeout=3)
    out = b""
    try:
        try:
            s.sendall(payload)
            if close_early:
                return b""
            s.settimeout(1.5)
            while True:
                got = s.recv(65536)
                if not got:
                    break
                out += got
        except OSError:
            pass
        return out
    finally:
        s.close()


def _valid_request(method: bytes, body: bytes) -> bytes:
    frame = bytes([len(method)]) + method + body
    return _LEN.pack(len(frame)) + frame


def test_rpc_framing_fuzz_server_survives(rpc_server):
    """Seeded mutants of the request framing: every abuse either gets a
    clean ERR or a dropped connection — and the server answers a valid
    request after each one."""
    rng = random.Random(90210)
    addr = rpc_server.addr
    abuses = [
        b"",                                     # connect + close
        b"\x00",                                 # partial length prefix
        _LEN.pack(10),                           # declared 10, sent 0
        _LEN.pack(5) + b"ab",                    # truncated body
        _LEN.pack(0),                            # empty frame
        _LEN.pack(1) + b"\xff",                  # mlen 255 > frame
        _LEN.pack(6) + bytes([4]) + b"\xff\xfe\xfd\xfc" + b"x",  # bad UTF-8
        _valid_request(b"no.Such", b""),          # unknown method
        _LEN.pack(200 * 1024 * 1024),            # oversized declaration
    ]
    for i in range(40):
        abuses.append(bytes(rng.randrange(256) for _ in range(rng.randrange(1, 64))))
    for i, raw in enumerate(abuses):
        _send_raw(addr, raw, close_early=(i % 3 == 2))
        assert _alive(rpc_server), f"server died after abuse #{i}: {raw[:16]!r}"


def test_rpc_oversized_declaration_rejected_without_read(rpc_server):
    """A frame declaring more than the 100MB limit is refused up front
    with an ERR frame — the server never tries to read (or buffer) the
    declared payload."""
    out = _send_raw(rpc_server.addr, _LEN.pack(101 * 1024 * 1024))
    assert out[:4] == _LEN.pack(len(out) - 4)
    assert out[4] == KIND_ERR
    assert b"too large" in out[5:]
    assert _alive(rpc_server)


def test_rpc_malformed_method_gets_err_frame(rpc_server):
    out = _send_raw(rpc_server.addr, _LEN.pack(1) + b"\x10")  # mlen 16 > 0
    assert out and out[4] == KIND_ERR and b"malformed" in out
    assert _alive(rpc_server)


def test_rpc_valid_after_interleaved_garbage(rpc_server):
    """Valid requests interleave with garbage connections; every valid
    one must round-trip exactly."""
    rng = random.Random(7)
    for i in range(10):
        _send_raw(
            rpc_server.addr,
            bytes(rng.randrange(256) for _ in range(rng.randrange(0, 32))),
        )
        got = RPCClient(*rpc_server.addr, timeout=5.0).call(
            "echo.Echo", b"n%d" % i
        )
        assert got == b"ok:n%d" % i


def test_rpc_tls_garbage_and_truncated_records():
    """Plaintext garbage and a truncated TLS record against a TLS
    server: both die in the handshake without hurting the listener."""
    from fabric_tpu.common.crypto import CA
    from fabric_tpu.comm.tls import credentials_from_ca

    ca = CA("fuzz-tls-ca", "org1")
    creds = credentials_from_ca(ca, "server")
    srv = RPCServer("127.0.0.1", 0, tls=creds)
    srv.register("echo.Echo", lambda body, stream: b"ok:" + body)
    srv.start()
    try:
        rng = random.Random(4)
        # plaintext garbage (no TLS at all)
        _send_raw(srv.addr, bytes(rng.randrange(256) for _ in range(40)))
        # a plausible TLS record header, then silence/close (truncated
        # handshake record)
        _send_raw(srv.addr, b"\x16\x03\x01\x40\x00" + b"\x01" * 10,
                  close_early=True)
        # a record whose declared length never arrives
        _send_raw(srv.addr, b"\x16\x03\x03\xff\xff" + b"\x02" * 5,
                  close_early=True)
        client = RPCClient(
            *srv.addr, timeout=5.0, tls=credentials_from_ca(ca, "client")
        )
        assert client.call("echo.Echo", b"tls") == b"ok:tls"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# TCP gossip comm
# ---------------------------------------------------------------------------


class _ToyMCS(MessageCryptoService):
    """Shared-secret signer: real (verifiable) signatures without MSPs,
    and — unlike the permissive base class — REJECTS bad ones."""

    def sign(self, payload: bytes) -> bytes:
        return hashlib.sha256(b"fuzz-secret" + payload).digest()

    def verify(self, identity: bytes, signature: bytes, payload: bytes) -> bool:
        return signature == hashlib.sha256(b"fuzz-secret" + payload).digest()


def _data_msg(payload: bytes) -> gpb.GossipMessage:
    m = gpb.GossipMessage()
    m.data_msg.block = payload
    m.data_msg.seq_num = 1
    return m


def _handshake(mcs: _ToyMCS, identity: bytes, endpoint: str) -> bytes:
    ce = gpb.ConnEstablish(
        pki_id=mcs.get_pki_id(identity), identity=identity,
        endpoint=endpoint,
    )
    ce.signature = mcs.sign(bytes(ce.pki_id) + b"" + endpoint.encode())
    raw = ce.SerializeToString()
    return _LEN.pack(len(raw)) + raw


def _signed_frame(mcs: _ToyMCS, msg: gpb.GossipMessage) -> bytes:
    payload = msg.SerializeToString()
    sm = gpb.SignedGossipMessage(payload=payload, signature=mcs.sign(payload))
    raw = sm.SerializeToString()
    return _LEN.pack(len(raw)) + raw


def test_gossip_frame_fuzz_connection_survives():
    """After a VALID handshake, mutated frames (garbage, truncated
    protos, oversized declarations on fresh connections) must never
    stop the receiver from processing a later valid message."""
    rng = random.Random(1337)
    mcs = _ToyMCS()
    b = TCPGossipComm(("127.0.0.1", 0), b"idB", mcs=mcs)
    got = []
    b.subscribe(lambda rm: got.append(bytes(rm.msg.data_msg.block)))
    try:
        host, port = b.endpoint.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=3)
        s.sendall(_handshake(mcs, b"idA", "127.0.0.1:1"))
        # malformed protobuf frames on the SAME connection
        for _ in range(25):
            junk = bytes(
                rng.randrange(256) for _ in range(rng.randrange(1, 80))
            )
            s.sendall(_LEN.pack(len(junk)) + junk)
        # then a valid signed message — the serving loop must still run
        s.sendall(_signed_frame(mcs, _data_msg(b"after-junk")))
        assert _wait(lambda: b"after-junk" in got), (
            "serving loop died on malformed frames"
        )
        s.close()
        # an oversized frame declaration drops the connection (no
        # unbounded buffering) but not the listener
        s2 = socket.create_connection((host, int(port)), timeout=3)
        s2.sendall(_handshake(mcs, b"idA", "127.0.0.1:1"))
        s2.sendall(_LEN.pack(2 ** 31))
        s2.close()
        a = TCPGossipComm(("127.0.0.1", 0), b"idC", mcs=mcs)
        try:
            a.send(b.endpoint, _data_msg(b"fresh-peer"))
            assert _wait(lambda: b"fresh-peer" in got)
        finally:
            a.close()
    finally:
        b.close()


def test_gossip_malformed_handshake_dropped_cleanly():
    """Garbage in the HANDSHAKE position (first frame) must drop the
    connection without a traceback — and without hurting the listener
    (the one malformed-input path the first hardening pass missed)."""
    rng = random.Random(99)
    mcs = _ToyMCS()
    b = TCPGossipComm(("127.0.0.1", 0), b"idB", mcs=mcs)
    got = []
    b.subscribe(lambda rm: got.append(bytes(rm.msg.data_msg.block)))
    try:
        host, port = b.endpoint.rsplit(":", 1)
        for _ in range(15):
            junk = bytes(
                rng.randrange(256) for _ in range(rng.randrange(1, 60))
            )
            s = socket.create_connection((host, int(port)), timeout=3)
            try:
                s.sendall(_LEN.pack(len(junk)) + junk)
            except OSError:
                pass
            s.close()
        a = TCPGossipComm(("127.0.0.1", 0), b"idA", mcs=mcs)
        try:
            a.send(b.endpoint, _data_msg(b"still-alive"))
            assert _wait(lambda: b"still-alive" in got)
        finally:
            a.close()
    finally:
        b.close()


def test_gossip_dialback_bound_to_source_host():
    """A handshake self-claiming a THIRD-PARTY listen endpoint must get
    NO dial-back reply path — otherwise every response (state batches
    especially) becomes reflected traffic at an attacker-chosen target.
    A claim matching the connection's source host keeps its reply
    path."""
    mcs = _ToyMCS()
    b = TCPGossipComm(("127.0.0.1", 0), b"idB", mcs=mcs)
    sent: list = []
    b.send = lambda ep, m: sent.append(ep)  # capture dial-back targets
    b.subscribe(lambda rm: rm.respond(_data_msg(b"pong")))
    try:
        host, port = b.endpoint.rsplit(":", 1)
        # reflection attempt: endpoint names a host we did NOT connect from
        s = socket.create_connection((host, int(port)), timeout=3)
        s.sendall(_handshake(mcs, b"attacker", "203.0.113.9:4444"))
        s.sendall(_signed_frame(mcs, _data_msg(b"reflect-me")))
        time.sleep(0.5)
        assert sent == [], "reply dialed back to an unverified endpoint"
        s.close()
        # honest claim: same host as the connection source, any port
        s2 = socket.create_connection((host, int(port)), timeout=3)
        s2.sendall(_handshake(mcs, b"honest", "127.0.0.1:65001"))
        s2.sendall(_signed_frame(mcs, _data_msg(b"ping")))
        assert _wait(lambda: "127.0.0.1:65001" in sent)
        s2.close()
    finally:
        b.close()


def test_gossip_unsigned_message_dropped():
    """A handshaken peer sending a WELL-FORMED but unsigned message must
    not reach subscribers (per-message signatures are mandatory; the
    old dispatch skipped verification when the signature was empty)."""
    mcs = _ToyMCS()
    b = TCPGossipComm(("127.0.0.1", 0), b"idB", mcs=mcs)
    got = []
    b.subscribe(lambda rm: got.append(bytes(rm.msg.data_msg.block)))
    try:
        host, port = b.endpoint.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=3)
        s.sendall(_handshake(mcs, b"idA", "127.0.0.1:1"))
        sm = gpb.SignedGossipMessage(
            payload=_data_msg(b"unsigned").SerializeToString()
        ).SerializeToString()
        s.sendall(_LEN.pack(len(sm)) + sm)
        # forged signature is dropped too
        sm2 = gpb.SignedGossipMessage(
            payload=_data_msg(b"forged").SerializeToString(),
            signature=b"\x00" * 32,
        ).SerializeToString()
        s.sendall(_LEN.pack(len(sm2)) + sm2)
        # and a properly signed one on the same connection still lands
        s.sendall(_signed_frame(mcs, _data_msg(b"signed")))
        assert _wait(lambda: b"signed" in got)
        assert b"unsigned" not in got and b"forged" not in got
        s.close()
    finally:
        b.close()


def test_gossip_subscriber_exception_isolated():
    """One raising subscriber must not starve later subscribers or kill
    the connection's serving loop."""
    mcs = _ToyMCS()
    b = TCPGossipComm(("127.0.0.1", 0), b"idB", mcs=mcs)
    got = []

    def bad(rm):
        raise RuntimeError("buggy subscriber")

    b.subscribe(bad)
    b.subscribe(lambda rm: got.append(bytes(rm.msg.data_msg.block)))
    a = TCPGossipComm(("127.0.0.1", 0), b"idA", mcs=mcs)
    try:
        a.send(b.endpoint, _data_msg(b"first"))
        assert _wait(lambda: b"first" in got)
        a.send(b.endpoint, _data_msg(b"second"))  # same connection reused
        assert _wait(lambda: b"second" in got)
    finally:
        a.close()
        b.close()
