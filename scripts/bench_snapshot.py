"""Channel-snapshot workload harness: export MB/s (CSP hash_batch vs a
per-file hashlib loop) and restore wall time, BENCH-style JSON lines so
future PRs can track the snapshot workload next to the validate/commit
benches.

    python scripts/bench_snapshot.py [--blocks 200] [--txs 20] \
        [--keys 4] [--value-size 256] [--provider sw|tpu]

Builds a disk-backed channel (no endorsement/crypto — this measures the
export/restore storage + hashing path, like bench_ledger), generates a
snapshot, restores it into a fresh provider, and prints one JSON line
per experiment.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    ),
)

from bench_ledger import _block_of  # noqa: E402


def _build_chain(n_blocks: int, n_txs: int, n_keys: int, vsize: int):
    # provider.open (no genesis/org setup): this harness measures the
    # export/restore storage + hashing path, not config crypto
    from fabric_tpu.ledger import LedgerProvider

    root = tempfile.mkdtemp(prefix="bench-snapshot-src-")
    ledger = LedgerProvider(root).open("benchledger")
    height = ledger.height
    for b in range(n_blocks):
        writes = [
            (f"snap-tx{b}-{i}", f"key{(b * n_txs + i) % (n_blocks * n_txs // 2 or 1)}")
            for i in range(n_txs)
        ]
        blk = _block_of(ledger, height, writes, n_keys, vsize, read=False)
        ledger.commit(blk)
        height += 1
    return ledger


def _snapshot_size(path: str) -> int:
    return sum(
        os.path.getsize(os.path.join(path, f)) for f in os.listdir(path)
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, default=200)
    ap.add_argument("--txs", type=int, default=20)
    ap.add_argument("--keys", type=int, default=4)
    ap.add_argument("--value-size", type=int, default=256)
    ap.add_argument("--provider", default="sw", choices=["sw", "tpu"],
                    help="CSP provider for hash_batch during export")
    args = ap.parse_args()

    ledger = _build_chain(args.blocks, args.txs, args.keys, args.value_size)
    from fabric_tpu.ledger.snapshot import generate_snapshot

    try:
        from fabric_tpu.csp.factory import init_factories

        csp = init_factories(args.provider, force=True)
    except ImportError:
        csp = None  # no crypto stack on this host: hashlib fallback
    snap_root = tempfile.mkdtemp(prefix="bench-snapshot-")

    # -- export with the CSP hash_batch path -------------------------------
    t0 = time.perf_counter()
    path = generate_snapshot(ledger, snap_root, csp=csp)
    export_s = time.perf_counter() - t0
    size = _snapshot_size(path)
    print(json.dumps({
        "experiment": "export_hash_batch",
        "provider": args.provider if csp is not None else "hashlib-fallback",
        "blocks": args.blocks,
        "txs_per_block": args.txs,
        "snapshot_bytes": size,
        "seconds": round(export_s, 4),
        "mb_per_s": round(size / export_s / 1e6, 2),
    }))

    # -- per-file hashlib baseline (what a non-batched exporter would do) --
    names = sorted(
        f for f in os.listdir(path) if not f.startswith("_snapshot")
    )
    t0 = time.perf_counter()
    for name in names:
        with open(os.path.join(path, name), "rb") as f:
            hashlib.sha256(f.read()).hexdigest()
    hashlib_s = time.perf_counter() - t0
    hashed = sum(os.path.getsize(os.path.join(path, n)) for n in names)
    print(json.dumps({
        "experiment": "hash_files_hashlib",
        "bytes": hashed,
        "seconds": round(hashlib_s, 4),
        "mb_per_s": round(hashed / hashlib_s / 1e6, 2) if hashlib_s else None,
    }))

    # -- restore ------------------------------------------------------------
    from fabric_tpu.ledger import LedgerProvider

    dst_root = tempfile.mkdtemp(prefix="bench-snapshot-dst-")
    t0 = time.perf_counter()
    restored = LedgerProvider(dst_root, csp=csp).create_from_snapshot(path)
    restore_s = time.perf_counter() - t0
    print(json.dumps({
        "experiment": "restore",
        "height": restored.height,
        "snapshot_bytes": size,
        "seconds": round(restore_s, 4),
        "mb_per_s": round(size / restore_s / 1e6, 2),
    }))


if __name__ == "__main__":
    main()
