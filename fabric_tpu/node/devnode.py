"""Single-process dev network: solo orderer + one committing peer.

The minimum end-to-end slice (SURVEY.md §7 step 4): one "model running".
Broadcast -> msgprocessor filters -> solo chain -> blockcutter ->
blockwriter -> (in-process deliver) -> batched txvalidator -> MVCC ->
kvledger commit.  Exercises every north-star metric on one chip.

Multi-process deployment splits this same wiring across the gRPC services
(AtomicBroadcast/Deliver), mirroring internal/peer/node/start.go serve()
and orderer/common/server/main.go Main().
"""

from __future__ import annotations

import queue

from fabric_tpu.common.channelconfig import bundle_from_genesis
from fabric_tpu.csp import factory as csp_factory
from fabric_tpu.ledger import BlockStore, LedgerProvider
from fabric_tpu.orderer.blockcutter import BlockCutter
from fabric_tpu.orderer.blockwriter import BlockWriter
from fabric_tpu.orderer.msgprocessor import (
    Classification,
    StandardChannelProcessor,
)
from fabric_tpu.orderer.solo import SoloChain
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.peer.txvalidator import TxValidator
from fabric_tpu.protos.common import common_pb2


class DevNode:
    def __init__(
        self,
        genesis: common_pb2.Block,
        root_dir: str | None = None,
        csp=None,
        peer_signer=None,
        chaincodes: dict | None = None,
        batch_timeout_s: float | None = None,
        definition_provider=None,
    ):
        self.csp = csp or csp_factory.get_default()
        self.bundle = bundle_from_genesis(genesis, self.csp)
        self.channel_id = self.bundle.channel_id
        self._peer_signer = peer_signer
        self._chaincodes = chaincodes or {}
        self._definitions = definition_provider

        # peer side
        self.provider = LedgerProvider(root_dir)
        self.ledger = self.provider.create(genesis)
        self.validator = TxValidator(
            self.channel_id, self.ledger, self.bundle, self.csp,
            definition_provider=definition_provider,
        )
        # single-process private-data loop: the endorser persists
        # cleartext collection writes to the transient store, the
        # commit coordinator reads them back at commit (no gossip leg
        # in a one-peer dev network)
        from fabric_tpu.common.privdata import LedgerBackedCollectionStore
        from fabric_tpu.gossip.privdata import PrivDataCoordinator
        from fabric_tpu.ledger.transientstore import TransientStore

        self.collections = LedgerBackedCollectionStore(
            definition_provider, self.bundle.msp_manager
        )
        self.transient = TransientStore(self.provider.kv, self.channel_id)
        self.ledger.set_btl_policy(self.collections.btl_policy())
        self.committer = PrivDataCoordinator(
            self.validator, self.ledger, self.transient, self.collections,
            self_identity=(
                peer_signer.serialize() if peer_signer is not None else b""
            ),
        )
        self.endorser = (
            Endorser(
                self.channel_id, self.ledger, self.bundle, peer_signer,
                chaincodes or {}, self.csp,
                pvt_handoff=lambda txid, pvt: self.transient.persist(
                    txid, self.ledger.height, pvt
                ),
            )
            if peer_signer is not None
            else None
        )
        self._commit_events: queue.Queue = queue.Queue()
        self.committer.add_commit_listener(
            lambda blk, flags: self._commit_events.put((blk.header.number, flags))
        )

        # orderer side
        oc = self.bundle.orderer_config
        self._orderer_store = BlockStore(None, name=f"orderer-{self.channel_id}")
        self._orderer_store.add_block(genesis)
        self.writer = BlockWriter(self._orderer_store)
        cutter = BlockCutter.from_orderer_config(oc) if oc else BlockCutter()
        self.processor = StandardChannelProcessor(
            self.channel_id, self.bundle, self.csp, signer=peer_signer
        )
        timeout = batch_timeout_s if batch_timeout_s is not None else (
            oc.batch_timeout_s if oc else 2.0
        )
        self.chain = SoloChain(
            cutter, self.writer, timeout, on_block=self._deliver_to_peer
        )
        self.chain.start()

    # in-process deliver: orderer block -> fresh copy -> commit pipeline
    def _deliver_to_peer(self, blk: common_pb2.Block) -> None:
        copy = common_pb2.Block.FromString(blk.SerializeToString())
        self.committer.store_block(copy)
        self._maybe_adopt_config(copy)

    def _maybe_adopt_config(self, blk: common_pb2.Block) -> None:
        """After a VALID config tx commits, swap in the new channel
        resources on both halves of the dev node (the registrar does
        this in multichannel._maybe_apply_config; without it, follow-up
        config updates validate against stale config and a maintenance
        migration can never reach its second step).  The dev node stays
        on its solo chain regardless of a consensus-type value change —
        it is a single-process tool; type changes only matter for the
        maintenance-filter semantics."""
        from fabric_tpu import protoutil

        try:
            env = protoutil.extract_envelope(blk, 0)
            chdr = protoutil.channel_header(env)
            if chdr.type != common_pb2.CONFIG:
                return
            if list(protoutil.tx_filter(blk))[:1] != [0]:
                return  # invalid config tx: keep the old bundle
            new_bundle = bundle_from_genesis(blk, self.csp)
        except Exception:
            return
        self.bundle = new_bundle
        self.processor.update_bundle(new_bundle)
        self.validator = TxValidator(
            self.channel_id, self.ledger, new_bundle, self.csp,
            definition_provider=self._definitions,
        )
        from fabric_tpu.gossip.privdata import PrivDataCoordinator

        self.committer = PrivDataCoordinator(
            self.validator, self.ledger, self.transient, self.collections,
            self_identity=(
                self._peer_signer.serialize()
                if self._peer_signer is not None
                else b""
            ),
        )
        self.committer.add_commit_listener(
            lambda b, flags: self._commit_events.put((b.header.number, flags))
        )
        if self.endorser is not None:
            self.endorser = Endorser(
                self.channel_id, self.ledger, new_bundle,
                self._peer_signer, self._chaincodes, self.csp,
                pvt_handoff=lambda txid, pvt: self.transient.persist(
                    txid, self.ledger.height, pvt
                ),
            )

    # -- client surface ----------------------------------------------------

    def broadcast(self, env: common_pb2.Envelope) -> None:
        """AtomicBroadcast.Broadcast equivalent (orderer/common/broadcast)."""
        kind = self.processor.classify(env)
        if kind == Classification.NORMAL:
            seq = self.processor.process_normal_msg(env)
            self.chain.order(env, seq)
        elif kind == Classification.CONFIG_UPDATE:
            # configtx engine + maintenance filter, same as the real
            # orderer's broadcast path (msgprocessor
            # process_config_update_msg)
            new_env, seq = self.processor.process_config_update_msg(env)
            self.chain.configure(new_env, seq)
        else:
            self.chain.configure(env, 0)

    def wait_commit(self, timeout: float = 10.0):
        """Block until the peer commits the next block; returns (num, flags)."""
        return self._commit_events.get(timeout=timeout)

    def shutdown(self) -> None:
        self.chain.halt()
        self.provider.close()


__all__ = ["DevNode"]
