"""Breakdown of batched-verify time: host prep vs transfer vs device kernel.

Usage: python scripts/profile_verify.py [N] [BLK]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from fabric_tpu.csp import SWCSP, VerifyBatchItem
from fabric_tpu.csp.tpu import pallas_ec
from fabric_tpu.csp.tpu.provider import TPUCSP


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
    blk = int(sys.argv[2]) if len(sys.argv) > 2 else pallas_ec.BLK
    csp = SWCSP()
    keys = [csp.key_gen() for _ in range(64)]
    items = []
    tuples = []
    for i in range(n):
        key = keys[i % 64]
        d = csp.hash(b"profile-%d" % i)
        sig = csp.sign(key, d)
        items.append(VerifyBatchItem(key.public_key(), d, sig))
        from fabric_tpu.csp import api
        r, s = api.unmarshal_ecdsa_signature(sig)
        pub = key.public_key()
        tuples.append((pub.x, pub.y, d, r, s))

    # host prep (numpy path)
    t0 = time.perf_counter()
    packed = pallas_ec.prepare_packed(tuples)
    t_prep = time.perf_counter() - t0

    # native marshal path
    tcsp = TPUCSP()
    t0 = time.perf_counter()
    pn = tcsp._marshal_native(items)
    t_native = time.perf_counter() - t0 if pn is not None else float("nan")

    # device: warm-up compile, then time the full call (transfer + kernel)
    collect = pallas_ec.verify_packed(packed, blk=blk)
    ok = collect()
    assert ok.all(), "verify failed"
    import jax

    t_e2e = []
    for _ in range(3):
        t0 = time.perf_counter()
        collect = pallas_ec.verify_packed(packed, blk=blk)
        collect()
        t_e2e.append(time.perf_counter() - t0)
    t_e2e = min(t_e2e)

    # device-resident: pre-place inputs on device, time kernel only
    nb = -(-n // blk)
    pad = nb * blk - n

    def padlanes(a):
        if pad:
            a = np.concatenate([a, np.zeros(a.shape[:-1] + (pad,), a.dtype)], axis=-1)
        return a

    flags = np.stack([
        np.asarray(packed["cand1_ok"], np.uint32),
        np.asarray(packed["valid"], np.uint32),
    ])
    c = pallas_ec._consts()
    inputs = [
        padlanes(packed["qx"]), padlanes(packed["qy"]),
        padlanes(packed["d1"]), padlanes(packed["d2"]),
        padlanes(packed["cand0"]),
        padlanes(flags),
        c["solmat"], c["bias"], c["r256"], c["r512"],
        c["sub_c"], c["p_limbs"], c["n_limbs"],
        c["gx"][:, :, 0], c["gy"][:, :, 0],
    ]
    dev_inputs = [jax.device_put(x) for x in inputs]
    call = pallas_ec._build_call(nb, blk, False)
    out = call(*dev_inputs)
    out.block_until_ready()
    t_dev = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = call(*dev_inputs)
        out.block_until_ready()
        t_dev.append(time.perf_counter() - t0)
    t_dev = min(t_dev)

    nbytes = sum(x.nbytes for x in inputs[:7])
    print(f"N={n} BLK={blk}")
    print(f"host prep (numpy):    {t_prep*1e3:8.1f} ms  ({n/t_prep:9.0f}/s)")
    print(f"host prep (native):   {t_native*1e3:8.1f} ms")
    print(f"transfer bytes:       {nbytes/1e6:8.2f} MB")
    print(f"e2e (xfer+kernel):    {t_e2e*1e3:8.1f} ms  ({n/t_e2e:9.0f}/s)")
    print(f"device-resident:      {t_dev*1e3:8.1f} ms  ({n/t_dev:9.0f}/s)")


if __name__ == "__main__":
    main()
