"""Idemix MSP provider (reference msp/idemixmsp.go, msp/idemix_roles.go).

An MSP whose identities are anonymous credentials instead of X.509 certs.
A serialized idemix identity (`SerializedIdemixIdentity`, wire-compatible
with the reference: msp/idemixmsp.go DeserializeIdentity) carries:

    nym_x/nym_y — the pseudonym (fresh per identity)
    ou          — disclosed organizational unit
    role        — disclosed role (MEMBER/ADMIN encoded as in idemix_roles.go)
    proof       — an idemix presentation Signature disclosing exactly
                  (OU, Role) and binding the nym to the hidden sk

Per-message signing then uses nym signatures against the same pseudonym.

The attribute layout matches the reference's 4-attribute convention
(msp/idemixmsp.go:  AttributeIndexOU=0, AttributeIndexRole=1,
AttributeIndexEnrollmentId=2, AttributeIndexRevocationHandle=3).
"""

from __future__ import annotations

import dataclasses

from fabric_tpu.idemix import bn254 as bn
from fabric_tpu.idemix import nymsignature, revocation as idemix_revocation
from fabric_tpu.idemix import signature as idemix_signature
from fabric_tpu.idemix.credential import (
    Credential,
    attribute_to_scalar,
    new_cred_request,
    new_credential,
)
from fabric_tpu.idemix.issuer import IssuerKey, IssuerPublicKey
from fabric_tpu.protos.msp import identities_pb2, msp_config_pb2
from fabric_tpu.protos.msp import msp_principal_pb2

ATTR_OU = 0
ATTR_ROLE = 1
ATTR_ENROLLMENT_ID = 2
ATTR_REVOCATION_HANDLE = 3
ATTR_NAMES = ["OU", "Role", "EnrollmentID", "RevocationHandle"]

ROLE_MEMBER = 1
ROLE_ADMIN = 2

IDEMIX = 1  # ProviderType (reference msp/msp.go ProviderType IDEMIX)


class IdemixMSPError(Exception):
    pass


@dataclasses.dataclass
class IdemixIdentity:
    """A deserialized (verified) anonymous identity."""

    mspid: str
    nym: tuple
    ou: str
    role: int
    proof: idemix_signature.Signature
    _serialized: bytes = b""

    def serialize(self) -> bytes:
        return self._serialized

    def get_identifier(self) -> str:
        import hashlib

        # fabriclint: allow[csp-seam] pseudonym fingerprint over a BN254
        # G1 point — idemix credential domain, not the P-256 seam
        return hashlib.sha256(bn.g1_to_bytes(self.nym)).hexdigest()

    @property
    def is_admin(self) -> bool:
        return self.role == ROLE_ADMIN


class IdemixSigningIdentity(IdemixIdentity):
    """Holds the user secret + credential; signs with nym signatures."""

    def __init__(
        self,
        mspid: str,
        sk: int,
        cred: Credential,
        ipk: IssuerPublicKey,
        ou: str,
        role: int,
        rng=None,
    ):
        nym, r_nym = idemix_signature.make_nym(sk, ipk, rng)
        proof = idemix_signature.new_signature(
            cred,
            sk,
            ipk,
            msg=b"",
            disclosure=[True, True, False, False],
            nym=nym,
            r_nym=r_nym,
            rng=rng,
        )
        serialized = identities_pb2.SerializedIdentity(
            mspid=mspid,
            id_bytes=identities_pb2.SerializedIdemixIdentity(
                nym_x=nym[0].to_bytes(32, "big"),
                nym_y=nym[1].to_bytes(32, "big"),
                ou=ou.encode(),
                role=role.to_bytes(4, "big"),
                proof=proof.to_bytes(),
            ).SerializeToString(),
        ).SerializeToString()
        super().__init__(
            mspid=mspid, nym=nym, ou=ou, role=role, proof=proof,
            _serialized=serialized,
        )
        self._sk = sk
        self._r_nym = r_nym
        self._ipk = ipk
        self._rng = rng

    def sign(self, msg: bytes) -> bytes:
        sig = nymsignature.new_nym_signature(
            self._sk, self.nym, self._r_nym, self._ipk, msg, rng=self._rng
        )
        import json

        return json.dumps(
            {"c": sig.challenge, "z_sk": sig.z_sk, "z_rnym": sig.z_rnym}
        ).encode()


class IdemixMSP:
    """MSP interface over idemix credentials (reference msp/idemixmsp.go
    Setup/DeserializeIdentity/Validate/SatisfiesPrincipal)."""

    provider_type = IDEMIX

    def __init__(self, mspid: str, ipk: IssuerPublicKey,
                 revocation_pk=None, epoch: int = 0):
        ipk.check()
        if ipk.attr_names != ATTR_NAMES:
            raise IdemixMSPError(
                f"issuer key must carry attributes {ATTR_NAMES}"
            )
        self.mspid = mspid
        self.ipk = ipk
        self.revocation_pk = revocation_pk
        self.epoch = epoch
        self._signer: IdemixSigningIdentity | None = None

    # -- config -------------------------------------------------------------

    @classmethod
    def from_config(cls, conf: msp_config_pb2.MSPConfig) -> "IdemixMSP":
        if conf.type != IDEMIX:
            raise IdemixMSPError("not an idemix MSP config")
        ic = msp_config_pb2.IdemixMSPConfig.FromString(conf.config)
        ipk = IssuerPublicKey.from_dict(__import__("json").loads(ic.ipk))
        msp = cls(ic.name, ipk, epoch=ic.epoch)
        if ic.signer:
            sc = msp_config_pb2.IdemixMSPSignerConfig.FromString(ic.signer)
            msp._signer = IdemixSigningIdentity(
                ic.name,
                int.from_bytes(sc.sk, "big"),
                Credential.from_bytes(sc.cred),
                ipk,
                sc.organizational_unit_identifier,
                sc.role,
            )
        return msp

    def get_default_signing_identity(self) -> IdemixSigningIdentity:
        if self._signer is None:
            raise IdemixMSPError("no signing identity configured")
        return self._signer

    # -- identity lifecycle -------------------------------------------------

    def deserialize_identity(self, serialized: bytes) -> IdemixIdentity:
        sid = identities_pb2.SerializedIdentity.FromString(serialized)
        if sid.mspid != self.mspid:
            raise IdemixMSPError(
                f"expected MSP ID {self.mspid}, got {sid.mspid}"
            )
        return self._deserialize_inner(sid.id_bytes, serialized)

    def _deserialize_inner(
        self, id_bytes: bytes, serialized: bytes
    ) -> IdemixIdentity:
        sii = identities_pb2.SerializedIdemixIdentity.FromString(id_bytes)
        try:
            nym = (
                int.from_bytes(sii.nym_x, "big"),
                int.from_bytes(sii.nym_y, "big"),
            )
            proof = idemix_signature.Signature.from_bytes(sii.proof)
        except Exception as exc:  # wire bytes are untrusted: any shape error
            raise IdemixMSPError(f"malformed idemix identity: {exc}") from exc
        if not bn.g1_is_on_curve(nym):
            raise IdemixMSPError("idemix identity: nym not on curve")
        ou = sii.ou.decode()
        role = int.from_bytes(sii.role, "big")
        # The proof must disclose exactly OU and Role, match the claimed
        # values, and bind the nym (reference idemixmsp.go Validate).
        if proof.disclosure != [True, True, False, False]:
            raise IdemixMSPError("idemix identity: wrong disclosure")
        if proof.nym != nym:
            raise IdemixMSPError("idemix identity: proof not bound to nym")
        if proof.disclosed_attrs.get(ATTR_OU) != attribute_to_scalar(ou):
            raise IdemixMSPError("idemix identity: OU mismatch")
        if proof.disclosed_attrs.get(ATTR_ROLE) != attribute_to_scalar(role):
            raise IdemixMSPError("idemix identity: role mismatch")
        if not idemix_signature.verify(proof, self.ipk, b""):
            raise IdemixMSPError("idemix identity: credential proof invalid")
        return IdemixIdentity(
            mspid=self.mspid, nym=nym, ou=ou, role=role, proof=proof,
            _serialized=serialized,
        )

    def validate(self, identity: IdemixIdentity) -> None:
        if identity.mspid != self.mspid:
            raise IdemixMSPError("identity from a different MSP")
        # deserialize_identity already verified the proof.

    # -- verification -------------------------------------------------------

    def verify(self, identity: IdemixIdentity, msg: bytes, sig: bytes) -> bool:
        import json

        try:
            d = json.loads(sig)
            nsig = nymsignature.NymSignature(
                challenge=int(d["c"]),
                z_sk=int(d["z_sk"]),
                z_rnym=int(d["z_rnym"]),
            )
        except (ValueError, KeyError, TypeError):
            return False
        return nymsignature.verify_nym(nsig, identity.nym, self.ipk, msg)

    def satisfies_principal(self, identity: IdemixIdentity, principal) -> None:
        """Reference idemixmsp.go SatisfiesPrincipal: ROLE (member/admin),
        ORGANIZATION_UNIT, IDENTITY-by-bytes."""
        pc = msp_principal_pb2.MSPPrincipal.Classification
        if principal.principal_classification == pc.ROLE:
            role = msp_principal_pb2.MSPRole.FromString(principal.principal)
            if role.msp_identifier != self.mspid:
                raise IdemixMSPError("role principal for a different MSP")
            if role.role == msp_principal_pb2.MSPRole.MEMBER:
                return
            if role.role == msp_principal_pb2.MSPRole.ADMIN:
                if not identity.is_admin:
                    raise IdemixMSPError("identity is not an admin")
                return
            raise IdemixMSPError(f"unsupported idemix role {role.role}")
        if principal.principal_classification == pc.ORGANIZATION_UNIT:
            ou = msp_principal_pb2.OrganizationUnit.FromString(
                principal.principal
            )
            if ou.msp_identifier != self.mspid:
                raise IdemixMSPError("OU principal for a different MSP")
            if ou.organizational_unit_identifier != identity.ou:
                raise IdemixMSPError("OU mismatch")
            return
        if principal.principal_classification == pc.IDENTITY:
            if bytes(principal.principal) != identity.serialize():
                raise IdemixMSPError("identity bytes mismatch")
            return
        raise IdemixMSPError(
            f"unsupported principal class {principal.principal_classification}"
        )


# ---------------------------------------------------------------------------
# Config generation (the idemixgen surface, reference cmd/idemixgen)
# ---------------------------------------------------------------------------


def generate_issuer(rng=None) -> IssuerKey:
    return IssuerKey.generate(ATTR_NAMES, rng=rng)


def issue_signer_config(
    issuer: IssuerKey,
    mspid: str,
    ou: str,
    role: int,
    enrollment_id: str,
    revocation_handle: int = 0,
    rng=None,
) -> msp_config_pb2.IdemixMSPSignerConfig:
    """Run the request->issue flow and emit a signer config (reference
    idemixgen's signerconfig output)."""
    sk = bn.rand_zr(rng)
    req = new_cred_request(sk, b"idemixgen", issuer.ipk, rng=rng)
    attrs = [
        attribute_to_scalar(ou),
        attribute_to_scalar(role),
        attribute_to_scalar(enrollment_id),
        attribute_to_scalar(revocation_handle),
    ]
    cred = new_credential(issuer, req, attrs, rng=rng)
    cred.ver(sk, issuer.ipk)
    return msp_config_pb2.IdemixMSPSignerConfig(
        cred=cred.to_bytes(),
        sk=sk.to_bytes(32, "big"),
        organizational_unit_identifier=ou,
        role=role,
        enrollment_id=enrollment_id.encode(),
    )


def idemix_msp_config(
    issuer: IssuerKey,
    mspid: str,
    signer: msp_config_pb2.IdemixMSPSignerConfig | None = None,
    epoch: int = 0,
) -> msp_config_pb2.MSPConfig:
    import json

    ic = msp_config_pb2.IdemixMSPConfig(
        name=mspid,
        ipk=json.dumps(issuer.ipk.to_dict()).encode(),
        epoch=epoch,
    )
    if signer is not None:
        ic.signer = signer.SerializeToString()
    return msp_config_pb2.MSPConfig(type=IDEMIX, config=ic.SerializeToString())


__all__ = [
    "IdemixMSP",
    "IdemixIdentity",
    "IdemixSigningIdentity",
    "IdemixMSPError",
    "generate_issuer",
    "issue_signer_config",
    "idemix_msp_config",
    "ROLE_MEMBER",
    "ROLE_ADMIN",
    "IDEMIX",
]
