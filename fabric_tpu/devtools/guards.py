"""Reviewed guarded-by declarations for fabriclint's racecheck rule.

Each entry pins a shared field to the lock ROLE that must be held at
every access reachable from a thread entry point.  Declarations beat
majority inference: they are the reviewed concurrency contract for the
hot structures (the commit pipeline, the snapshot manager, the TPU
CSP's coalescing lane state, gossip membership), so a refactor that
quietly drops the lock around one access fails the lint gate even if
it also shifts the statistical majority.

Role spellings
--------------
* a ``lockwatch`` role string (``named_lock("kvledger.commit_lock")``)
  for locks created through the lockwatch seam — the runtime
  ``lockwatch.guarded(obj, field, by=role)`` assertions use the same
  strings, so the static map and the dynamic cross-check can never
  drift apart;
* the member's own qname (``fabric_tpu.csp.tpu.provider.TPUCSP.
  _ewma_lock``) as a pseudo-role for plain ``threading`` primitives.

Fields NOT listed here still get a guard when a strict majority of
their access sites hold one lock (see ``dataflow.Project._racecheck``);
this table exists for the structures where "majority" is not a strong
enough word for the invariant.

Since fabriclint v4 the racecheck engine also models happens-before
edges (thread start/join, Event set->wait, Queue put->get, workpool
submit->result): a field whose every access is publication-ordered
needs NO entry here (it resolves as ``hb-publish`` in the guard map),
and an entry whose every access becomes HB-proven — with at least one
access genuinely thread-reachable — is flagged STALE so this table
only shrinks.  Declare a guard when the invariant is the reviewed
contract (locks); let publication idioms be proven, not declared.

v5 sharpened both sides of that bargain: events and accesses are
ordered by CFG dominance/reachability rather than line position (a
back edge that carries a write after a previous iteration's start is
a finding, a start that dominates every access path is a proof), and
the lockset consulted at each access is the flow-sensitive must-hold
meet over paths — so a conditional acquire or early-return release
can neither fake a guard here nor hide from one.
"""

from __future__ import annotations

DECLARED_GUARDS: dict[str, str] = {
    # -- commit pipeline (PR 2 group commit) -------------------------------
    # the open CommitGroup and the durability watermark only move under
    # the commit lock; a thread reading them lock-free would see a
    # half-flushed group boundary
    "fabric_tpu.ledger.kvledger.KVLedger._active_group":
        "kvledger.commit_lock",
    "fabric_tpu.ledger.kvledger.KVLedger._durable_height":
        "kvledger.commit_lock",
    "fabric_tpu.ledger.kvledger.KVLedger._durable_hash":
        "kvledger.commit_lock",
    # -- sharded statedb (PR 17 storage engine v2) -------------------------
    # the two-phase flush epoch only advances under the flush lock; a
    # concurrent flush reading it lock-free could stage two batches
    # under the same epoch and make recovery ambiguous
    "fabric_tpu.ledger.kvstore.ShardedKVStore._epoch":
        "kvstore.shard_flush",
    # -- snapshot manager (PR 1/2) -----------------------------------------
    "fabric_tpu.ledger.snapshot.SnapshotManager._pending":
        "snapshot.manager",
    "fabric_tpu.ledger.snapshot.SnapshotManager._inflight":
        "snapshot.idle",
    "fabric_tpu.ledger.snapshot.SnapshotManager._spawn_seq":
        "snapshot.idle",
    "fabric_tpu.ledger.snapshot.SnapshotManager._ack_seq":
        "snapshot.idle",
    # -- TPU CSP coalescing lane state (PR 2/6) ----------------------------
    "fabric_tpu.csp.tpu.provider.TPUCSP._pend_batches": "csp.tpu.pend",
    "fabric_tpu.csp.tpu.provider.TPUCSP._pend_lanes": "csp.tpu.pend",
    "fabric_tpu.csp.tpu.provider.TPUCSP._flushed": "csp.tpu.pend",
    "fabric_tpu.csp.tpu.provider.TPUCSP._inflight": "csp.tpu.pend",
    "fabric_tpu.csp.tpu.provider.TPUCSP._gen": "csp.tpu.pend",
    "fabric_tpu.csp.tpu.provider.TPUCSP._lane_wall_ewma":
        "fabric_tpu.csp.tpu.provider.TPUCSP._ewma_lock",
    # process-wide measured host verify rate (module global)
    "fabric_tpu.csp.tpu.provider._host_rate_ewma":
        "fabric_tpu.csp.tpu.provider._host_rate_lock",
    # -- shared host work pool (PR 9 parallel collect/prepare) -------------
    # the lazily-created process-wide executor singleton: creation and
    # teardown race between first users and shutdown callers
    "fabric_tpu.common.workpool._pool":
        "fabric_tpu.common.workpool._pool_lock",
    # -- gossip membership --------------------------------------------------
    "fabric_tpu.gossip.discovery.DiscoveryCore._peers":
        "gossip.discovery.members",
    "fabric_tpu.gossip.discovery.DiscoveryCore._tick":
        "gossip.discovery.members",
    "fabric_tpu.gossip.discovery.DiscoveryCore._seq":
        "gossip.discovery.members",
    # -- netscope telemetry collector (PR 12) -------------------------------
    # the scraper thread ingests rounds while the harness thread reads
    # series/marks events/writes artifacts; every shared structure
    # moves under one state lock
    "fabric_tpu.devtools.netscope.Netscope._series": "netscope.state",
    "fabric_tpu.devtools.netscope.Netscope._health": "netscope.state",
    "fabric_tpu.devtools.netscope.Netscope._events": "netscope.state",
    "fabric_tpu.devtools.netscope.Netscope._trace_events":
        "netscope.state",
    "fabric_tpu.devtools.netscope.Netscope._trace_cursor":
        "netscope.state",
    "fabric_tpu.devtools.netscope.Netscope._stalls": "netscope.state",
    "fabric_tpu.devtools.netscope.Netscope._height_window":
        "netscope.state",
    # -- profscope profiling plane (PR 15) ----------------------------------
    # the sampler service thread folds sweeps into the aggregates while
    # feed points (lockwatch contention, workpool chunks) write from
    # arbitrary threads and export() snapshots from the harness thread;
    # everything shared moves under the profiler's own plain lock (a
    # plain primitive on purpose: a watched lock here would recurse
    # through the very note_lock_wait hook it feeds)
    "fabric_tpu.common.profile.Profiler._stacks":
        "fabric_tpu.common.profile.Profiler._lock",
    "fabric_tpu.common.profile.Profiler._spans":
        "fabric_tpu.common.profile.Profiler._lock",
    "fabric_tpu.common.profile.Profiler._locks":
        "fabric_tpu.common.profile.Profiler._lock",
    "fabric_tpu.common.profile.Profiler._chunks":
        "fabric_tpu.common.profile.Profiler._lock",
    "fabric_tpu.common.profile.Profiler._samples":
        "fabric_tpu.common.profile.Profiler._lock",
    "fabric_tpu.common.profile.Profiler._dropped":
        "fabric_tpu.common.profile.Profiler._lock",
    "fabric_tpu.common.profile.Profiler._t0":
        "fabric_tpu.common.profile.Profiler._lock",
}

__all__ = ["DECLARED_GUARDS"]
