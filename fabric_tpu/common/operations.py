"""Operations HTTP endpoint: /metrics, /healthz, /version, /logspec,
/traces.

Reference: core/operations/system.go:75-265 — an HTTP server exposing
prometheus metrics, health checks with registered checkers, the build
version, and GET/PUT of the runtime log spec (flogging httpadmin).
``GET /traces`` goes beyond the reference: it serves the tracelens
flight recorder as Chrome trace-event JSON (empty, with
``otherData.armed=false``, while ``FABRIC_TPU_TRACE`` is unset).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from fabric_tpu.devtools.lockwatch import spawn_thread

from fabric_tpu.common import flogging
from fabric_tpu.common.metrics import (
    DisabledProvider,
    PrometheusProvider,
    StatsdProvider,
)

VERSION = "0.1.0"


class System:
    """Reference operations.System: owns the metrics provider + server."""

    def __init__(
        self,
        listen_address: tuple[str, int] = ("127.0.0.1", 0),
        provider: str = "prometheus",
        version: str = VERSION,
        statsd_send=None,
        process_metrics: bool = False,
    ):
        self.version = version
        self._checkers: dict[str, object] = {}
        self._last_errors: dict[str, str] = {}
        self._snapshot_metrics = None
        self._commit_metrics = None
        self._validate_metrics = None
        self._csp_metrics = None
        self._raft_metrics = None
        self._workpool_metrics = None
        self._gossip_metrics = None
        self._deliver_metrics = None
        self._gateway_metrics = None
        self._ledger_metrics = None
        self._lock_metrics = None
        self._process_metrics = None
        self._lock = threading.Lock()
        if provider == "prometheus":
            self.metrics_provider = PrometheusProvider()
            self._registry = self.metrics_provider.registry
            if process_metrics:
                # standard process gauges (CPU seconds, RSS, open fds,
                # GC collections/pauses) read at scrape time — opt-in
                # because their values track the real process clock,
                # which would break virtual-clock scrape determinism
                from fabric_tpu.common.metrics import ProcessMetrics

                self._process_metrics = ProcessMetrics(
                    self.metrics_provider
                )
                self._registry.register_collector(
                    self._process_metrics.collect
                )
        elif provider == "statsd":
            self.metrics_provider = StatsdProvider(
                statsd_send or (lambda line: None)
            )
            self._registry = None
        else:
            self.metrics_provider = DisabledProvider()
            self._registry = None
        system = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, code: int, body: bytes, ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    if system._registry is None:
                        self._reply(404, b"metrics provider is not prometheus")
                        return
                    self._reply(
                        200,
                        system._registry.expose().encode(),
                        "text/plain; version=0.0.4",
                    )
                elif self.path == "/healthz" or self.path.startswith(
                    "/healthz?"
                ):
                    from urllib.parse import parse_qs, urlsplit

                    qs = parse_qs(urlsplit(self.path).query)
                    detail = qs.get("detail", ["0"])[0] not in ("", "0")
                    status, body = system.health(detail=detail)
                    self._reply(200 if status else 503, json.dumps(body).encode())
                elif self.path == "/version":
                    self._reply(
                        200, json.dumps({"Version": system.version}).encode()
                    )
                elif self.path == "/logspec":
                    self._reply(
                        200, json.dumps({"spec": flogging.spec()}).encode()
                    )
                elif self.path == "/traces" or self.path.startswith(
                    "/traces?"
                ):
                    from urllib.parse import parse_qs, urlsplit

                    from fabric_tpu.common import tracing

                    qs = parse_qs(urlsplit(self.path).query)
                    since = None
                    if "since" in qs:
                        try:
                            since = int(qs["since"][0])
                        except ValueError:
                            self._reply(
                                400,
                                json.dumps(
                                    {"error": "since must be an integer "
                                              "event id"}
                                ).encode(),
                            )
                            return
                    self._reply(
                        200,
                        json.dumps(
                            tracing.export(since=since), sort_keys=True
                        ).encode(),
                    )
                elif self.path == "/profile/heap":
                    from fabric_tpu.common import profile

                    self._reply(
                        200,
                        json.dumps(
                            profile.heap_doc(), sort_keys=True
                        ).encode(),
                    )
                elif self.path == "/profile" or self.path.startswith(
                    "/profile?"
                ):
                    from urllib.parse import parse_qs, urlsplit

                    from fabric_tpu.common import profile

                    qs = parse_qs(urlsplit(self.path).query)
                    try:
                        seconds = float(qs.get("seconds", ["0"])[0])
                    except ValueError:
                        self._reply(
                            400,
                            json.dumps(
                                {"error": "seconds must be a number"}
                            ).encode(),
                        )
                        return
                    if seconds > 0:
                        # on-demand session sampled inline in THIS
                        # handler thread (the server is threading, so
                        # other endpoints stay live); capped like the
                        # old pprof listener
                        doc = profile.sample_for(min(seconds, 120.0))
                    else:
                        # the armed profiler's accumulated aggregate
                        # (or the valid disarmed doc)
                        doc = profile.export()
                    self._reply(
                        200, json.dumps(doc, sort_keys=True).encode()
                    )
                else:
                    self._reply(404, b"not found", "text/plain")

            def do_PUT(self):
                if self.path != "/logspec":
                    self._reply(404, b"not found", "text/plain")
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    flogging.activate_spec(payload.get("spec", ""))
                except (ValueError, flogging.LogSpecError) as exc:
                    self._reply(400, json.dumps({"error": str(exc)}).encode())
                    return
                self._reply(204, b"")

            do_POST = do_PUT

        self._server = ThreadingHTTPServer(listen_address, Handler)
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    @property
    def addr(self) -> tuple[str, int]:
        return self._server.server_address

    def start(self) -> None:
        self._thread = spawn_thread(
            target=self._server.serve_forever, name="operations-server",
            kind="service",
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- workload metric bundles -------------------------------------------

    def snapshot_metrics(self):
        """Lazily-built channel-snapshot metrics bound to this system's
        provider, so snapshot generation/pending gauges surface on the
        /metrics endpoint (prometheus) or the statsd stream."""
        with self._lock:
            if self._snapshot_metrics is None:
                from fabric_tpu.common.metrics import SnapshotMetrics

                self._snapshot_metrics = SnapshotMetrics(
                    self.metrics_provider
                )
            return self._snapshot_metrics

    def commit_metrics(self):
        """Lazily-built ledger-commit stage metrics bound to this
        system's provider — the per-stage mvcc/append/pvt/state/history/
        fsync breakdown on the /metrics endpoint."""
        with self._lock:
            if self._commit_metrics is None:
                from fabric_tpu.common.metrics import CommitMetrics

                self._commit_metrics = CommitMetrics(self.metrics_provider)
            return self._commit_metrics

    def validate_metrics(self):
        """Lazily-built block-validate stage metrics (the
        collect/verify_wait/policy split) bound to this system's
        provider — hand it to TxValidator(metrics=...)."""
        with self._lock:
            if self._validate_metrics is None:
                from fabric_tpu.common.metrics import ValidateMetrics

                self._validate_metrics = ValidateMetrics(
                    self.metrics_provider
                )
            return self._validate_metrics

    def csp_metrics(self):
        """Lazily-built TPU-CSP degraded-mode metrics (circuit-breaker
        state/trips, device failures, recovery probes) bound to this
        system's provider — hand it to TPUCSP(metrics=...) or
        set_metrics() so breaker transitions surface on /metrics."""
        with self._lock:
            if self._csp_metrics is None:
                from fabric_tpu.common.metrics import CSPMetrics

                self._csp_metrics = CSPMetrics(self.metrics_provider)
            return self._csp_metrics

    def raft_metrics(self):
        """Lazily-built raft cluster-comm metrics (dropped sends,
        dial attempts) for TCPTransport(metrics=...)."""
        with self._lock:
            if self._raft_metrics is None:
                from fabric_tpu.common.metrics import RaftMetrics

                self._raft_metrics = RaftMetrics(self.metrics_provider)
            return self._raft_metrics

    def workpool_metrics(self):
        """Lazily-built shared-host-work-pool metrics (queue depth,
        in-flight chunks, worker saturation) — hand the bundle to
        ``workpool.set_metrics`` so the parallel collect/prepare
        stages' fan-out pressure surfaces on /metrics."""
        with self._lock:
            if self._workpool_metrics is None:
                from fabric_tpu.common.metrics import WorkpoolMetrics

                self._workpool_metrics = WorkpoolMetrics(
                    self.metrics_provider
                )
            return self._workpool_metrics

    def gossip_metrics(self):
        """Lazily-built gossip-plane metrics (message flow, state
        transfer, membership) — hand the bundle to
        ``GossipService.set_metrics`` so the netscope scraper sees the
        dissemination layer."""
        with self._lock:
            if self._gossip_metrics is None:
                from fabric_tpu.common.metrics import GossipMetrics

                self._gossip_metrics = GossipMetrics(self.metrics_provider)
            return self._gossip_metrics

    def deliver_metrics(self):
        """Lazily-built deliver-client metrics (blocks pulled,
        reconnect episodes, cumulative backoff) for
        ``DeliverClient(metrics=...)``."""
        with self._lock:
            if self._deliver_metrics is None:
                from fabric_tpu.common.metrics import DeliverMetrics

                self._deliver_metrics = DeliverMetrics(
                    self.metrics_provider
                )
            return self._deliver_metrics

    def gateway_metrics(self):
        """Lazily-built gateway front-end metrics (admission queue
        depth, adaptive in-flight window, dedup hits, rejections,
        failover episodes, submit→commit latency) for
        ``Gateway(metrics=...)`` — the series netscope's scraper and
        SLO rollup read off the gateway's /metrics."""
        with self._lock:
            if self._gateway_metrics is None:
                from fabric_tpu.common.metrics import GatewayMetrics

                self._gateway_metrics = GatewayMetrics(
                    self.metrics_provider
                )
            return self._gateway_metrics

    def ledger_metrics(self):
        """Lazily-built per-channel ledger progress metrics (height /
        durable_height gauges + block/tx counters) for
        ``LedgerProvider(ledger_metrics=...)`` — the series netscope
        derives cross-peer commit lag from."""
        with self._lock:
            if self._ledger_metrics is None:
                from fabric_tpu.common.metrics import LedgerMetrics

                self._ledger_metrics = LedgerMetrics(self.metrics_provider)
            return self._ledger_metrics

    def lock_metrics(self):
        """Lazily-built lock-contention histograms
        (``lock_wait_seconds{role}`` / ``lock_hold_seconds{role}``) —
        hand the bundle to ``profile.set_lock_metrics`` so an armed
        profscope's acquire-wait/hold observations surface on
        /metrics (the runtime complement to fabriclint's static
        lock-order graph)."""
        with self._lock:
            if self._lock_metrics is None:
                from fabric_tpu.common.metrics import LockMetrics

                self._lock_metrics = LockMetrics(self.metrics_provider)
            return self._lock_metrics

    # -- health ------------------------------------------------------------

    def register_checker(self, component: str, checker) -> None:
        """checker() raises or returns False when unhealthy (reference
        healthz registered checkers, e.g. couchdb/docker)."""
        with self._lock:
            self._checkers[component] = checker

    def health(self, detail: bool = False) -> tuple[bool, dict]:
        """Run every registered checker.  Plain mode keeps the
        reference healthz body (``status`` + ``failed_checks``);
        ``detail`` (``GET /healthz?detail=1``) adds one entry per
        checker with its name, pass/fail status, and the failure
        reason — the netscope health timeline's per-checker input.
        ``last_error`` persists across calls: a checker that failed
        once and recovered still shows what went wrong last."""
        failed = []
        checks = []
        with self._lock:
            checkers = dict(self._checkers)
        for name, check in sorted(checkers.items()):
            error = None
            try:
                if check() is False:
                    error = "check returned False"
            except Exception as exc:
                error = str(exc) or type(exc).__name__
            if error is not None:
                failed.append(
                    name if error == "check returned False"
                    else f"{name}: {error}"
                )
                with self._lock:
                    self._last_errors[name] = error
                last = error
            else:
                with self._lock:
                    last = self._last_errors.get(name)
            checks.append({
                "component": name,
                "status": "OK" if error is None else "failed",
                "last_error": last,
            })
        ok = not failed
        body: dict = (
            {"status": "OK"} if ok
            else {"status": "Service Unavailable", "failed_checks": failed}
        )
        if detail:
            body["checks"] = checks
        return ok, body


__all__ = ["System", "VERSION"]
