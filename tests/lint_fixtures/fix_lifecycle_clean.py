"""Clean twin of fix_lifecycle_dirty: the handle is kept on the
owner, the loop blocks on a stop Event, and stop() sets it and joins —
a statically reachable stop path on both the handle and the entry."""

import threading

from fabric_tpu.devtools.lockwatch import spawn_thread


def emit():
    return None


class Beacon:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = spawn_thread(
            target=self._loop, name="beacon", kind="service"
        )

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)

    def _loop(self):
        while not self._stop.is_set():
            emit()
