"""TPU CSP provider: the `bccsp/tpu` seam.

The sibling the reference never had (BASELINE.json north star): same SPI as
the `sw` provider (bccsp/sw/impl.go dispatch surface), but `verify_batch`
and `hash_batch` execute as single jitted XLA programs over the whole batch
instead of per-item host calls.

Key management and signing delegate to the host `sw` provider — the
reference's hot path is *verification* at commit time (SURVEY.md §3.4:
N_txs x (1 creator + K endorsers) ECDSA verifies per block); signing is
one-per-proposal on the endorser and stays host-side.

Static-shape discipline (SURVEY.md §7 hard part (1)): batches are padded to
bucket sizes (powers of two) so XLA compiles once per bucket; oversized
batches are chunked.  Per-item failure semantics are preserved end to end:
host prechecks mark items invalid without throwing, and the kernel returns
a per-lane mask (hard part (4)).
"""

from __future__ import annotations

import hashlib
from typing import Sequence

import numpy as np

from fabric_tpu.csp import api
from fabric_tpu.csp.api import (
    CSP,
    ECDSAP256PrivateKey,
    ECDSAP256PublicKey,
    Key,
    VerifyBatchItem,
)
from fabric_tpu.csp.sw import SWCSP

_BATCH_BUCKETS = (32, 128, 512, 2048, 4096, 8192, 32768)  # single dispatch
# for big batches: per-call transport overhead beats chunk-pipelining wins
# (4096 matters: a 1000-tx block at 3-of-5 is 4000 sigs)
_HASH_BUCKETS = (32, 128, 512, 2048, 8192)


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class TPUCSP(CSP):
    """Batched JAX/XLA crypto provider (ECDSA-P256 verify + SHA-256)."""

    def __init__(self, sw: SWCSP | None = None, min_device_batch: int = 16):
        self._sw = sw or SWCSP()
        # Below this size, host verify wins on latency (device dispatch
        # overhead); the sw provider is also the fallback oracle.
        self._min_device_batch = min_device_batch

    # -- key management / signing: host side ------------------------------

    def key_gen(self) -> ECDSAP256PrivateKey:
        return self._sw.key_gen()

    def key_import(self, raw: bytes, private: bool = False) -> Key:
        return self._sw.key_import(raw, private)

    def get_key(self, ski: bytes) -> Key:
        return self._sw.get_key(ski)

    def sign(self, key: Key, digest: bytes) -> bytes:
        return self._sw.sign(key, digest)

    # -- hashing -----------------------------------------------------------

    def hash(self, msg: bytes) -> bytes:
        return hashlib.sha256(msg).digest()

    def hash_batch(self, msgs: Sequence[bytes]) -> list[bytes]:
        if len(msgs) < self._min_device_batch:
            return [hashlib.sha256(m).digest() for m in msgs]
        from fabric_tpu.csp.tpu import sha256 as dev_sha

        # Bucket by padded block count AND batch size to bound compiles.
        nb = max((len(m) + 9 + 63) // 64 for m in msgs)
        nb = 1 << (nb - 1).bit_length()
        n = len(msgs)
        bsz = _bucket(n, _HASH_BUCKETS)
        out: list[bytes] = []
        for off in range(0, n, bsz):
            chunk = list(msgs[off : off + bsz])
            pad = bsz - len(chunk)
            chunk += [b""] * pad
            digs = dev_sha.sha256_batch(chunk, n_blocks=nb)
            out.extend(digs[: bsz - pad])
        return out

    # -- verification ------------------------------------------------------

    def verify(self, key: Key, signature: bytes, digest: bytes) -> bool:
        return self._sw.verify(key, signature, digest)

    def verify_batch(self, items: Sequence[VerifyBatchItem]) -> list[bool]:
        return self.verify_batch_async(items)()

    def verify_batch_async(self, items: Sequence[VerifyBatchItem]):
        """Dispatch host prep + device call(s), return the collector.

        The device executes asynchronously after dispatch, so the caller
        can run the next block's collect phase while this one verifies
        (txvalidator.validate_pipeline)."""
        if len(items) < self._min_device_batch:
            result = self._sw.verify_batch(items)
            return lambda: result
        from fabric_tpu.csp.tpu import pallas_ec

        import jax

        def make_tuples():
            # Python-side DER parse — only for the fallback paths; the
            # native marshaller parses DER itself.
            tuples = []
            for it in items:
                key = it.key
                if isinstance(key, ECDSAP256PrivateKey):
                    key = key.public_key()
                try:
                    r, s = api.unmarshal_ecdsa_signature(it.signature)
                except ValueError:
                    r, s = -1, -1  # prepare marks the lane invalid
                tuples.append((key.x, key.y, it.digest, r, s))
            return tuples

        def chunks():
            tuples = make_tuples()
            bsz = _bucket(len(tuples), _BATCH_BUCKETS)
            for off in range(0, len(tuples), bsz):
                chunk = tuples[off : off + bsz]
                keep = len(chunk)
                chunk = chunk + [
                    (api.P256_GX, api.P256_GY, b"", -1, -1)
                ] * (bsz - keep)
                yield chunk, keep

        if jax.default_backend() != "tpu":
            # The fused kernel is TPU-only (Mosaic); other backends get
            # the portable XLA kernel (interpreted Pallas would be
            # orders of magnitude slower on CPU test runs).  Dispatch is
            # async here too (JAX queues the computation); only the
            # np.asarray conversion blocks, and it lives in the
            # collector so pipelined callers keep their overlap.
            from fabric_tpu.csp.tpu import ec

            dispatched = [
                (ec.verify_prepared(**ec.prepare_batch(chunk)), keep)
                for chunk, keep in chunks()
            ]

            def collect_xla():
                results: list[bool] = []
                for out, keep in dispatched:
                    mask = np.asarray(out)
                    results.extend(bool(v) for v in mask[:keep])
                return results

            return collect_xla

        # Chunked pipeline over the fused Pallas kernel: every chunk is
        # dispatched (host prep + async device call) before any result is
        # collected, so host packing and the host->device hop of chunk
        # k+1 overlap chunk k's device time.  Host prep runs in the C++
        # marshaller when available (DER + prechecks + batch inversion +
        # packing in one pass), else the numpy path.
        packed_all = self._marshal_native(items)
        pending = []
        if packed_all is not None:
            # one np.unique + one key-table upload for the whole batch;
            # chunks slice only the per-lane arrays (the shared ktab
            # rides along by reference)
            packed_all = pallas_ec.dedup_keys(packed_all)
            shared = ("ktabx", "ktaby")
            n = len(items)
            bsz = _bucket(n, _BATCH_BUCKETS)
            for off in range(0, n, bsz):
                sl = {}
                for k, v in packed_all.items():
                    if k in shared:
                        sl[k] = v
                    elif v.ndim == 2:
                        sl[k] = v[:, off:off + bsz]
                    else:
                        sl[k] = v[off:off + bsz]
                keep = sl["valid"].shape[0]
                if keep < bsz:
                    # zero-pad (valid=False lanes) to the bucket size so
                    # every chunk reuses the same compiled kernel shape
                    sl = {
                        k: (v if k in shared else np.concatenate(
                            [v, np.zeros(
                                v.shape[:-1] + (bsz - keep,), v.dtype
                            )],
                            axis=-1,
                        ))
                        for k, v in sl.items()
                    }
                pending.append((pallas_ec.verify_packed(sl), keep))
        else:
            for chunk, keep in chunks():
                packed = pallas_ec.prepare_packed(chunk)
                pending.append(
                    (pallas_ec.verify_packed(pallas_ec.dedup_keys(packed)),
                     keep)
                )
        def collect_all():
            results = []
            for collect, keep in pending:
                results.extend(bool(v) for v in collect()[:keep])
            return results

        return collect_all

    @staticmethod
    def _marshal_native(items) -> dict | None:
        from fabric_tpu import native

        if not native.available():
            return None
        xs, ys, digs, sigs, offs = [], [], [], [], [0]
        bad_digest = []
        for i, it in enumerate(items):
            key = it.key
            if isinstance(key, ECDSAP256PrivateKey):
                key = key.public_key()
            xs.append(key.x.to_bytes(32, "big"))
            ys.append(key.y.to_bytes(32, "big"))
            if len(it.digest) == 32:
                digs.append(it.digest)
            else:
                digs.append(b"\0" * 32)
                bad_digest.append(i)
            sigs.append(it.signature)
            offs.append(offs[-1] + len(it.signature))
        packed = native.marshal_batch(
            b"".join(xs), b"".join(ys), b"".join(digs), b"".join(sigs),
            np.asarray(offs, np.int32),
        )
        if packed is not None and bad_digest:
            packed["valid"][bad_digest] = False
        return packed


__all__ = ["TPUCSP"]
