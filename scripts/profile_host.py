"""Profile the non-crypto host cost of the measured pipeline.

Uses a CSP whose verify_batch returns all-True instantly, so every
millisecond measured is host-side Python (collect glue, footprint,
policy prepare/finish, MVCC, persistence) — the serial budget that
bounds committed tx/s once device verify is overlapped.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for p in (_ROOT, os.path.join(_ROOT, "scripts"), os.path.join(_ROOT, "tests")):
    if p not in sys.path:
        sys.path.insert(0, p)

from bench_pipeline import _build_world, _make_blocks  # noqa: E402

from fabric_tpu.csp import SWCSP  # noqa: E402
from fabric_tpu.ledger import LedgerProvider  # noqa: E402
from fabric_tpu.peer.committer import Committer  # noqa: E402
from fabric_tpu.peer.txvalidator import TxValidator  # noqa: E402
from fabric_tpu.protos.common import common_pb2  # noqa: E402


class NullCSP(SWCSP):
    """All signatures 'verify' instantly."""

    def verify_batch(self, items):
        return [True] * len(items)

    def verify_batch_async(self, items):
        n = len(items)
        return lambda: [True] * n


def main() -> None:
    n_txs, n_blocks = 1000, 8
    sw = SWCSP()
    orgs, genesis = _build_world(5)
    _, bundle, blocks = _make_blocks(orgs, genesis, sw, n_txs, 3, n_blocks)
    csp = NullCSP()

    tmp = tempfile.TemporaryDirectory(prefix="fabric-prof-")
    fresh_n = [0]

    def fresh_ledger():
        fresh_n[0] += 1
        provider = LedgerProvider(os.path.join(tmp.name, f"run{fresh_n[0]}"))
        return provider.create(genesis)

    def copies(k):
        out = []
        for j in range(k):
            b = common_pb2.Block()
            b.CopyFrom(blocks[j % n_blocks])
            out.append(b)
        return out

    # warm
    led = fresh_ledger()
    Committer(TxValidator("benchch", led, bundle, csp), led).store_block(copies(1)[0])

    # total host wall for the stream
    best = float("inf")
    for _ in range(3):
        led = fresh_ledger()
        committer = Committer(TxValidator("benchch", led, bundle, csp), led)
        bs = copies(n_blocks)
        t0 = time.perf_counter()
        for flags in committer.store_stream(iter(bs), depth=4):
            assert all(f == 0 for f in flags)
        best = min(best, time.perf_counter() - t0)
    print(f"stream host wall: {best:.3f}s total, {best / n_blocks * 1e3:.1f} ms/block, {n_blocks * n_txs / best:.0f} tx/s ceiling")

    # per-phase breakdown on the serial path
    import cProfile
    import pstats

    led = fresh_ledger()
    committer = Committer(TxValidator("benchch", led, bundle, csp), led)
    bs = copies(n_blocks)
    pr = cProfile.Profile()
    pr.enable()
    for flags in committer.store_stream(iter(bs), depth=4):
        pass
    pr.disable()
    st = pstats.Stats(pr)
    st.sort_stats("cumulative")
    st.print_stats(35)


if __name__ == "__main__":
    main()
