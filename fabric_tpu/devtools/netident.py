"""netident — the netharness's stdlib-only network identity plane.

The multi-process network harness (``devtools/netharness.py`` +
``devtools/netnode.py``) must run in minimal containers WITHOUT the
``cryptography`` package, the same constraint tests/test_parallel_commit
and tests/gossip_worker already live under.  This module packages that
established fake-world pattern once, for every netharness consumer:

- a deterministic hash-derived key/signature scheme (``key_of`` /
  ``sign_as``) driving the REAL TxValidator through ``FakeBundle`` /
  ``FakeCSP`` — signatures verify iff they were produced by
  ``sign_as`` for the claimed identity, so the endorsement-policy and
  creator-signature lanes stay live;
- an HMAC-style gossip MessageCryptoService (``NetMCS``) keyed by a
  shared network secret, the multi-process analogue of the
  ``gossip_worker.ToyMCS`` pattern;
- deterministic genesis-block and endorser-envelope builders
  (``make_genesis`` / ``make_tx``) so every node of a topology derives
  the byte-identical chain anchor from the channel id alone.

This plane fakes IDENTITY only.  Everything else in a netharness
topology — raft ordering, TCP transports, gossip dissemination, the
commit pipeline, ledger recovery — is the production code.
"""

from __future__ import annotations

from fabric_tpu import protoutil
from fabric_tpu.common.hashing import sha256
from fabric_tpu.csp.api import VerifyBatchItem
from fabric_tpu.gossip.comm import MessageCryptoService
from fabric_tpu.ledger.kvstore import MemKVStore
from fabric_tpu.ledger.statedb import VersionedDB
from fabric_tpu.ledger.txmgmt import TxSimulator
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.peer import (
    proposal_pb2,
    proposal_response_pb2,
    transaction_pb2,
)


# -- hash-derived keys & signatures -------------------------------------------


class FakeKey:
    """Hash-derived public key carrying the .x/.y ints the validator's
    _ItemSink dedup key and the device marshaling layer expect."""

    __slots__ = ("x", "y")

    def __init__(self, x: int, y: int):
        self.x = x
        self.y = y

    def __eq__(self, other):
        return (self.x, self.y) == (other.x, other.y)

    def __hash__(self):
        return hash((self.x, self.y))


def key_of(ident_bytes: bytes) -> FakeKey:
    h = sha256(b"key:" + ident_bytes)
    return FakeKey(
        int.from_bytes(h[:16], "big"), int.from_bytes(h[16:], "big")
    )


def sign_as(ident_bytes: bytes, digest: bytes) -> bytes:
    k = key_of(ident_bytes)
    return sha256(b"sig:%d:%d:" % (k.x, k.y) + digest)


def _sig_ok(key: FakeKey, digest: bytes, sig: bytes) -> bool:
    return bytes(sig) == sha256(
        b"sig:%d:%d:" % (key.x, key.y) + bytes(digest)
    )


class FakeIdentity:
    def __init__(self, raw: bytes):
        self.raw = raw
        self.public_key = key_of(raw)

    def verification_item(self, msg: bytes, sig: bytes) -> VerifyBatchItem:
        return VerifyBatchItem(self.public_key, sha256(msg), sig)

    def verify(self, msg: bytes, sig: bytes) -> bool:
        return _sig_ok(self.public_key, sha256(msg), sig)


class FakeMSPManager:
    def deserialize_identity(self, raw: bytes) -> FakeIdentity:
        if bytes(raw).startswith(b"badid"):
            raise ValueError("unknown identity")
        return FakeIdentity(bytes(raw))

    def validate(self, ident: FakeIdentity) -> None:
        pass


class _FakePending:
    def __init__(self, items: list, k: int):
        self.items = items
        self._k = k

    def finish(self, mask) -> bool:
        return sum(bool(m) for m in mask) >= self._k


class FakePolicy:
    """k-of-n policy speaking BOTH policy interfaces the stack uses:
    the validator's two-phase prepare/finish batch protocol and the
    deliver service's evaluate_signed_data."""

    def __init__(self, k: int):
        self._k = k

    def prepare(self, signed) -> _FakePending:
        items = [
            VerifyBatchItem(
                key_of(bytes(sd.identity)),
                sd.digest if sd.digest is not None else sha256(sd.data),
                sd.signature,
            )
            for sd in signed
        ]
        return _FakePending(items, self._k)

    def evaluate_signed_data(self, signed, csp) -> bool:
        ok = 0
        for sd in signed:
            if not sd.identity:
                continue  # netharness deliver clients sign with no creator
            if _sig_ok(
                key_of(bytes(sd.identity)), sha256(sd.data), sd.signature
            ):
                ok += 1
        # deliver access is gated at 1-of-any (the reference's Readers
        # policy role); endorsement keeps the k-of-n bar via prepare()
        return ok >= 1


class FakePolicyManager:
    def __init__(self, k: int = 2):
        self._policy = FakePolicy(k)

    def get_policy(self, name: str) -> FakePolicy:
        return self._policy


class _FakeConfig:
    sequence = 0


class FakeBundle:
    """The minimal channel-config surface TxValidator + DeliverService
    consult: policy manager, MSP manager, and a config sequence."""

    def __init__(self, k: int = 2):
        self.policy_manager = FakePolicyManager(k)
        self.msp_manager = FakeMSPManager()
        self.config = _FakeConfig()


class FakeCSP:
    """Deterministic verify/hash backend: a signature is valid iff it is
    sign_as(identity, digest) for the item's hash-derived key."""

    def hash_batch(self, msgs):
        return [sha256(m) for m in msgs]

    def _mask(self, items):
        return [
            _sig_ok(it.key, it.digest, it.signature) for it in items
        ]

    def verify_batch_async(self, items):
        mask = self._mask(list(items))
        return lambda: mask

    def verify_batch(self, items):
        return self.verify_batch_async(items)()


# -- gossip crypto service ----------------------------------------------------


class NetMCS(MessageCryptoService):
    """Shared-secret gossip MCS: every node of one network signs with
    sign_as(secret || identity) — forged messages from outside the
    topology fail verification, and each node keeps a distinct pki id
    (its identity bytes are its node name)."""

    def __init__(self, secret: bytes):
        self._secret = bytes(secret)

    def sign(self, payload: bytes) -> bytes:
        return sha256(self._secret + b":" + payload)

    def verify(self, identity: bytes, signature: bytes,
               payload: bytes) -> bool:
        return bytes(signature) == sha256(self._secret + b":" + payload)


# -- deterministic chain anchors & transactions -------------------------------


def make_genesis(channel_id: str) -> common_pb2.Block:
    """The topology's byte-deterministic block 0: a CONFIG-typed
    envelope carrying the channel id, so every orderer and peer derives
    the identical chain anchor from the channel id alone (no shared
    disk, no coordination)."""
    chdr = protoutil.make_channel_header(
        common_pb2.CONFIG, channel_id=channel_id, timestamp=0,
    )
    shdr = protoutil.make_signature_header(b"netharness", b"genesis-nonce")
    payload = common_pb2.Payload(data=b"netharness-genesis")
    payload.header.channel_header = chdr.SerializeToString()
    payload.header.signature_header = shdr.SerializeToString()
    env = common_pb2.Envelope(payload=payload.SerializeToString())
    blk = common_pb2.Block()
    blk.header.number = 0
    blk.header.previous_hash = b""
    blk.data.data.append(env.SerializeToString())
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    protoutil.init_block_metadata(blk)
    protoutil.set_tx_filter(blk, bytearray(1))
    return blk


def org_endorsers(orgs: int) -> list[bytes]:
    return [b"end:org%d" % i for i in range(1, max(orgs, 1) + 1)]


def make_tx(channel_id: str, key: str, value: bytes,
            orgs: int = 3, cc: str = "netcc",
            creator: bytes | None = None) -> bytes:
    """One fully well-formed, policy-satisfying endorser envelope over
    the fake plane: endorsed by every org's endorser (2-of-n policy),
    deterministic txid from the write key."""
    sim = TxSimulator(VersionedDB(MemKVStore()))
    sim.set_state(cc, key, value)
    rwset = sim.get_tx_simulation_results()
    creator = creator or b"cre:net-client"
    nonce = sha256(b"nonce:" + channel_id.encode() + b":" + key.encode())
    txid = protoutil.compute_tx_id(nonce, creator)
    ext = proposal_pb2.ChaincodeHeaderExtension()
    ext.chaincode_id.name = cc
    chdr = protoutil.make_channel_header(
        common_pb2.ENDORSER_TRANSACTION, channel_id, tx_id=txid,
        extension=ext.SerializeToString(), timestamp=0,
    )
    shdr = protoutil.make_signature_header(creator, nonce)
    chdr_b = chdr.SerializeToString()
    shdr_b = shdr.SerializeToString()
    ccpp_b = proposal_pb2.ChaincodeProposalPayload(
        input=b"input:" + key.encode()
    ).SerializeToString()
    action = proposal_pb2.ChaincodeAction(results=rwset)
    action.chaincode_id.name = cc
    prp = proposal_response_pb2.ProposalResponsePayload(
        proposal_hash=protoutil.proposal_hash2(chdr_b, shdr_b, ccpp_b),
        extension=action.SerializeToString(),
    )
    prp_b = prp.SerializeToString()
    endos = [
        proposal_response_pb2.Endorsement(
            endorser=eb, signature=sign_as(eb, sha256(prp_b + eb))
        )
        for eb in org_endorsers(orgs)[:3] or [b"end:org1"]
    ]
    cap = transaction_pb2.ChaincodeActionPayload(
        chaincode_proposal_payload=ccpp_b,
        action=transaction_pb2.ChaincodeEndorsedAction(
            proposal_response_payload=prp_b, endorsements=endos
        ),
    )
    tx = transaction_pb2.Transaction(
        actions=[
            transaction_pb2.TransactionAction(payload=cap.SerializeToString())
        ]
    )
    payload_b = common_pb2.Payload(
        header=common_pb2.Header(
            channel_header=chdr_b, signature_header=shdr_b
        ),
        data=tx.SerializeToString(),
    ).SerializeToString()
    return common_pb2.Envelope(
        payload=payload_b, signature=sign_as(creator, sha256(payload_b))
    ).SerializeToString()


__all__ = [
    "FakeKey", "FakeIdentity", "FakeMSPManager", "FakePolicy",
    "FakePolicyManager", "FakeBundle", "FakeCSP", "NetMCS",
    "key_of", "sign_as", "make_genesis", "make_tx", "org_endorsers",
]
