"""Policy engine semantics vs the reference's cauthdsl behavior:
NOutOf combinatorics, signature dedup, the used[] no-double-spend rule,
DSL parsing, implicit meta thresholds."""

import pytest

from fabric_tpu.msp import MSPManager
from fabric_tpu.policies import (
    ImplicitMetaPolicy,
    SignaturePolicy,
    from_string,
    manager_from_config_group,
)
from fabric_tpu.protos.common import configtx_pb2, policies_pb2
from fabric_tpu.protoutil import SignedData

from orgfix import make_org


def sd(signer, msg=b"payload"):
    return SignedData(msg, signer.serialize(), signer.sign(msg))


def bad_sd(signer, msg=b"payload"):
    return SignedData(msg, signer.serialize(), b"\x30\x03\x02\x01\x01")


@pytest.fixture(scope="module")
def orgs():
    org1 = make_org("Org1MSP")
    org2 = make_org("Org2MSP")
    org3 = make_org("Org3MSP")
    mgr = MSPManager([org1.msp, org2.msp, org3.msp])
    return org1, org2, org3, mgr


def test_dsl_parse_shapes():
    env = from_string("AND('Org1MSP.member', OR('Org2MSP.admin', 'Org3MSP.peer'))")
    assert env.rule.n_out_of.n == 2
    assert len(env.identities) == 3
    inner = env.rule.n_out_of.rules[1]
    assert inner.n_out_of.n == 1
    env2 = from_string("OutOf(2, 'A.member', 'B.member', 'C.member')")
    assert env2.rule.n_out_of.n == 2
    # dedup of repeated principals
    env3 = from_string("OR('A.member', 'A.member')")
    assert len(env3.identities) == 1
    with pytest.raises(Exception):
        from_string("NAND('A.member')")
    with pytest.raises(Exception):
        from_string("OutOf(4, 'A.member')")


def test_one_of_and_two_of(orgs):
    org1, org2, org3, mgr = orgs
    csp = org1.csp
    pol = SignaturePolicy(
        from_string("OR('Org1MSP.member', 'Org2MSP.member')"), mgr
    )
    s1 = org1.signer("peer0")
    s2 = org2.signer("peer0")
    s3 = org3.signer("peer0")
    assert pol.evaluate_signed_data([sd(s1)], csp)
    assert pol.evaluate_signed_data([sd(s2)], csp)
    assert not pol.evaluate_signed_data([sd(s3)], csp)
    assert not pol.evaluate_signed_data([bad_sd(s1)], csp)

    and_pol = SignaturePolicy(
        from_string("AND('Org1MSP.member', 'Org2MSP.member')"), mgr
    )
    assert and_pol.evaluate_signed_data([sd(s1), sd(s2)], csp)
    assert not and_pol.evaluate_signed_data([sd(s1)], csp)
    # invalid second signature: AND fails even though identity satisfies
    assert not and_pol.evaluate_signed_data([sd(s1), bad_sd(s2)], csp)


def test_same_identity_cannot_satisfy_two_leaves(orgs):
    """The used[] rule (cauthdsl.go:40-60): one signer cannot count twice
    for AND('Org1.member','Org1.member')."""
    org1, _, _, mgr = orgs
    pol = SignaturePolicy(
        from_string("AND('Org1MSP.member', 'Org1MSP.member')"), mgr
    )
    s1 = org1.signer("peer0")
    s1b = org1.signer("peer1")
    # the same signed-data twice dedups to one identity -> fails
    assert not pol.evaluate_signed_data([sd(s1), sd(s1)], org1.csp)
    # two distinct org members pass
    assert pol.evaluate_signed_data([sd(s1), sd(s1b)], org1.csp)


def test_three_of_five(orgs):
    org1, org2, org3, mgr = orgs
    signers = [org1.signer(f"p{i}") for i in range(3)] + [
        org2.signer("p3"), org3.signer("p4")
    ]
    pol = SignaturePolicy(
        from_string(
            "OutOf(3, 'Org1MSP.member', 'Org1MSP.member', 'Org1MSP.member',"
            " 'Org2MSP.member', 'Org3MSP.member')"
        ),
        mgr,
    )
    csp = org1.csp
    assert pol.evaluate_signed_data([sd(s) for s in signers[:3]], csp)
    assert pol.evaluate_signed_data([sd(signers[0]), sd(signers[3]), sd(signers[4])], csp)
    assert not pol.evaluate_signed_data([sd(signers[0]), sd(signers[3])], csp)
    # 3 sigs, one invalid -> only 2 valid -> fail
    assert not pol.evaluate_signed_data(
        [sd(signers[0]), sd(signers[3]), bad_sd(signers[4])], csp
    )


def test_prepare_finish_batching_split(orgs):
    """The two-phase protocol: items collected without verification, then
    finish() consumes an externally-computed mask."""
    org1, org2, _, mgr = orgs
    pol = SignaturePolicy(from_string("AND('Org1MSP.member', 'Org2MSP.member')"), mgr)
    s1, s2 = org1.signer("x"), org2.signer("y")
    pending = pol.prepare([sd(s1), sd(s2)])
    assert len(pending.items) == 2
    assert pending.finish([True, True])
    assert not pending.finish([True, False])
    mask = org1.csp.verify_batch(pending.items)
    assert pending.finish(mask)


def test_implicit_meta_and_manager(orgs):
    org1, org2, org3, mgr = orgs
    csp = org1.csp

    def group_with_writers(dsl):
        g = configtx_pb2.ConfigGroup()
        g.policies["Writers"].policy.type = policies_pb2.Policy.SIGNATURE
        g.policies["Writers"].policy.value = from_string(dsl).SerializeToString()
        return g

    app = configtx_pb2.ConfigGroup()
    app.groups["Org1"].CopyFrom(group_with_writers("'Org1MSP.member'"))
    app.groups["Org2"].CopyFrom(group_with_writers("'Org2MSP.member'"))
    app.groups["Org3"].CopyFrom(group_with_writers("'Org3MSP.member'"))
    app.policies["Writers"].policy.type = policies_pb2.Policy.IMPLICIT_META
    app.policies["Writers"].policy.value = policies_pb2.ImplicitMetaPolicy(
        sub_policy="Writers", rule=policies_pb2.ImplicitMetaPolicy.MAJORITY
    ).SerializeToString()
    channel = configtx_pb2.ConfigGroup()
    channel.groups["Application"].CopyFrom(app)

    mgr_tree = manager_from_config_group("Channel", channel, mgr)
    pol = mgr_tree.get_policy("/Channel/Application/Writers")
    s1, s2, s3 = org1.signer("a"), org2.signer("b"), org3.signer("c")
    # MAJORITY of 3 orgs = 2
    assert pol.evaluate_signed_data([sd(s1), sd(s2)], csp)
    assert not pol.evaluate_signed_data([sd(s1)], csp)
    assert pol.evaluate_signed_data([sd(s1), sd(s2), sd(s3)], csp)
    # relative lookup from the Application manager
    app_mgr = mgr_tree.manager(["Application"])
    assert app_mgr.get_policy("Org1/Writers").evaluate_signed_data([sd(s1)], csp)
    # unknown policy rejects
    assert not mgr_tree.get_policy("/Channel/Nope/Writers").evaluate_signed_data(
        [sd(s1)], csp
    )
