"""Gateway submission front-end (ISSUE 16 tentpole): pipelined
broadcast with commit-status tracking, txid dedup, adaptive-window
backpressure, and deterministic orderer failover.

Tier-1 pins:
- resubmitting a txid is idempotent: the orderer sees ONE copy while
  the first is in flight, and a resolved txid answers from the dedup
  map with its final status;
- a full admission window rejects with a retry-after hint and recovers
  as the deliver tail resolves records;
- mid-stream orderer death (deterministic handler kill in-proc, real
  SIGKILL in the netharness case) triggers ONE failover to the next
  endpoint in index order and every accepted tx still reaches a
  definitive status — zero lost-and-unreported;
- a `wait` that expires resolves the record to TIMEOUT under the
  virtual clock (no real sleeps), and later commits cannot flip it;
- `stop()` resolves leftover PENDING records to TIMEOUT;
- every gateway.* faultline point self-registers under an observer
  plan, and a seeded raise at gateway.stream.write takes the same
  requeue-and-failover path a real torn write does.
"""

from __future__ import annotations

import threading
import time

import pytest

from fabric_tpu.comm import RPCServer
from fabric_tpu.common.metrics import GatewayMetrics, PrometheusProvider
from fabric_tpu.devtools.netscope import parse_prometheus
from fabric_tpu.devtools import clockskew, faultline, netident
from fabric_tpu.gateway import (
    Gateway,
    STATUS_INVALID,
    STATUS_PENDING,
    STATUS_TIMEOUT,
    STATUS_VALID,
)
from fabric_tpu.gateway.core import txid_of

from fabric_tpu import protoutil
from fabric_tpu.protos.common import common_pb2

CHANNEL = "netchan"


def _wait_until(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timeout waiting for {msg}")


def _env(key: str, val: bytes = b"v") -> bytes:
    return netident.make_tx(CHANNEL, key, val, orgs=1)


def _block(envs, flags, num=0) -> bytes:
    blk = common_pb2.Block()
    blk.header.number = num
    for e in envs:
        blk.data.data.append(e)
    protoutil.set_tx_filter(blk, bytes(flags))
    return blk.SerializeToString()


class _MiniOrderer:
    """An in-proc ab.BroadcastStream endpoint over the REAL framed-RPC
    transport.  ``die_after`` kills the stream (handler raise -> ERR
    frame + close) after N envelopes — a deterministic mid-stream
    orderer death."""

    def __init__(self, die_after: int | None = None):
        self.received: list[bytes] = []
        self._lock = threading.Lock()
        self._die_after = die_after
        self.srv = RPCServer("127.0.0.1", 0)
        self.srv.register("ab.BroadcastStream", self._handle)
        self.srv.start()

    def _handle(self, body, stream):
        while True:
            frame = stream.recv()
            if not frame:
                return None
            with self._lock:
                self.received.append(frame)
                n = len(self.received)
            if self._die_after is not None and n >= self._die_after:
                raise OSError("orderer died mid-stream (test)")
            stream.send(b"\x00")

    def count(self) -> int:
        with self._lock:
            return len(self.received)

    def txids(self) -> set:
        with self._lock:
            return {txid_of(f) for f in self.received}

    def connect_factory(self):
        from fabric_tpu.comm import RPCClient

        host, port = self.srv.addr
        return lambda: RPCClient(host, port, timeout=5).duplex(
            "ab.BroadcastStream"
        )

    def stop(self):
        self.srv.stop()


class _FakeStream:
    """Socket-free duplex stream for virtual-clock tests: swallows
    sends, recv blocks until close."""

    def __init__(self, sent: list):
        self._sent = sent
        self._closed = threading.Event()

    def send(self, body):
        self._sent.append(body)

    def finish(self):
        pass

    def recv(self):
        self._closed.wait()
        return None

    def close(self):
        self._closed.set()


# ---------------------------------------------------------------------------
# dedup idempotency
# ---------------------------------------------------------------------------


def test_dedup_idempotent_resubmission():
    ord0 = _MiniOrderer()
    provider = PrometheusProvider()
    gw = Gateway(
        CHANNEL, [ord0.connect_factory()],
        metrics=GatewayMetrics(provider),
    )
    gw.start()
    try:
        env_a, env_b = _env("da"), _env("db")
        tx_a = txid_of(env_a)
        r1 = gw.submit(env_a)
        assert r1.accepted and not r1.dedup and r1.txid == tx_a
        # in-flight resubmission: answered from the dedup map, nothing
        # new enters the send queue
        r2 = gw.submit(env_a)
        assert r2.accepted and r2.dedup and r2.status == STATUS_PENDING
        r3 = gw.submit(env_b)
        assert r3.accepted and not r3.dedup
        _wait_until(lambda: ord0.count() == 2, msg="both txs ordered")
        time.sleep(0.05)  # grace: a duplicate write would land now
        assert ord0.count() == 2, "dedup let a duplicate through"
        assert ord0.txids() == {tx_a, txid_of(env_b)}
        # resolve A valid, B invalid; a resolved txid answers
        # idempotently with its FINAL status
        gw.observe_block(0, _block([env_a, env_b], [0, 1]))
        r4 = gw.submit(env_a)
        assert r4.accepted and r4.dedup and r4.status == STATUS_VALID
        assert gw.submit_and_wait(env_a, timeout=1.0) == STATUS_VALID
        assert gw.status(txid_of(env_b)) == STATUS_INVALID
        assert gw.in_flight == 0
        samples = parse_prometheus(provider.registry.expose())
        hits = [v for n, _, v in samples
                if n == "gateway_dedup_hits_total"]
        assert hits and hits[0] >= 3.0
    finally:
        gw.stop()
        ord0.stop()


# ---------------------------------------------------------------------------
# backpressure: reject with retry-after, recover on resolution
# ---------------------------------------------------------------------------


def test_backpressure_reject_and_recover():
    ord0 = _MiniOrderer()
    provider = PrometheusProvider()
    gw = Gateway(
        CHANNEL, [ord0.connect_factory()],
        metrics=GatewayMetrics(provider),
        min_window=1, max_window=4, initial_window=2,
    )
    gw.start()
    try:
        envs = [_env(f"bp{i}") for i in range(3)]
        assert gw.submit(envs[0]).accepted
        assert gw.submit(envs[1]).accepted
        rej = gw.submit(envs[2])
        assert not rej.accepted
        assert rej.retry_after_s > 0.0
        assert rej.status == STATUS_PENDING
        # the rejected envelope was NOT admitted
        assert gw.in_flight == 2
        # deliver-observed resolution frees the window
        gw.observe_block(0, _block(envs[:2], [0, 0]))
        assert gw.in_flight == 0
        ok = gw.submit(envs[2])
        assert ok.accepted and not ok.dedup
        samples = parse_prometheus(provider.registry.expose())
        rej_n = [v for n, _, v in samples
                 if n == "gateway_rejections_total"]
        assert rej_n == [1.0]
    finally:
        gw.stop()
        ord0.stop()


def test_adaptive_window_follows_commit_rate():
    gw = Gateway(
        CHANNEL, [lambda: _FakeStream([])],
        min_window=2, max_window=64, initial_window=8,
        window_horizon_s=1.0,
    )
    # no threads needed: observe_block drives the window directly
    with clockskew.use_virtual(clockskew.VirtualClock(start=100.0)) as clk:
        envs = [_env(f"aw{i}") for i in range(4)]
        gw.observe_block(0, _block(envs[:2], [0, 0], num=0))
        clk.advance(0.1)  # 2 txs / 0.1s -> 20 tx/s
        gw.observe_block(1, _block(envs[2:], [0, 0], num=1))
    assert 2 <= gw.window <= 20  # EWMA-clamped, far below max
    # a replayed block is idempotent: tail height holds
    before = gw.window
    gw.observe_block(0, _block(envs[:2], [0, 0], num=0))
    assert gw.window == before


# ---------------------------------------------------------------------------
# failover: mid-stream orderer death, zero lost-and-unreported
# ---------------------------------------------------------------------------


def test_failover_orderer_death_mid_stream_zero_lost():
    # orderer A dies deterministically after 3 envelopes; B survives
    ord_a = _MiniOrderer(die_after=3)
    ord_b = _MiniOrderer()
    gw = Gateway(
        CHANNEL,
        [ord_a.connect_factory(), ord_b.connect_factory()],
        max_backoff_s=0.05,
    )
    gw.start()
    try:
        envs = [_env(f"fo{i}") for i in range(10)]
        for e in envs:
            assert gw.submit(e).accepted
        all_txids = {txid_of(e) for e in envs}
        # every accepted envelope must reach the SURVIVING orderer:
        # the dead one may have dropped any of its 3, so all
        # sent-but-unresolved envelopes are resubmitted
        _wait_until(
            lambda: ord_b.txids() >= all_txids,
            msg="survivor ordered every accepted tx",
        )
        assert gw.failovers >= 1
        # deterministic rotation: index 0 first, then index 1
        log = list(gw.endpoint_log)
        assert log[0] == 0 and 1 in log
        # commit everything -> every accepted tx has a definitive
        # status (zero lost-and-unreported)
        gw.observe_block(0, _block(envs, [0] * len(envs)))
        assert gw.in_flight == 0
        assert all(
            gw.status(t) == STATUS_VALID for t in all_txids
        )
    finally:
        gw.stop()
        ord_a.stop()
        ord_b.stop()


def test_submit_after_stream_loss_still_delivers():
    # death between submissions: the gateway reconnects lazily on the
    # next write, not only when traffic is already flowing
    ord_a = _MiniOrderer(die_after=1)
    ord_b = _MiniOrderer()
    gw = Gateway(
        CHANNEL,
        [ord_a.connect_factory(), ord_b.connect_factory()],
        max_backoff_s=0.05,
    )
    gw.start()
    try:
        e0 = _env("ls0")
        gw.submit(e0)
        _wait_until(lambda: ord_a.count() >= 1, msg="first tx ordered")
        _wait_until(lambda: gw.failovers >= 1, msg="stream loss noticed")
        e1 = _env("ls1")
        gw.submit(e1)
        _wait_until(
            lambda: ord_b.txids() >= {txid_of(e0), txid_of(e1)},
            msg="both txs on the survivor",
        )
    finally:
        gw.stop()
        ord_a.stop()
        ord_b.stop()


# ---------------------------------------------------------------------------
# commit-status timeout under the virtual clock (no real sleeps)
# ---------------------------------------------------------------------------


def test_wait_timeout_resolves_definitively_virtual_clock():
    sent: list = []
    gw = Gateway(CHANNEL, [lambda: _FakeStream(sent)])
    gw.start()
    try:
        with clockskew.use_virtual(clockskew.VirtualClock(start=50.0)):
            env = _env("to0")
            txid = txid_of(env)
            assert gw.submit(env).accepted
            t0 = time.monotonic()
            st = gw.wait(txid, timeout=30.0)
            real = time.monotonic() - t0
            assert st == STATUS_TIMEOUT
            assert real < 5.0, "virtual-clock wait slept for real"
            # the expiry RESOLVED the record: window freed, status
            # definitive, a late commit cannot flip it
            assert gw.in_flight == 0
            gw.observe_block(0, _block([env], [0]))
            assert gw.status(txid) == STATUS_TIMEOUT
            assert gw.submit(env).status == STATUS_TIMEOUT
    finally:
        gw.stop()


def test_stop_resolves_pending_to_timeout():
    sent: list = []
    gw = Gateway(CHANNEL, [lambda: _FakeStream(sent)])
    gw.start()
    envs = [_env(f"sp{i}") for i in range(3)]
    for e in envs:
        assert gw.submit(e).accepted
    gw.stop()
    # shutdown reports, it never silently drops
    assert gw.in_flight == 0
    for e in envs:
        assert gw.status(txid_of(e)) == STATUS_TIMEOUT


# ---------------------------------------------------------------------------
# faultline: observer-plan discovery + seeded mid-stream loss
# ---------------------------------------------------------------------------


def test_observer_plan_discovers_gateway_points():
    faultline.reset_registry()
    ord_a = _MiniOrderer(die_after=2)
    ord_b = _MiniOrderer()
    with faultline.observe():
        gw = Gateway(
            CHANNEL,
            [ord_a.connect_factory(), ord_b.connect_factory()],
            max_backoff_s=0.05,
        )
        gw.start()
        try:
            envs = [_env(f"ob{i}") for i in range(4)]
            for e in envs:
                gw.submit(e)
            _wait_until(lambda: gw.failovers >= 1, msg="failover")
            _wait_until(
                lambda: ord_b.txids() >= {txid_of(e) for e in envs},
                msg="survivor ordered everything",
            )
            gw.observe_block(0, _block(envs, [0] * 4))
        finally:
            gw.stop()
            ord_a.stop()
            ord_b.stop()
        assert faultline.trips() == []  # observer never fires
    reg = faultline.registry()
    for point in (
        "gateway.admission",
        "gateway.stream.write",
        "gateway.failover",
        "gateway.status.resolve",
    ):
        assert point in reg, f"{point} missing from discovery"
        assert reg[point]["kinds"] == ["point"]
    faultline.reset_registry()


def test_seeded_raise_at_stream_write_takes_failover_path():
    # an armed raise at gateway.stream.write IS a torn mid-stream
    # write: same requeue + failover + resubmit path, and the tx still
    # reaches a definitive status
    ord_a = _MiniOrderer()
    ord_b = _MiniOrderer()
    gw = Gateway(
        CHANNEL,
        [ord_a.connect_factory(), ord_b.connect_factory()],
        max_backoff_s=0.05,
    )
    gw.start()
    try:
        with faultline.use_plan({"label": "gw-loss", "faults": [
            {"point": "gateway.stream.write", "action": "raise",
             "error": "OSError", "count": 1},
        ]}):
            envs = [_env(f"sr{i}") for i in range(5)]
            for e in envs:
                assert gw.submit(e).accepted
            all_txids = {txid_of(e) for e in envs}
            _wait_until(lambda: gw.failovers >= 1, msg="injected loss")
            _wait_until(
                lambda: ord_a.txids() | ord_b.txids() >= all_txids,
                msg="every tx ordered despite the injected loss",
            )
            trips = faultline.trips()
            assert any(
                t["point"] == "gateway.stream.write" for t in trips
            )
        gw.observe_block(0, _block(envs, [0] * 5))
        assert all(gw.status(t) == STATUS_VALID for t in all_txids)
        assert gw.in_flight == 0
    finally:
        gw.stop()
        ord_a.stop()
        ord_b.stop()


def test_armed_plan_trips_every_gateway_point():
    """Pinned arming plan for the gateway's three bare ``point`` seams
    (admission / status.resolve / failover): zero-delay counting rules
    plus a seeded mid-stream loss to force the failover path, asserting
    each seam actually trips.  This is the plan the chaos-coverage
    faultmap cross-check counts as coverage for these names."""
    ord_a = _MiniOrderer()
    ord_b = _MiniOrderer()
    gw = Gateway(
        CHANNEL,
        [ord_a.connect_factory(), ord_b.connect_factory()],
        max_backoff_s=0.05,
    )
    gw.start()
    try:
        with faultline.use_plan({"seed": 1, "label": "gw-arm", "faults": [
            {"point": "gateway.admission", "action": "delay",
             "delay_s": 0.0, "count": 100},
            {"point": "gateway.status.resolve", "action": "delay",
             "delay_s": 0.0, "count": 100},
            {"point": "gateway.failover", "action": "delay",
             "delay_s": 0.0, "count": 100},
            {"point": "gateway.stream.write", "action": "raise",
             "error": "OSError", "count": 1},
        ]}):
            envs = [_env(f"ap{i}") for i in range(5)]
            for e in envs:
                assert gw.submit(e).accepted
            all_txids = {txid_of(e) for e in envs}
            _wait_until(lambda: gw.failovers >= 1, msg="injected loss")
            _wait_until(
                lambda: ord_a.txids() | ord_b.txids() >= all_txids,
                msg="every tx ordered despite the injected loss",
            )
            gw.observe_block(0, _block(envs, [0] * 5))
            assert all(gw.status(t) == STATUS_VALID for t in all_txids)
            tripped = {t["point"] for t in faultline.trips()}
        for point in ("gateway.admission", "gateway.status.resolve",
                      "gateway.failover"):
            assert point in tripped, f"{point} never tripped"
    finally:
        gw.stop()
        ord_a.stop()
        ord_b.stop()


# ---------------------------------------------------------------------------
# the real thing: orderer SIGKILL mid-stream under the netharness
# ---------------------------------------------------------------------------


def test_gateway_survives_orderer_kill9_multiprocess(tmp_path):
    from fabric_tpu.devtools import netharness as nh

    topo = nh.Topology(orgs=1, peers_per_org=2, orderers=3, seed=7)
    # the gateway's deterministic rotation starts at index 0 — SIGKILL
    # exactly the orderer it is streaming to, mid-stream
    schedule = [nh.KillRule(
        node=topo.orderer_names()[0], at_height=3, sig="kill9",
        rejoin="restart", restart_after_s=0.5,
    )]
    with nh.Network(str(tmp_path / "net"), topo) as net:
        net.start()
        result = nh.run_stream(
            net, txs=80, kill_schedule=schedule, settle_timeout_s=120,
            driver="gateway",
        )
    assert result["errors"] == []
    assert result["ok"], result
    assert result["state_digests_agree"]
    assert result["missing"] == []
    gwd = result["gateway"]
    # the SIGKILL produced at least one failover to a DIFFERENT index,
    # and every accepted tx resolved before stop (zero unreported)
    assert gwd["failovers"] >= 1, gwd
    assert len(set(gwd["endpoint_log"])) >= 2, gwd
    assert gwd["unresolved_at_stop"] == 0, gwd
