from fabric_tpu.chaincode.shim import Chaincode, ChaincodeStub, shim_main
from fabric_tpu.chaincode.support import ChaincodeSupport, InProcStream

__all__ = [
    "Chaincode",
    "ChaincodeStub",
    "shim_main",
    "ChaincodeSupport",
    "InProcStream",
]
