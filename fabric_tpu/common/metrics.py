"""Metrics provider SPI + prometheus-text / statsd-line / disabled impls.

Reference: common/metrics — provider SPI (provider.go: Counter/Gauge/
Histogram created from *Opts, each supporting With(label pairs)),
prometheus provider (prometheus/provider.go:20-48), statsd provider
(statsd/provider.go with go-kit), disabled provider, and the gendoc
metric catalog.  The operations server (fabric_tpu/common/operations.py)
scrapes `PrometheusRegistry.expose()` for its /metrics endpoint.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class CounterOpts:
    namespace: str = ""
    subsystem: str = ""
    name: str = ""
    help: str = ""
    label_names: tuple[str, ...] = ()
    statsd_format: str = ""


@dataclasses.dataclass(frozen=True)
class GaugeOpts:
    namespace: str = ""
    subsystem: str = ""
    name: str = ""
    help: str = ""
    label_names: tuple[str, ...] = ()
    statsd_format: str = ""


@dataclasses.dataclass(frozen=True)
class HistogramOpts:
    namespace: str = ""
    subsystem: str = ""
    name: str = ""
    help: str = ""
    label_names: tuple[str, ...] = ()
    buckets: tuple[float, ...] = (
        0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10,
    )
    statsd_format: str = ""


def _fqname(opts) -> str:
    return "_".join(p for p in (opts.namespace, opts.subsystem, opts.name) if p)


def _label_key(
    label_names: Sequence[str], label_values: Sequence[str]
) -> tuple[tuple[str, str], ...]:
    if len(label_values) % 2 == 0 and not label_names:
        # With("name", "value", ...) pairs form
        it = iter(label_values)
        return tuple(sorted(zip(it, it)))
    raise ValueError("labels must be alternating name/value pairs")


class _Metric:
    """Base: holds per-labelset series."""

    def __init__(self, opts, registry):
        self.opts = opts
        self.name = _fqname(opts)
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()
        self._labels: tuple[tuple[str, str], ...] = ()
        if registry is not None:
            registry._register(self)

    def with_labels(self, *pairs: str) -> "_Metric":
        c = type(self).__new__(type(self))
        c.opts = self.opts
        c.name = self.name
        c._series = self._series
        c._lock = self._lock
        it = iter(pairs)
        c._labels = tuple(sorted(self._labels + tuple(zip(it, it))))
        return c

    # go-kit naming
    With = with_labels


class Counter(_Metric):
    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self._series[self._labels] = (
                self._series.get(self._labels, 0.0) + delta
            )


class Gauge(_Metric):
    def set(self, value: float) -> None:
        with self._lock:
            self._series[self._labels] = value

    def add(self, delta: float) -> None:
        with self._lock:
            self._series[self._labels] = (
                self._series.get(self._labels, 0.0) + delta
            )


class Histogram(_Metric):
    def __init__(self, opts, registry):
        super().__init__(opts, registry)
        self._obs: dict[tuple, list] = {}

    def with_labels(self, *pairs: str) -> "Histogram":
        c = super().with_labels(*pairs)
        c._obs = self._obs
        return c

    With = with_labels

    def observe(self, value: float) -> None:
        with self._lock:
            rec = self._obs.setdefault(
                self._labels, [0, 0.0, [0] * len(self.opts.buckets)]
            )
            rec[0] += 1
            rec[1] += value
            # per-bucket counts are NON-cumulative here; expose()
            # cumulates once.  (The old form incremented every bucket
            # >= value AND re-cumulated at exposition, so a rendered
            # _bucket count could exceed _count — non-monotonic output
            # that a strict scraper rejects.)
            for i, b in enumerate(self.opts.buckets):
                if value <= b:
                    rec[2][i] += 1
                    break


class PrometheusRegistry:
    """Collects metrics and renders the prometheus text format for the
    operations endpoint."""

    def __init__(self):
        self._metrics: list[_Metric] = []
        self._collectors: list = []
        self._lock = threading.Lock()

    def _register(self, m: _Metric) -> None:
        with self._lock:
            self._metrics.append(m)

    def register_collector(self, fn) -> None:
        """Register a zero-arg callable invoked at the top of every
        expose() — the prometheus Collector idiom for values that are
        READ at scrape time rather than observed as they change
        (process CPU/RSS/fds, GC totals).  A collector that raises is
        skipped for that scrape, never fails the endpoint."""
        with self._lock:
            self._collectors.append(fn)

    @staticmethod
    def _escape_label_value(v) -> str:
        """Prometheus text-format label-value escaping: backslash,
        double quote, and newline (exposition format spec) — a label
        value carrying any of them must not corrupt the line framing
        the netscope parser (and any real scraper) relies on."""
        return (
            str(v)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    @classmethod
    def _fmt_labels(cls, labels) -> str:
        if not labels:
            return ""
        inner = ",".join(
            f'{k}="{cls._escape_label_value(v)}"' for k, v in labels
        )
        return "{" + inner + "}"

    def expose(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass
        for m in metrics:
            kind = (
                "counter" if isinstance(m, Counter)
                else "histogram" if isinstance(m, Histogram)
                else "gauge"
            )
            if m.opts.help:
                lines.append(f"# HELP {m.name} {m.opts.help}")
            lines.append(f"# TYPE {m.name} {kind}")
            if isinstance(m, Histogram):
                for labels, (count, total, buckets) in sorted(
                    m._obs.items()
                ):
                    cum = 0
                    for b, n in zip(m.opts.buckets, buckets):
                        cum += n
                        lb = dict(labels)
                        lb["le"] = (
                            f"{b:g}" if not math.isinf(b) else "+Inf"
                        )
                        lines.append(
                            f"{m.name}_bucket"
                            f"{self._fmt_labels(sorted(lb.items()))} {cum}"
                        )
                    inf = dict(labels)
                    inf["le"] = "+Inf"
                    lines.append(
                        f"{m.name}_bucket"
                        f"{self._fmt_labels(sorted(inf.items()))} {count}"
                    )
                    lines.append(
                        f"{m.name}_sum{self._fmt_labels(labels)} {total:g}"
                    )
                    lines.append(
                        f"{m.name}_count{self._fmt_labels(labels)} {count}"
                    )
            else:
                for labels, v in sorted(m._series.items()):
                    lines.append(
                        f"{m.name}{self._fmt_labels(labels)} {v:g}"
                    )
        return "\n".join(lines) + "\n"


class PrometheusProvider:
    """Reference prometheus/provider.go: NewCounter/NewGauge/NewHistogram."""

    def __init__(self, registry: PrometheusRegistry | None = None):
        self.registry = registry or PrometheusRegistry()

    def new_counter(self, opts: CounterOpts) -> Counter:
        return Counter(opts, self.registry)

    def new_gauge(self, opts: GaugeOpts) -> Gauge:
        return Gauge(opts, self.registry)

    def new_histogram(self, opts: HistogramOpts) -> Histogram:
        return Histogram(opts, self.registry)


class StatsdProvider:
    """Emits statsd lines through a supplied `send(line: str)` callable
    (reference statsd/provider.go; the gokit statsd emitter is replaced by
    the callable so tests/deployments choose the socket)."""

    def __init__(self, send, prefix: str = ""):
        self._send = send
        self._prefix = prefix

    def _name(self, opts, labels=()) -> str:
        base = _fqname(opts)
        if self._prefix:
            base = f"{self._prefix}.{base}"
        fmt = opts.statsd_format
        if fmt:
            for k, v in labels:
                fmt = fmt.replace("%{" + k + "}", v)
            return f"{base}.{fmt}" if fmt else base
        if labels:
            base += "." + ".".join(v for _, v in labels)
        return base.replace("_", ".")

    def new_counter(self, opts: CounterOpts):
        return _StatsdCounter(self, opts)

    def new_gauge(self, opts: GaugeOpts):
        return _StatsdGauge(self, opts)

    def new_histogram(self, opts: HistogramOpts):
        return _StatsdHistogram(self, opts)


class _StatsdMetric:
    def __init__(self, provider, opts, labels=()):
        self._p = provider
        self.opts = opts
        self._labels = labels

    def with_labels(self, *pairs):
        it = iter(pairs)
        return type(self)(
            self._p, self.opts, self._labels + tuple(zip(it, it))
        )

    With = with_labels


class _StatsdCounter(_StatsdMetric):
    def add(self, delta: float = 1.0) -> None:
        self._p._send(
            f"{self._p._name(self.opts, self._labels)}:{delta:g}|c"
        )


class _StatsdGauge(_StatsdMetric):
    def set(self, value: float) -> None:
        self._p._send(
            f"{self._p._name(self.opts, self._labels)}:{value:g}|g"
        )

    def add(self, delta: float) -> None:
        sign = "+" if delta >= 0 else ""
        self._p._send(
            f"{self._p._name(self.opts, self._labels)}:{sign}{delta:g}|g"
        )


class _StatsdHistogram(_StatsdMetric):
    def observe(self, value: float) -> None:
        self._p._send(
            f"{self._p._name(self.opts, self._labels)}:{value:g}|ms"
        )


class DisabledProvider:
    """No-op provider (reference disabled/provider.go)."""

    def new_counter(self, opts):
        return _Noop()

    def new_gauge(self, opts):
        return _Noop()

    def new_histogram(self, opts):
        return _Noop()


class _Noop:
    def with_labels(self, *p):
        return self

    With = with_labels

    def add(self, *_):
        pass

    def set(self, *_):
        pass

    def observe(self, *_):
        pass


class SnapshotMetrics:
    """Channel-snapshot workload metrics (the gendoc-catalog role for
    the new subsystem): generation latency, bytes pushed through the
    CSP hash_batch path with its observed throughput, and the pending-
    request gauge.  Built from any metrics provider; the operations
    System exposes a prometheus-registered instance
    (common/operations.py snapshot_metrics())."""

    def __init__(self, provider):
        self.generation_duration = provider.new_histogram(HistogramOpts(
            namespace="snapshot",
            name="generation_duration",
            help="Seconds to generate one channel snapshot.",
            statsd_format="%{channel}",
        ))
        self.bytes_hashed = provider.new_counter(CounterOpts(
            namespace="snapshot",
            name="bytes_hashed",
            help="Total snapshot bytes digested through the CSP "
                 "hash_batch path.",
            statsd_format="%{channel}",
        ))
        self.hash_mb_per_s = provider.new_gauge(GaugeOpts(
            namespace="snapshot",
            name="hash_batch_mb_per_s",
            help="hash_batch throughput observed during the last "
                 "snapshot export (MB/s).",
            statsd_format="%{channel}",
        ))
        self.pending_requests = provider.new_gauge(GaugeOpts(
            namespace="snapshot",
            name="pending_requests",
            help="Number of pending snapshot requests.",
            statsd_format="%{channel}",
        ))


class ValidateMetrics:
    """Per-stage block-validate timing: host collect (parse + identity
    + policy prepare, possibly fanned out over the work pool), the wait
    on the device verify batch, and the host policy finish — the
    validate-side counterpart of CommitMetrics, so the /metrics reader
    can see which side of the validate->commit pipeline owns the p99."""

    STAGES = ("collect", "verify_wait", "policy")

    def __init__(self, provider):
        self.stage_duration = provider.new_histogram(HistogramOpts(
            namespace="validator",
            subsystem="block",
            name="stage_duration",
            help="Seconds spent in one validate stage for one block "
                 "(collect/verify_wait/policy).",
            buckets=(
                0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                0.1, 0.25, 0.5, 1.0, 2.5,
            ),
            statsd_format="%{channel}.%{stage}",
        ))


class CommitMetrics:
    """Per-stage ledger-commit pipeline timing (the group-commit
    tentpole's instrumentation): one histogram labeled (channel, stage)
    over the stages mvcc / block_append / pvt / state / history (per
    block) and fsync / kv_txn (per group boundary), plus how many
    blocks each fsync+txn boundary made durable — the breakdown the
    next optimisation round reads off /metrics and bench.py's JSON
    line."""

    STAGES = (
        "mvcc", "block_append", "pvt", "state", "history",
        "fsync", "kv_txn",
    )

    def __init__(self, provider):
        self.stage_duration = provider.new_histogram(HistogramOpts(
            namespace="ledger",
            subsystem="commit",
            name="stage_duration",
            help="Seconds spent in one commit-pipeline stage for one "
                 "block (mvcc/block_append/pvt/state/history) or one "
                 "group boundary (fsync/kv_txn).",
            buckets=(
                0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                0.1, 0.25, 0.5, 1.0, 2.5,
            ),
            statsd_format="%{channel}.%{stage}",
        ))
        self.blocks_per_sync = provider.new_histogram(HistogramOpts(
            namespace="ledger",
            subsystem="commit",
            name="blocks_per_sync",
            help="Blocks made durable by one group-commit fsync+txn "
                 "boundary (1 = no coalescing).",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32),
            statsd_format="%{channel}",
        ))


class CSPMetrics:
    """TPU-CSP degraded-mode instrumentation (the faultline tentpole's
    hardening half): the circuit breaker's state and trip counts, raw
    device-path failures, and recovery probes — the signals an operator
    watches to know the node is serving from the host oracle."""

    def __init__(self, provider):
        self.breaker_state = provider.new_gauge(GaugeOpts(
            namespace="csp",
            subsystem="tpu",
            name="breaker_state",
            help="1 while the TPU degraded-mode circuit breaker is open "
                 "(verify/hash served by the host path, no device "
                 "queuing), 0 when closed.",
        ))
        self.breaker_trips = provider.new_counter(CounterOpts(
            namespace="csp",
            subsystem="tpu",
            name="breaker_trips_total",
            help="Times the breaker opened after consecutive device "
                 "failures.",
        ))
        self.device_failures = provider.new_counter(CounterOpts(
            namespace="csp",
            subsystem="tpu",
            name="device_failures_total",
            help="Device-path failures observed by the TPU provider "
                 "(dispatch, collect, or hash).",
        ))
        self.probes = provider.new_counter(CounterOpts(
            namespace="csp",
            subsystem="tpu",
            name="breaker_probes_total",
            help="Recovery probe batches sent while the breaker was "
                 "open, labeled by result.",
            statsd_format="%{result}",
        ))
        self.breaker_state.set(0)


class WorkpoolMetrics:
    """Shared host-work-pool observability (the PR 9 pool had none):
    how deep the executor's queue is, how many run_chunked chunks are
    in flight, and how saturated the worker set is — the signals that
    say whether FABRIC_TPU_COLLECT_POOL/_MVCC_POOL widths are starving
    or flooding the one process-wide pool."""

    def __init__(self, provider):
        self.queue_depth = provider.new_gauge(GaugeOpts(
            namespace="workpool",
            name="queue_depth",
            help="Tasks waiting in the shared host work pool's "
                 "executor queue at the last fan-out.",
        ))
        self.in_flight = provider.new_gauge(GaugeOpts(
            namespace="workpool",
            name="in_flight_chunks",
            help="run_chunked chunks currently submitted and not yet "
                 "collected.",
        ))
        self.saturation = provider.new_gauge(GaugeOpts(
            namespace="workpool",
            name="worker_saturation",
            help="In-flight chunks over the pool's worker cap, capped "
                 "at 1.0 — sustained 1.0 means fan-outs queue behind "
                 "each other.",
        ))


class RaftMetrics:
    """Raft cluster-comm instrumentation: the silent-loss counters the
    transport used to drop into the void.  `send_dropped` counts
    StepRequests discarded on a full outbound queue (raft retransmits,
    so an occasional drop is benign — sustained growth means a peer is
    down or a link is saturated); `dials` counts outbound connection
    attempts, so reconnect storms are visible next to the backoff."""

    def __init__(self, provider):
        self.send_dropped = provider.new_counter(CounterOpts(
            namespace="raft",
            name="send_dropped_total",
            help="StepRequests dropped because a peer's outbound queue "
                 "was full.",
            statsd_format="%{dest}",
        ))
        self.dials = provider.new_counter(CounterOpts(
            namespace="raft",
            name="dial_total",
            help="Outbound link connection attempts, labeled by "
                 "destination node.",
            statsd_format="%{dest}",
        ))
        # netscope gap closure: the consensus-state signals the
        # telemetry plane reads per scrape round
        self.term = provider.new_gauge(GaugeOpts(
            namespace="raft",
            name="term",
            help="This node's current raft term.",
        ))
        self.leader_changes = provider.new_counter(CounterOpts(
            namespace="raft",
            name="leader_changes_total",
            help="Observed leadership transitions (any leader -> a "
                 "different nonzero leader).",
        ))
        self.committed_index = provider.new_gauge(GaugeOpts(
            namespace="raft",
            name="last_committed_index",
            help="Last raft log index known committed on this node.",
        ))
        self.queue_depth = provider.new_gauge(GaugeOpts(
            namespace="raft",
            name="outbound_queue_depth",
            help="Depth of the per-peer outbound send queue at the "
                 "last enqueue, labeled by destination node.",
            statsd_format="%{dest}",
        ))
        self.wal_append = provider.new_histogram(HistogramOpts(
            namespace="raft",
            subsystem="wal",
            name="append_seconds",
            help="Seconds writing one WAL record batch (pre-fsync).",
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25,
            ),
        ))
        self.wal_fsync = provider.new_histogram(HistogramOpts(
            namespace="raft",
            subsystem="wal",
            name="fsync_seconds",
            help="Seconds in one WAL fsync.",
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25,
            ),
        ))


class GossipMetrics:
    """Gossip-plane instrumentation (a netscope gap closure: the gossip
    stack had NO metrics): message flow in/out, the state-transfer
    request/served-block counters that make catch-up visible, and the
    membership gauge the health rollup reads."""

    def __init__(self, provider):
        self.messages_received = provider.new_counter(CounterOpts(
            namespace="gossip",
            name="messages_received_total",
            help="Verified inbound gossip messages dispatched to "
                 "subscribers, labeled by content kind.",
            statsd_format="%{content}",
        ))
        self.messages_sent = provider.new_counter(CounterOpts(
            namespace="gossip",
            name="messages_sent_total",
            help="Outbound gossip messages signed and handed to a "
                 "transport.",
        ))
        self.state_requests_sent = provider.new_counter(CounterOpts(
            namespace="gossip",
            name="state_requests_sent_total",
            help="Anti-entropy state-transfer requests sent while "
                 "behind a peer's advertised height.",
        ))
        self.state_requests_served = provider.new_counter(CounterOpts(
            namespace="gossip",
            name="state_requests_served_total",
            help="Inbound state-transfer requests answered with at "
                 "least one block.",
        ))
        self.state_blocks_served = provider.new_counter(CounterOpts(
            namespace="gossip",
            name="state_blocks_served_total",
            help="Blocks shipped in state-transfer responses.",
        ))
        self.membership = provider.new_gauge(GaugeOpts(
            namespace="gossip",
            name="membership_size",
            help="Alive peers known to discovery at the last tick "
                 "(excluding self).",
        ))


class DeliverMetrics:
    """Deliver-client instrumentation (netscope gap closure): blocks
    pulled from the ordering service, reconnect episodes, and the
    cumulative backoff the client has waited out — a climbing
    reconnect counter with a flat block counter is the silent-wedge
    signature the stall detector confirms from the outside."""

    def __init__(self, provider):
        self.blocks = provider.new_counter(CounterOpts(
            namespace="deliver",
            name="blocks_total",
            help="Blocks verified and handed to the sink.",
            statsd_format="%{channel}",
        ))
        self.reconnects = provider.new_counter(CounterOpts(
            namespace="deliver",
            name="reconnects_total",
            help="Reconnect/rotation episodes (a stream ended or "
                 "failed and the client moved to the next endpoint).",
            statsd_format="%{channel}",
        ))
        self.backoff_seconds = provider.new_counter(CounterOpts(
            namespace="deliver",
            name="backoff_seconds_total",
            help="Cumulative seconds the client has spent in "
                 "reconnect backoff.",
            statsd_format="%{channel}",
        ))


class GatewayMetrics:
    """Gateway submission front-end instrumentation: admission queue
    depth and the adaptive in-flight window (the backpressure pair —
    depth pinned at the window with zero resolutions is the
    stuck-gateway signature), dedup hits, backpressure rejections,
    orderer failover episodes, per-status resolution counters, and the
    submit→commit latency histogram netscope's SLO rollup reads."""

    def __init__(self, provider):
        self.queue_depth = provider.new_gauge(GaugeOpts(
            namespace="gateway",
            name="queue_depth",
            help="Envelopes accepted but not yet written to an "
                 "orderer broadcast stream.",
            statsd_format="%{channel}",
        ))
        self.in_flight = provider.new_gauge(GaugeOpts(
            namespace="gateway",
            name="in_flight",
            help="Accepted txids not yet resolved to a commit status.",
            statsd_format="%{channel}",
        ))
        self.window = provider.new_gauge(GaugeOpts(
            namespace="gateway",
            name="window",
            help="Current admission window (max unresolved txids), "
                 "adapted to the deliver-observed commit rate.",
            statsd_format="%{channel}",
        ))
        self.dedup_hits = provider.new_counter(CounterOpts(
            namespace="gateway",
            name="dedup_hits_total",
            help="Resubmissions answered idempotently from the txid "
                 "dedup map.",
            statsd_format="%{channel}",
        ))
        self.rejections = provider.new_counter(CounterOpts(
            namespace="gateway",
            name="rejections_total",
            help="Submissions rejected with retry-after because the "
                 "admission window was full.",
            statsd_format="%{channel}",
        ))
        self.failovers = provider.new_counter(CounterOpts(
            namespace="gateway",
            name="failovers_total",
            help="Orderer stream failover episodes (connection loss "
                 "-> rotation + in-flight resubmission).",
            statsd_format="%{channel}",
        ))
        self.resolved = provider.new_counter(CounterOpts(
            namespace="gateway",
            name="resolved_total",
            help="Txids resolved to a definitive commit status, by "
                 "status (VALID/INVALID/TIMEOUT).",
            statsd_format="%{channel}.%{status}",
        ))
        self.submit_to_commit_seconds = provider.new_histogram(HistogramOpts(
            namespace="gateway",
            name="submit_to_commit_seconds",
            help="Latency from gateway admission to commit-status "
                 "resolution via the deliver tail.",
            statsd_format="%{channel}",
        ))


class LedgerMetrics:
    """Per-channel ledger progress (netscope gap closure): the height
    and durability-watermark gauges the telemetry plane derives
    cross-peer commit lag from, plus committed block/tx counters for
    sustained-throughput SLO rollups."""

    def __init__(self, provider):
        self.height = provider.new_gauge(GaugeOpts(
            namespace="ledger",
            name="height",
            help="Committed block height (next block number), per "
                 "channel.",
            statsd_format="%{channel}",
        ))
        self.durable_height = provider.new_gauge(GaugeOpts(
            namespace="ledger",
            name="durable_height",
            help="Durability watermark: every block at or below it has "
                 "its block file fsynced and its KV txn committed.",
            statsd_format="%{channel}",
        ))
        self.blocks_committed = provider.new_counter(CounterOpts(
            namespace="ledger",
            name="blocks_committed_total",
            help="Blocks committed since process start, per channel.",
            statsd_format="%{channel}",
        ))
        self.transactions = provider.new_counter(CounterOpts(
            namespace="ledger",
            name="transactions_total",
            help="VALID transactions committed since process start, "
                 "per channel.",
            statsd_format="%{channel}",
        ))


class LockMetrics:
    """Lock-contention observability (profscope, PR 15): per-role
    acquire-wait and hold-time histograms — the runtime complement to
    fabriclint's static lock-order graph.  Fed by
    ``profile.note_lock_wait/note_lock_hold`` (lockwatch's watched and
    profiled lock wrappers) only while profiling is armed, so a
    disarmed node's /metrics is unchanged."""

    # lock waits live in the microsecond..second range, far below the
    # default request buckets
    _BUCKETS = (
        1e-6, 1e-5, 1e-4, 1e-3, 5e-3, 0.025, 0.1, 0.5, 2.0,
    )

    def __init__(self, provider):
        self.wait = provider.new_histogram(HistogramOpts(
            namespace="lock",
            name="wait_seconds",
            help="Seconds a thread spent blocked acquiring the lock "
                 "with this role (profscope armed only).",
            buckets=self._BUCKETS,
            statsd_format="%{role}",
        ))
        self.hold = provider.new_histogram(HistogramOpts(
            namespace="lock",
            name="hold_seconds",
            help="Seconds the lock with this role was held, outermost "
                 "acquire to final release (profscope armed only).",
            buckets=self._BUCKETS,
            statsd_format="%{role}",
        ))


# process-wide GC pause accounting for ProcessMetrics: one idempotent
# gc callback accumulates collection time; plain float adds are
# GIL-atomic enough for a monotone scrape-time read
_gc_pause_total = [0.0]
_gc_cb_state = {"installed": False, "t0": None}


def _install_gc_callback() -> None:
    if _gc_cb_state["installed"]:
        return
    _gc_cb_state["installed"] = True
    import gc
    import time

    def _cb(phase, info):
        if phase == "start":
            _gc_cb_state["t0"] = time.monotonic()
        else:
            t0 = _gc_cb_state["t0"]
            if t0 is not None:
                _gc_pause_total[0] += time.monotonic() - t0
                _gc_cb_state["t0"] = None

    gc.callbacks.append(_cb)


class ProcessMetrics:
    """Standard process-level gauges (the prometheus client-library
    conventions) so netscope series can correlate node saturation with
    commit lag: CPU seconds, RSS, open fds, GC collections and pause
    time.  Values are read at scrape time — register :meth:`collect`
    with ``PrometheusRegistry.register_collector``."""

    def __init__(self, provider):
        self.cpu_seconds = provider.new_gauge(GaugeOpts(
            name="process_cpu_seconds_total",
            help="Total user+system CPU seconds of this process "
                 "(monotone; exposed as a scrape-time gauge).",
        ))
        self.rss_bytes = provider.new_gauge(GaugeOpts(
            name="process_resident_memory_bytes",
            help="Resident set size in bytes.",
        ))
        self.open_fds = provider.new_gauge(GaugeOpts(
            name="process_open_fds",
            help="Open file descriptors.",
        ))
        self.gc_collections = provider.new_gauge(GaugeOpts(
            name="process_gc_collections_total",
            help="Cyclic GC collections since process start, per "
                 "generation.",
        ))
        self.gc_pause_seconds = provider.new_gauge(GaugeOpts(
            name="process_gc_pause_seconds_total",
            help="Cumulative seconds spent inside cyclic GC "
                 "collections (gc callback timing).",
        ))
        _install_gc_callback()

    def collect(self) -> None:
        import gc
        import os

        t = os.times()
        self.cpu_seconds.set(t.user + t.system)
        try:
            with open("/proc/self/statm", "r", encoding="ascii") as f:
                pages = int(f.read().split()[1])
            self.rss_bytes.set(pages * (os.sysconf("SC_PAGE_SIZE")))
        except (OSError, ValueError, IndexError):
            pass
        try:
            self.open_fds.set(len(os.listdir("/proc/self/fd")))
        except OSError:
            pass
        for gen, st in enumerate(gc.get_stats()):
            self.gc_collections.With(
                "generation", str(gen)
            ).set(st.get("collections", 0))
        self.gc_pause_seconds.set(_gc_pause_total[0])


__all__ = [
    "CounterOpts",
    "GaugeOpts",
    "HistogramOpts",
    "Counter",
    "Gauge",
    "Histogram",
    "PrometheusProvider",
    "PrometheusRegistry",
    "StatsdProvider",
    "DisabledProvider",
    "SnapshotMetrics",
    "CommitMetrics",
    "CSPMetrics",
    "RaftMetrics",
    "WorkpoolMetrics",
    "GossipMetrics",
    "DeliverMetrics",
    "GatewayMetrics",
    "LedgerMetrics",
    "LockMetrics",
    "ProcessMetrics",
]
