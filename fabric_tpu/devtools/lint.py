"""fabriclint — domain-aware AST invariant checker.

The north star routes ALL block-validation crypto through the pluggable
CSP seam so it can batch onto TPU, and PR 2 made lock/fsync discipline
in the commit path load-bearing.  Those invariants are enforced here by
machine, not reviewer memory: tier-1 runs this linter over the whole
tree (tests/test_lint_clean.py) and fails on any unsuppressed violation.

Rules
-----
csp-seam
    No direct ``hashlib`` use outside ``fabric_tpu/csp/`` and
    ``fabric_tpu/common/crypto.py``.  Everything else must call the CSP
    hash seam (``common.hashing.sha256``/``sha256_many`` or a CSP's
    ``hash``/``hash_batch``) so new call sites stay visible to the
    TPU-batched provider — or carry a reviewed pragma.

exception-discipline
    No ``except Exception`` (or bare ``except``) in ``peer/``,
    ``policies/``, ``ledger/`` whose handler swallows without a
    structured sentinel: a handler consisting only of
    ``pass``/``continue``/``break``/trivial-constant ``return`` hides
    failures on the validation path (the ``ERR_UNKNOWN_SKI`` direction
    from the custody work).  Re-raising, assigning a sentinel, calling a
    logger, or returning a named error code all count as structured.

determinism
    In validation/commit/policy paths where peers must agree (``peer/``,
    ``policies/``, ``ledger/``, ``protoutil/``): ban ``time.time()``,
    ``datetime.now()``/``utcnow()``, module-level ``random.*`` calls
    (an injected seeded ``random.Random`` instance is fine), and
    ``json.dumps`` without ``sort_keys=True`` (dict-order-dependent
    serialization).

lock-discipline
    (a) a bare ``x.acquire()`` expression statement outside a
    try/finally that releases (``__enter__`` methods are exempt — their
    release lives in ``__exit__``); (b) lexically nested ``with`` lock
    acquisitions that inverse the canonical order
    ``commit_lock -> manager _lock -> _idle``; (c) blocking I/O (fsync,
    sqlite txn flush/execute, sleep) — directly or through a same-class
    helper method — while lexically holding ``commit_lock``, outside the
    approved group-commit seam (allowlisted, with reasons).

jax-hygiene
    No host synchronization (``block_until_ready``, ``device_get``)
    inside per-item ``for``/``while`` loops: batch paths must make ONE
    device round-trip per batch, not one per item.

Suppression
-----------
Inline pragma: a ``fabriclint: allow[<rule>] <reason>`` comment on the
offending line, or in the contiguous comment block immediately above it,
or in the comment block opening the flagged statement's body (so an
``except Exception:`` can carry its pragma inside the handler, where the
explanation reads naturally).  Only real comments count — pragma-shaped
text inside strings and docstrings (like the example in this one) is
ignored.

A pragma MUST carry a non-empty reason and MUST suppress something —
reason-less and unused pragmas are violations themselves.  Cross-file
entries live in ``fabric_tpu/devtools/allowlist.py``; unused entries are
violations too, so the allowlist can only shrink as code is fixed.

CLI
---
``python -m fabric_tpu.devtools.lint [--json] [targets...]`` — exits
non-zero on any unsuppressed violation; ``--json`` emits one JSON object
per violation plus a final machine-readable summary line.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import os
import re
import sys
import tokenize

RULES = (
    "csp-seam",
    "exception-discipline",
    "determinism",
    "lock-discipline",
    "jax-hygiene",
)

# meta rules: problems with the suppression machinery itself; never
# themselves suppressible
META_RULES = ("pragma", "allowlist")

PRAGMA_RE = re.compile(
    r"#\s*fabriclint:\s*allow\[([a-z, -]+)\]\s*(.*?)\s*$"
)

# -- scopes ------------------------------------------------------------------

# modules allowed to touch hashlib directly: the CSP providers (they ARE
# the seam) and the seam's own stdlib-only host side (re-exported by
# common/crypto.py for cert-side callers)
CSP_SEAM_ALLOWED = (
    "fabric_tpu/csp/",
    "fabric_tpu/common/hashing.py",
    "fabric_tpu/common/crypto.py",
)

EXC_SCOPE = (
    "fabric_tpu/peer/",
    "fabric_tpu/policies/",
    "fabric_tpu/ledger/",
)

DET_SCOPE = EXC_SCOPE + ("fabric_tpu/protoutil/",)

# generated code is exempt from everything
SKIP_PREFIXES = ("fabric_tpu/protos/",)

LOCK_RANKS = {
    # canonical acquisition order: commit lock strictly before any
    # manager/bookkeeping lock, which come before condition helpers
    "commit_lock": 0,
    "_commit_lock": 0,
    "_lock": 1,
    "_idle": 2,
}

COMMIT_LOCK_NAMES = ("commit_lock", "_commit_lock")

BLOCKING_CALLS = frozenset(
    {"fsync", "sync_files", "sleep", "flush", "execute", "executemany"}
)

JAX_SYNC_CALLS = frozenset({"block_until_ready", "device_get"})


@dataclasses.dataclass
class Violation:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    suppression: str | None = None  # "pragma: <reason>" / "allowlist: <reason>"

    def __str__(self) -> str:
        tag = f" (suppressed: {self.suppression})" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class AllowEntry:
    """One reviewed cross-file suppression.  `match` must be a substring
    of the flagged source line, so entries survive line-number drift but
    die (as unused-entry violations) when the code they covered goes
    away."""

    rule: str
    path: str
    match: str
    reason: str


# -- per-module pre-pass: which class methods (transitively) block -----------


def _method_blocking_map(tree: ast.Module) -> dict[str, set[str]]:
    """class name -> names of its methods that perform a blocking call
    directly or through other methods of the same class (fixpoint over
    ``self.x()`` edges)."""
    out: dict[str, set[str]] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        direct: set[str] = set()
        calls: dict[str, set[str]] = {}
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            calls[fn.name] = set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr in BLOCKING_CALLS:
                        direct.add(fn.name)
                    if (
                        isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                    ):
                        calls[fn.name].add(f.attr)
        blocking = set(direct)
        changed = True
        while changed:
            changed = False
            for name, callees in calls.items():
                if name not in blocking and callees & blocking:
                    blocking.add(name)
                    changed = True
        out[cls.name] = blocking
    return out


# -- the checker -------------------------------------------------------------


def _in_scope(rel: str, prefixes) -> bool:
    return any(rel.startswith(p) for p in prefixes)


def _is_trivial_return_value(v) -> bool:
    """True for values whose return carries no information: None,
    constants, tuples of constants, and empty containers."""
    if v is None or isinstance(v, ast.Constant):
        return True
    if isinstance(v, ast.Tuple):
        return all(isinstance(e, ast.Constant) for e in v.elts)
    if isinstance(v, (ast.List, ast.Set)):
        return not v.elts
    if isinstance(v, ast.Dict):
        return not v.keys
    return False


def _swallows(handler: ast.ExceptHandler) -> bool:
    if any(isinstance(n, ast.Raise) for n in ast.walk(handler)):
        return False
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return) and _is_trivial_return_value(
            stmt.value
        ):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant
        ):
            continue  # docstring / ellipsis
        return False
    return True


def _catches_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _lock_name(expr) -> str | None:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _finally_releases(node: ast.Try) -> bool:
    return any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == "release"
        for stmt in node.finalbody
        for n in ast.walk(stmt)
    )


def _acquires_before_try_finally(tree: ast.Module) -> set[int]:
    """Node ids of `x.acquire()` statements whose immediately-following
    sibling is a try whose finally releases — the canonical safe idiom
    (acquire OUTSIDE the try: a failed acquire must not reach the
    finally and release a lock it never took)."""
    ok: set[int] = set()
    for node in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(node, field, None)
            if not isinstance(stmts, list):
                continue
            for a, b in zip(stmts, stmts[1:]):
                if (
                    isinstance(a, ast.Expr)
                    and isinstance(a.value, ast.Call)
                    and isinstance(a.value.func, ast.Attribute)
                    and a.value.func.attr == "acquire"
                    and isinstance(b, ast.Try)
                    and _finally_releases(b)
                ):
                    ok.add(id(a))
    return ok


def _dotted_name(expr) -> str | None:
    """`a.b.c` as the string "a.b.c"; None for non-Name/Attribute chains."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


class _FileChecker(ast.NodeVisitor):
    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.violations: list[Violation] = []
        self._seen: set[tuple[str, int]] = set()
        self._hashlib_aliases: set[str] = set()
        self._time_fn_aliases: set[str] = set()
        self._random_fn_aliases: set[str] = set()
        self._datetime_aliases: set[str] = {"datetime", "date"}
        self._func_stack: list[str] = []
        self._class_stack: list[str] = []
        self._with_locks: list[str] = []
        self._loop_depth = 0
        self._protected_depth = 0  # inside a try whose finally releases
        self._blocking = _method_blocking_map(tree)
        self._preacquire_ok = _acquires_before_try_finally(tree)

    # -- helpers -----------------------------------------------------------

    def _flag(self, rule: str, node, message: str) -> None:
        key = (rule, node.lineno)
        if key in self._seen:
            return
        self._seen.add(key)
        self.violations.append(
            Violation(rule=rule, path=self.rel, line=node.lineno,
                      message=message)
        )

    # -- imports (csp-seam alias tracking) ---------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name == "hashlib":
                self._hashlib_aliases.add(alias.asname or "hashlib")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "hashlib" and not _in_scope(
            self.rel, CSP_SEAM_ALLOWED
        ):
            self._flag(
                "csp-seam", node,
                "from-import of hashlib outside the CSP seam "
                "(route through common.hashing.sha256/sha256_many or a "
                "CSP hash/hash_batch)",
            )
        if node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    self._time_fn_aliases.add(alias.asname or "time")
        if node.module == "random":
            # module-level functions share the hidden global Random();
            # the class constructors are fine (callers seed their own)
            for alias in node.names:
                if alias.name not in ("Random", "SystemRandom"):
                    self._random_fn_aliases.add(alias.asname or alias.name)
        if node.module == "datetime":
            for alias in node.names:
                if alias.name in ("datetime", "date"):
                    self._datetime_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id in self._hashlib_aliases
            and not _in_scope(self.rel, CSP_SEAM_ALLOWED)
        ):
            self._flag(
                "csp-seam", node,
                f"direct hashlib.{node.attr} outside the CSP seam — "
                "invisible to hash_batch/TPU batching (route through "
                "common.hashing.sha256/sha256_many or the CSP)",
            )
        self.generic_visit(node)

    # -- exception discipline ----------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if (
            _in_scope(self.rel, EXC_SCOPE)
            and _catches_broad(node)
            and _swallows(node)
        ):
            self._flag(
                "exception-discipline", node,
                "broad except swallows without a structured sentinel, "
                "re-raise, or logged reason",
            )
        self.generic_visit(node)

    # -- calls: determinism + lock blocking + jax hygiene -------------------

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else None
        # full dotted base so `datetime.datetime.now()` resolves — a
        # Name-only base would see None and let the qualified spelling
        # through the gate
        base = (
            _dotted_name(f.value) if isinstance(f, ast.Attribute) else None
        )
        base_tail = base.rsplit(".", 1)[-1] if base else None

        if _in_scope(self.rel, DET_SCOPE):
            if (base == "time" and attr == "time") or (
                isinstance(f, ast.Name) and f.id in self._time_fn_aliases
            ):
                self._flag(
                    "determinism", node,
                    "time.time() on a consensus path — wall-clock "
                    "differs across peers (use an explicit timestamp "
                    "argument, or time.monotonic/perf_counter for "
                    "intervals)",
                )
            elif (
                attr in ("now", "utcnow", "today")
                and base_tail in self._datetime_aliases
            ):
                self._flag(
                    "determinism", node,
                    f"datetime.{attr}() on a consensus path",
                )
            elif (base == "random" and attr not in ("Random", "SystemRandom")
                  ) or (
                isinstance(f, ast.Name) and f.id in self._random_fn_aliases
            ):
                name = attr if attr is not None else f.id
                self._flag(
                    "determinism", node,
                    f"module-level random.{name}() on a consensus path "
                    "(inject a seeded random.Random instead)",
                )
            elif base == "json" and attr == "dumps":
                sorted_kw = any(
                    kw.arg == "sort_keys"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords
                )
                if not sorted_kw:
                    self._flag(
                        "determinism", node,
                        "json.dumps without sort_keys=True on a "
                        "consensus path — dict order leaks into bytes",
                    )

        if attr is not None and any(
            n in COMMIT_LOCK_NAMES for n in self._with_locks
        ):
            cls = self._class_stack[-1] if self._class_stack else None
            if attr in BLOCKING_CALLS:
                self._flag(
                    "lock-discipline", node,
                    f"blocking call .{attr}() while holding the commit "
                    "lock, outside the approved group-commit seam",
                )
            elif (
                base == "self"
                and cls is not None
                and attr in self._blocking.get(cls, ())
            ):
                self._flag(
                    "lock-discipline", node,
                    f"self.{attr}() performs blocking I/O (transitively) "
                    "while holding the commit lock, outside the approved "
                    "group-commit seam",
                )

        if attr in JAX_SYNC_CALLS and self._loop_depth > 0:
            self._flag(
                "jax-hygiene", node,
                f".{attr}() inside a per-item loop — host sync per "
                "item serializes the device; sync once per batch",
            )

        self.generic_visit(node)

    # -- lock discipline: bare acquire + with-order -------------------------

    def visit_Try(self, node: ast.Try) -> None:
        if _finally_releases(node):
            self._protected_depth += 1
            for stmt in node.body + node.orelse:
                self.visit(stmt)
            self._protected_depth -= 1
            for h in node.handlers:
                self.visit(h)
            for stmt in node.finalbody:
                self.visit(stmt)
        else:
            self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        v = node.value
        if (
            isinstance(v, ast.Call)
            and isinstance(v.func, ast.Attribute)
            and v.func.attr == "acquire"
            and self._protected_depth == 0
            and id(node) not in self._preacquire_ok
            and (not self._func_stack or self._func_stack[-1] != "__enter__")
        ):
            self._flag(
                "lock-discipline", node,
                "bare .acquire() without try/finally release "
                "(use `with`, or release in a finally)",
            )
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        names = []
        for item in node.items:
            n = _lock_name(item.context_expr)
            if n is not None and n in LOCK_RANKS:
                for outer in self._with_locks:
                    if LOCK_RANKS[n] < LOCK_RANKS[outer]:
                        self._flag(
                            "lock-discipline", node,
                            f"lock-order inversion: {n!r} (rank "
                            f"{LOCK_RANKS[n]}) acquired while holding "
                            f"{outer!r} (rank {LOCK_RANKS[outer]}); "
                            f"canonical order is commit_lock -> _lock "
                            f"-> _idle",
                        )
                names.append(n)
                self._with_locks.append(n)
        for item in node.items:
            self.visit(item)
        for stmt in node.body:
            self.visit(stmt)
        for _ in names:
            self._with_locks.pop()

    # -- structure tracking -------------------------------------------------

    def visit_FunctionDef(self, node) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_For(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_AsyncFor = visit_For
    visit_While = visit_For


# -- suppression -------------------------------------------------------------


def _parse_pragmas(source: str, rel: str):
    """Tokenize-based pragma scan: only REAL comment tokens count, so
    pragma-shaped text inside strings/docstrings never registers.

    Returns (pragmas, comment_only, meta) where `pragmas` maps line
    number -> (rules, reason), `comment_only` is the set of lines whose
    sole content is a comment (used to associate a pragma with the
    statement its comment block annotates), and `meta` lists violations
    for malformed pragmas (unknown rule, missing reason)."""
    pragmas: dict[int, tuple[set[str], str]] = {}
    comment_only: set[int] = set()
    meta: list[Violation] = []
    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(source).readline)
        )
    except (tokenize.TokenError, IndentationError):
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        i = tok.start[0]
        if not tok.line[: tok.start[1]].strip():
            comment_only.add(i)
        m = PRAGMA_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = m.group(2).strip()
        unknown = rules - set(RULES)
        if unknown:
            meta.append(Violation(
                rule="pragma", path=rel, line=i,
                message=f"pragma names unknown rule(s): "
                        f"{', '.join(sorted(unknown))}",
            ))
        if not reason:
            meta.append(Violation(
                rule="pragma", path=rel, line=i,
                message="pragma without a reason — every suppression "
                        "must say why it was reviewed",
            ))
        pragmas[i] = (rules, reason)
    return pragmas, comment_only, meta


def _pragma_candidate_lines(line: int, comment_only: set[int],
                            lines: list[str]):
    """Lines whose pragma may suppress a violation on `line`: the line
    itself (trailing comment), the contiguous comment-only block
    immediately above it (comments wrap; the pragma may sit a couple of
    lines up), and — ONLY when the flagged line opens a block (``except
    Exception:``) — the comment block at the top of that block's body.
    The body scan requires deeper indentation than the opener so a
    pragma written for the NEXT statement at the same level never leaks
    upward onto a neighboring, unreviewed violation."""
    yield line
    ln = line - 1
    while ln >= 1 and ln in comment_only:
        yield ln
        ln -= 1
    src = lines[line - 1] if 0 < line <= len(lines) else ""
    if src.split("#", 1)[0].rstrip().endswith(":"):
        opener_indent = len(src) - len(src.lstrip())
        ln = line + 1
        while ln <= len(lines) and ln in comment_only:
            body = lines[ln - 1]
            if len(body) - len(body.lstrip()) <= opener_indent:
                break
            yield ln
            ln += 1


def _apply_suppressions(
    violations: list[Violation],
    pragmas: dict[int, tuple[set[str], str]],
    comment_only: set[int],
    lines: list[str],
    allowlist: list[AllowEntry],
    used_entries: set[int],
) -> set[int]:
    """Mark violations suppressed in place; returns used pragma lines."""
    used_pragmas: set[int] = set()
    for v in violations:
        for ln in _pragma_candidate_lines(v.line, comment_only, lines):
            p = pragmas.get(ln)
            if p and v.rule in p[0]:
                v.suppressed = True
                v.suppression = f"pragma: {p[1]}"
                used_pragmas.add(ln)
                break
        if v.suppressed:
            continue
        src = lines[v.line - 1] if 0 < v.line <= len(lines) else ""
        for idx, e in enumerate(allowlist):
            if e.rule == v.rule and e.path == v.path and e.match in src:
                v.suppressed = True
                v.suppression = f"allowlist: {e.reason}"
                used_entries.add(idx)
                break
    return used_pragmas


# -- drivers -----------------------------------------------------------------


def lint_source(
    source: str,
    rel: str,
    allowlist: list[AllowEntry] | None = None,
    used_entries: set[int] | None = None,
) -> list[Violation]:
    """Lint one module's source as if it lived at repo-relative `rel`."""
    allowlist = allowlist if allowlist is not None else []
    used_entries = used_entries if used_entries is not None else set()
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Violation(
            rule="pragma", path=rel, line=exc.lineno or 0,
            message=f"file does not parse: {exc.msg}",
        )]
    lines = source.splitlines()
    pragmas, comment_only, meta = _parse_pragmas(source, rel)
    checker = _FileChecker(rel, tree)
    checker.visit(tree)
    violations = checker.violations
    used_pragmas = _apply_suppressions(
        violations, pragmas, comment_only, lines, allowlist, used_entries
    )
    for ln in sorted(set(pragmas) - used_pragmas):
        meta.append(Violation(
            rule="pragma", path=rel, line=ln,
            message="unused pragma — it suppresses nothing; remove it "
                    "(or it is masking a rule that moved)",
        ))
    return violations + meta


def repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def iter_target_files(root: str, targets) -> list[str]:
    rels: list[str] = []
    for target in targets:
        abs_t = os.path.join(root, target)
        if os.path.isfile(abs_t):
            rels.append(target.replace(os.sep, "/"))
            continue
        # a typo'd / renamed target must not silently report "clean"
        if not os.path.isdir(abs_t):
            raise FileNotFoundError(
                f"lint target {target!r} matches no file or directory "
                f"under {root}"
            )
        before = len(rels)
        for dirpath, dirnames, filenames in os.walk(abs_t):
            dirnames[:] = [
                d for d in sorted(dirnames) if d != "__pycache__"
            ]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(
                    os.path.join(dirpath, fn), root
                ).replace(os.sep, "/")
                if not _in_scope(rel, SKIP_PREFIXES):
                    rels.append(rel)
        if len(rels) == before:
            raise FileNotFoundError(
                f"lint target {target!r} contains no lintable .py files"
            )
    return rels


@dataclasses.dataclass
class LintReport:
    files: int
    violations: list[Violation]

    @property
    def unsuppressed(self) -> list[Violation]:
        return [v for v in self.violations if not v.suppressed]

    @property
    def suppressed(self) -> list[Violation]:
        return [v for v in self.violations if v.suppressed]

    def summary(self) -> dict:
        by_rule: dict[str, int] = {}
        for v in self.unsuppressed:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        return {
            "tool": "fabriclint",
            "files": self.files,
            "violations": len(self.unsuppressed),
            "suppressed": len(self.suppressed),
            "by_rule": dict(sorted(by_rule.items())),
            "clean": not self.unsuppressed,
        }


def lint_tree(
    root: str | None = None,
    targets=("fabric_tpu",),
    allowlist: list[AllowEntry] | None = None,
) -> LintReport:
    root = root or repo_root()
    if allowlist is None:
        from fabric_tpu.devtools.allowlist import ALLOWLIST

        allowlist = list(ALLOWLIST)
    used_entries: set[int] = set()
    violations: list[Violation] = []
    rels = iter_target_files(root, targets)
    for rel in rels:
        with open(os.path.join(root, rel), "r", encoding="utf-8") as f:
            source = f.read()
        violations.extend(
            lint_source(source, rel, allowlist, used_entries)
        )
    # an entry is in this run's scope if its file was linted, or if it
    # falls under a directory target (so full-tree runs flag entries
    # whose file was DELETED, while partial runs — one file, one subdir —
    # don't false-positive on entries they never had a chance to use)
    linted = set(rels)
    dir_prefixes = tuple(
        t.rstrip("/") + "/" for t in targets
        if not os.path.isfile(os.path.join(root, t))
    )
    for idx, e in enumerate(allowlist):
        in_scope = e.path in linted or e.path.startswith(dir_prefixes)
        if idx not in used_entries and in_scope:
            violations.append(Violation(
                rule="allowlist",
                path="fabric_tpu/devtools/allowlist.py",
                line=0,
                message=f"unused allowlist entry ({e.rule} @ {e.path} "
                        f"matching {e.match!r}) — the code it covered "
                        f"is gone; remove the entry",
            ))
    return LintReport(files=len(rels), violations=violations)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fabric_tpu.devtools.lint",
        description="fabriclint: AST invariant checker for fabric_tpu",
    )
    ap.add_argument(
        "targets", nargs="*", default=["fabric_tpu"],
        help="repo-relative files/dirs to lint (default: fabric_tpu)",
    )
    ap.add_argument("--root", default=None, help="repo root override")
    ap.add_argument(
        "--json", action="store_true",
        help="one JSON object per violation + a JSON summary line",
    )
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed violations",
    )
    args = ap.parse_args(argv)

    try:
        report = lint_tree(root=args.root, targets=tuple(args.targets))
    except FileNotFoundError as exc:
        print(json.dumps({"tool": "fabriclint", "error": str(exc)})
              if args.json else f"fabriclint: error: {exc}",
              file=sys.stderr)
        return 2
    shown = report.violations if args.show_suppressed else report.unsuppressed
    for v in shown:
        print(json.dumps(v.to_dict()) if args.json else str(v))
    print(json.dumps(report.summary()))
    return 0 if not report.unsuppressed else 1


if __name__ == "__main__":
    sys.exit(main())
