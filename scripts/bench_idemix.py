"""Idemix BN254 batch-verify benchmark (BASELINE.md config #5).

The reference verifies each idemix signature with ~10 G1/G2 scalar
multiplications re-deriving the ZK commitments plus TWO pairings
(idemix/signature.go:243,290-291, FP256BN.Ate).  The TPU build's
verify_batch collapses all pairing checks for one issuer into TWO
pairings per batch via random linear combination, leaving per-item
Schnorr recomputation as the host cost.

    python scripts/bench_idemix.py [--sigs 64] [--device]

Prints one JSON line: sequential vs batched sigs/s (and, with
--device, the TPU-batched Schnorr path — csp/tpu/bn254_batch.py — at
the same batch size; one warm-up call pays the per-shape compile).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sigs", type=int, default=64)
    ap.add_argument("--device", action="store_true")
    args = ap.parse_args()

    from fabric_tpu.idemix import bn254 as bn
    from fabric_tpu.idemix import signature
    from fabric_tpu.idemix.credential import (
        attribute_to_scalar,
        new_cred_request,
        new_credential,
    )
    from fabric_tpu.idemix.issuer import IssuerKey

    rng = random.Random(42)
    ik = IssuerKey.generate(["OU", "Role"], rng=rng)
    sk = bn.rand_zr(rng)
    req = new_cred_request(sk, b"nonce", ik.ipk, rng=rng)
    attrs = [attribute_to_scalar("org1"), attribute_to_scalar(2)]
    cred = new_credential(ik, req, attrs, rng=rng)

    sigs, msgs = [], []
    for i in range(args.sigs):
        m = b"bench-%d" % i
        sigs.append(signature.new_signature(
            cred, sk, ik.ipk, m, rng=rng
        ))
        msgs.append(m)

    t0 = time.perf_counter()
    ok = [signature.verify(s, ik.ipk, m) for s, m in zip(sigs, msgs)]
    t_seq = time.perf_counter() - t0
    assert all(ok)

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        ok = signature.verify_batch(sigs, ik.ipk, msgs, rng)
        best = min(best, time.perf_counter() - t0)
    assert all(ok)

    out = {
        "metric": "idemix_bn254_batch_verify",
        "sigs": args.sigs,
        "sequential_sigs_s": round(args.sigs / t_seq, 2),
        "batched_sigs_s": round(args.sigs / best, 2),
        "speedup": round(t_seq / best, 2),
    }
    if args.device:
        ok = signature.verify_batch_device(sigs, ik.ipk, msgs, rng)  # warm
        assert all(ok)
        dbest = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            ok = signature.verify_batch_device(sigs, ik.ipk, msgs, rng)
            dbest = min(dbest, time.perf_counter() - t0)
        assert all(ok)
        out["device_batched_sigs_s"] = round(args.sigs / dbest, 2)
        out["device_speedup_vs_host_batch"] = round(best / dbest, 2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
