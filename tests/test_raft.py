"""Raft consensus tests: core protocol, WAL recovery, and the consenter
chain on an in-process 3-node cluster (the reference tests etcdraft the
same way — fake network, deterministic clocks; orderer/consensus/etcdraft
chain_test.go)."""

import os
import threading
import time

import pytest

from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.orderer.blockcutter import BlockCutter
from fabric_tpu.orderer.blockwriter import BlockWriter
from fabric_tpu.orderer.raft import (
    InProcTransport,
    MemoryLog,
    RaftChain,
    RaftNode,
    WAL,
)
from fabric_tpu.orderer.raft.raftcore import LEADER
from fabric_tpu.protos.common import common_pb2
from fabric_tpu.protos.orderer import raft_pb2 as rpb
from fabric_tpu import protoutil


# ---------------------------------------------------------------------------
# deterministic in-test cluster harness for the raw state machine
# ---------------------------------------------------------------------------

class Cluster:
    def __init__(self, n: int, seed: int = 7):
        import random

        self.nodes = {
            i: RaftNode(i, set(range(1, n + 1)), rng=random.Random(seed + i))
            for i in range(1, n + 1)
        }
        self.dropped: set[int] = set()  # node ids cut off from the network
        self.applied: dict[int, list[bytes]] = {i: [] for i in self.nodes}

    def flush(self, rounds: int = 20) -> None:
        """Deliver messages until quiescent."""
        for _ in range(rounds):
            moved = False
            for nid, node in self.nodes.items():
                rd = node.ready()
                for e in rd.committed:
                    if e.type == rpb.ENTRY_CONF_CHANGE:
                        cc = rpb.ConfChange.FromString(e.data)
                        node.apply_conf_change(cc)
                    elif e.data:
                        self.applied[nid].append(e.data)
                for m in rd.messages:
                    moved = True
                    if nid in self.dropped or m.to in self.dropped:
                        continue
                    if m.to in self.nodes:
                        self.nodes[m.to].step(m)
            if not moved:
                return

    def tick_all(self, n: int = 1) -> None:
        for _ in range(n):
            for nid, node in self.nodes.items():
                if nid not in self.dropped:
                    node.tick()
            self.flush()

    def elect(self, max_ticks: int = 200) -> RaftNode:
        for _ in range(max_ticks):
            self.tick_all()
            leaders = [
                n
                for i, n in self.nodes.items()
                if n.state == LEADER and i not in self.dropped
            ]
            if leaders:
                return leaders[0]
        raise AssertionError("no leader elected")


def test_single_node_self_elects_and_commits():
    c = Cluster(1)
    leader = c.elect()
    assert leader.propose(b"tx1")
    c.flush()
    assert c.applied[leader.id] == [b"tx1"]


def test_three_node_election_and_replication():
    c = Cluster(3)
    leader = c.elect()
    for i in range(5):
        assert leader.propose(b"tx%d" % i)
    c.flush()
    want = [b"tx%d" % i for i in range(5)]
    for nid in c.nodes:
        assert c.applied[nid] == want


def test_leader_failure_reelection_preserves_log():
    c = Cluster(3)
    leader = c.elect()
    leader.propose(b"before")
    c.flush()
    c.dropped.add(leader.id)
    new_leader = c.elect()
    assert new_leader.id != leader.id
    new_leader.propose(b"after")
    c.flush()
    for nid in c.nodes:
        if nid not in c.dropped:
            assert c.applied[nid] == [b"before", b"after"]
    # old leader rejoins and catches up
    c.dropped.clear()
    c.tick_all(5)
    assert c.applied[leader.id] == [b"before", b"after"]


def test_stale_leader_proposal_discarded_on_rejoin():
    c = Cluster(3)
    leader = c.elect()
    leader.propose(b"committed")
    c.flush()
    # partition the leader, let it append an entry nobody sees
    c.dropped.add(leader.id)
    leader.propose(b"lost")
    new_leader = c.elect()
    new_leader.propose(b"won")
    c.flush()
    c.dropped.clear()
    c.tick_all(10)
    want = [b"committed", b"won"]
    for nid in c.nodes:
        assert c.applied[nid] == want, f"node {nid}"


def test_conf_change_add_and_remove_node():
    c = Cluster(3)
    leader = c.elect()
    cc = rpb.ConfChange(action=rpb.ConfChange.ADD_NODE)
    cc.consenter.id = 4
    assert leader.propose_conf_change(cc)
    c.flush()
    assert 4 in leader.voters
    # quorum is now 3 of 4
    cc2 = rpb.ConfChange(action=rpb.ConfChange.REMOVE_NODE)
    cc2.consenter.id = 4
    leader.propose_conf_change(cc2)
    c.flush()
    assert 4 not in leader.voters


def test_quorum_loss_blocks_commit():
    c = Cluster(3)
    leader = c.elect()
    c.dropped.update(set(c.nodes) - {leader.id})
    leader.propose(b"stuck")
    c.tick_all(5)
    assert c.applied[leader.id] == []  # cannot commit without quorum


# ---------------------------------------------------------------------------
# WAL
# ---------------------------------------------------------------------------

def test_wal_roundtrip_and_torn_tail(tmp_path):
    w = WAL(str(tmp_path))
    hs, log, snap = w.load()
    assert hs.term == 0 and log.last_index == 0 and snap is None
    entries = [
        rpb.Entry(index=1, term=1, data=b"a"),
        rpb.Entry(index=2, term=1, data=b"b"),
    ]
    w.save(rpb.HardState(term=1, voted_for=2, commit=2), entries)
    w.close()
    # simulate a torn final write
    path = os.path.join(str(tmp_path), "raft.wal")
    with open(path, "ab") as f:
        f.write(b"\x00\x00\x00\xffgarbage")
    w2 = WAL(str(tmp_path))
    hs2, log2, _ = w2.load()
    assert hs2.term == 1 and hs2.voted_for == 2 and hs2.commit == 2
    assert [e.data for e in log2.entries] == [b"a", b"b"]
    w2.close()


def test_wal_snapshot_compacts_replay(tmp_path):
    w = WAL(str(tmp_path))
    w.load()
    w.save(None, [rpb.Entry(index=i, term=1, data=b"e%d" % i) for i in (1, 2, 3)])
    snap = rpb.Snapshot()
    snap.meta.index = 2
    snap.meta.term = 1
    snap.meta.voters.extend([1, 2, 3])
    snap.block_number = 7
    w.save_snapshot(snap)
    w.close()
    w2 = WAL(str(tmp_path))
    hs, log, snap2 = w2.load()
    assert snap2.block_number == 7
    assert log.snap_index == 2
    assert [e.data for e in log.entries] == [b"e3"]
    w2.close()


# ---------------------------------------------------------------------------
# RaftChain: 3 ordering nodes, in-process transport, real block stores
# ---------------------------------------------------------------------------

def _mk_chain(nid, transport, tmp_path, consenters, genesis, **kw):
    store = BlockStore(None, name=f"orderer{nid}")
    store.add_block(genesis)
    writer = BlockWriter(store)
    delivered = []
    chain = RaftChain(
        "testchannel",
        nid,
        consenters,
        BlockCutter(max_message_count=2),
        writer,
        transport,
        wal_dir=str(tmp_path / f"wal{nid}"),
        batch_timeout_s=0.2,
        tick_interval_s=0.01,
        on_block=delivered.append,
        **kw,
    )
    transport.register(nid, chain.handle_step)
    return chain, store, delivered


def _genesis():
    blk = protoutil.new_block(0, b"")
    blk.data.data.append(b"genesis-config")
    blk.header.data_hash = protoutil.block_data_hash(blk.data)
    return blk


def _env(data: bytes) -> common_pb2.Envelope:
    return common_pb2.Envelope(payload=data)


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


@pytest.fixture
def chain_cluster(tmp_path):
    transport = InProcTransport()
    consenters = [rpb.Consenter(id=i) for i in (1, 2, 3)]
    genesis = _genesis()
    chains = {}
    for nid in (1, 2, 3):
        chains[nid] = _mk_chain(nid, transport, tmp_path, consenters, genesis)
    for c, _, _ in chains.values():
        c.start()
    yield transport, chains
    for c, _, _ in chains.values():
        if not c._halted.is_set():
            c.halt()


def _leader(chains, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for nid, (c, _, _) in chains.items():
            if c.is_leader:
                return nid
        time.sleep(0.02)
    raise AssertionError("no chain leader")


def test_chain_orders_and_replicates_blocks(chain_cluster):
    transport, chains = chain_cluster
    lead = _leader(chains)
    leader_chain = chains[lead][0]
    for i in range(4):
        leader_chain.order(_env(b"tx-%d" % i))
    # 4 txs, cutter max 2 -> blocks 1 and 2 on every node
    for nid, (c, store, delivered) in chains.items():
        _wait(lambda s=store: s.height == 3, msg=f"height 3 on node {nid}")
    blk1 = chains[1][1].get_block_by_number(1)
    assert list(blk1.data.data) == [
        _env(b"tx-0").SerializeToString(),
        _env(b"tx-1").SerializeToString(),
    ]
    # all stores identical
    h1 = protoutil.block_header_hash(blk1.header)
    for nid in (2, 3):
        assert (
            protoutil.block_header_hash(
                chains[nid][1].get_block_by_number(1).header
            )
            == h1
        )


def test_chain_consensus_loop_spans_join_block_root(chain_cluster):
    """Orderer consensus-loop tracing (ISSUE 12 satellite): on the
    proposing node, ``raft.propose`` and ``raft.apply`` both nest
    under ONE detached per-block root (`raft.block`) — the orderer
    mirror of the validator's pipeline root — and the root itself
    reaches the recorder when the block applies."""
    from fabric_tpu.common import tracing

    transport, chains = chain_cluster
    lead = _leader(chains)
    leader_chain = chains[lead][0]
    with tracing.scope() as rec:
        leader_chain.order(_env(b"span-a"))
        leader_chain.order(_env(b"span-b"))  # cutter max 2 -> block 1
        for nid, (c, store, _) in chains.items():
            _wait(lambda s=store: s.height == 2,
                  msg=f"block applied on node {nid}")

        def events(name):
            return [
                ev for ev in rec.snapshot() if ev.get("name") == name
            ]

        _wait(lambda: events("raft.block"),
              msg="block root reaches the recorder")
        roots = [
            ev for ev in events("raft.block")
            if ev["args"].get("block") == 1
        ]
        assert len(roots) == 1
        root = roots[0]
        assert root["cat"] == "pipeline"
        trace, span = root["args"]["trace"], root["args"]["span"]
        proposes = [
            ev for ev in events("raft.propose")
            if ev["args"].get("block") == 1
        ]
        assert len(proposes) == 1
        assert proposes[0]["args"]["trace"] == trace
        assert proposes[0]["args"]["parent"] == span
        # every node applies the block, but only the PROPOSER's apply
        # joins the root's trace; follower applies root fresh traces
        applies = [
            ev for ev in events("raft.apply")
            if ev["args"].get("block") == 1
        ]
        assert len(applies) == len(chains)
        joined = [
            ev for ev in applies if ev["args"]["trace"] == trace
        ]
        assert len(joined) == 1
        assert joined[0]["args"]["parent"] == span


def test_chain_follower_forwards_to_leader(chain_cluster):
    transport, chains = chain_cluster
    lead = _leader(chains)
    follower = next(nid for nid in chains if nid != lead)
    chains[follower][0].order(_env(b"via-follower"))
    chains[follower][0].order(_env(b"via-follower-2"))
    for nid, (c, store, _) in chains.items():
        _wait(lambda s=store: s.height == 2, msg=f"block on node {nid}")


def test_chain_batch_timeout_cuts_partial_block(chain_cluster):
    transport, chains = chain_cluster
    lead = _leader(chains)
    chains[lead][0].order(_env(b"lonely"))
    _wait(lambda: chains[lead][1].height == 2, msg="timeout cut")


def test_chain_restart_recovers_from_wal(tmp_path):
    transport = InProcTransport()
    consenters = [rpb.Consenter(id=1)]
    genesis = _genesis()
    chain, store, _ = _mk_chain(1, transport, tmp_path, consenters, genesis)
    chain.start()
    chain.order(_env(b"a"))
    chain.order(_env(b"b"))
    _wait(lambda: store.height == 2, msg="block before restart")
    chain.halt()
    transport.unregister(1)

    # "restart": same WAL dir, fresh empty-but-genesis block store replays
    # committed raft entries into the writer
    store2 = BlockStore(None, name="orderer1-restarted")
    store2.add_block(genesis)
    writer2 = BlockWriter(store2)
    chain2 = RaftChain(
        "testchannel",
        1,
        consenters,
        BlockCutter(max_message_count=2),
        writer2,
        transport,
        wal_dir=str(tmp_path / "wal1"),
        batch_timeout_s=0.2,
        tick_interval_s=0.01,
    )
    transport.register(1, chain2.handle_step)
    chain2.start()
    _wait(lambda: store2.height == 2, msg="block replayed from WAL")
    assert (
        store2.get_block_by_number(1).SerializeToString()
        == store.get_block_by_number(1).SerializeToString()
    )
    chain2.order(_env(b"c"))
    chain2.order(_env(b"d"))
    _wait(lambda: store2.height == 3, msg="new block after restart")
    chain2.halt()


def test_evicted_node_demotes_instead_of_campaigning(tmp_path):
    """Eviction suspicion (reference etcdraft/eviction.go): node 3 is
    partitioned, the leader removes it from the consenter set, the
    partition heals.  Node 3 never hears from the leader again (it left
    the peer set), so after the suspicion window it probes the cluster,
    finds itself absent from the active consenter set, halts, and fires
    on_eviction — instead of campaigning forever on its stale voter
    list.  Nodes 1 and 2 keep ordering."""
    transport = InProcTransport()
    consenters = [rpb.Consenter(id=i) for i in (1, 2, 3)]
    genesis = _genesis()
    chains = {}
    evicted = threading.Event()

    partitioned = threading.Event()

    def probe():
        # the probe rides the cluster RPC transport, so it honors the
        # partition: unreachable peers -> None (keep waiting)
        if partitioned.is_set():
            return None
        return set(chains[1][0].consenters)

    for nid in (1, 2, 3):
        kw = {}
        if nid == 3:
            kw = dict(
                eviction_suspicion_ticks=10,
                active_consenters_probe=probe,
                on_eviction=evicted.set,
            )
        chains[nid] = _mk_chain(
            nid, transport, tmp_path, consenters, genesis, **kw
        )
    for c, _, _ in chains.values():
        c.start()
    try:
        lead = _leader(chains)
        assert lead in (1, 2, 3)
        # partition node 3 away, then remove it from the config
        partitioned.set()
        transport.partition(3, 1)
        transport.partition(3, 2)
        if lead == 3:
            # make sure the removal is decided by the surviving majority
            _wait(
                lambda: any(
                    chains[n][0].is_leader for n in (1, 2)
                ),
                msg="new leader among 1,2",
            )
            lead = 1 if chains[1][0].is_leader else 2
        cc = rpb.ConfChange(action=rpb.ConfChange.REMOVE_NODE)
        cc.consenter.id = 3
        chains[lead][0].propose_conf_change(cc)
        _wait(
            lambda: 3 not in chains[lead][0].consenters,
            msg="removal applied on the leader",
        )
        # heal; node 3 is no longer a member, hears nothing, suspects,
        # probes, confirms, demotes
        transport.heal()
        partitioned.clear()
        assert evicted.wait(10.0), "evicted node must fire on_eviction"
        assert chains[3][0].evicted.is_set()
        assert chains[3][0]._halted.is_set()
        # the surviving cluster still orders
        leader_chain = chains[lead][0]
        h0 = chains[1][1].height
        leader_chain.order(_env(b"after-eviction"))
        _wait(
            lambda: chains[1][1].height > h0,
            msg="cluster keeps ordering after the eviction",
        )
    finally:
        for c, _, _ in chains.values():
            if not c._halted.is_set():
                c.halt()


def test_ready_persist_crash_contract(tmp_path):
    """Pins the ready()/WAL-persist crash contract (reference
    etcdraft/node.go follows the etcd Ready pattern: persist HardState +
    entries BEFORE sending messages or applying).  Our _drain_ready does
    the same, and ready() advances applied state eagerly — so a crash
    BETWEEN ready() and the WAL save loses only in-memory state that
    was never externally visible:

    * entries committed in an earlier (saved) ready are re-emitted as
      committed on restart — the chain re-applies them idempotently
      (its _apply skips blocks below writer.height);
    * entries handed out in the UNSAVED ready are simply gone, which is
      correct: their persistence was a precondition for any message or
      apply, none of which happened."""
    w = WAL(str(tmp_path))
    n = RaftNode(1, {1})
    while not n.is_leader:
        n.tick()
    rd = n.ready()
    w.save(rd.hard_state, rd.persist_entries)
    assert n.propose(b"E1") and n.propose(b"E2")
    rd = n.ready()
    assert [e.data for e in rd.committed if e.data] == [b"E1", b"E2"]
    w.save(rd.hard_state, rd.persist_entries)  # persisted AND committed
    assert n.propose(b"E3")
    rd2 = n.ready()
    assert any(e.data == b"E3" for e in rd2.persist_entries)
    # CRASH: rd2 is never saved; E3 was never persisted, sent, or applied
    w.close()

    w2 = WAL(str(tmp_path))
    hs, log, _snap = w2.load()
    n2 = RaftNode(
        1, {1}, log=log, term=hs.term, voted_for=hs.voted_for,
        commit=hs.commit,
    )
    while not n2.is_leader:
        n2.tick()
    rd = n2.ready()
    datas = [e.data for e in rd.committed if e.data]
    assert b"E1" in datas and b"E2" in datas, "committed entries replay"
    assert b"E3" not in datas, "never-persisted entry must not resurrect"
    w2.close()


def test_chain_crash_between_apply_and_next_ready_is_idempotent(tmp_path):
    """The chain-level half of the crash contract: a chain restarted
    from a WAL whose commit index is AHEAD of the blocks it managed to
    write re-applies the missing entries exactly once and skips the
    ones already in the store (writer-height check in _apply)."""
    transport = InProcTransport()
    consenters = [rpb.Consenter(id=1)]
    genesis = _genesis()
    chain, store, delivered = _mk_chain(
        1, transport, tmp_path, consenters, genesis
    )
    chain.start()
    try:
        _wait(lambda: chain.is_leader, msg="single node elects")
        for i in range(4):
            chain.order(_env(b"tx-%d" % i))
        _wait(lambda: store.height == 3, msg="blocks 1,2 written")
    finally:
        chain.halt()
    # "crash": restart a fresh chain over the SAME wal + SAME store —
    # replay re-emits every committed entry; _apply must skip blocks
    # already in the store and keep ordering from the right height
    transport2 = InProcTransport()
    chain2, store2, _ = _mk_chain(
        1, transport2, tmp_path, consenters, genesis
    )
    # share the persisted block store state: re-drive onto a copy
    chain2._writer = chain._writer  # same underlying store
    chain2.start()
    try:
        _wait(lambda: chain2.is_leader, msg="restarted node elects")
        assert store.height == 3, "replay must not duplicate blocks"
        chain2.order(_env(b"post-restart"))
        chain2.order(_env(b"post-restart-2"))
        _wait(lambda: store.height == 4, msg="ordering resumes")
        nums = [
            store.get_block_by_number(i).header.number
            for i in range(store.height)
        ]
        assert nums == [0, 1, 2, 3], "no gaps, no duplicates"
    finally:
        chain2.halt()
