"""Seeded violation (racecheck, v5 CFG pass): the loop body writes the
shared field and THEN starts the worker — on iteration 2 the write
races with the thread started on iteration 1.  Line numbers say
write-before-start; the back edge says otherwise, and only the
flow-sensitive happens-before pass sees it."""

from fabric_tpu.devtools.lockwatch import spawn_thread


def handle(item):
    return item


class BatchPump:
    def __init__(self):
        self._batch = []
        self._threads = []

    def launch(self, specs):
        for spec in specs:
            # iteration 2 rebinds the field the iteration-1 worker is
            # reading: the back edge carries this write AFTER a start
            self._batch = [spec]  # <- racecheck fires HERE
            t = spawn_thread(
                target=self._run, name="pump", kind="worker"
            )
            t.start()
            self._threads.append(t)
        for t in self._threads:
            t.join()

    def _run(self):
        for item in list(self._batch):
            handle(item)
