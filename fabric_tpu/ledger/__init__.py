"""Ledger stack: block storage, versioned state, MVCC, history.

Reference: common/ledger (blkstorage, leveldbhelper) + core/ledger
(kvledger, txmgmt, statedb, history).  See each module's docstring for the
exact reference surface it mirrors.
"""

from fabric_tpu.ledger.kvstore import (
    KVStore,
    MemKVStore,
    NamedDB,
    SqliteKVStore,
    WriteBatchCollector,
    open_kvstore,
)
from fabric_tpu.ledger.statedb import Height, VersionedDB, VersionedValue
from fabric_tpu.ledger.blkstorage import BlockStore, BlockStoreError
from fabric_tpu.ledger.history import HistoryDB
from fabric_tpu.ledger.txmgmt import MVCCValidator, TxSimulator
from fabric_tpu.ledger.kvledger import (
    CommitGroup,
    KVLedger,
    LedgerProvider,
    extract_rwsets,
)
from fabric_tpu.ledger.snapshot import (
    SnapshotError,
    SnapshotManager,
    generate_snapshot,
    verify_snapshot,
)

__all__ = [
    "SnapshotError",
    "SnapshotManager",
    "generate_snapshot",
    "verify_snapshot",
    "KVStore",
    "MemKVStore",
    "SqliteKVStore",
    "NamedDB",
    "WriteBatchCollector",
    "open_kvstore",
    "CommitGroup",
    "Height",
    "VersionedDB",
    "VersionedValue",
    "BlockStore",
    "BlockStoreError",
    "HistoryDB",
    "MVCCValidator",
    "TxSimulator",
    "KVLedger",
    "LedgerProvider",
    "extract_rwsets",
]
