"""Rich (JSON selector) state queries — the CouchDB-backend capability
(reference core/ledger/kvledger/txmgmt/statedb/statecouchdb with its
Mango selector queries, surfaced to chaincode as GetQueryResult).

Supported selector subset: implicit equality, $eq $ne $gt $gte $lt
$lte $in $nin $exists, dotted field paths, $and / $or combinators, and
an optional "limit".

Execution is index-assisted when the statedb defines an index on a
field the selector constrains conjunctively (statedb.VersionedDB
define_index; reference statecouchdb.go:53 index-backed queries): the
planner prefers a COMPOUND index whose field prefix is covered by
equality conditions (optionally one trailing $in/range — longer
prefixes win), then a single-field condition ($eq, then $in, then a
range); either way it range-scans the order-preserving index for
candidate keys and rechecks every candidate document with the full
selector — so an imprecise index can only over-select, never change
results.  Results are key-ordered
and limit-truncated identically to the scan path, keeping endorsement
read/write sets deterministic whether or not an index exists.  Without
a usable index, selectors run as the full-namespace scan (semantically
the reference's behavior on an unindexed CouchDB field).

As in the reference, rich-query results are NOT protected by MVCC
phantom detection (statecouchdb documents this caveat); only range
queries get hash-based phantom checks.
"""

from __future__ import annotations

import json
from typing import Iterable


def _field(doc, path: str):
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None, False
        cur = cur[part]
    return cur, True


def _cmp_ok(a, b, op: str) -> bool:
    try:
        if op == "$gt":
            return a > b
        if op == "$gte":
            return a >= b
        if op == "$lt":
            return a < b
        if op == "$lte":
            return a <= b
    except TypeError:
        return False
    return False


def _match_cond(value, present: bool, cond) -> bool:
    if not isinstance(cond, dict):
        return present and value == cond
    for op, operand in cond.items():
        if op == "$eq":
            if not (present and value == operand):
                return False
        elif op == "$ne":
            if present and value == operand:
                return False
        elif op in ("$gt", "$gte", "$lt", "$lte"):
            if not (present and _cmp_ok(value, operand, op)):
                return False
        elif op == "$in":
            if not (present and value in operand):
                return False
        elif op == "$nin":
            if present and value in operand:
                return False
        elif op == "$exists":
            if bool(operand) != present:
                return False
        else:
            raise ValueError(f"unsupported operator {op!r}")
    return True


def match_selector(doc, selector: dict) -> bool:
    for key, cond in selector.items():
        if key == "$and":
            if not all(match_selector(doc, s) for s in cond):
                return False
        elif key == "$or":
            if not any(match_selector(doc, s) for s in cond):
                return False
        else:
            value, present = _field(doc, key)
            if not _match_cond(value, present, cond):
                return False
    return True


def _parse_query(query: str) -> tuple[dict, int | None]:
    q = json.loads(query)
    selector = q.get("selector", {}) if isinstance(q, dict) else {}
    limit = q.get("limit") if isinstance(q, dict) else None
    if limit is not None:
        if not isinstance(limit, int) or isinstance(limit, bool) or limit < 0:
            raise ValueError(f"invalid limit {limit!r}")
    return selector, limit


def _conjunctive_conds(selector: dict) -> list[tuple[str, object]]:
    """(field, condition) pairs that must ALL hold — top-level fields
    plus $and arms; $or arms contribute nothing (any single-field
    prefilter would under-select a disjunction)."""
    out: list[tuple[str, object]] = []
    for key, cond in selector.items():
        if key == "$and":
            for sub in cond:
                if isinstance(sub, dict):
                    out.extend(_conjunctive_conds(sub))
        elif key != "$or":
            out.append((key, cond))
    return out


def _field_conds(selector: dict) -> dict:
    """field -> first usable condition kind for index planning:
    ("eq", v) | ("in", [vs]) | ("range", lo|None, hi|None).  eq wins
    over in over range when a field carries several conjuncts."""
    out: dict = {}

    def rank(kind):  # lower is better
        return {"eq": 0, "in": 1, "range": 2}[kind]

    for f, cond in _conjunctive_conds(selector):
        cand = None
        if not isinstance(cond, dict):
            cand = ("eq", cond)
        elif "$eq" in cond:
            cand = ("eq", cond["$eq"])
        elif isinstance(cond.get("$in"), list):
            cand = ("in", cond["$in"])
        else:
            lo = cond.get("$gte", cond.get("$gt"))
            hi = cond.get("$lte", cond.get("$lt"))
            if lo is not None or hi is not None:
                cand = ("range", lo, hi)
        if cand is None:
            continue
        cur = out.get(f)
        if cur is None or rank(cand[0]) < rank(cur[0]):
            out[f] = cand
    return out


def plan_compound(selector: dict, indexed: set) -> tuple | None:
    """Best compound-index prefilter: ("comp", spec, fields, eq_values,
    last|None) where eq_values cover fields[:len(eq_values)] and `last`
    is an ("in", vs) / ("range", lo, hi) condition on the LAST field.

    A compound index is usable ONLY when the selector constrains EVERY
    field of the index (equalities on all but optionally the last,
    which may carry one in/range): a document missing any indexed
    field is absent from the index, so a selector that leaves a field
    unconstrained could match documents the index cannot return —
    CouchDB's well-known partial-index under-selection gotcha, which
    this planner must never reproduce.  Every planned condition
    requires presence of a scalar, so index membership covers exactly
    the candidate set.  More fields win; all-eq beats a trailing
    range."""
    from fabric_tpu.ledger.statedb import INDEX_SPEC_SEP

    conds = _field_conds(selector)
    best = None  # (score, plan)
    for spec in indexed:
        if INDEX_SPEC_SEP not in spec:
            continue
        fields = spec.split(INDEX_SPEC_SEP)
        eq_values: list = []
        last = None
        for pos, f in enumerate(fields):
            c = conds.get(f)
            if c is None:
                break
            if c[0] == "eq":
                eq_values.append(c[1])
                continue
            if pos == len(fields) - 1:
                last = c  # non-eq allowed only on the final field
            break
        if len(eq_values) + (1 if last is not None else 0) != len(fields):
            continue  # not fully covered: unusable (see docstring)
        score = (len(fields), 1 if last is None else 0)
        if best is None or score > best[0]:
            best = (score, ("comp", spec, fields, eq_values, last))
    return best[1] if best else None


def plan_index(selector: dict, indexed: set) -> tuple | None:
    """Pick the best indexed prefilter: ("comp", ...) (see
    plan_compound) | ("eq", field, value) | ("in", field, values) |
    ("range", field, lo|None, hi|None) | None.  Range bounds are
    widened to inclusive (the recheck restores exactness)."""
    comp = plan_compound(selector, indexed)
    if comp is not None:
        return comp
    return plan_single(selector, indexed)


def plan_single(selector: dict, indexed: set) -> tuple | None:
    """The single-field arm of plan_index — also the EXECUTION-TIME
    fallback when a compound plan turns out unservable (non-scalar
    operand, probe fan-out): a query a single-field index served before
    a compound index existed must keep being served after."""
    conds = [
        (f, c) for f, c in _conjunctive_conds(selector) if f in indexed
    ]
    for field, cond in conds:
        if not isinstance(cond, dict):
            return ("eq", field, cond)
        if "$eq" in cond:
            return ("eq", field, cond["$eq"])
    for field, cond in conds:
        if isinstance(cond, dict) and isinstance(cond.get("$in"), list):
            return ("in", field, cond["$in"])
    for field, cond in conds:
        if not isinstance(cond, dict):
            continue
        lo = cond.get("$gte", cond.get("$gt"))
        hi = cond.get("$lte", cond.get("$lt"))
        if lo is not None or hi is not None:
            return ("range", field, lo, hi)
    return None


def _eq_encodings(v) -> list[bytes] | None:
    """All index encodings an equality operand must probe, or None when
    the index cannot serve it (caller falls back to the full scan).

    Two invariants keep "index can only over-select" true: (a) docs with
    non-scalar values (arrays/objects) are never indexed, so an
    unencodable operand means the index would silently drop matches;
    (b) match_selector compares with Python ==, under which True == 1
    and False == 0, while bool and number encode under different type
    tags — so bool operands also probe the numeric entry and 0/1
    numeric operands also probe the bool entry."""
    from fabric_tpu.ledger.statedb import encode_scalar

    enc = encode_scalar(v)
    if enc is None:
        return None
    probes = [enc]
    if isinstance(v, bool):
        probes.append(encode_scalar(int(v)))
    elif isinstance(v, (int, float)) and v in (0, 1):
        probes.append(encode_scalar(bool(v)))
    return probes


def _component_probes(v) -> list[bytes] | None:
    """_eq_encodings in compound-component form (strings carry their
    composite terminator)."""
    probes = _eq_encodings(v)
    if probes is None:
        return None
    return [p + b"\x00" if p[:1] == b"\x04" else p for p in probes]


def _compound_keys(db, ns: str, plan) -> list | None:
    """Candidate state keys for a ("comp", ...) plan, or None when an
    operand cannot ride the index (caller falls back to the scan)."""
    from fabric_tpu.ledger.statedb import encode_scalar

    _, spec, _fields, eq_values, last = plan
    # cartesian product of per-component probe sets (bool/number twin
    # probes give at most 2 per component; cap the fan-out anyway)
    prefixes = [b""]
    for v in eq_values:
        probes = _component_probes(v)
        if probes is None:
            return None
        prefixes = [p + e for p in prefixes for e in probes]
        if len(prefixes) > 32:
            return None
    keys: list = []
    if last is None:
        for p in prefixes:
            keys.extend(db.index_scan(ns, spec, p, p))
        return keys
    if last[0] == "in":
        for v in last[1]:
            probes = _component_probes(v)
            if probes is None:
                return None
            for p in prefixes:
                for e in probes:
                    keys.extend(db.index_scan(ns, spec, p + e, p + e))
        return keys
    # trailing range on the next component
    _, lo, hi = last
    if isinstance(lo, bool) or isinstance(hi, bool):
        return None  # bool bounds cross-compare with numbers: scan
    lo_enc = encode_scalar(lo) if lo is not None else None
    hi_enc = encode_scalar(hi) if hi is not None else None
    if (lo is not None and lo_enc is None) or (
        hi is not None and hi_enc is None
    ):
        return None
    if lo_enc is not None and lo_enc[:1] == b"\x04":
        lo_enc += b"\x00"
    if hi_enc is not None and hi_enc[:1] == b"\x04":
        hi_enc += b"\x00"
    for p in prefixes:
        # open ends stay INSIDE this eq-prefix: every component
        # encoding starts with a tag <= \x04, so \xfd\xff caps the
        # prefix's region without crossing into the next prefix
        start = p + (lo_enc if lo_enc is not None else b"")
        end = p + (hi_enc if hi_enc is not None else b"\xfd\xff")
        keys.extend(db.index_scan(ns, spec, start, end))
        lo_num = lo if isinstance(lo, (int, float)) else None
        hi_num = hi if isinstance(hi, (int, float)) else None
        if (lo_num is not None or hi_num is not None) and (
            lo_num is None or lo_num <= 1
        ) and (hi_num is None or hi_num >= 0):
            # bool doc values order-compare with numeric bounds under
            # Python but live under a different type tag (see the
            # single-field sweep below)
            bool_lo = p + encode_scalar(False)
            bool_hi = p + encode_scalar(True)
            keys.extend(db.index_scan(ns, spec, bool_lo, bool_hi))
    return keys


def execute_query_indexed(db, ns: str, query: str):
    """Index-assisted execution against a statedb.VersionedDB; returns
    [(key, value, version)] in key order, or None when no defined index
    matches the selector (caller falls back to the scan path)."""
    from fabric_tpu.ledger.statedb import encode_scalar

    selector, limit = _parse_query(query)
    indexed = db.indexes_for(ns)
    p = plan_index(selector, indexed)
    if p is not None and p[0] == "comp":
        keys = _compound_keys(db, ns, p)
        if keys is None:
            # compound plan unservable at execution time (non-scalar
            # operand, probe fan-out): retry the single-field planner
            # before surrendering to the full scan
            p = plan_single(selector, indexed)
        else:
            p = ("_done",)
    if p is None:
        return None
    if p[0] == "_done":
        pass
    elif p[0] in ("eq", "in"):
        operands = [p[2]] if p[0] == "eq" else list(p[2])
        keys = []
        for v in operands:
            probes = _eq_encodings(v)
            if probes is None:
                return None  # index can't serve this operand: full scan
            for enc in probes:
                keys.extend(db.index_scan(ns, p[1], enc, enc))
    else:
        _, field, lo, hi = p
        if isinstance(lo, bool) or isinstance(hi, bool):
            return None  # bool bounds cross-compare with numbers: scan
        lo_enc = encode_scalar(lo) if lo is not None else None
        hi_enc = encode_scalar(hi) if hi is not None else None
        if (lo is not None and lo_enc is None) or (
            hi is not None and hi_enc is None
        ):
            return None  # unencodable bound: fall back to the scan
        keys = list(db.index_scan(ns, field, lo_enc, hi_enc))
        lo_num = lo if isinstance(lo, (int, float)) else None
        hi_num = hi if isinstance(hi, (int, float)) else None
        if (lo_num is not None or hi_num is not None) and (
            lo_num is None or lo_num <= 1
        ) and (hi_num is None or hi_num >= 0):
            # bool doc values order-compare with numeric bounds under
            # Python (True >= 1), but live under a different type tag —
            # sweep the (two-value) bool region when the bounds overlap
            # [False, True] ≡ [0, 1]; the recheck is exact
            keys.extend(
                db.index_scan(ns, field, encode_scalar(False), encode_scalar(True))
            )
    out = []
    for key in sorted(set(keys)):
        vv = db.get_state(ns, key)
        if vv is None:
            continue
        try:
            doc = json.loads(vv.value.decode("utf-8"))
        except Exception:
            # fabriclint: allow[exception-discipline] non-JSON values never
            # match a selector (couchdb attachment semantics)
            continue
        if isinstance(doc, dict) and match_selector(doc, selector):
            out.append((key, vv.value, vv.version))
            if limit is not None and len(out) >= limit:
                break
    return out


def execute_query(
    pairs: Iterable[tuple[str, bytes]], query: str
) -> list[tuple[str, bytes]]:
    """Filter (key, value) pairs by a JSON selector query string."""
    selector, limit = _parse_query(query)
    out = []
    for key, value in pairs:
        if limit is not None and len(out) >= limit:
            break
        try:
            doc = json.loads(value.decode("utf-8"))
        except Exception:
            # fabriclint: allow[exception-discipline] non-JSON values never
            # match a selector (couchdb attachment semantics)
            continue
        if not isinstance(doc, dict):
            continue
        if match_selector(doc, selector):
            out.append((key, value))
    return out


__all__ = [
    "match_selector",
    "execute_query",
    "execute_query_indexed",
    "plan_index",
    "plan_compound",
]
