"""IdentityMapper expiration semantics + certstore verification:
forged pki bindings and wrong-signer identity messages are rejected;
expired identities are purged (and the comm layer notified)."""

from __future__ import annotations

import hashlib

from fabric_tpu.gossip.certstore import CertStore
from fabric_tpu.gossip.comm import (
    InProcGossipComm,
    InProcGossipNet,
    MessageCryptoService,
)
from fabric_tpu.gossip.identity import IdentityMapper, identity_expiration
from fabric_tpu.protos.gossip import message_pb2 as gpb


class ToyMCS(MessageCryptoService):
    """Per-identity deterministic signatures (key = the identity)."""

    def sign_as(self, identity: bytes, payload: bytes) -> bytes:
        return hashlib.sha256(identity + b"|" + payload).digest()

    def verify(self, identity: bytes, signature: bytes, payload: bytes) -> bool:
        return signature == self.sign_as(identity, payload)


class SelfSigningMCS(ToyMCS):
    def __init__(self, identity: bytes):
        self._id = identity

    def sign(self, payload: bytes) -> bytes:
        return self.sign_as(self._id, payload)


def test_mapper_expiration_and_purge_hook():
    now = [1000.0]
    purged = []
    mcs = MessageCryptoService()
    m = IdentityMapper(
        mcs, b"me", default_ttl_s=50, clock=lambda: now[0],
        on_purge=purged.append,
    )
    pki = m.put(b"other")
    assert m.get(pki) == b"other"
    now[0] += 49
    assert m.get(pki) == b"other"
    now[0] += 2  # past the TTL
    assert m.get(pki) is None
    assert purged == [pki]
    assert all(p != pki for p, _ in m.known())


def test_mapper_x509_expiration_from_cert():
    from fabric_tpu.common.crypto import CA
    from fabric_tpu.protos.msp import identities_pb2

    ca = CA("expca", "org")
    pair = ca.issue("ephemeral", validity_days=1)
    sid = identities_pb2.SerializedIdentity(
        mspid="OrgMSP", id_bytes=pair.cert_pem
    ).SerializeToString()
    exp = identity_expiration(sid)
    assert exp is not None
    # mapper honors the certificate's notAfter
    m = IdentityMapper(MessageCryptoService(), b"me", clock=lambda: exp + 1)
    try:
        m.put(sid)
        raise AssertionError("expired identity must be rejected")
    except ValueError:
        pass


def _certstore_pair():
    net = InProcGossipNet()
    a = InProcGossipComm("a", net, b"idA", mcs=SelfSigningMCS(b"idA"))
    b = InProcGossipComm("b", net, b"idB", mcs=SelfSigningMCS(b"idB"))
    ma = IdentityMapper(a.mcs, b"idA")
    mb = IdentityMapper(b.mcs, b"idB")
    csa = CertStore(a, ma, lambda: ["b"])
    csb = CertStore(b, mb, lambda: ["a"])
    csa.endpoint_lookup = lambda pki: "b" if pki == b.pki_id else "a"
    csb.endpoint_lookup = lambda pki: "a" if pki == a.pki_id else "b"
    return a, b, ma, mb, csa, csb


def test_certstore_pull_disseminates_identities():
    a, b, ma, mb, csa, csb = _certstore_pair()
    assert mb.get(a.pki_id) is None
    csb.tick()  # b pulls from a
    assert mb.get(a.pki_id) == b"idA"
    assert b.identity_of(a.pki_id) == b"idA"
    csa.tick()
    assert ma.get(b.pki_id) == b"idB"


def test_certstore_rejects_forged_pki_binding():
    a, b, ma, mb, csa, csb = _certstore_pair()
    # craft an identity message whose pki does not derive from the cert
    m = gpb.GossipMessage()
    m.peer_identity.pki_id = b"\x00" * 16
    m.peer_identity.cert = b"idZ"
    signed = gpb.SignedGossipMessage(payload=m.SerializeToString())
    signed.signature = a.mcs.sign_as(b"idZ", signed.payload)
    csb._learn(signed)
    assert mb.get(b"\x00" * 16) is None


def test_certstore_rejects_wrong_signer():
    a, b, ma, mb, csa, csb = _certstore_pair()
    m = gpb.GossipMessage()
    m.peer_identity.pki_id = a.mcs.get_pki_id(b"idZ")
    m.peer_identity.cert = b"idZ"
    signed = gpb.SignedGossipMessage(payload=m.SerializeToString())
    # signed by idA, not by idZ's owner
    signed.signature = a.mcs.sign_as(b"idA", signed.payload)
    csb._learn(signed)
    assert mb.get(a.mcs.get_pki_id(b"idZ")) is None


def test_certstore_evicts_purged_identities():
    """Identities the mapper expires must stop being advertised and
    served by the certstore (reference certstore deletes purged ids
    from the pull mediator) — otherwise every pull round re-offers
    certs receivers can only reject."""
    net = InProcGossipNet()
    a = InProcGossipComm("a", net, b"idA", mcs=SelfSigningMCS(b"idA"))
    now = [1000.0]
    ma = IdentityMapper(a.mcs, b"idA", default_ttl_s=50, clock=lambda: now[0])
    csa = CertStore(a, ma, lambda: [])
    other_pki = ma.put(b"idOther")
    csa._signed[other_pki.hex()] = b"envelope"  # as if pulled earlier
    assert other_pki.hex() in csa.known_pkis()
    now[0] += 60
    assert other_pki in ma.sweep()
    assert other_pki.hex() not in csa.known_pkis()
    # own identity is never evicted
    assert a.pki_id.hex() in csa.known_pkis()


def test_mapper_multiple_purge_listeners():
    purged_a, purged_b = [], []
    now = [0.0]
    m = IdentityMapper(
        MessageCryptoService(), b"me", default_ttl_s=10,
        clock=lambda: now[0], on_purge=purged_a.append,
    )
    m.add_purge_listener(purged_b.append)
    pki = m.put(b"x")
    now[0] += 11
    m.sweep()
    assert pki in purged_a and pki in purged_b
