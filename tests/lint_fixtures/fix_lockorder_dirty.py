"""Seeded violation: two methods acquire the same two lock roles in
opposite orders — a role-level cycle in the static acquisition-order
graph (lock-order, the static twin of lockwatch's LockOrderError)."""

from fabric_tpu.devtools.lockwatch import named_lock


def touch():
    return None


class Pair:
    def __init__(self):
        self._a = named_lock("fixture.order.a")
        self._b = named_lock("fixture.order.b")

    def forward(self):
        with self._a:
            with self._b:  # establishes a -> b
                touch()

    def backward(self):
        with self._b:
            with self._a:  # <- lock-order fires HERE
                touch()
