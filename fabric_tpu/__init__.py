"""fabric-tpu: a TPU-native permissioned distributed-ledger framework.

A ground-up rebuild of the capability surface of Hyperledger Fabric
(reference: /root/reference) designed TPU-first:

- the *control plane* (ordering, ledger, policies, identity, p2p) is a lean
  re-implementation of the reference's architecture, and
- the *data plane* -- hashing, signature verification, pairing checks -- is a
  batched JAX/XLA/Pallas service: every signature in a block is verified in a
  single device call instead of one-goroutine-per-tx ECDSA
  (reference: core/committer/txvalidator/v20/validator.go:180-265,
  common/policies/policy.go:365-402).

Layer map (mirrors reference SURVEY.md section 1):
  protos/    wire format + proto utilities        (reference: protoutil/)
  csp/       crypto service provider, sw + tpu    (reference: bccsp/)
  msp/       X.509 membership service provider    (reference: msp/)
  policies/  policy manager + signature policies  (reference: common/policies,
             common/cauthdsl, common/policydsl)
  ledger/    block store + MVCC kv ledger         (reference: common/ledger,
             core/ledger)
  orderer/   blockcutter, consenters, multichannel (reference: orderer/)
  peer/      txvalidator, committer, endorser     (reference: core/)
  gossip/    membership + dissemination           (reference: gossip/)
  common/    logging, metrics, config             (reference: common/flogging,
             common/metrics)
  node/      process assembly                     (reference: internal/peer/node,
             orderer/common/server)
"""

__version__ = "0.1.0"
