"""Per-channel leader election over gossip.

Capability parity with the reference's gossip/election
(election.go:147 LeaderElectionService: peers propose themselves, the
smallest PKI-ID wins, the leader periodically re-declares, followers
re-elect when declarations stop).  The elected peer runs the channel's
deliver client (pulls blocks from the orderer for the whole org) —
gossip/service wiring in the reference.

Tick-driven core: each tick the node (a) expires a silent leader,
(b) declares itself leader if it believes it should lead, (c) otherwise
proposes.  Convergence: all nodes apply "smallest pki-id among proposals
seen this round wins".
"""

from __future__ import annotations

import threading

from fabric_tpu.protos.gossip import message_pb2 as gpb


class LeaderElection:
    def __init__(
        self,
        channel_id: str,
        comm,
        membership,  # callable -> list[str] endpoints in channel
        on_leadership_change=None,  # callback(is_leader: bool)
        leader_timeout_ticks: int = 5,
    ):
        self.channel_id = channel_id
        self._chan = channel_id.encode()
        self._comm = comm
        self._membership = membership
        self._on_change = on_leadership_change or (lambda is_leader: None)
        self._timeout = leader_timeout_ticks
        self._tick = 0
        self._seq = 0
        self._leader: bytes | None = None
        self._leader_seen_tick = 0
        self._proposals: dict[bytes, int] = {}  # pki -> last tick seen
        self._lock = threading.Lock()
        self.is_leader = False
        comm.subscribe(self._handle)

    def _broadcast(self, declaration: bool) -> None:
        self._seq += 1
        m = gpb.GossipMessage(channel=self._chan, tag=gpb.GossipMessage.CHAN_ONLY)
        m.leadership_msg.pki_id = self._comm.pki_id
        m.leadership_msg.seq_num = self._seq
        m.leadership_msg.is_declaration = declaration
        for ep in self._membership():
            self._comm.send(ep, m)

    def tick(self) -> None:
        self._tick += 1
        with self._lock:
            leader_expired = (
                self._leader is not None
                and self._leader != self._comm.pki_id
                and self._tick - self._leader_seen_tick > self._timeout
            )
            if leader_expired:
                self._leader = None
            # drop stale proposals
            self._proposals = {
                p: t
                for p, t in self._proposals.items()
                if self._tick - t <= self._timeout
            }
            candidates = set(self._proposals) | {self._comm.pki_id}
            if self._leader is not None and not leader_expired:
                should_lead = self._leader == self._comm.pki_id
            else:
                should_lead = min(candidates) == self._comm.pki_id
        if should_lead:
            with self._lock:
                self._leader = self._comm.pki_id
                self._leader_seen_tick = self._tick
            self._broadcast(declaration=True)
            self._set_leader(True)
        else:
            self._broadcast(declaration=False)
            self._set_leader(False)

    def _set_leader(self, val: bool) -> None:
        if val != self.is_leader:
            self.is_leader = val
            self._on_change(val)

    def leader(self) -> bytes | None:
        with self._lock:
            return self._leader

    def _handle(self, rm) -> None:
        msg = rm.msg
        if (
            bytes(msg.channel) != self._chan
            or msg.WhichOneof("content") != "leadership_msg"
        ):
            return
        lm = msg.leadership_msg
        pki = bytes(lm.pki_id)
        with self._lock:
            self._proposals[pki] = self._tick
            if lm.is_declaration:
                # yield to a declared leader with smaller pki-id; contest
                # (by continuing to declare) otherwise
                if self._leader is None or pki <= self._leader:
                    self._leader = pki
                    self._leader_seen_tick = self._tick
                relinquish = pki < self._comm.pki_id
        if lm.is_declaration and relinquish:
            self._set_leader(False)


__all__ = ["LeaderElection"]
