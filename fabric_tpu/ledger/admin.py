"""Offline ledger repair operations (reference internal/peer/node/
{reset,rollback,rebuild_dbs}.go + core/ledger/kvledger rollback/reset):
run against a stopped peer's storage root, like the reference CLIs.

- rebuild_dbs: drop the derived DBs (state/history); they are replayed
  from the block store on next open (kvledger recovery).
- rollback: truncate a channel's chain to a target block, then rebuild
  the derived DBs.
- reset: rollback every channel to its genesis block.
"""

from __future__ import annotations

import os
import shutil

from fabric_tpu.ledger.blkstorage import BlockStore
from fabric_tpu.ledger.kvstore import open_kvstore, wipe_prefix
from fabric_tpu.ledger.kvledger import LedgerProvider


def _derived_prefixes(ledger_id: str) -> list[bytes]:
    return [
        f"statedb/{ledger_id}".encode() + b"\x00\xff",
        f"historydb/{ledger_id}".encode() + b"\x00\xff",
    ]


def _index_prefix(ledger_id: str) -> bytes:
    return f"blkindex/{ledger_id}".encode() + b"\x00\xff"


def _open_kv(root_dir: str):
    return open_kvstore(os.path.join(root_dir, "index.sqlite"))


def list_channels(root_dir: str) -> list[str]:
    return sorted(
        e for e in os.listdir(root_dir)
        if os.path.isdir(os.path.join(root_dir, e, "chains"))
    )


def _check_not_snapshot_bootstrapped(kv, ledger_id: str, op: str) -> None:
    """Refuse repair ops that would truncate or rebuild through a
    snapshot bootstrap: blocks below the bootstrap height do not exist
    locally, so neither a rollback target below it nor a derived-DB
    replay from block 0 is possible (the reference's rollback/reset/
    rebuild validation refuses bootstrapped channels the same way)."""
    from fabric_tpu.ledger.blkstorage import read_bootstrap_height

    bh = read_bootstrap_height(kv, ledger_id)
    if bh:
        raise ValueError(
            f"channel {ledger_id!r} was bootstrapped from a snapshot at "
            f"block {bh - 1}: {op} would truncate it below its bootstrap "
            f"height {bh}, and blocks before the snapshot do not exist "
            "locally to replay"
        )


def rebuild_dbs(root_dir: str, ledger_id: str | None = None) -> list[str]:
    """Drop state/history DBs for one (or every) channel; next open
    replays them from blocks (reference rebuild-dbs + RebuildDBs)."""
    ids = [ledger_id] if ledger_id else list_channels(root_dir)
    kv = _open_kv(root_dir)
    try:
        for lid in ids:
            _check_not_snapshot_bootstrapped(kv, lid, "rebuild-dbs")
        for lid in ids:
            for p in _derived_prefixes(lid):
                wipe_prefix(kv, p)
    finally:
        kv.close()
    return ids


def rollback(root_dir: str, ledger_id: str, target_block: int) -> int:
    """Truncate the channel's chain so `target_block` is the last block,
    then drop the derived DBs for replay (reference peer node rollback +
    kvledger/rollback.go).  Returns the new height."""
    kv = _open_kv(root_dir)
    try:
        _check_not_snapshot_bootstrapped(kv, ledger_id, "rollback")
        chains_dir = os.path.join(root_dir, ledger_id, "chains")
        store = BlockStore(chains_dir, kv, name=ledger_id)
        if store.height == 0:
            raise ValueError(f"channel {ledger_id!r} has no blocks")
        if target_block >= store.height:
            raise ValueError(
                f"target block {target_block} >= height {store.height}"
            )
        # stream retained blocks through a sidecar chain dir so memory
        # stays O(1) even on long chains, then swap directories
        tmp_dir = chains_dir + ".rollback"
        if os.path.isdir(tmp_dir):
            shutil.rmtree(tmp_dir)
        tmp_name = f"{ledger_id}.rollback"
        wipe_prefix(kv, _index_prefix(tmp_name))
        store2 = BlockStore(tmp_dir, kv, name=tmp_name)
        for n in range(target_block + 1):
            store2.add_block(store.get_block_by_number(n))
        wipe_prefix(kv, _index_prefix(ledger_id))
        wipe_prefix(kv, _index_prefix(tmp_name))
        shutil.rmtree(chains_dir)
        os.rename(tmp_dir, chains_dir)
        # reindex under the real name from the swapped files
        store3 = BlockStore(chains_dir, kv, name=ledger_id)
        for p in _derived_prefixes(ledger_id):
            wipe_prefix(kv, p)
        return store3.height
    finally:
        kv.close()


def reset(root_dir: str) -> dict[str, int]:
    """Roll every channel back to its genesis block (reference peer node
    reset)."""
    out = {}
    channels = list_channels(root_dir)
    # validate EVERY channel before truncating the first one — failing
    # mid-loop would leave an irreversible half-reset
    kv = _open_kv(root_dir)
    try:
        for lid in channels:
            _check_not_snapshot_bootstrapped(kv, lid, "reset")
    finally:
        kv.close()
    for lid in channels:
        kv = _open_kv(root_dir)
        try:
            store = BlockStore(
                os.path.join(root_dir, lid, "chains"), kv, name=lid
            )
            height = store.height
        finally:
            kv.close()
        out[lid] = rollback(root_dir, lid, 0) if height > 1 else height
    return out


def verify_rebuild(root_dir: str, ledger_id: str) -> int:
    """Open the ledger (triggering recovery replay) and return its
    height — the post-repair sanity check."""
    provider = LedgerProvider(root_dir)
    try:
        return provider.open(ledger_id).height
    finally:
        provider.close()


__all__ = ["rebuild_dbs", "rollback", "reset", "list_channels",
           "verify_rebuild"]


# -- pause / resume / upgrade-dbs (reference internal/peer/node/
# {pause,resume,upgrade_dbs}.go) --------------------------------------------

_PAUSED_KEY = b"admin/paused/"
# Data-format version stamp (reference dataformat.Version checks in
# kvledger upgrade_dbs): bump when derived-DB encodings change.
DATA_FORMAT_VERSION = b"fabric-tpu/2.0"
_FORMAT_KEY = b"admin/dataformat"


def pause(root_dir: str, ledger_id: str) -> None:
    """Mark a channel paused: the peer skips it at startup until resume
    (reference pauseChannelCmd -> kvledger.PauseChannel)."""
    kv = _open_kv(root_dir)
    try:
        kv.put(_PAUSED_KEY + ledger_id.encode(), b"1")
    finally:
        kv.close()


def resume(root_dir: str, ledger_id: str) -> None:
    kv = _open_kv(root_dir)
    try:
        kv.delete(_PAUSED_KEY + ledger_id.encode())
    finally:
        kv.close()


def paused_channels(root_dir: str) -> set[str]:
    kv = _open_kv(root_dir)
    try:
        return {
            k[len(_PAUSED_KEY):].decode()
            for k, _ in kv.iterate(_PAUSED_KEY, _PAUSED_KEY + b"\xff")
        }
    finally:
        kv.close()


def upgrade_dbs(root_dir: str) -> list[str]:
    """Upgrade derived databases to the current data format: when the
    stored format stamp differs, drop + rebuild every derived DB from
    the block store (the reference's upgradeDBs resets statedb/history/
    etc. and replays; rebuild_dbs is exactly that) and stamp the new
    version."""
    kv = _open_kv(root_dir)
    try:
        current = kv.get(_FORMAT_KEY)
    finally:
        kv.close()
    if current == DATA_FORMAT_VERSION:
        return []
    rebuilt = rebuild_dbs(root_dir)
    kv = _open_kv(root_dir)
    try:
        kv.put(_FORMAT_KEY, DATA_FORMAT_VERSION)
    finally:
        kv.close()
    return rebuilt
