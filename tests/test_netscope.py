"""Netscope: the cluster-wide telemetry plane (ISSUE 12 tentpole).

Tier-1 pins:
- TSDB-lite mechanics: bounded rings, derived cross-peer-lag series,
  health timeline (ok / unhealthy with reasons / down);
- byte-determinism: two same-seed virtual-clock scrape sessions over
  the same endpoint serialize to identical ``netscope.jsonl`` bytes;
- the stall detector: flags a node strictly behind the tip whose
  height froze while a quorum of peers advanced over the window, stays
  quiet for tip-quiescent nodes, clears on recovery, and drops a
  tracelens instant mark;
- SLO rollups: catch-up seconds from restart markers + height series,
  sustained tx/s from the committed-tx counter slope, threshold
  judgments;
- artifacts: jsonl line shapes and the self-contained HTML report;
- END TO END (multi-process): a 1-org × 2-peer network with one peer's
  block-ingestion wedged by a per-node faultline plan — netscope flags
  exactly that node in the run verdict while the invariants oracle
  stays green on the survivors;
- a netbench ``--metrics-out`` run (slow: the acceptance-shaped
  2-org × 4-peer seeded campaign) emits netscope.jsonl + the HTML
  report with per-node height series and kill markers.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from fabric_tpu.common import tracing
from fabric_tpu.common.metrics import GaugeOpts, CounterOpts
from fabric_tpu.common.operations import System
from fabric_tpu.devtools import clockskew
from fabric_tpu.devtools import netharness as nh
from fabric_tpu.devtools import netident
from fabric_tpu.devtools.netscope import Netscope, write_artifacts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def ops_system():
    s = System(("127.0.0.1", 0))
    s.start()
    yield s
    s.stop()


def _gauge(system, name, namespace="ledger"):
    return system.metrics_provider.new_gauge(
        GaugeOpts(namespace=namespace, name=name)
    )


# ---------------------------------------------------------------------------
# TSDB-lite mechanics
# ---------------------------------------------------------------------------


def test_ring_buffer_bound_and_series_query(ops_system):
    g = _gauge(ops_system, "height")
    g.With("channel", "ch").set(0)
    scope = Netscope(
        {"n1": ops_system.addr}, interval_s=0.01, window=4,
    )
    for i in range(9):
        g.With("channel", "ch").set(i)
        scope.scrape_once()
    pts = scope.series("n1", "ledger_height", (("channel", "ch"),))
    assert len(pts) == 4  # ring bounded at the window
    assert [v for _, v in pts] == [5.0, 6.0, 7.0, 8.0]
    assert scope.latest(
        "n1", "ledger_height", (("channel", "ch"),)
    ) == 8.0


def test_derived_lag_and_health_timeline(ops_system):
    g = _gauge(ops_system, "height")
    g.With("channel", "ch").set(10)
    down = Netscope({
        "up": ops_system.addr,
        "gone": ("127.0.0.1", 1),  # nothing listens here
    }, interval_s=0.01)
    down.scrape_once()
    # the dead node lands on the health timeline as down, and the lag
    # series only covers nodes that actually answered
    with down._lock:
        assert [s for _, s, _ in down._health["gone"]] == ["down"]
        assert [s for _, s, _ in down._health["up"]] == ["ok"]
    assert down.series("_derived", "cross_peer_lag_blocks")[0][1] == 0.0

    # a failing checker flips the timeline to unhealthy with reasons
    ops_system.register_checker("statedb", lambda: False)
    down.scrape_once()
    with down._lock:
        t, status, failed = down._health["up"][-1]
    assert status == "unhealthy" and failed == ["statedb"]


def test_two_virtual_clock_sessions_byte_identical(ops_system):
    g = _gauge(ops_system, "height")
    c = ops_system.metrics_provider.new_counter(
        CounterOpts(namespace="ledger", name="transactions_total")
    )

    def session(path):
        with clockskew.use_virtual():
            scope = Netscope(
                {"n1": ops_system.addr}, interval_s=0.25, seed=11,
            )
            for i in range(6):
                g.With("channel", "ch").set(i)
                scope.scrape_once()
                clockskew.sleep(scope._next_interval())
            scope.write_jsonl(path)
        with open(path, "rb") as f:
            return f.read()

    a = session("/tmp/netscope_det_a.jsonl")
    # replay the counter to the identical value sequence
    c._series.clear()
    b = session("/tmp/netscope_det_b.jsonl")
    assert a == b


# ---------------------------------------------------------------------------
# stall detector
# ---------------------------------------------------------------------------


def _scrape_heights(scope, gauges, rounds):
    for hs in rounds:
        for node, g in gauges.items():
            g.set(hs[node])
        scope.scrape_once()


def test_stall_detector_flags_behind_node_only(ops_system):
    """Three 'nodes' scraped off three Systems: one freezes strictly
    behind while the others advance -> flagged, with the evidence
    window and a tracelens instant mark; the tip node that stops
    because it IS the tip stays unflagged."""
    systems = {"a": ops_system}
    for n in ("b", "c"):
        s = System(("127.0.0.1", 0))
        s.start()
        systems[n] = s
    try:
        gauges = {
            n: _gauge(s, "height").With("channel", "ch")
            for n, s in systems.items()
        }
        scope = Netscope(
            {n: s.addr for n, s in systems.items()},
            interval_s=0.01, stall_window=3,
        )
        with tracing.scope() as rec:
            # b freezes at 2 while a and c advance past it
            rounds = [
                {"a": h, "b": min(h, 2), "c": h} for h in range(1, 8)
            ]
            _scrape_heights(scope, gauges, rounds)
            assert scope.stalled_nodes() == ["b"]
            episode = scope.stall_episodes()[0]
            assert episode["node"] == "b"
            assert len(episode["evidence"]) >= scope.stall_window + 1
            marks = [
                ev for ev in rec.snapshot()
                if ev.get("name") == "netscope.stall"
            ]
            assert len(marks) == 1
            assert marks[0]["args"]["node"] == "b"
        # recovery clears the flag (stall_clear event recorded)
        _scrape_heights(
            scope, gauges,
            [{"a": 8, "b": 9, "c": 8}],
        )
        assert scope.stalled_nodes() == []
        with scope._lock:
            kinds = [e["event"] for e in scope._events]
        assert kinds == ["stall", "stall_clear"]

        # tip-quiescence is NOT a stall: a stops at 12 (the tip) while
        # b/c climb toward it from behind
        scope2 = Netscope(
            {n: s.addr for n, s in systems.items()},
            interval_s=0.01, stall_window=3,
        )
        rounds = [
            {"a": 12, "b": h, "c": h} for h in range(3, 11)
        ]
        _scrape_heights(scope2, gauges, rounds)
        assert scope2.stalled_nodes() == []
    finally:
        for n in ("b", "c"):
            systems[n].stop()


# ---------------------------------------------------------------------------
# SLO rollups
# ---------------------------------------------------------------------------


def test_slo_rollups_catch_up_and_tx_rate(ops_system):
    g = _gauge(ops_system, "height").With("channel", "ch")
    tx = ops_system.metrics_provider.new_counter(
        CounterOpts(namespace="ledger", name="transactions_total")
    ).With("channel", "ch")
    with clockskew.use_virtual():
        scope = Netscope(
            {"n1": ops_system.addr}, interval_s=1.0, seed=0,
        )
        # 10 tx/s against the virtual clock; node "restarts" at ~2s
        # and rejoins the tip at the next round
        for i in range(6):
            g.set(i)
            tx.add(10)
            scope.scrape_once()
            if i == 2:
                scope.mark("kill", "n1", sig="kill9")
                scope.mark("restart", "n1")
            clockskew.sleep(1.0)
        # keep the stream going well past the stall-detector's short
        # height window: catch-up must be computed from the FULL
        # series rings (regression: the first cut read the ~8-round
        # stall window, so a long run evicted the rejoin rounds and
        # reported the earliest retained round — grossly inflated)
        for i in range(6, 18):
            g.set(i)
            tx.add(10)
            scope.scrape_once()
            clockskew.sleep(1.0)
        slo = scope.slo({
            "p99_cross_peer_lag_blocks": 1,
            "catch_up_s": 10.0,
            "min_tx_per_s": 5.0,
        })
    assert slo["catch_up_s"]["n1"] == pytest.approx(1.0, abs=0.2)
    assert slo["sustained_tx_per_s"] == pytest.approx(10.0, rel=0.1)
    assert slo["stalled_nodes"] == []
    assert all(j["ok"] for j in slo["judgments"].values())
    assert slo["pass"] is True
    # a violated threshold fails its judgment and the rollup
    bad = scope.slo({"min_tx_per_s": 1000.0})
    assert bad["judgments"]["min_tx_per_s"]["ok"] is False
    assert bad["pass"] is False


# ---------------------------------------------------------------------------
# artifacts
# ---------------------------------------------------------------------------


def test_jsonl_and_html_artifacts(tmp_path, ops_system):
    g = _gauge(ops_system, "height").With("channel", "ch")
    scope = Netscope({"n1": ops_system.addr}, interval_s=0.01)
    for i in range(4):
        g.set(i)
        scope.scrape_once()
    scope.mark("kill", "n1", sig="kill9")
    scope.mark("restart", "n1")
    paths = write_artifacts(scope, str(tmp_path), prefix="netscope")
    lines = [
        json.loads(ln)
        for ln in open(paths["jsonl"], encoding="utf-8")
    ]
    kinds = [ln["kind"] for ln in lines]
    assert kinds[0] == "netscope-meta"
    assert kinds[-1] == "slo"
    series = [ln for ln in lines if ln["kind"] == "series"]
    assert any(
        s["name"] == "ledger_height" and s["node"] == "n1"
        and [p[1] for p in s["points"]] == [0.0, 1.0, 2.0, 3.0]
        for s in series
    )
    assert any(
        s["name"] == "cross_peer_lag_blocks" and s["node"] == "_derived"
        for s in series
    )
    events = [ln for ln in lines if ln["kind"] == "event"]
    assert [e["event"] for e in events] == ["kill", "restart"]
    health = [ln for ln in lines if ln["kind"] == "health"]
    assert health and health[0]["node"] == "n1"

    html = open(paths["html"], encoding="utf-8").read()
    assert "<svg" in html and "polyline" in html  # sparklines
    assert "ledger_height" in html
    assert "netscope report" in html
    # kill/restart markers drawn as vertical lines with titles
    assert "kill" in html and "restart" in html


# ---------------------------------------------------------------------------
# end to end: the wedged-peer stall, multi-process
# ---------------------------------------------------------------------------


def test_wedged_peer_flagged_in_verdict_survivors_green(tmp_path):
    """A per-node faultline plan wedges one peer's block ingestion
    (deliver connect + the gossip.state.payload funnel — the silent
    deliver-client-wedge class PR 11 caught by luck).  The victim is
    chosen as the gossip election NON-leader so the survivors keep
    committing; netscope must flag exactly the victim in the verdict
    while the invariants oracle stays green on every node."""
    from fabric_tpu.common.hashing import sha256

    peers = ["org1-peer0", "org1-peer1"]
    # gossip leadership: smallest pki-id (sha256(name)[:16]) wins and
    # runs the deliver client for the org — wedge the OTHER peer
    victim = max(peers, key=lambda n: sha256(n.encode())[:16])
    plan = {"seed": 1, "faults": [
        {"point": "gossip.state.payload", "action": "raise",
         "error": "RuntimeError", "every": 1, "count": 10 ** 9},
        {"point": "deliver.connect", "action": "raise",
         "error": "ConnectionResetError", "every": 1, "count": 10 ** 9},
    ]}
    topo = nh.Topology(
        orgs=1, peers_per_org=2, orderers=1, seed=23, ops=True,
        faultline={victim: plan},
    )
    with nh.Network(str(tmp_path / "net"), topo) as net:
        net.start()
        scope = nh.attach_netscope(net, interval_s=0.15)
        try:
            result = nh.run_stream(
                net, txs=60, settle_timeout_s=20, scope=scope,
            )
        finally:
            scope.stop()
    assert result["stalled_nodes"] == [victim]
    assert result["ok"] is False  # a stalled node fails the run
    verdict = nh.verdict_doc(result)
    assert verdict["stalled_nodes"] == [victim]
    # invariants green EVERYWHERE: the victim's ledger is consistent
    # (just short), the survivors committed the stream
    assert result["violations"] == {}
    survivor = next(p for p in peers if p != victim)
    assert result["heights"][survivor] > result["heights"][victim]
    # the stall episode carries its evidence window, and the episode
    # (evidence included) rides the jsonl artifact beside a repro
    episode = next(
        e for e in scope.stall_episodes() if e["node"] == victim
    )
    assert episode["evidence"]
    paths = write_artifacts(scope, str(tmp_path / "out"))
    lines = [
        json.loads(ln)
        for ln in open(paths["jsonl"], encoding="utf-8")
    ]
    episodes = [ln for ln in lines if ln["kind"] == "stall_episode"]
    assert [e["node"] for e in episodes] == [victim]
    assert episodes[0]["evidence"]


# ---------------------------------------------------------------------------
# tier-1: runtime ⊆ static (v6 metrics-conformance cross-check)
# ---------------------------------------------------------------------------


def test_runtime_scrape_series_subset_of_static_metricmap(tmp_path):
    """v6 runtime ⊆ static contract, metrics plane: every series name
    a live per-node ``/metrics`` exposition actually serves must be in
    the static ``--metricmap`` artifact's ``exposed`` set (which
    already expands histograms to their ``_bucket``/``_sum``/``_count``
    series).  A scraped series missing from the map means the
    metrics-conformance scan lost a producer — pinned here against a
    real network, not a fixture."""
    import urllib.request

    from fabric_tpu.devtools.lint import lint_tree
    from fabric_tpu.devtools.netscope import parse_prometheus

    topo = nh.Topology(
        orgs=1, peers_per_org=1, orderers=1, seed=13, ops=True,
    )
    observed: set[str] = set()
    with nh.Network(str(tmp_path / "net"), topo) as net:
        net.start()
        result = nh.run_stream(net, txs=10, settle_timeout_s=120)
        for host, port in net.ops_addrs().values():
            with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10
            ) as resp:
                text = resp.read().decode("utf-8")
            observed.update(
                name for name, _labels, _v in parse_prometheus(text)
            )
    assert result["ok"], result

    # non-vacuous: the scrape saw the consensus plane and a histogram
    assert "ledger_blocks_committed_total" in observed, sorted(observed)
    assert any(n.endswith("_bucket") for n in observed), sorted(observed)

    exposed = set(lint_tree().metricmap()["exposed"])
    assert observed <= exposed, (
        "scraped series missing from static metricmap: "
        f"{sorted(observed - exposed)}"
    )


# ---------------------------------------------------------------------------
# netbench --metrics-out (slow: acceptance-shaped seeded campaign)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_netbench_metrics_out_2org_4peer(tmp_path):
    out = tmp_path / "metrics"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "netbench.py"),
         "--orgs", "2", "--peers", "2", "--orderers", "1",
         "--txs", "120", "--seed", "9", "--kills", "1",
         "--metrics-out", str(out),
         "--workdir", str(tmp_path / "work")],
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["ok"] is True
    assert line["stalled_nodes"] == []
    assert line["netscope"]["pass"] is True
    lines = [
        json.loads(ln)
        for ln in open(out / "netscope.jsonl", encoding="utf-8")
    ]
    series = [ln for ln in lines if ln["kind"] == "series"]
    peer_nodes = {
        s["node"] for s in series if s["name"] == "ledger_height"
    }
    # every node of the 2-org × 4-peer (+1 orderer) topology reported
    # a height series
    assert len(peer_nodes) == 5
    events = [ln for ln in lines if ln["kind"] == "event"]
    assert any(e["event"] == "kill" for e in events)
    html = (out / "netscope.html").read_text(encoding="utf-8")
    assert "polyline" in html and "ledger_height" in html
